"""Full training-state checkpointing in the torch ckpt.pt schema
(SURVEY.md §3.4): {model, optimizer, model_args, iter_num, best_val_loss,
config}. A ckpt.pt written here resumes under the torch trainer and vice
versa — including optimizer moments, so resume is bit-honest, not just
weights (train.py:272-281 defines the schema; model.py:255-271 defines the
torch AdamW param grouping we must reproduce).
"""

import collections
import os

import jax
import numpy as np
from flax import nnx

from avenir_tpu.checkpoint.bridge import (
    export_torch_state_dict,
    restack_scanned_paths,
    torch_key_to_nnx_path,
    torch_sd_to_flat_paths,
)
from avenir_tpu.checkpoint.torch_pt import LazyArray, load_pt, save_pt


def torch_param_order(sd, model_family="gpt"):
    """Reproduce torch `named_parameters()` order (module insertion order,
    tied lm_head deduplicated) for the reference GPT (model.py:133-151).
    Needed because torch optimizer state is keyed by param *index*."""
    assert model_family == "gpt", "optimizer bridge currently covers gpt"
    keys = ["transformer.wte.weight", "transformer.wpe.weight"]
    i = 0
    while f"transformer.h.{i}.ln_1.weight" in sd:
        b = f"transformer.h.{i}."
        keys += [
            b + "ln_1.weight", b + "ln_1.bias",
            b + "attn.c_attn.weight", b + "attn.c_attn.bias",
            b + "attn.c_proj.weight", b + "attn.c_proj.bias",
            b + "ln_2.weight", b + "ln_2.bias",
            b + "mlp.c_fc.weight", b + "mlp.c_fc.bias",
            b + "mlp.c_proj.weight", b + "mlp.c_proj.bias",
        ]
        i += 1
    keys += ["transformer.ln_f.weight", "transformer.ln_f.bias"]
    return [k for k in keys if k in sd]


def _adam_groups(order, sd):
    """torch configure_optimizers grouping: decay = ndim>=2 first, then
    nodecay; param indices are global across groups (model.py:258-264)."""
    decay = [k for k in order if sd[k].ndim >= 2]
    nodecay = [k for k in order if sd[k].ndim < 2]
    return decay, nodecay


def _find_adam_state(opt_state):
    """Locate the ScaleByAdamState node inside an optax chain state."""
    found = []

    def walk(node):
        if hasattr(node, "mu") and hasattr(node, "nu") and hasattr(node, "count"):
            found.append(node)
            return
        if isinstance(node, tuple):
            for c in node:
                walk(c)

    walk(opt_state)
    assert len(found) == 1, f"expected exactly one adam state, found {len(found)}"
    return found[0]


def _replace_adam_state(opt_state, new_adam):
    def walk(node):
        if hasattr(node, "mu") and hasattr(node, "nu") and hasattr(node, "count"):
            return new_adam
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*(walk(c) for c in node))
        if isinstance(node, tuple):
            return tuple(walk(c) for c in node)
        return node

    return walk(opt_state)


def _gather_one(x):
    """Pull one (possibly sharded) jax array to host numpy. On a
    multi-host mesh every process participates in the all-gather; the
    coordinator alone writes the file (SURVEY.md §3.4 ⟨proc⟩ note)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def gather_to_host(tree):
    """Eager whole-tree host gather (small trees / tests)."""
    return jax.tree.map(_gather_one, tree)


def lazy_gather_tree(tree):
    """Replace every jax array leaf with a LazyArray that gathers it on
    materialize. The streaming .pt writer then pulls ONE tensor to host at
    a time — peak host memory is the largest tensor, not the full tree
    (the big-model save path, SURVEY.md §5 checkpoint bullet)."""
    def lazy(x):
        if isinstance(x, jax.Array):
            out = LazyArray(x.shape, np.dtype(x.dtype),
                            lambda x=x: _gather_one(x), source=x)
            # device-side slicing hook for lazy_unstack: x[i] slices on
            # device; gather pulls just that layer to host
            out.gather_fn = _gather_one
            return out
        return np.asarray(x)

    return jax.tree.map(lazy, tree)


def _tied(model_family):
    return model_family == "gpt"  # llama/mixtral have a real lm_head param


def save_checkpoint(out_dir, *, params, opt_state, hyper, model_args,
                    iter_num, best_val_loss, config, model_family="gpt"):
    """Write out_dir/ckpt.pt in the torch schema. `params` is the nnx Param
    State; `opt_state` the optax state; `hyper` carries the torch
    param_group hyperparams (lr, betas, eps, weight_decay).

    gpt: the optimizer entry is a torch AdamW state_dict (param-index
    keyed, model.py:255-271 grouping) so torch can resume it. llama/
    mixtral have no torch counterpart in-repo; their moments are stored
    under torch-style KEYS instead of indices ("format": "avenir_adamw"),
    same container."""
    tied = _tied(model_family)
    # lazy leaves: nothing is gathered here — the streaming save_pt pulls
    # one tensor to host at a time while writing
    sd = export_torch_state_dict(lazy_gather_tree(params),
                                 model_family=model_family,
                                 tied_lm_head=tied)
    adam = _find_adam_state(opt_state)
    mu_sd = export_torch_state_dict(lazy_gather_tree(adam.mu),
                                    model_family=model_family,
                                    tied_lm_head=False)
    nu_sd = export_torch_state_dict(lazy_gather_tree(adam.nu),
                                    model_family=model_family,
                                    tied_lm_head=False)
    step = float(np.asarray(_gather_one(adam.count)))

    if model_family == "gpt":
        order = torch_param_order(sd, model_family)
        decay, nodecay = _adam_groups(order, sd)
        opt_sd = {
            "state": {
                i: {
                    "step": np.asarray(step, np.float32),
                    "exp_avg": mu_sd[k],
                    "exp_avg_sq": nu_sd[k],
                }
                for i, k in enumerate(decay + nodecay)
            },
            "param_groups": [
                {
                    "lr": hyper["lr"], "betas": tuple(hyper["betas"]),
                    "eps": hyper["eps"], "weight_decay": wd,
                    "amsgrad": False, "maximize": False, "foreach": None,
                    "capturable": False, "differentiable": False,
                    "fused": None, "decoupled_weight_decay": True,
                    "params": list(range(start, start + len(group))),
                }
                for group, wd, start in (
                    (decay, hyper["weight_decay"], 0),
                    (nodecay, 0.0, len(decay)),
                )
            ],
        }
        model_sd = collections.OrderedDict(
            (k, sd[k]) for k in list(order) + ["lm_head.weight"]
        )
    else:
        opt_sd = {
            "format": "avenir_adamw", "step": step,
            "exp_avg": mu_sd, "exp_avg_sq": nu_sd,
            "hyper": dict(hyper),
        }
        model_sd = collections.OrderedDict(sorted(sd.items()))

    ckpt = {
        "model": model_sd,
        "optimizer": opt_sd,
        "model_args": dict(model_args),
        "iter_num": int(iter_num),
        "best_val_loss": float(best_val_loss),
        "config": dict(config),
        "model_family": model_family,
    }
    # every process materializes (collective per-leaf gathers); only the
    # coordinator writes the file
    # atomic: stream to .part, then rename — a crash or SIGKILL mid-write
    # (preemption grace periods end in SIGKILL) never destroys the
    # previous good checkpoint
    write = jax.process_index() == 0
    path = os.path.join(out_dir, "ckpt.pt")
    if write:
        os.makedirs(out_dir, exist_ok=True)
    save_pt(ckpt, path + ".part", write=write)
    if write:
        os.replace(path + ".part", path)


class AsyncCheckpoint:
    """In-flight background save. `join()` re-raises any writer exception;
    at most one should be in flight (the training loop joins the previous
    before starting the next). `thread=None` marks a save that already
    completed synchronously (the HBM capacity guard's fallback)."""

    def __init__(self, thread):
        self._thread = thread
        self.error = None

    def join(self):
        if self._thread is not None:
            self._thread.join()
        if self.error is not None:
            raise self.error

    def done(self):
        return self._thread is None or not self._thread.is_alive()


def _tree_device_bytes(tree):
    """Bytes a jnp.copy of `tree` would allocate on the WORST local
    device: per-device shard totals, maxed. A REPLICATED leaf holds a
    full copy per device (its per-device cost is the full nbytes, NOT
    nbytes / n_shards — dividing would understate the guard by
    device_count× exactly when params are replicated, e.g. pure-DP
    meshes); mixed replicated/sharded trees can load devices unevenly,
    so the guard takes the max, not device 0's total."""
    per_dev = {}
    host_only = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                per_dev[s.device] = per_dev.get(s.device, 0) + s.data.nbytes
        elif hasattr(leaf, "nbytes"):
            host_only += int(leaf.nbytes)
    return (max(per_dev.values()) if per_dev else 0) + host_only


def _device_free_bytes():
    """Free HBM on the TIGHTEST local device (min over local devices), or
    None when the platform exposes no memory stats (CPU harness). Min,
    not device 0: asymmetric residency (replicated leaves beside sharded
    ones) means the copy can OOM on a device other than the first."""
    frees = []
    for d in jax.local_devices():
        try:  # per-device: one stats-less device must not disable the guard
            stats = d.memory_stats() or {}
            frees.append(int(stats["bytes_limit"]) - int(stats["bytes_in_use"]))
        except Exception:
            continue
    return min(frees) if frees else None


def save_checkpoint_async(out_dir, *, params, opt_state, **kw):
    """save_checkpoint in a daemon thread, single-process only.

    The params/opt trees are SNAPSHOT with device-side copies on the
    calling thread first — the training step donates its state buffers,
    and a donated buffer is deleted out from under any lingering Python
    reference (holding the original tree is NOT a snapshot; learned the
    hard way: "Buffer has been deleted or donated"). The copies cost one
    transient params+moments footprint in HBM while the save is in
    flight. Crash-safety comes from save_checkpoint's own
    .part-then-rename atomicity.

    Multi-process saves gather collectively on every process and CANNOT
    run from a thread (the thread's collectives would race the training
    step's); callers must use the synchronous save on pods."""
    import threading

    import jax.numpy as jnp

    assert jax.process_count() == 1, (
        "save_checkpoint_async is single-process only (multi-process saves "
        "issue collective gathers that must run on the main thread)"
    )
    # HBM capacity guard (VERDICT r3 weak #5): the snapshot doubles the
    # params+moments footprint while the save is in flight. At the
    # capacity-bound deep rungs that's an OOM mid-run — degrade to the
    # synchronous save (training pauses for the write, but survives)
    # instead. 10% headroom keeps the copy from landing exactly at the
    # limit (XLA needs scratch).
    # ONE combined tree: params' heaviest device and opt_state's can
    # differ; maxing them separately would overstate any single device
    need = _tree_device_bytes((params, opt_state))
    free = _device_free_bytes()
    if free is not None and need > 0.9 * free:
        print(f"[ckpt] async snapshot needs {need / 1e9:.2f} GB but only "
              f"{free / 1e9:.2f} GB HBM is free — falling back to a "
              "synchronous save")
        handle = AsyncCheckpoint(None)
        try:
            save_checkpoint(out_dir, params=params, opt_state=opt_state,
                            **kw)
        except Exception as e:  # KeyboardInterrupt etc. propagate: this
            handle.error = e    # runs on the MAIN thread, unlike run()
        return handle
    params = jax.tree.map(jnp.copy, params)
    opt_state = jax.tree.map(jnp.copy, opt_state)

    def run():
        try:
            save_checkpoint(out_dir, params=params, opt_state=opt_state,
                            **kw)
        except BaseException as e:  # noqa: BLE001 — surfaced via join()
            handle.error = e

    t = threading.Thread(target=run, name="avenir-async-ckpt", daemon=True)
    handle = AsyncCheckpoint(t)
    t.start()
    return handle


def load_checkpoint(out_dir, lazy=False):
    """Read out_dir/ckpt.pt (either backend's) into host numpy. Returns the
    raw dict; use restore_params/restore_opt_state to place on device.
    `lazy=True`: tensors are LazyArray stubs read from the zip only when
    restore places them — the host never holds the full tree."""
    return load_pt(os.path.join(out_dir, "ckpt.pt"), lazy=lazy)


def _strip_compile_prefix(sd):
    pre = "_orig_mod."
    return {k[len(pre):] if k.startswith(pre) else k: v for k, v in sd.items()}


def restore_params(ckpt, abs_state, shardings, model_family="gpt"):
    """Map ckpt['model'] (torch layout) onto the param State, placing each
    leaf with its NamedSharding (sharded host→device transfer)."""
    sd = _strip_compile_prefix(dict(ckpt["model"]))
    flat = {p: v for p, v in abs_state.flat_state()}
    out = {}
    arrays = restack_scanned_paths(
        torch_sd_to_flat_paths(sd, tied_lm_head=_tied(model_family)),
        flat.keys(),
    )
    for path, a in arrays.items():
        assert path in flat, f"checkpoint path {path} not in model"
        var = flat[path]
        # materialize ONE tensor at a time (lazy checkpoints) and free the
        # host copy as soon as device_put returns; astype(copy=False) keeps
        # peak at one tensor when the dtype already matches
        a = np.ascontiguousarray(np.asarray(a))
        a = a.astype(var.get_value().dtype, copy=False)
        out[path] = var.replace(jax.device_put(a, shardings[path]))
        del a
    missing = set(flat) - set(out)
    assert not missing, f"checkpoint missing params: {sorted(missing)}"
    return nnx.State.from_flat_path(out)


def _set_all_counts(opt_state, count):
    """Set `count` on EVERY stateful node that carries one — ScaleByAdam
    AND ScaleBySchedule: restoring only the adam count would silently
    replay the LR schedule from 0 after resume."""
    c = np.asarray(count, np.int32)

    def walk(node):
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            node = type(node)(*(walk(x) for x in node))
            if "count" in node._fields:
                node = node._replace(count=c)
            return node
        if isinstance(node, tuple):
            return tuple(walk(x) for x in node)
        return node

    return walk(opt_state)


def restore_opt_state(ckpt, opt_state, params, param_shardings,
                      model_family="gpt"):
    """Rebuild the optax adam moments from the checkpoint's optimizer entry
    (torch param-index schema for gpt, key schema for other families) and
    splice them into a freshly init'd opt_state."""
    opt_entry = ckpt["optimizer"]
    flat_shard = dict(param_shardings)
    mu_flat, nu_flat = {}, {}

    if "param_groups" in opt_entry:  # torch AdamW schema
        sd = _strip_compile_prefix(dict(ckpt["model"]))
        order = torch_param_order(sd, model_family)
        decay, nodecay = _adam_groups(order, sd)
        indexed = decay + nodecay
        tstate = opt_entry["state"]
        step = 0.0
        from avenir_tpu.checkpoint.bridge import _swap_last2

        for i, key in enumerate(indexed):
            ent = tstate[i]
            path, transpose = torch_key_to_nnx_path(key)
            # torch may store step as a 0-d or 1-element tensor
            step = float(np.asarray(ent["step"]).reshape(-1)[0])
            for src, dst in (("exp_avg", mu_flat), ("exp_avg_sq", nu_flat)):
                a = ent[src]  # may be a LazyArray; stays lazy until placed
                dst[path] = _swap_last2(a) if transpose else a
    else:  # avenir_adamw schema (llama/mixtral)
        assert opt_entry.get("format") == "avenir_adamw", opt_entry.keys()
        step = float(opt_entry["step"])
        for src_name, dst in (("exp_avg", mu_flat), ("exp_avg_sq", nu_flat)):
            for path, a in torch_sd_to_flat_paths(
                opt_entry[src_name], tied_lm_head=False
            ).items():
                dst[path] = a

    def _place(flat):
        # one tensor on host at a time: materialize → device_put → free
        out = {}
        for p, a in restack_scanned_paths(flat, flat_shard.keys()).items():
            arr = np.ascontiguousarray(np.asarray(a), dtype=np.float32)
            out[p] = jax.device_put(arr, flat_shard[p])
            del arr
        return out

    mu_flat = _place(mu_flat)
    nu_flat = _place(nu_flat)
    pflat = {p: v for p, v in params.flat_state()}
    mu = nnx.State.from_flat_path(
        {p: pflat[p].replace(mu_flat[p]) for p in pflat}
    )
    nu = nnx.State.from_flat_path(
        {p: pflat[p].replace(nu_flat[p]) for p in pflat}
    )
    adam = _find_adam_state(opt_state)
    new_adam = adam._replace(mu=mu, nu=nu)
    return _set_all_counts(_replace_adam_state(opt_state, new_adam), int(step))
