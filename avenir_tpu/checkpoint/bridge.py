"""torch state_dict ↔ nnx state key/layout mapping (SURVEY.md §3.4).

The contract: a state_dict produced by model.py's `GPT.state_dict()` maps
1:1 onto `avenir_tpu.models.gpt.GPT`'s param state, with

  - `transformer.` prefix stripped,
  - torch Linear `weight` (out, in) transposed to nnx `kernel` (in, out),
  - torch LayerNorm/RMSNorm `weight` renamed to nnx `scale`,
  - embeddings (`wte`, `wpe`, `embed_tokens`) mapped to `embedding`
    untransposed,
  - tied `lm_head.weight` dropped on load (the nnx model has no separate
    lm_head param; model.py:149-151 ties it) and re-emitted on export.

The same rules cover the Llama/Mixtral families (their torch-side names
follow the HF convention); anything unrecognized raises — fail loud, per
the partition-rule miss policy (SURVEY.md §4).
"""

import numpy as np
from flax import nnx

from avenir_tpu.checkpoint.torch_pt import LazyArray, lazy_unstack


def _swap_last2(arr):
    """Transpose the last two axes, staying lazy for LazyArray entries
    (the streaming checkpoint path materializes one tensor at a time)."""
    if isinstance(arr, LazyArray):
        shp = arr.shape[:-2] + (arr.shape[-1], arr.shape[-2])
        return arr.transform(
            lambda x: np.ascontiguousarray(np.swapaxes(x, -1, -2)), shape=shp
        )
    return np.swapaxes(np.asarray(arr), -1, -2)

# module attribute names that are nnx.Linear (torch weight needs transpose)
_LINEAR = {
    "c_attn", "c_proj", "c_fc",                      # gpt
    "q_proj", "k_proj", "v_proj", "o_proj",          # llama attention
    "gate_proj", "up_proj", "down_proj",             # llama mlp
    "gate",                                          # mixtral router
    "w1", "w2", "w3",                                # mixtral experts
}
_NORM = {"ln_1", "ln_2", "ln_f", "input_layernorm", "post_attention_layernorm", "norm"}
_EMBED = {"wte", "wpe", "embed_tokens"}
_LINEAR_TOP = {"lm_head"}  # top-level Linear modules (untied output head)


def torch_key_to_nnx_path(key, tied_lm_head=True):
    """Map a torch state_dict key to (nnx path tuple, transpose flag).

    `tied_lm_head=True` (GPT-2, model.py:149-151): `lm_head.weight` is an
    alias of the embedding and has no nnx param → returns (None, False).
    `tied_lm_head=False` (Llama-3/Mixtral): `lm_head.weight` maps to a real
    nnx Linear kernel (transposed)."""
    if key == "lm_head.weight":
        if tied_lm_head:
            return None, False
        return ("lm_head", "kernel"), True
    parts = key.split(".")
    if parts[0] in ("transformer", "model"):
        parts = parts[1:]
    path = []
    for p in parts[:-1]:
        path.append(int(p) if p.isdigit() else p)
    leaf = parts[-1]
    owner = path[-1] if path else None
    if owner in _EMBED:
        assert leaf == "weight", key
        path.append("embedding")
        return tuple(path), False
    if owner in _NORM:
        assert leaf in ("weight", "bias"), key
        path.append("scale" if leaf == "weight" else "bias")
        return tuple(path), False
    if owner in _LINEAR:
        assert leaf in ("weight", "bias"), key
        path.append("kernel" if leaf == "weight" else "bias")
        return tuple(path), leaf == "weight"
    raise KeyError(f"no bridge rule for torch key {key!r}")


def nnx_path_to_torch_key(path, model_family="gpt"):
    """Inverse of torch_key_to_nnx_path. Returns (torch key, transpose)."""
    parts = list(path)
    leaf = parts[-1]
    if leaf == "embedding":
        parts[-1] = "weight"
        transpose = False
    elif leaf == "scale":
        parts[-1] = "weight"
        transpose = False
    elif leaf == "kernel":
        parts[-1] = "weight"
        transpose = True
    elif leaf == "bias":
        transpose = False
    else:
        raise KeyError(f"no bridge rule for nnx path {path!r}")
    if parts[0] in _LINEAR_TOP:  # lm_head lives at the top level, unprefixed
        return ".".join(str(p) for p in parts), transpose
    prefix = "transformer" if model_family == "gpt" else "model"
    return ".".join(str(p) for p in ([prefix] + parts)), transpose


_EXPERT_RE = __import__("re").compile(
    r"^(?P<pre>.*\bexperts)\.(?P<idx>\d+)\.(?P<w>w[123])\.weight$"
)


def _as_state(model_or_state):
    if isinstance(model_or_state, nnx.Module):
        return nnx.state(model_or_state, nnx.Param)
    return model_or_state


# ---- scan-stacked layer containers (models/common.stacked_layers) ----
#
# A model built with scan_layers=True stores its L homogeneous layers as ONE
# submodule named `<base>_scan` (h_scan, layers_scan) whose params carry a
# leading (L, ...) axis. On disk we keep the EXACT per-layer torch schema
# (transformer.h.0..., model.layers.0...), so scanned and unscanned models
# produce byte-identical checkpoints: export splits the stacked arrays,
# import re-stacks them when the target model expects the scanned layout.


def _scan_seg_index(path):
    for i, seg in enumerate(path):
        if isinstance(seg, str) and seg.endswith("_scan"):
            return i
    return None


def _fill_stack(arrs):
    """Stack lazy slices incrementally: preallocate the (L, ...) result and
    materialize one slice at a time, so host peak on a scanned/expert-stack
    restore is the stacked container + ONE slice — not the container plus
    every slice at once (ADVICE r2: the np.stack-of-list form held all L)."""
    first = np.asarray(arrs[0])
    out = np.empty((len(arrs),) + first.shape, first.dtype)
    out[0] = first
    del first
    for j in range(1, len(arrs)):
        out[j] = np.asarray(arrs[j])
    return out


def unstack_scanned_paths(flat):
    """{nnx path: array} → same dict with every `<base>_scan` entry split
    into per-layer `(<base>, l, ...)` entries along its leading axis.
    LazyArray entries split into lazy slices (base gathered once, freed
    after the last slice is consumed)."""
    out = {}
    for path, arr in flat.items():
        i = _scan_seg_index(path)
        if i is None:
            out[path] = arr
            continue
        base = path[i][: -len("_scan")]
        n = int(arr.shape[0])
        if isinstance(arr, LazyArray):
            slices = lazy_unstack(arr, n)
        else:
            a = np.asarray(arr)
            slices = [a[l] for l in range(n)]
        for l in range(n):
            out[path[:i] + (base, l) + path[i + 1:]] = slices[l]
    return out


def restack_scanned_paths(flat, target_paths):
    """Inverse of unstack_scanned_paths: for each target path that crosses a
    `<base>_scan` container, collect the per-layer `(<base>, l, ...)` source
    entries from `flat` and stack them. Non-scan entries pass through.
    Lazy sources stay lazy (the stack happens when the target is placed on
    device, one stacked tensor on host at a time)."""
    out = dict(flat)
    for tp in target_paths:
        i = _scan_seg_index(tp)
        if i is None:
            continue
        base = tp[i][: -len("_scan")]
        layers = []
        while True:
            src = tp[:i] + (base, len(layers)) + tp[i + 1:]
            if src not in out:
                break
            layers.append(out.pop(src))
        if not layers:
            continue
        if any(isinstance(a, LazyArray) for a in layers):
            first = layers[0]
            out[tp] = LazyArray(
                (len(layers),) + tuple(first.shape), first.dtype,
                lambda ls=layers: _fill_stack(ls),
            )
        else:
            out[tp] = np.stack([np.asarray(a) for a in layers])
    return out


def _stack_expert_keys(sd):
    """HF Mixtral stores one 2-D tensor per expert
    (…block_sparse_moe.experts.N.w1.weight, (out, in)); our model stacks
    them as (E, in, out). Group, transpose last two dims, stack — and
    return the remaining plain entries untouched."""
    groups, rest = {}, {}
    for key, arr in sd.items():
        m = _EXPERT_RE.match(key)
        if not m:
            rest[key] = arr
            continue
        gkey = (m.group("pre"), m.group("w"))
        groups.setdefault(gkey, {})[int(m.group("idx"))] = arr
    stacked = {}
    for (pre, w), by_idx in groups.items():
        arrs = [_swap_last2(by_idx[i]) for i in range(len(by_idx))]
        parts = pre.split(".")
        if parts[0] in ("transformer", "model"):
            parts = parts[1:]
        path = tuple(int(p) if p.isdigit() else p for p in parts) + (w,)
        if any(isinstance(a, LazyArray) for a in arrs):
            # keep the stack lazy: expert tensors are the bulk of an MoE
            # model — materializing all E here would defeat streaming
            first = arrs[0]
            stacked[path] = LazyArray(
                (len(arrs),) + tuple(first.shape), first.dtype,
                lambda ls=arrs: _fill_stack(ls),
            )
        else:
            stacked[path] = np.stack(arrs)
    return stacked, rest


def torch_sd_to_flat_paths(sd, tied_lm_head=True):
    """{torch key: array} → {nnx path: correctly-laid-out numpy array}
    (transposes applied, per-expert tensors stacked, tied aliases dropped).
    Shared by in-place loading and sharded checkpoint restore."""
    stacked, rest = _stack_expert_keys(sd)
    out = dict(stacked)
    for key, arr in rest.items():
        path, transpose = torch_key_to_nnx_path(key, tied_lm_head=tied_lm_head)
        if path is None:
            continue  # tied weight
        if transpose:
            arr = _swap_last2(arr)
        elif not isinstance(arr, LazyArray):
            arr = np.asarray(arr)
        out[path] = arr
    return out


def load_torch_state_dict(model, sd, strict=True, tied_lm_head=True):
    """Load a torch-layout state_dict (key → numpy array) into an nnx model
    in place. `sd` values must be numpy/jax arrays (call .numpy() on torch
    tensors first — this module never imports torch)."""
    state = nnx.state(model, nnx.Param)
    flat = {path: v for path, v in state.flat_state()}
    seen = set()
    arrays = restack_scanned_paths(
        torch_sd_to_flat_paths(sd, tied_lm_head), flat.keys()
    )
    for path, arr in arrays.items():
        if path not in flat:
            if strict:
                raise KeyError(
                    f"state_dict path {path!r} does not exist in the model"
                )
            continue
        var = flat[path]
        expected = var.get_value().shape
        assert arr.shape == tuple(expected), (
            f"{path}: shape {arr.shape} != model {tuple(expected)}"
        )
        var.set_value(arr.astype(np.asarray(var.get_value()).dtype))
        seen.add(path)
    if strict:
        missing = set(flat) - seen
        if missing:
            raise KeyError(f"state_dict missing params for nnx paths: {sorted(missing)}")
    nnx.update(model, nnx.State.from_flat_path(flat))
    return model


def export_torch_state_dict(model, model_family="gpt", tied_lm_head=True):
    """Export nnx params as a torch-layout state_dict (key → numpy array).
    With `tied_lm_head` (GPT-2), re-emit the `lm_head.weight` alias the
    torch model's state_dict contains; untied families (Llama-3) export
    their real lm_head kernel through the normal path rules.

    `model` may be an nnx Module or a Param State (e.g. gathered host
    params, or an optimizer-moment tree with the same structure)."""
    state = _as_state(model)
    sd = {}
    prefix = "transformer" if model_family == "gpt" else "model"

    def _host(v):
        x = v.get_value()
        return x if isinstance(x, LazyArray) else np.asarray(x)

    flat = unstack_scanned_paths(
        {path: _host(var) for path, var in state.flat_state()}
    )
    for path, arr in flat.items():
        if path[-1] in ("w1", "w2", "w3") and "experts" in path:
            # stacked (E, in, out) → HF per-expert (out, in) tensors
            base = ".".join(str(p) for p in ([prefix] + list(path[:-1])))
            E = int(arr.shape[0])
            slices = (lazy_unstack(arr, E) if isinstance(arr, LazyArray)
                      else [arr[e] for e in range(E)])
            for e in range(E):
                sd[f"{base}.{e}.{path[-1]}.weight"] = _swap_last2(slices[e])
            continue
        key, transpose = nnx_path_to_torch_key(path, model_family=model_family)
        sd[key] = _swap_last2(arr) if transpose else arr
    if tied_lm_head:
        wte_key = (
            "transformer.wte.weight" if model_family == "gpt"
            else "model.embed_tokens.weight"
        )
        assert "lm_head.weight" not in sd, "model has an untied lm_head param"
        sd["lm_head.weight"] = sd[wte_key]
    return sd
