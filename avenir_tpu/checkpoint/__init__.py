"""avenir_tpu.checkpoint — cross-backend checkpointing (SURVEY.md §2b T7).

Two halves:
  - bridge.py: key/layout mapping between torch state_dicts and nnx state
    (Linear kernels transposed, LayerNorm weight→scale, tied lm_head).
  - torch_pt.py: read/write the torch `.pt` zipfile container in pure
    Python — no torch import — so a TPU pod can resume a CUDA checkpoint
    and vice versa (BASELINE.json:5 "same ... checkpoint format").
"""

from avenir_tpu.checkpoint.bridge import (
    export_torch_state_dict,
    load_torch_state_dict,
    nnx_path_to_torch_key,
    torch_key_to_nnx_path,
)
