"""Fleet-wide KV reuse policy — the "KV CDN" (ISSUE 17 tentpole).

Two cooperating layers turn the fleet into one cache:

1. **Prefix-affinity placement.** The Router's placement score gains an
   affinity term fed by the fleet cache map (`serve/cache_map.py`, the
   ISSUE 16 content view): route each request toward the replica whose
   advertised chains share the deepest prefix with the prompt. The
   bonus is `weight * shared_tokens / prompt_tokens`, CAPPED by the
   candidate's free-slot fraction — a hot system prompt cannot hotspot
   one replica, because the bonus decays exactly as fast as the
   replica's capacity does. Prefixes nobody holds yet get a tiny
   consistent-hash nudge (`shard_weight`) toward a stable home, so
   cold prefix families shard across the fleet's aggregate cache
   capacity instead of herding onto the tie-break winner.

2. **Peer prefix pull (miss path).** When the chosen replica misses but
   a peer advertises a materially deeper prefix (`pull_min_tokens`
   threshold), the router brokers a pull: the peer exports the shared
   chain's pages over the existing PT_KVPAGES frame path, the receiver
   splices them via `PageAllocator.import_chain`, and the request
   prefills from the first unshared token.

The failover contract is unchanged: a pull that dies, times out, or
CRC-trips falls back to local re-prefill from prompt+rng, bit-exact —
pulls are an optimization, NEVER a correctness dependency. The map's
depths may overstate the real attach by up to one page (cache_map's
documented approximation); every consumer here tolerates that because
`import_chain` dedupes and the engine's own `plan()` re-derives the
true attach at admission.

This module is pure policy — dataclass knobs plus side-effect-free
score/plan helpers — so the math is unit-testable without a fleet.
The wiring (map reads, RPC brokering, counters) lives in
`serve/router.py`.
"""

import zlib
from dataclasses import dataclass


@dataclass
class AffinityPolicy:
    """Knobs for prefix-affinity placement + peer prefix pull.

    weight           scale on the shared-prefix fraction added to the
                     placement score (the free-slot cap applies after)
    staleness_s      ignore a replica's advertised chains older than
                     this many fleet-clock seconds (None = trust
                     forever; corpses are dropped by failover anyway)
    pull             broker peer pulls on a placement miss (False =
                     placement-only affinity)
    pull_min_tokens  minimum ADVANTAGE (peer depth minus chosen
                     replica's depth, tokens) before a pull is worth
                     brokering; None resolves to 2 x page_size at the
                     router — shallower wins cost more in frames than
                     they save in prefill
    shard_weight     small score nudge toward the prompt's consistent-
                     hash home replica (CRC of the first KV page of
                     tokens). Cold prefix families thereby SHARD across
                     the fleet's aggregate cache instead of herding
                     onto the tie-break winner and LRU-churning each
                     other out of one pool; any real observed match
                     (weight, default 1.0) outbids it, and so does one
                     free slot of load imbalance — keep it well under
                     1/n_slots. 0 disables.
    """

    weight: float = 1.0
    staleness_s: float = 30.0
    pull: bool = True
    pull_min_tokens: int = None
    shard_weight: float = 0.05

    def __post_init__(self):
        assert self.weight >= 0.0, "affinity weight must be >= 0"
        assert self.staleness_s is None or self.staleness_s > 0.0, (
            "staleness_s must be positive (or None to trust forever)")
        assert self.pull_min_tokens is None or self.pull_min_tokens > 0, (
            "pull_min_tokens must be positive (or None for the "
            "2 x page_size default)")
        assert self.shard_weight >= 0.0, "shard_weight must be >= 0"


def resolve_affinity(affinity):
    """Normalize the `Router(affinity=)` knob: False/None -> off,
    True -> defaults, dict -> AffinityPolicy(**dict), an instance
    passes through."""
    if affinity is None or affinity is False:
        return None
    if affinity is True:
        return AffinityPolicy()
    if isinstance(affinity, AffinityPolicy):
        return affinity
    if isinstance(affinity, dict):
        return AffinityPolicy(**affinity)
    raise TypeError(
        f"Router(affinity=...) takes bool, dict, or AffinityPolicy, "
        f"got {type(affinity).__name__}")


def affinity_bonus(policy, shared_tokens, prompt_tokens, free_frac):
    """The placement-score affinity term: `weight * shared/prompt`,
    capped by the candidate's free-slot fraction (the anti-hotspot
    trade-off the tentpole specifies — a loaded replica's cache
    gravity shrinks with its remaining capacity)."""
    if shared_tokens <= 0 or prompt_tokens <= 0:
        return 0.0
    bonus = policy.weight * (shared_tokens / prompt_tokens)
    return min(bonus, max(0.0, free_frac))


def shard_home(policy, prompt, page_size, candidate_ids):
    """Deterministic cold-start shard: CRC32 of the prompt's first KV
    page maps every prefix family to a stable home among the (sorted)
    healthy candidates. Requests sharing a system prompt agree on a
    home before any replica has ever seen it — the fleet's caches
    partition the tenant set instead of all competing for the same
    LRU. Returns a replica id, or None when disabled/no candidates."""
    if policy.shard_weight <= 0.0 or not candidate_ids:
        return None
    head = ",".join(str(int(t)) for t in prompt[:int(page_size)])
    ids = sorted(candidate_ids, key=str)
    return ids[zlib.crc32(head.encode()) % len(ids)]


def pull_plan(policy, match, chosen_id, page_size):
    """Decide whether a peer pull is worth brokering for a request
    placed on `chosen_id`, given the staleness-filtered cache-map
    `match` ({replica_id: shared tokens}). Returns
    (src_replica_id, src_tokens, local_tokens) or None.

    The advantage threshold is `pull_min_tokens` (default
    2 x page_size): below it the frame round-trip costs more than the
    prefill it saves. Deterministic tie-break on replica id, matching
    `FleetCacheMap.best_match`."""
    if not policy.pull:
        return None
    local = int(match.get(chosen_id, 0))
    best_rid, best = None, local
    for rid in sorted(match, key=str):
        if rid != chosen_id and int(match[rid]) > best:
            best_rid, best = rid, int(match[rid])
    if best_rid is None:
        return None
    min_tok = policy.pull_min_tokens
    if min_tok is None:
        min_tok = 2 * int(page_size)
    if best - local < max(int(min_tok), 1):
        return None
    return best_rid, best, local
