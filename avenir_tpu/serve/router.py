"""Multi-replica serve router: health checks, failover, admission
control, priority fair-share (ISSUE 6 tentpole, part 2).

One engine per chip is not a fleet. The router owns the front door over
N `serve/replica.py` replicas and makes four promises:

1. **No accepted request is ever lost.** Every submit that is not
   refused at the door finishes exactly once — with its tokens, or with
   an explicit `timeout`. Requests in flight on a replica that dies or
   stops heartbeating are requeued (oldest first, ahead of new work)
   and re-prefilled FROM THE ORIGINAL PROMPT with the ORIGINAL rng on a
   healthy replica, so a completed output is bit-identical to a one-shot
   `generate_cached` run no matter how many failovers it survived — the
   engine's parity contract (tests/test_serve.py) is the oracle, and
   the partial tokens of the dead attempt are discarded, not spliced.
2. **Bounded memory under overload.** Per-priority queue depth limits
   plus an admission-time projected-wait check against `deadline_ms`:
   work that would miss its deadline anyway is refused immediately with
   `finish_reason='shed'` (`serve_shed`) instead of growing the queue —
   backpressure the caller can see.
3. **Batch can never starve interactive.** Two priority classes with
   weighted fair-share dispatch (smoothed weighted round-robin — with
   weights 4:1 a saturated fleet serves I I I I B ...): batch soaks up
   idle capacity, interactive keeps its share the moment it arrives.
4. **SLO-aware placement, not round-robin.** A dispatch goes to the
   healthy replica maximizing free-slot fraction minus its engine queue
   backlog, and a tight-deadline request additionally penalizes slow
   replicas by the ticks of slack they would burn (`deadline_ms`, queue
   depth and slot occupancy are the routing signals — the same ones
   METRIC_SCHEMA already exports).

Orca-style iteration-level scheduling (serve/scheduler.py) stays the
per-replica substrate; vLLM's continuous-batching serving stack is the
reference for the fleet shape (PAPERS.md). Synchronous and network-free
like the engine: `step()` is one fleet iteration (health check ->
respawn -> expire -> dispatch -> step replicas -> harvest), `drain()`
runs it to empty. A transport in front of this owns no scheduling
logic — which is what lets `backend='process'` (ISSUE 8) swap each
replica for its own OS process (serve/proc.py over the serve/frames.py
pipe protocol) without changing ONE failover/admission/fair-share
decision: the same tests pass over both backends, and a real SIGKILL
is now a routable event instead of a fleet crash.
"""

import dataclasses
import statistics
import time
from collections import deque
from typing import Optional, Tuple

import jax

from avenir_tpu.obs import NullSink, get_registry
from avenir_tpu.serve.affinity import affinity_bonus, pull_plan, \
    resolve_affinity, shard_home
from avenir_tpu.serve.cache_map import FleetCacheMap
from avenir_tpu.serve.engine import FinishedRequest
from avenir_tpu.serve.replica import (
    DEAD,
    DRAINING,
    HEALTHY,
    Replica,
    ReplicaGone,
)

PRIORITIES = ("interactive", "batch")
BACKENDS = ("inproc", "process")


@dataclasses.dataclass
class RoutedRequest:
    """Router-side request record: everything needed to (re)submit to
    any engine — failover restarts from the original prompt + rng."""

    rid: int
    prompt: Tuple[int, ...]
    max_new_tokens: int
    temperature: float
    top_k: Optional[int]
    stop_tokens: Tuple[int, ...]
    rng: object
    priority: str
    deadline_ms: Optional[float]
    submit_t: float            # ORIGINAL submission (router clock secs)
    failovers: int = 0
    dispatch_t: Optional[float] = None
    # class queue depth the moment this request was enqueued — the
    # wait predictor's feature (ISSUE 12; None when tracing is off)
    depth_at_submit: Optional[int] = None
    # shared-prefix tokens a peer pull landed on the CHOSEN replica for
    # THIS dispatch (ISSUE 17) — reset per decision, so the reuse audit
    # counts pulled tokens as reused (they were shipped, not recomputed)
    pulled_tokens: int = 0

    def expired(self, now):
        return (self.deadline_ms is not None
                and (now - self.submit_t) * 1e3 >= self.deadline_ms)


@dataclasses.dataclass
class RouterFinished(FinishedRequest):
    """FinishedRequest plus the fleet-level facts. finish_reason adds
    'shed' (refused at admission) to the engine's set; `failovers` is
    how many replica deaths this request survived."""

    priority: str = "interactive"
    replica: int = -1
    failovers: int = 0


class _SpawnHandle:
    """In-flight background replica build (Router.begin_add_replica)."""

    __slots__ = ("replica_id", "thread", "result", "error")

    def __init__(self, replica_id):
        self.replica_id = replica_id
        self.thread = None
        self.result = None
        self.error = None

    def ready(self):
        return self.thread is not None and not self.thread.is_alive()


class Router:
    def __init__(self, model, *, n_replicas=2, n_slots=4, max_seq_len=None,
                 detokenize=None, registry=None, sink=None, seed=0,
                 clock=None, weights=None, queue_limits=None,
                 stall_floor_secs=10.0, stall_factor=10.0,
                 backend="inproc", model_spec=None, supervise=False,
                 respawn_policy=None, max_respawns=5, proc_kwargs=None,
                 engine_kwargs=None, tracer=None, draft_model=None,
                 n_prefill=0, disagg_min_prompt=None, anomaly=None,
                 cache_telescope=False, affinity=False):
        """`weights`: dispatch shares per priority class (default
        interactive 4 : batch 1). `queue_limits`: max queued per class
        before shedding (default 16/64 x fleet slots). `clock` is shared
        with every replica engine (injectable for tests).

        `backend` (ISSUE 8): 'inproc' keeps replicas as engine wrappers
        in this process; 'process' puts each replica in its OWN OS
        process (serve/proc.py + serve/worker.py) so a real SIGKILL
        kills one replica, not the fleet. The router's failover,
        admission and fair-share semantics are IDENTICAL over both —
        only the replica class changes. For 'process', `model_spec`
        overrides the default spec derived from `model` (pass a
        {"kind": "checkpoint", "out_dir": ...} spec to keep big weights
        off the handshake pipe); `supervise=True` auto-respawns dead
        workers with capped exponential backoff (`respawn_policy`, a
        utils/retry.RetryPolicy) up to `max_respawns` consecutive
        failures per replica; `proc_kwargs` forwards extra ProcReplica
        knobs (rpc_slack_secs, compile_grace_secs, env).

        `engine_kwargs` (ISSUE 9) forwards per-engine knobs to every
        replica — the paged-KV ones (`kv_impl`, `page_size`, `n_pages`,
        `max_pages_per_seq`, `prefill_chunk`, `prefix_sharing`,
        `paged_attn_impl`) and the decode-speed ones (`kv_dtype`,
        `spec_decode`, `spec_k`, ISSUE 11) ride the process backend's
        hello handshake unchanged, so a fleet of paged / int8 /
        speculative workers is one flag away. `draft_model` is the
        spec-decode draft: shipped to process workers exactly like the
        target weights (bit-identical numpy-state spec in the hello; a
        {"kind": "checkpoint"} draft_spec can ride `proc_kwargs`
        instead) — the router itself needs ZERO semantic changes for
        spec decoding, engines just finish more tokens per step.

        `n_prefill` (ISSUE 13, disaggregated prefill/decode): the first
        `n_prefill` of `n_replicas` become PREFILL-CLASS replicas
        (Engine role='prefill'), the rest decode-class. Long prompts
        (>= `disagg_min_prompt`, default the engine's prefill_chunk)
        dispatch to the prefill class, which chunk-prefills and streams
        each finished KV page over PT_KVPAGES frames to a pinned
        least-loaded decode replica WHILE the remaining chunks compute;
        at 'prefilled' the router hands the request off — the decode
        replica's admission prefix-attaches the imported pages and only
        computes the sub-page tail, so one long prompt never steals a
        decode tick fleet-wide and the two classes scale independently
        (prefill is compute-bound, decode bandwidth-bound). Short
        prompts skip the handoff and dispatch straight to the decode
        class. Failover stays bit-exact: a request whose prefill OR
        decode replica dies mid-transfer falls back to the ordinary
        requeue + re-prefill-from-prompt+rng path, and the per-request
        parity oracle (generate_cached equality) covers every path.
        Requires engine_kwargs kv_impl='paged' with prefix sharing on.

        `tracer` (ISSUE 10): an obs/trace.py Tracer — the fleet flight
        recorder. The router emits the fleet-level lifecycle events
        (submit/admit/dispatch/failover/requeue/terminal refusals) and
        absorbs each replica's engine events every step, translating
        engine-local rids to fleet rids (process-backend events arrive
        as age deltas and are restamped on the fleet clock). None (the
        default) disables tracing end to end — replicas then build no
        buffers and workers ship no trace frames.

        `cache_telescope` (ISSUE 16): arms the fleet cache telescope —
        every replica ships its allocator's top-K prefix-chain summary
        (chain_topk rides `engine_kwargs`; process workers attach
        deltas to step-reply heartbeats, in-process engines are read
        directly) into a router-side FleetCacheMap, and every dispatch
        decision is audited COUNTERFACTUALLY: the chosen replica's
        shared-prefix depth vs the fleet-best placement's. The audit
        partitions each dispatched prompt's tokens exactly into the
        `prefix_tokens_reused` / `prefix_tokens_missed` /
        `prefix_tokens_cold` counters and emits a `missed_reuse` trace
        event when a better placement existed. Observability ONLY —
        routing reads NOTHING from the map this issue (the PR 17
        affinity router is the consumer); False (the default) disables
        it end to end behind one pointer check. Pass True for the
        default top-K of 32 or an int to set the per-replica summary
        cap (heartbeat growth is bounded at ~60 bytes/node).

        `affinity` (ISSUE 17, the fleet KV CDN): arms prefix-affinity
        routing + peer prefix pull on top of the telescope's content
        view. Placement: each candidate's score gains
        `weight * shared_prefix_frac`, capped by its free-slot fraction
        (serve/affinity.py — a hot prefix cannot hotspot a loaded
        replica). Miss path: when the chosen replica misses but a peer
        advertises a chain deeper by >= `pull_min_tokens` (default
        2 x page_size), the router brokers a pull — the peer exports
        the shared chain over the PT_KVPAGES frame path, the receiver
        splices it via `import_chain`, and prefill starts at the first
        unshared token. A pull that dies, times out, or CRC-trips
        falls back to local re-prefill from prompt+rng, bit-exact —
        pulls are an optimization, NEVER a correctness dependency.
        Pass True for defaults, a dict of AffinityPolicy fields, or an
        AffinityPolicy. Requires `cache_telescope` armed (fail-loud:
        the map IS the affinity signal) and paged KV. False (the
        default) keeps routing affinity-blind.

        `anomaly` (ISSUE 14): an obs/anomaly.py AnomalyEngine — the
        fleet health tier. Each step the router feeds it replica step
        walls, heartbeat age, oldest-queued wait, TTFT/TPOT of finished
        requests, the spec accept rate and io_retries, then runs its
        check: drifts/trends/collapses fire as `anomaly` counter +
        record + trace event + flight dump BEFORE the stall/SLO tiers
        react. None (the default) disables it — every consult is the
        `tr is not None`-style single-branch guard, micro-pinned."""
        assert n_replicas >= 1
        assert backend in BACKENDS, f"unknown backend {backend!r}"
        self._clock = clock if clock is not None else time.perf_counter
        self._reg = registry if registry is not None else get_registry()
        self.sink = sink if sink is not None else NullSink()
        self.tracer = tracer
        self._anomaly = anomaly  # None = fleet health engine off
        self.backend = backend
        self._supervisor = None
        self._rollout = None   # live weight lifecycle (ISSUE 20)
        # replica build recipe, retained so the autoscaler can grow the
        # fleet after construction (add_replica, ISSUE 12)
        self._model = model
        self._rep_cfg = dict(
            n_slots=int(n_slots), max_seq_len=max_seq_len,
            detokenize=detokenize, seed=seed,
            stall_floor_secs=stall_floor_secs,
            stall_factor=stall_factor)
        self._engine_kwargs = dict(engine_kwargs or {})
        # fleet cache telescope (ISSUE 16): content view + reuse audit.
        # Armed BEFORE replicas build so chain_topk rides every hello
        self._cache_map = None
        if cache_telescope:
            topk = 32 if cache_telescope is True else int(cache_telescope)
            assert topk > 0, "cache_telescope top-K must be positive"
            self._engine_kwargs.setdefault("chain_topk", topk)
            self._cache_map = FleetCacheMap(clock=self._clock)
            # pre-create the partition counters so a zero-traffic fleet
            # still exports all three (and the schema lint sees them)
            self._reg.counter("prefix_tokens_reused")
            self._reg.counter("prefix_tokens_missed")
            self._reg.counter("prefix_tokens_cold")
        # fleet KV CDN (ISSUE 17): prefix-affinity placement + peer pull
        self._affinity = resolve_affinity(affinity)
        if self._affinity is not None:
            assert self._cache_map is not None, (
                "Router(affinity=...) routes on the fleet cache map — "
                "arm cache_telescope=True (the content view is the "
                "affinity signal; placement without it would be blind "
                "guessing, so this fails loud)")
            assert self._engine_kwargs.get("kv_impl") == "paged", (
                "affinity routes on prefix-chain identity and pulls "
                "ship KV PAGES — pass engine_kwargs={'kv_impl': "
                "'paged', ...}")
            assert self._engine_kwargs.get("prefix_sharing", True), (
                "peer pulls splice chains through prefix sharing — "
                "prefix_sharing must stay on")
            # pre-create so a zero-pull fleet still exports all four
            self._reg.counter("affinity_hits")
            self._reg.counter("prefix_pull_pages")
            self._reg.counter("prefix_pull_bytes")
            self._reg.counter("prefix_pull_fallbacks")
        self._draft_model = draft_model
        self._spec = None
        self._pk = {}
        self._retiring = set()   # replica_ids draining toward removal
        self._next_replica_id = n_replicas
        # disaggregated prefill/decode (ISSUE 13)
        self.n_prefill = int(n_prefill)
        self._role = {}          # replica_id -> 'prefill' | 'both'
        self._transfer = {}      # rid -> in-flight page-transfer state
        if self.n_prefill:
            assert 0 < self.n_prefill < n_replicas, (
                "disaggregation needs at least one replica of EACH "
                f"class (n_prefill={n_prefill} of {n_replicas})")
            assert self._engine_kwargs.get("kv_impl") == "paged", (
                "disaggregation ships KV PAGES between replica classes "
                "— pass engine_kwargs={'kv_impl': 'paged', ...}")
            assert self._engine_kwargs.get("prefix_sharing", True), (
                "disaggregation splices transferred pages through the "
                "prefix chain — prefix_sharing must stay on")
            # spec × disagg (ISSUE 18): no assertion anymore — the
            # draft never rides a page transfer. Decode-class replicas
            # run propose/verify on chains spliced via import_chain,
            # and the draft seeds from the SHIPPED PROMPT (draft-only
            # catch-up chunks over the imported prefix, or no draft KV
            # at all for draft_model='ngram'); prefill-class replicas
            # get the spec knobs stripped in _make_replica.
        self.disagg_min_prompt = (
            int(disagg_min_prompt) if disagg_min_prompt is not None
            else int(self._engine_kwargs.get("prefill_chunk")
                     or 4 * int(self._engine_kwargs.get("page_size", 16))))
        if backend == "process":
            from avenir_tpu.serve.proc import (
                RespawnSupervisor,
                model_spec_from_model,
            )

            self._spec = model_spec if model_spec is not None \
                else model_spec_from_model(model)
            self._pk = dict(proc_kwargs or {})
            # draft_model='ngram' (ISSUE 18) is a string, not a model:
            # nothing to spec — it rides the engine kwargs instead
            # (_make_replica), so the hello ships NO second model
            if (draft_model is not None
                    and not isinstance(draft_model, str)
                    and "draft_spec" not in self._pk):
                self._pk["draft_spec"] = model_spec_from_model(draft_model)
            self.replicas = [
                self._make_replica(
                    i, role=("prefill" if i < self.n_prefill else "both"),
                    defer_handshake=True)
                for i in range(n_replicas)
            ]
            for r in self.replicas:  # workers warmed up concurrently
                r.finish_handshake()
            if supervise:
                self._supervisor = RespawnSupervisor(
                    policy=respawn_policy, max_respawns=max_respawns,
                    clock=self._clock, registry=self._reg,
                ).attach(self.replicas)
        else:
            assert not supervise, (
                "supervised respawn is the process backend's restart "
                "story; in-process replicas are revived explicitly "
                "(revive_replica)")
            self.replicas = [
                self._make_replica(
                    i, role=("prefill" if i < self.n_prefill else "both"))
                for i in range(n_replicas)]
        eng0 = self.replicas[0].engine
        self.T_max = eng0.T_max
        # budget-aware admission limit (ISSUE 9): under paged KV the
        # per-sequence page budget binds, not T_max — the engine (or
        # the worker's hello reply, for the process backend) says which
        self.max_total_tokens = getattr(eng0, "max_total_tokens",
                                        None) or eng0.T_max
        self._limit_name = getattr(eng0, "limit_name", "max_seq_len")
        self.detokenize = detokenize
        self.weights = dict(weights or {"interactive": 4.0, "batch": 1.0})
        assert set(self.weights) == set(PRIORITIES)
        assert all(w > 0 for w in self.weights.values())
        total_slots = n_replicas * int(n_slots)
        self.queue_limits = dict(queue_limits or {
            "interactive": 16 * total_slots, "batch": 64 * total_slots})
        self._queues = {c: deque() for c in PRIORITIES}
        self._wrr = {c: 0.0 for c in PRIORITIES}  # smoothed-WRR credits
        self._next_id = 0
        self._base_rng = jax.random.key(seed)
        self._pending = []     # shed/rejected/failover-timeout records
        self._open = {}        # rid -> RoutedRequest (queued or in flight)
        self._where = {}       # rid -> replica_id, while dispatched
        self._by_replica = {r.replica_id: {} for r in self.replicas}
        #                    replica_id -> {engine_rid: rid}
        self._holds = []       # recent slot-hold durations (clock secs)
        # predictive admission (ISSUE 12): when tracing is armed, a
        # per-class WaitPredictor is fit on the submit -> dispatch
        # deltas the trace events stamp, and projected_wait_ms consults
        # it; with tracing off the static median-slot-hold rule stands
        self.wait_predictor = None
        if tracer is not None:
            from avenir_tpu.serve.autoscale import WaitPredictor

            self.wait_predictor = {c: WaitPredictor()
                                   for c in PRIORITIES}

    # ---- replica construction (ctor + autoscaler growth) ----

    def _make_replica(self, i, *, role="both", prewarm=False,
                      defer_handshake=False):
        """Build one replica from the retained recipe. `prewarm` rides
        the engine kwargs: the engine (worker hello, for the process
        backend) runs one synthetic prefill + decode tick per bucket
        BEFORE the replica is dispatchable, so a fresh replica never
        serves its first compile to a user (Engine.prewarm). `role`
        (ISSUE 13): 'prefill' builds a prefill-class replica — the knob
        rides the engine kwargs like every other per-engine choice, so
        the process backend's hello carries it unchanged."""
        ekw = dict(self._engine_kwargs)
        self._role[i] = role
        pk = self._pk
        if role == "prefill":
            ekw["role"] = "prefill"
            # spec × disagg (ISSUE 18): speculation is a decode-class
            # concern — a prefill replica never decodes, so it gets the
            # spec knobs (and the draft weights, for the process
            # backend's hello) stripped instead of the whole fleet
            # being asserted spec-off at construction
            for k in ("spec_decode", "spec_k", "draft_model"):
                ekw.pop(k, None)
            if "draft_spec" in pk:
                pk = {k: v for k, v in pk.items() if k != "draft_spec"}
        elif (isinstance(self._draft_model, str)
              and self.backend == "process"):
            # the draft-free self-draft is a knob, not a model: ride
            # the engine kwargs so the process worker's Engine ctor
            # sees it without a model spec in the hello (the in-process
            # Replica takes the string through its draft_model param)
            ekw["draft_model"] = self._draft_model
        if prewarm:
            ekw["prewarm"] = True
        trace = (self.tracer.decode_sample
                 if self.tracer is not None else 0)
        if self.backend == "process":
            from avenir_tpu.serve.proc import ProcReplica

            return ProcReplica(self._spec, i, registry=self._reg,
                               sink=self.sink, clock=self._clock,
                               defer_handshake=defer_handshake,
                               engine_kwargs=ekw, trace=trace,
                               **self._rep_cfg, **pk)
        draft = None if role == "prefill" else self._draft_model
        return Replica(self._model, i, registry=self._reg,
                       sink=self.sink, clock=self._clock,
                       engine_kwargs=ekw, trace=trace,
                       draft_model=draft, **self._rep_cfg)

    # ---- fleet elasticity (the autoscaler's actuators, ISSUE 12) ----

    @property
    def fleet_size(self):
        """Serving replicas: non-dead and not retiring (a draining
        retiree still finishes its in-flight work — and still bills
        replica-seconds — but takes no new dispatches)."""
        return sum(r.state != DEAD and r.replica_id not in self._retiring
                   for r in self.replicas)

    def fleet_size_by_class(self):
        """Serving replicas per disagg class — the per-class autoscaler
        surface (ISSUE 13 satellite). Homogeneous fleets report
        everything under 'decode'."""
        out = {"prefill": 0, "decode": 0}
        for r in self.replicas:
            if r.state == DEAD or r.replica_id in self._retiring:
                continue
            cls = ("prefill"
                   if self._role.get(r.replica_id) == "prefill"
                   else "decode")
            out[cls] += 1
        return out

    def add_replica(self, *, prewarm=False, role="both"):
        """Grow the fleet by one replica (blocking: a process-backend
        spawn pays its jax import, handshake, and — with `prewarm` —
        its compile pre-warm before returning). Returns the replica.
        `role='prefill'` grows the prefill class (disagg fleets)."""
        return self.finish_add_replica(
            self.begin_add_replica(prewarm=prewarm, role=role))

    def begin_add_replica(self, *, prewarm=False, role="both"):
        """Start building the next replica on a BACKGROUND thread and
        return a handle: the fleet keeps serving while the newcomer
        pays its spawn + compile pre-warm (seconds), and
        `finish_add_replica(handle)` joins it in once
        `handle.ready()`. Construction touches no router state beyond
        reserving the replica id, so the serving loop and the build
        never race — the newcomer only becomes visible at finish."""
        import threading

        i = self._next_replica_id
        self._next_replica_id += 1
        h = _SpawnHandle(i)
        # record the role NOW (main thread): dispatch/placement must
        # never observe a joined replica with an unknown class
        self._role[i] = role

        def build():
            try:
                h.result = self._make_replica(i, role=role,
                                              prewarm=prewarm)
            except BaseException as e:  # noqa: BLE001 — surfaced at join
                h.error = e

        h.thread = threading.Thread(
            target=build, daemon=True,
            name=f"replica-{i}-spawn")
        h.thread.start()
        return h

    def finish_add_replica(self, handle):
        """Join a begin_add_replica build into the fleet (blocks until
        the build finishes; call after `handle.ready()` to not block).
        Raises whatever the build raised — the replica id is burned
        but no fleet state changed."""
        handle.thread.join()
        if handle.error is not None:
            # a failed spawn must not leave a phantom class entry —
            # a stale 'prefill' value would keep disagg routing (and
            # the autoscaler's _disagg()) alive on a fleet that has no
            # prefill replica
            self._role.pop(handle.replica_id, None)
            raise handle.error
        rep = handle.result
        self.replicas.append(rep)
        self._by_replica[rep.replica_id] = {}
        if self._supervisor is not None:
            self._supervisor.attach(
                [r for r in self.replicas
                 if r.replica_id not in self._retiring])
        return rep

    def retire_replica(self, i):
        """Begin retiring a replica: it drains (no new admissions,
        in-flight work finishes) and is removed — process workers shut
        down — by the first step() that finds it idle. A retiree that
        dies instead fails its work over like any death and is removed
        without waiting."""
        rep = self._rep(i)
        self._retiring.add(rep.replica_id)
        rep.drain()
        if self._supervisor is not None:
            # the supervisor must not respawn a replica the control
            # plane decided to retire
            self._supervisor.attach(
                [r for r in self.replicas
                 if r.replica_id not in self._retiring])

    def _reap_retired(self):
        for rep in [r for r in self.replicas
                    if r.replica_id in self._retiring]:
            if rep.state == DEAD or not rep.busy:
                assert not self._by_replica[rep.replica_id], (
                    "retiring an idle replica left mapped work behind")
                self._retiring.discard(rep.replica_id)
                self._by_replica.pop(rep.replica_id)
                self._role.pop(rep.replica_id, None)
                if self._cache_map is not None:
                    self._cache_map.drop(rep.replica_id)
                self.replicas.remove(rep)
                if hasattr(rep, "close"):
                    rep.close()

    def _rep(self, i):
        for r in self.replicas:
            if r.replica_id == i:
                return r
        raise KeyError(f"no replica with id {i}")

    # ---- API ----

    def submit(self, prompt, *, max_new_tokens, temperature=1.0,
               top_k=None, stop_tokens=(), rng=None, deadline_ms=None,
               priority="interactive"):
        """Enqueue (or refuse) a request; returns its router id. `rng`
        defaults to fold_in(router seed, id) — routing decisions never
        touch it, so a request's reference stream is fixed at submit.
        Refusals ('rejected' for an impossible shape, 'shed' for
        admission control) surface as finished records from the next
        `step()` — the caller sees one terminal record per submit either
        way."""
        assert priority in PRIORITIES, f"unknown priority {priority!r}"
        prompt = tuple(int(t) for t in prompt)
        assert prompt, "empty prompt"
        assert max_new_tokens >= 1
        assert deadline_ms is None or deadline_ms > 0
        rid = self._next_id
        self._next_id += 1
        if rng is None:
            rng = jax.random.fold_in(self._base_rng, rid)
        now = self._clock()
        if self.tracer is not None:
            self.tracer.emit(rid, "submit", t=now, n_prompt=len(prompt),
                             max_new=int(max_new_tokens),
                             priority=priority, deadline_ms=deadline_ms)
        if len(prompt) + int(max_new_tokens) > self.max_total_tokens:
            self._reg.counter("serve_rejected").add(1)
            self._refuse(rid, prompt, priority, "rejected",
                         reject_limit=self._limit_name)
            return rid
        q = self._queues[priority]
        if len(q) >= self.queue_limits[priority]:
            self._reg.counter("serve_shed").add(1)
            self._refuse(rid, prompt, priority, "shed")
            return rid
        if (deadline_ms is not None
                and self.projected_wait_ms(priority) >= deadline_ms):
            self._reg.counter("serve_shed").add(1)
            self._refuse(rid, prompt, priority, "shed")
            return rid
        req = RoutedRequest(
            rid=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), top_k=top_k,
            stop_tokens=tuple(stop_tokens or ()), rng=rng,
            priority=priority,
            deadline_ms=None if deadline_ms is None else float(deadline_ms),
            submit_t=now,
            depth_at_submit=(len(q) if self.wait_predictor is not None
                             else None),
        )
        q.append(req)
        self._open[rid] = req
        if self.tracer is not None:
            self.tracer.emit(rid, "admit", t=now,
                             queue_depth=len(q))
        self._reg.gauge("router_queue_depth").set(self.queue_depth)
        return rid

    def step(self):
        """One fleet iteration: health-check + failover, expire hopeless
        queued work, fair-share dispatch, step every replica, harvest.
        Returns every request that reached a terminal state."""
        finished = self._pending
        self._pending = []
        now = self._clock()
        for rep in self.replicas:
            if rep.state != DEAD and rep.check_health(now) == DEAD:
                self._failover(rep)
        if self._supervisor is not None:
            # respawn BEFORE dispatch so a freshly revived worker can
            # take work this very step (it rejoins empty; its former
            # assignments were requeued at death) — and credit every
            # live replica the blocking time: a respawn's spawn +
            # handshake takes seconds, during which no peer can beat,
            # and a small stall floor would otherwise false-kill
            # healthy replicas right after every supervised restart
            t_sup = self._clock()
            self._supervisor.poll(now)
            dt_sup = self._clock() - t_sup
            if dt_sup > 0:
                for rep in self.replicas:
                    if rep.state != DEAD:
                        rep.last_beat += dt_sup
        if self._rollout is not None and self._rollout.active:
            # drive the weight-lifecycle state machine (ISSUE 20). A
            # swap's reload/handshake blocks the fleet loop exactly
            # like a supervisor respawn — credit the blocking time to
            # every live replica for the same reason as above
            t_ro = self._clock()
            self._rollout.poll(now)
            dt_ro = self._clock() - t_ro
            if dt_ro > 0:
                for rep in self.replicas:
                    if rep.state != DEAD:
                        rep.last_beat += dt_ro
        self._expire_queued(now, finished)
        self._dispatch_all(now)
        ae = self._anomaly  # the single-branch disabled guard (ISSUE 14)
        for rep in self.replicas:
            was_dead = rep.state == DEAD
            was_busy = ae is not None and rep.busy
            t_before = self._clock()
            # median BEFORE the step: a fresh worker's first (compiling)
            # step otherwise becomes its own median, zeroing the slack
            # exactly when the credit matters most
            med_before = rep.median_step_secs()
            fins = rep.step()
            if self.tracer is not None:
                # absorb BEFORE harvesting: _harvest pops the engine-rid
                # -> fleet-rid map, and finished requests' engine events
                # (their finish, this step's first tokens) still need it
                evs, dropped = rep.take_trace()
                if evs or dropped:
                    self.tracer.absorb(
                        evs, rid_map=self._by_replica[rep.replica_id],
                        replica=rep.replica_id, dropped=dropped)
            if self._is_prefill(rep) and rep.state != DEAD:
                # stream finished pages to the decode class NOW, while
                # the prefill replica's remaining chunks still compute
                # — the overlap that hides handoff latency (ISSUE 13)
                self._pump_exports(rep)
            for f in fins:
                if f.finish_reason == "prefilled":
                    self._handoff(rep, f, finished)
                else:
                    finished.append(self._harvest(rep, f))
            dt = self._clock() - t_before
            # credit every OTHER live replica the ANOMALOUS part of the
            # time this step consumed: the fleet loop is single-threaded,
            # so while one replica compiles (or a process worker's RPC
            # runs out its hang-detection timeout) no peer gets a chance
            # to beat — reading the router's own blocking as peer silence
            # false-kills healthy replicas (the process chaos drill
            # caught exactly this). Only the excess over the stepping
            # replica's own median is credited: crediting ordinary step
            # time too would let a genuinely stalled peer age only at
            # loop-overhead speed, making detection latency unbounded
            slack = dt - max(med_before, 1e-3)
            if slack > 0:
                for other in self.replicas:
                    if other is not rep and other.state != DEAD:
                        other.last_beat += slack
            if was_busy and rep.state != DEAD:
                # replica step walls feed the step-time drift detector
                # (only BUSY steps, the same rule _record_beat applies
                # to the stall-threshold median)
                ae.observe("step_time_ms", dt * 1e3, t=self._clock())
            if rep.state == DEAD and not was_dead:
                # died inside this step (serve_step_fail): nothing it
                # held finished — requeue all of it right away
                self._failover(rep)
        finished.extend(self._pending)
        self._pending = []
        # reap retirees that finished draining (ISSUE 12): their slots
        # left the capacity pool at retire time (dispatchable_slots is
        # 0 while draining); removal frees the process/engine itself
        self._reap_retired()
        self._reg.gauge("router_queue_depth").set(self.queue_depth)
        self._reg.gauge("replica_healthy").set(self.n_healthy)
        # the engines share ONE registry, so their per-step gauge writes
        # are last-replica-wins; re-set them to the FLEET view here so
        # the values a log reader sees are aggregates, not whichever
        # replica happened to step last
        self._reg.gauge("queue_depth").set(
            sum(r.engine.sched.queue_depth for r in self.replicas))
        total = sum(r.n_slots for r in self.replicas)
        # a scaled-to-zero fleet has no slots to occupy — write 0.0
        # rather than skipping, or the gauge freezes at its last
        # pre-retirement value for as long as the fleet sleeps
        self._reg.gauge("slot_occupancy").set(
            sum(len(r.engine._live) for r in self.replicas) / total
            if total else 0.0)
        alive = [r for r in self.replicas if r.state != DEAD]
        if alive:
            # oldest heartbeat across the live fleet: a rising value is
            # a stall FORMING — visible before the threshold declares it
            self._reg.gauge("heartbeat_age_s").set(
                max(self._clock() - r.last_beat for r in alive))
            # the weight_version gauge only moves when the fleet has
            # CONVERGED on one version (ISSUE 20) — mid-rollout it
            # holds the previous converged value, so a plot of this
            # gauge shows exactly when each campaign landed
            vers = {getattr(r, "weight_version", "0") for r in alive}
            if len(vers) == 1:
                from avenir_tpu.serve.rollout import version_number

                self._reg.gauge("weight_version").set(
                    version_number(vers.pop()))
        # paged-KV gauges get the same fleet-aggregate treatment as
        # queue_depth above (N engines, one registry): pages_free sums,
        # util/prefix-hit average over the replicas reporting them.
        # Inproc replicas read their engine directly; process replicas
        # read the heartbeat mirror (proxy.kv)
        kvs = []
        for r in self.replicas:
            paged = getattr(r.engine, "_paged", None)
            if paged is not None:
                a = paged.alloc.stats()
                kvs.append((a["free"] + a["cached"], a["util"],
                            paged.prefix_hit_rate(),
                            paged.prompt_tokens))
            elif getattr(r.engine, "kv", None):
                kv = r.engine.kv
                kvs.append((kv.get("pages_free", 0),
                            kv.get("page_util", 0.0),
                            kv.get("prefix_hit_rate", 0.0),
                            kv.get("prefix_attempts", 0)))
        if kvs:
            self._reg.gauge("kv_pages_free").set(sum(k[0] for k in kvs))
            self._reg.gauge("kv_page_util").set(
                sum(k[1] for k in kvs) / len(kvs))
            # attempt-weighted, not a plain mean of per-replica rates: a
            # replica that admitted 3 prompts must not drag down (or
            # prop up) the fleet rate as hard as one that admitted 300.
            # Weights are prompt-token attach attempts — inproc read
            # directly, process shipped in the heartbeat kv dict
            # (`prefix_attempts`); a fleet with no attempts yet falls
            # back to the unweighted mean (all rates are 0.0 anyway)
            w = sum(k[3] for k in kvs)
            self._reg.gauge("prefix_hit_rate").set(
                sum(k[2] * k[3] for k in kvs) / w if w
                else sum(k[2] for k in kvs) / len(kvs))
        cm = self._cache_map
        if cm is not None:
            # refresh the content view AFTER replicas stepped, so this
            # step's admissions are visible to next step's audits.
            # Inproc engines are read directly; process replicas expose
            # the heartbeat-delta-merged mirror (proxy.chains, None
            # until the worker's first summary ships)
            t_cm = self._clock()
            for r in self.replicas:
                if r.state == DEAD:
                    continue
                eng = r.engine
                # version-keyed (ISSUE 20): the summary is stamped with
                # the version the replica serves NOW, so a swap re-keys
                # its advertisement the first post-swap refresh and the
                # old version's entries can never match again
                if getattr(eng, "_paged", None) is not None:
                    cm.update(r.replica_id, eng.chain_summary(), now=t_cm,
                              version=r.weight_version)
                elif getattr(eng, "chains", None) is not None:
                    cm.update(r.replica_id, eng.chains, now=t_cm,
                              version=r.weight_version)
        if ae is not None:
            self._feed_anomaly(ae, finished)
        if self._rollout is not None and self._rollout.active:
            # canary analysis feed (ISSUE 20): phase-filtered terminal
            # records into the campaign's private detector store — the
            # fleet fed during BASELINE is the drift baseline the
            # canary's own records are later compared against
            self._rollout.observe(finished, now=self._clock())
        return finished

    def _feed_anomaly(self, ae, finished):
        """One fleet-step feed of the health engine (ISSUE 14): latency
        series from this step's terminal records, the liveness signals
        (heartbeat age, oldest-queued wait), the decode-quality and IO
        signals, then the paced detector check. Caller holds the
        `ae is not None` guard — a fleet without the engine never
        reaches here."""
        now = self._clock()
        ae.observe_finished(finished, t=now)
        alive = [r for r in self.replicas if r.state != DEAD]
        if alive:
            ae.observe("heartbeat_age_s",
                       max(now - r.last_beat for r in alive), t=now)
        oldest = None
        for q in self._queues.values():
            for req in q:
                if oldest is None or req.submit_t < oldest:
                    oldest = req.submit_t
        ae.observe("queue_wait_ms",
                   0.0 if oldest is None else (now - oldest) * 1e3,
                   t=now)
        rate = self._reg.gauge("spec_accept_rate").value
        if rate is not None:
            ae.observe("spec_accept_rate", rate, t=now)
        ae.observe_counter_rate("io_retries", t=now)
        ae.check(now)

    def drain(self, max_steps=None):
        """Step until every accepted request reached a terminal state.
        Raises if no non-dead replica remains while work is still open
        (a fleet with nothing to run it on cannot drain — revive one).
        Under a supervisor (process backend), an all-dead fleet with
        respawn budget left WAITS OUT the backoff window instead — the
        work is queued, a worker is coming back, and failing loud here
        would turn one survivable crash into a dropped drain; only a
        supervisor that has exhausted its retries makes all-dead final
        (ISSUE 8 satellite)."""
        bound = max_steps or (
            20 + len(self._pending) + 2 * len(self._open)
            + 4 * sum(r.max_new_tokens for r in self._open.values())
            # paged engines prefill in chunks: a long prompt takes up to
            # ceil(len/chunk) extra ticks (chunk >= 1), and page-budget
            # admission can hold the queue head while earlier requests
            # drain — prompt length is the safe per-request overbound
            + sum(len(r.prompt) for r in self._open.values()))
        out = []
        steps = 0
        waits = 0
        while self._pending or self._open:
            if (self._open and not self._pending
                    and all(r.state == DEAD for r in self.replicas)):
                if (self._supervisor is not None
                        and self._supervisor.pending()
                        and waits < 20_000):
                    # bounded wait: the supervisor's next attempt is on
                    # a real-time backoff clock — don't burn the step
                    # bound spinning, and don't spin hot either
                    waits += 1
                    time.sleep(0.01)
                    out.extend(self.step())
                    continue
                causes = "; ".join(
                    f"replica {r.replica_id}: {r.last_error!r}"
                    for r in self.replicas if r.last_error is not None)
                if self.tracer is not None:
                    self.tracer.flight_dump("drain-all-dead")
                raise RuntimeError(
                    ("fleet scaled to zero with open requests — drive "
                     "the loop through Autoscaler.run_step/drain so "
                     "the burst wake can fire"
                     if not self.replicas else
                     "all replicas dead with open requests — revive one")
                    + (" (supervisor exhausted its respawn budget)"
                       if self._supervisor is not None else "")
                    + (f" (causes of death: {causes})" if causes else ""))
            out.extend(self.step())
            steps += 1
            if steps > bound:
                if self.tracer is not None:
                    self.tracer.flight_dump("drain-stuck")
                raise RuntimeError(
                    f"router failed to drain within {bound} iterations")
        return out

    def close(self):
        """Shut down process-backend workers (no-op for inproc)."""
        for r in self.replicas:
            if hasattr(r, "close"):
                r.close()

    # -- fleet controls (chaos harness / operator surface) --

    def kill_replica(self, i):
        """Abrupt replica death (the chaos drill's kill): mark dead and
        fail its work over immediately. `i` is the replica_id — under
        an elastic fleet (add/retire) list positions drift, ids don't."""
        rep = self._rep(i)
        if rep.state != DEAD:
            rep.mark_dead()
            self._failover(rep)

    def drain_replica(self, i):
        self._rep(i).drain()

    def revive_replica(self, i):
        # a dead replica's assignments were already requeued by
        # _failover, so there is nothing to clear here; reviving a
        # draining replica must keep its live assignment map intact
        self._rep(i).revive()

    # -- live weight lifecycle (serve/rollout.py, ISSUE 20) --

    def rollout(self, version, *, state=None, out_dir=None, **kw):
        """Start a rolling weight swap to `version` (canary first, then
        replica by replica; anomaly-triggered auto-rollback). Returns
        the armed RolloutManager — Router.step drives it; poll
        `rollout_active` / the manager's `.status()` for progress.

        `version` names a checkpoint generation when `out_dir` is given
        (resolved via checkpoint/io.list_generations; 'latest' picks
        the newest); for the in-process backend (or tests) pass `state`
        — the target nnx parameter state — directly. Extra kwargs reach
        RolloutConfig (canary_min_requests, max_mixing_s, ...)."""
        from avenir_tpu.serve.rollout import RolloutManager

        if self._rollout is not None and self._rollout.active:
            raise RuntimeError(
                "a rollout is already active — one campaign at a time "
                "(roll it back or let it land first)")
        self._rollout = RolloutManager(
            self, version, state=state, out_dir=out_dir, **kw)
        self._rollout.begin()
        return self._rollout

    @property
    def rollout_active(self):
        """True while a rollout (or its rollback) is converging the
        fleet — the autoscaler suppresses scale-down/idle-to-zero for
        the duration (a mid-campaign retire would thrash the version
        accounting and the mixing-window bound)."""
        return self._rollout is not None and self._rollout.active

    # -- observable surface --

    @property
    def queue_depth(self):
        return sum(len(q) for q in self._queues.values())

    @property
    def n_healthy(self):
        return sum(r.state == HEALTHY for r in self.replicas)

    @property
    def open_requests(self):
        """Accepted and not yet terminal (queued or in flight)."""
        return len(self._open)

    def projected_wait_ms(self, priority):
        """Admission-time queue-wait estimate for a new request of this
        class: its queue drains at the fleet's healthy slot capacity
        times the class's fair share, one median slot-hold per round.
        Deliberately coarse — it exists to refuse work that would miss
        its deadline ANYWAY, so erring generous (0 until the first
        completion lands) only delays shedding, never loses work.
        With no healthy replica the wait is infinite and every
        deadline-carrying submit sheds.

        Predictive upgrade (ISSUE 12): when tracing is armed, a
        per-class WaitPredictor fit on the traced submit -> dispatch
        history answers instead — measured drain behavior under the
        CURRENT load shape, not a static median — and this rule is the
        fallback until it is fit (or whenever tracing is off)."""
        cap = sum(r.n_slots for r in self.replicas
                  if r.state == HEALTHY
                  and r.replica_id not in self._retiring)
        if cap == 0:
            return float("inf")
        if self.wait_predictor is not None:
            p = self.wait_predictor[priority].predict_ms(
                len(self._queues[priority]))
            if p is not None:
                return p
        hold = statistics.median_low(self._holds) if self._holds else 0.0
        contending = [c for c in PRIORITIES
                      if self._queues[c] or c == priority]
        share = self.weights[priority] / sum(self.weights[c]
                                             for c in contending)
        return len(self._queues[priority]) / (cap * share) * hold * 1e3

    def fleet_tick_secs(self):
        """Median decode-tick estimate across healthy replicas — the
        router-queue analogue of the engine's dispatch-time expiry
        lookahead."""
        ticks = [r.engine.tick_estimate_s() for r in self.replicas
                 if r.state == HEALTHY]
        return statistics.median_low(ticks) if ticks else 0.0

    # ---- internals ----

    def _refuse(self, rid, prompt, priority, reason, reject_limit=None):
        """Terminal-at-the-door record ('rejected'/'shed'): no queue
        entry, no slot, delivered from the next step(). A rejection
        names which limit fired (`reject_limit`, ISSUE 9)."""
        self._pending.append(RouterFinished(
            req_id=rid, tokens=list(prompt), n_prompt=len(prompt),
            n_out=0, finish_reason=reason,
            text="" if self.detokenize is not None else None,
            ttft_ms=None, tpot_ms=0.0, reject_limit=reject_limit,
            priority=priority,
        ))
        record = {
            "kind": "request", "t": time.time(), "id": rid,
            "n_prompt": len(prompt), "n_out": 0, "finish_reason": reason,
            "priority": priority,
        }
        if reject_limit is not None:
            record["reject_limit"] = reject_limit
        self.sink.write(record)
        if self.tracer is not None:
            kw = {} if reject_limit is None \
                else {"reject_limit": reject_limit}
            self.tracer.emit(rid, "finish", reason=reason, n_out=0, **kw)

    def _expire_queued(self, now, out):
        """Router-queue deadline sweep with one fleet tick of lookahead:
        a request that could not emit even one token if dispatched right
        now finishes 'timeout' instead of ever taking a slot."""
        horizon = now + self.fleet_tick_secs()
        for c in PRIORITIES:
            q = self._queues[c]
            if not any(r.expired(horizon) for r in q):
                continue
            keep = deque()
            for req in q:
                if req.expired(horizon):
                    out.append(self._finish_router_timeout(req))
                else:
                    keep.append(req)
            self._queues[c] = keep

    def _pick_class(self):
        """Smoothed weighted round-robin over non-empty classes: each
        pick credits every contender its weight, serves the largest
        credit, then debits the total — weights 4:1 interleave
        I I I I B ... exactly. An empty class's credit resets, so batch
        absorbs idle capacity without banking a starvation-sized burst
        for later."""
        live = [c for c in PRIORITIES if self._queues[c]]
        if not live:
            return None
        for c in PRIORITIES:
            if c not in live:
                self._wrr[c] = 0.0
        for c in live:
            self._wrr[c] += self.weights[c]
        pick = max(live, key=lambda c: (self._wrr[c], -PRIORITIES.index(c)))
        self._wrr[pick] -= sum(self.weights[c] for c in live)
        return pick

    def _is_prefill(self, rep):
        return self._role.get(rep.replica_id) == "prefill"

    def _healthy_class(self, prefill):
        return [r for r in self.replicas
                if r.state == HEALTHY
                and r.replica_id not in self._retiring
                and self._is_prefill(r) == prefill]

    def _pick_replica(self, req, now, match=None):
        """SLO-aware placement: free-slot fraction, minus any engine
        queue backlog, minus — for deadline-carrying requests — the
        replica's step time scaled by the inverse of the remaining
        slack (a tight deadline prefers the fastest replica; an
        unhurried one just fills the emptiest). Deterministic tiebreak
        on replica id.

        Affinity (ISSUE 17): when `match` (the staleness-filtered
        cache-map view, {replica_id: shared tokens}) is passed, each
        candidate gains `weight * shared/prompt` capped by its OWN
        free-slot fraction (serve/affinity.py) — cache gravity decays
        exactly as fast as capacity does, so a hot prefix spills to
        the next replica instead of hotspotting one. Every candidate is
        also scored against the prompt's consistent-hash home
        (`shard_weight` nudge): cold prefix families shard across the
        fleet's aggregate cache instead of herding onto the tie-break
        winner. The disagg class filter still dominates: affinity
        only reorders within the eligible class.

        Disagg (ISSUE 13): prompt length routes the CLASS — a long
        prompt (>= disagg_min_prompt, i.e. more than one chunk of
        prefill) goes to the prefill class when one is healthy AND a
        decode replica exists to hand off to; everything else (short
        prompts, a degraded prefill class) dispatches to the decode
        class, whose replicas serve the full lifecycle. Queue depth
        then picks WITHIN the class via the dispatchable-fraction
        score, same as ever."""
        cands = [r for r in self.replicas if r.dispatchable_slots > 0]
        if self.n_prefill or any(v == "prefill"
                                 for v in self._role.values()):
            long = len(req.prompt) >= self.disagg_min_prompt
            use_prefill = (long and self._healthy_class(True)
                           and self._healthy_class(False))
            cands = [r for r in cands
                     if self._is_prefill(r) == bool(use_prefill)]
        if not cands:
            return None
        slack_s = None
        if req.deadline_ms is not None:
            slack_s = max(req.deadline_ms / 1e3 - (now - req.submit_t),
                          1e-3)
        home = None
        if self._affinity is not None:
            home = shard_home(
                self._affinity, req.prompt,
                int(self._engine_kwargs.get("page_size", 16)),
                [r.replica_id for r in cands])

        def score(r):
            # dispatchable fraction already nets out the engine-queue
            # backlog (replica.dispatchable_slots), so occupancy and
            # queue depth are both in this one term
            s = r.dispatchable_slots / r.n_slots
            if match:
                s += affinity_bonus(
                    self._affinity, match.get(r.replica_id, 0),
                    len(req.prompt), r.dispatchable_slots / r.n_slots)
            if r.replica_id == home:
                s += self._affinity.shard_weight
            if slack_s is not None:
                s -= r.median_step_secs() / slack_s
            return (s, -r.replica_id)

        return max(cands, key=score)

    def _dispatch_all(self, now):
        while any(r.dispatchable_slots > 0 for r in self.replicas):
            c = self._pick_class()
            if c is None:
                return
            req = self._queues[c].popleft()
            m = (self._affinity_match(req)
                 if self._affinity is not None else None)
            rep = self._pick_replica(req, now, match=m)
            if rep is None:
                # free slots exist only on the wrong disagg class this
                # tick (e.g. decode slots open while the head wants the
                # prefill class) — FCFS holds: put the head back and
                # stop, same documented policy as the engine's
                # too-long-head admission block
                self._queues[c].appendleft(req)
                return
            if m is not None:
                req.pulled_tokens = 0  # per-DECISION: a failover's new
                #                        replica holds no pulled pages
                if m.get(rep.replica_id, 0) > 0:
                    self._reg.counter("affinity_hits").add(1)
                if not self._maybe_pull(req, rep, m):
                    # the CHOSEN replica died under the pull import: the
                    # request never landed — same recovery as a death
                    # under submit (front of queue, fail the corpse
                    # over, re-pick next pass)
                    self._queues[req.priority].appendleft(req)
                    self._failover(rep)
                    continue
            try:
                eng_rid = rep.engine.submit(
                    req.prompt, max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    stop_tokens=req.stop_tokens, rng=req.rng,
                    deadline_ms=req.deadline_ms, submit_t=req.submit_t,
                )
            except ReplicaGone:
                # the worker died UNDER the dispatch (process backend):
                # the request never landed — front of its class queue,
                # next loop pass picks a different (live) replica; the
                # corpse's earlier (older) in-flight work requeues ahead
                self._queues[req.priority].appendleft(req)
                self._failover(rep)
                continue
            req.dispatch_t = self._clock()
            if (self.wait_predictor is not None and req.failovers == 0
                    and req.depth_at_submit is not None):
                # the predictor learns from FIRST dispatches only: a
                # failover requeue's wait measures replica death, not
                # queue behavior (these are the same submit->dispatch
                # deltas the trace events below stamp)
                self.wait_predictor[req.priority].observe(
                    req.depth_at_submit, req.dispatch_t - req.submit_t)
            self._where[req.rid] = rep.replica_id
            self._by_replica[rep.replica_id][eng_rid] = req.rid
            if self.tracer is not None:
                self.tracer.emit(req.rid, "dispatch", t=req.dispatch_t,
                                 replica=rep.replica_id,
                                 eng_rid=eng_rid,
                                 failovers=req.failovers)
            if self._cache_map is not None:
                self._audit_dispatch(req, rep)

    def _audit_dispatch(self, req, rep):
        """Counterfactual reuse audit (ISSUE 16): compare the CHOSEN
        replica's shared-prefix depth for this prompt against the
        fleet-best placement's, per the cache map's content view. The
        prompt's tokens are partitioned EXACTLY into three counters —
        reused (the chosen replica already holds them), missed (some
        OTHER replica holds them: the fleet is about to recompute a
        prefix it has), cold (no tracked replica holds them) — and a
        `missed_reuse` trace event fires when a better placement
        existed. Audits the dispatch DECISION: a failover or disagg
        handoff re-dispatch is a new decision and is re-audited, so
        the partition identity is per-dispatch, not per-admit.

        With the KV CDN armed (ISSUE 17) a successful peer pull counts
        its shipped tokens as REUSED — they were transferred, not
        recomputed, and `missed` must keep meaning "the fleet is about
        to redo work it already has". The residual missed fraction is
        exactly what affinity routing could not reclaim."""
        cm = self._cache_map
        m = cm.match(req.prompt, versions=self._fleet_versions())
        n = len(req.prompt)
        reused = min(max(m.get(rep.replica_id, 0), req.pulled_tokens), n)
        best_rid, best = rep.replica_id, reused
        for rid in sorted(m, key=str):
            if m[rid] > best:
                best_rid, best = rid, m[rid]
        missed = best - reused
        cold = n - best
        self._reg.counter("prefix_tokens_reused").add(reused)
        self._reg.counter("prefix_tokens_missed").add(missed)
        self._reg.counter("prefix_tokens_cold").add(cold)
        if missed > 0 and self.tracer is not None:
            # est saved ms: fleet-observed per-token prefill cost x the
            # tokens about to be recomputed — serve_prefill_ms over the
            # tokens prefill actually computed so far (missed + cold)
            computed = (self._reg.counter("prefix_tokens_missed").total
                        + self._reg.counter("prefix_tokens_cold").total)
            cost = (self._reg.counter("serve_prefill_ms").total / computed
                    if computed else 0.0)
            self.tracer.emit(
                req.rid, "missed_reuse", t=req.dispatch_t,
                replica=rep.replica_id, best_replica=best_rid,
                reused=reused, missed=missed, cold=cold,
                est_ms_saved=round(missed * cost, 3))

    # ---- fleet KV CDN: affinity placement + peer pull (ISSUE 17) ----

    def _fleet_versions(self):
        """{replica_id: weight_version} across non-dead replicas — the
        live view the cache map filters matches against (ISSUE 20): an
        advertisement recorded under a version its replica no longer
        serves scores zero, so a post-swap replica's old chains can
        never win placement, source a pull, or count as fleet reuse."""
        return {r.replica_id: getattr(r, "weight_version", "0")
                for r in self.replicas if r.state != DEAD}

    def _affinity_match(self, req):
        """The staleness-filtered cache-map view for placement:
        {replica_id: deepest shared-chain tokens}, dropping zero
        matches and replicas whose advertised summary is older than
        the policy's `staleness_s` (a stale advert routes traffic at a
        cache that may be long evicted — better to fall back to pure
        load placement than to chase ghosts)."""
        pol, cm = self._affinity, self._cache_map
        now = self._clock()
        out = {}
        m = cm.match(req.prompt, versions=self._fleet_versions())
        for rid, n in m.items():
            if n <= 0:
                continue
            st = cm.staleness_s(rid, now=now)
            if (pol.staleness_s is not None and st is not None
                    and st > pol.staleness_s):
                continue
            out[rid] = n
        return out

    def _maybe_pull(self, req, rep, match):
        """Peer prefix pull, the KV CDN miss path: when a peer
        advertises a chain materially deeper than the chosen replica's
        (`pull_plan` threshold), broker it — the peer exports the
        shared chain's surviving pages (one PT_KVPAGES frame), the
        chosen replica splices them via `import_chain`, and the
        upcoming submit's plan() attaches them so prefill starts at
        the first unshared token.

        Returns False ONLY when the CHOSEN replica died under the
        import (the caller requeues + fails it over, exactly the
        death-under-submit path). Every other failure — source died
        mid-transfer, source evicted the chain, frame CRC trip, RPC
        timeout — counts a `prefix_pull_fallbacks`, emits the
        `prefix_pull` trace outcome, and returns True: the request
        proceeds to local re-prefill from prompt+rng, bit-exact. Pulls
        are an optimization, never a correctness dependency."""
        ps = int(self._engine_kwargs.get("page_size", 16))
        plan = pull_plan(self._affinity, match, rep.replica_id, ps)
        if plan is None:
            return True
        src_rid, best, local = plan
        fallbacks = self._reg.counter("prefix_pull_fallbacks")

        def trace(outcome, pages=0):
            if self.tracer is not None:
                self.tracer.emit(
                    req.rid, "prefix_pull", t=self._clock(),
                    src=src_rid, dst=rep.replica_id, pages=pages,
                    depth=best, outcome=outcome)

        src = next((r for r in self.replicas
                    if r.replica_id == src_rid and r.state == HEALTHY),
                   None)
        if src is None:
            # advertised-then-retired/died between map refresh and now
            fallbacks.add(1)
            trace("src_gone")
            return True
        if (getattr(src, "weight_version", "0")
                != getattr(rep, "weight_version", "0")):
            # a weight swap landed between map refresh and this pull
            # (ISSUE 20): KV produced under one version must never
            # splice into an engine serving another — that is silent
            # wrongness, not a perf loss. Local re-prefill instead
            fallbacks.add(1)
            trace("version_mismatch")
            return True
        token_pages = [req.prompt[i * ps:(i + 1) * ps]
                       for i in range(best // ps)]
        try:
            rec = src.export_chain(token_pages, n_prefix=local // ps)
        except ReplicaGone:
            # source died mid-transfer: fail IT over; the request's
            # own placement is intact — local prefill covers it
            fallbacks.add(1)
            trace("src_dead")
            self._failover(src)
            return True
        if rec is None:
            # the chain was evicted since the map advertised it — the
            # allocator walk found nothing past the receiver's prefix
            fallbacks.add(1)
            trace("src_evicted")
            return True
        try:
            written, nbytes = rep.import_pages([rec])
        except ReplicaGone:
            fallbacks.add(1)
            trace("dst_dead")
            return False
        self._reg.counter("prefix_pull_pages").add(written)
        self._reg.counter("prefix_pull_bytes").add(nbytes)
        req.pulled_tokens = (local // ps + written) * ps
        trace("ok", pages=written)
        return True

    # ---- disaggregated page transfer + handoff (ISSUE 13) ----

    def _pick_decode_target(self, version=None):
        """Least-loaded healthy decode replica — the handoff target.
        Dispatchable fraction first (it nets out the engine backlog),
        then live count, then id (deterministic). `version` (ISSUE 20)
        restricts candidates to replicas serving that weight version:
        prefilled pages splice only into the weights that made them,
        so mid-rollout a cross-version handoff waits (bounded by the
        mixing window) instead of decoding wrong."""
        cands = self._healthy_class(False)
        if version is not None:
            cands = [r for r in cands
                     if getattr(r, "weight_version", "0") == version]
        if not cands:
            return None
        return max(cands, key=lambda r: (
            r.dispatchable_slots / max(r.n_slots, 1),
            -len(r.engine._live), -r.replica_id))

    def _pump_exports(self, rep):
        """Drain a prefill replica's finished-page exports and stream
        each to the request's pinned decode target (pinned at first
        export so the whole chain accumulates on one replica). Records
        are RETAINED until handoff completes: if the pinned target dies
        mid-transfer, the next export (or the handoff itself) re-pins
        and re-ships the full accumulation — the pages are host-side
        numpy, so a dead importer costs a re-send, never a recompute."""
        for rec in rep.take_page_exports():
            rid = self._by_replica[rep.replica_id].get(rec["eng_rid"])
            if rid is None or rid not in self._open:
                continue  # already failed over/expired: transfer moot
            tr = self._transfer.setdefault(
                rid, {"recs": [], "target": None, "shipped": 0,
                      "bytes": 0, "src": rep.replica_id,
                      "ver": rep.weight_version})
            tr["src"] = rep.replica_id
            tr["ver"] = rep.weight_version
            tr["recs"].append(rec)
            self._ship(rid, tr)

    def _ship(self, rid, tr):
        """Ship `tr`'s unshipped records to its (re)pinned target.
        Returns the target replica, or None when no healthy decode
        replica exists right now (the handoff will retry)."""
        tgt = None
        ver = tr.get("ver")
        if tr["target"] is not None:
            for r in self._healthy_class(False):
                # a pinned target that swapped versions under the
                # transfer is no longer importable (ISSUE 20) — fall
                # through to a same-version re-pick + full re-ship
                if r.replica_id == tr["target"] and (
                        ver is None
                        or getattr(r, "weight_version", "0") == ver):
                    tgt = r
                    break
        if tgt is None:
            tgt = self._pick_decode_target(version=ver)
            if tgt is None:
                tr["target"] = None
                tr["shipped"] = 0
                return None
            if tr["target"] is not None \
                    and tr["target"] != tgt.replica_id:
                tr["shipped"] = 0  # new importer: re-ship the chain
            tr["target"] = tgt.replica_id
        recs = tr["recs"][tr["shipped"]:]
        if not recs:
            return tgt
        try:
            written, nbytes = tgt.import_pages(recs)
        except ReplicaGone:
            self._failover(tgt)
            tr["target"] = None
            tr["shipped"] = 0
            return None
        tr["shipped"] = len(tr["recs"])
        tr["bytes"] += nbytes
        self._reg.counter("kv_transfer_bytes").add(nbytes)
        if self.tracer is not None:
            self.tracer.emit(
                rid, "kv_transfer", t=self._clock(),
                pages=sum(len(r["tokens"]) - r.get("n_prefix", 0)
                          for r in recs),
                written=written, bytes=nbytes, src=tr["src"],
                dst=tgt.replica_id)
        return tgt

    def _handoff(self, rep, f, finished):
        """A prefill-class replica finished a prompt: ship any last
        pages, then submit the request — original prompt, rng, submit_t
        and deadline — to the decode target, front-of-engine-queue (it
        served its fleet FCFS wait already). The decode admission
        prefix-attaches the imported chain and computes only the tail,
        so the output is bit-identical to a full local prefill. With no
        healthy decode replica the request requeues at the front of its
        class and retries the whole path later (correct, just slower —
        its next prefill prefix-hits the prefill replica's warm chain).
        """
        rid = self._by_replica[rep.replica_id].pop(f.req_id, None)
        if rid is None:
            return
        req = self._open.get(rid)
        if req is None:
            self._transfer.pop(rid, None)
            return
        self._where.pop(rid, None)
        now = self._clock()
        tr = self._transfer.pop(rid, {"recs": [], "target": None,
                                      "shipped": 0, "bytes": 0,
                                      "src": rep.replica_id,
                                      "ver": rep.weight_version})
        if req.expired(now):
            # the deadline died during prefill+transfer: account it,
            # free the accumulated pages, never burn a decode slot
            finished.append(self._finish_router_timeout(req))
            return
        if self.tracer is not None:
            # the handoff marker OPENS the `transfer` TTFT segment: the
            # non-overlapped remainder of the transfer (final ship +
            # handoff submit) runs between this stamp and the decode
            # dispatch stamp below — streamed pages already hid behind
            # prefill compute and cost the request nothing here
            self.tracer.emit(
                rid, "kv_transfer", t=now, handoff=True,
                pages=sum(len(r["tokens"]) - r.get("n_prefix", 0)
                          for r in tr["recs"]),
                bytes=tr["bytes"], src=rep.replica_id,
                dst=tr["target"])
        tgt = self._ship(rid, tr)
        if tgt is None:
            req.dispatch_t = None
            self._queues[req.priority].appendleft(req)
            if self.tracer is not None:
                self.tracer.emit(rid, "requeue", t=now,
                                 failovers=req.failovers,
                                 handoff_retry=True)
            return
        self._reg.counter("kv_transfers").add(1)
        try:
            eng_rid = tgt.engine.submit(
                req.prompt, max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, top_k=req.top_k,
                stop_tokens=req.stop_tokens, rng=req.rng,
                deadline_ms=req.deadline_ms, submit_t=req.submit_t,
                front=True,
            )
        except ReplicaGone:
            self._failover(tgt)
            req.dispatch_t = None
            self._queues[req.priority].appendleft(req)
            if self.tracer is not None:
                self.tracer.emit(rid, "requeue", t=now,
                                 failovers=req.failovers,
                                 handoff_retry=True)
            return
        req.dispatch_t = self._clock()
        self._where[rid] = tgt.replica_id
        self._by_replica[tgt.replica_id][eng_rid] = rid
        if self.tracer is not None:
            self.tracer.emit(req.rid, "dispatch", t=req.dispatch_t,
                             replica=tgt.replica_id, eng_rid=eng_rid,
                             failovers=req.failovers, handoff=True)
        if self._cache_map is not None:
            self._audit_dispatch(req, tgt)

    def _harvest(self, rep, f):
        """Map an engine FinishedRequest back to its router identity."""
        rid = self._by_replica[rep.replica_id].pop(f.req_id)
        req = self._open.pop(rid)
        self._where.pop(rid, None)
        # a terminal WITHOUT a handoff (e.g. deadline eviction on the
        # prefill class after pages exported) must still free the
        # retained transfer records — only _handoff/_failover otherwise
        # touch them, and they hold host-side page tensors
        self._transfer.pop(rid, None)
        if req.dispatch_t is not None:
            self._holds.append(self._clock() - req.dispatch_t)
            if len(self._holds) > 64:
                del self._holds[:32]
        return RouterFinished(
            **{**dataclasses.asdict(f), "req_id": rid},
            priority=req.priority, replica=rep.replica_id,
            failovers=req.failovers,
        )

    def _failover(self, rep):
        """A replica died: every request it held goes back to the FRONT
        of its class queue (oldest first — they have waited longest) for
        a from-scratch re-prefill elsewhere; the dead attempt's partial
        tokens are discarded so the eventual output is the one-shot
        stream. A request already past its deadline finishes 'timeout'
        here instead of being requeued."""
        if self.tracer is not None:
            # absorb whatever the corpse had buffered FIRST — the map
            # below is about to be cleared and the dying tick's events
            # (its last prefill chunks, first tokens) would lose their
            # fleet attribution
            evs, dropped = rep.take_trace()
            if evs or dropped:
                self.tracer.absorb(
                    evs, rid_map=self._by_replica[rep.replica_id],
                    replica=rep.replica_id, dropped=dropped)
            # a replica death is exactly the incident the flight
            # recorder exists for: dump the ring (no-op without an
            # out_dir), whether or not the corpse held work
            self.tracer.flight_dump(f"replica{rep.replica_id}-death")
        # disagg (ISSUE 13): transfers PINNED to this corpse lose their
        # imported pages with it — unpin so the next ship re-targets
        # and re-sends the retained records (host-side numpy, no
        # recompute); transfers FROM this corpse die with their
        # requests' failed-over attempts just below
        for tr in self._transfer.values():
            if tr.get("target") == rep.replica_id:
                tr["target"] = None
                tr["shipped"] = 0
        if self._cache_map is not None:
            # BEFORE the idle-corpse early return: a dead replica's
            # advertised cache content must leave the map even when it
            # held no work — a corpse must never win best_match
            self._cache_map.drop(rep.replica_id)
        assigned = self._by_replica[rep.replica_id]
        if not assigned:
            return
        reqs = sorted((self._open[rid] for rid in assigned.values()),
                      key=lambda r: (r.submit_t, r.rid))
        for rid in assigned.values():
            # a dead PREFILL replica's accumulated exports are the dead
            # attempt's work product: discard — the requeued request
            # re-prefills from prompt+rng and re-exports, bit-identical
            self._transfer.pop(rid, None)
        assigned.clear()
        now = self._clock()
        for req in reversed(reqs):
            self._where.pop(req.rid, None)
            req.dispatch_t = None
            req.failovers += 1
            if self.tracer is not None:
                self.tracer.emit(
                    req.rid, "failover", t=now, replica=rep.replica_id,
                    error=repr(rep.last_error) if rep.last_error
                    else None)
            if req.expired(now):
                # not a failover (nothing is re-prefilled): the death
                # just surfaced a deadline that had already passed
                self._pending.append(self._finish_router_timeout(req))
            else:
                self._reg.counter("serve_failovers").add(1)
                self._queues[req.priority].appendleft(req)
                if self.tracer is not None:
                    self.tracer.emit(req.rid, "requeue", t=now,
                                     failovers=req.failovers)

    def _finish_router_timeout(self, req):
        """Deadline death in the ROUTER's hands (queued, or orphaned by
        a failover past its deadline): same counters and record shape as
        the engine's queued-timeout path."""
        self._open.pop(req.rid, None)
        self._reg.counter("serve_requests").add(1)
        self._reg.counter("serve_timeouts").add(1)
        self.sink.write({
            "kind": "request", "t": time.time(), "id": req.rid,
            "n_prompt": len(req.prompt), "n_out": 0,
            "finish_reason": "timeout", "priority": req.priority,
        })
        if self.tracer is not None:
            self.tracer.emit(req.rid, "finish", reason="timeout",
                             n_out=0, router_queued=True)
        return RouterFinished(
            req_id=req.rid, tokens=list(req.prompt),
            n_prompt=len(req.prompt), n_out=0, finish_reason="timeout",
            text="" if self.detokenize is not None else None,
            ttft_ms=None, tpot_ms=0.0, priority=req.priority,
            failovers=req.failovers,
        )
