"""avenir_tpu.serve — continuous-batching inference engine (ISSUE 2)
plus the multi-replica fleet layer over it (ISSUE 6).

- slots.py:     fixed (L, n_slots, T_max, H_kv, D) KV slot pool + per-slot
                decode state, donated through the jitted step
- pages.py:     paged KV (ISSUE 9, kv_impl='paged'): ref-counted block
                allocator with shared-prefix pages + copy-on-write,
                fixed-width page tables (never retrace), chunked
                prefill, gather-based reference paged attention (the
                Pallas kernel lives in ops/pallas/paged_attention.py)
- scheduler.py: FCFS admission, power-of-2 prompt bucketing (bounded
                prefill compiles), iteration-level slot recycling
- engine.py:    submit()/step()/drain() driver over the shared
                infer/decode.py forward; per-request bit-parity with
                one-shot generate_cached
- replica.py:   health-checked engine wrapper — heartbeat from step
                progress, healthy/draining/dead state machine, fault
                sites (serve_step_fail, replica_stall)
- router.py:    fleet front door — failover (no accepted request ever
                lost), admission control + load shedding, priority
                fair-share, SLO-aware dispatch; `backend='process'`
                swaps in process-isolated replicas (ISSUE 8)
- frames.py:    length-prefixed, CRC-checked, versioned frame protocol
                over pipes (stdlib-only)
- worker.py:    `python -m avenir_tpu.serve.worker` — one Engine in its
                own OS process behind a frame-RPC loop
- proc.py:      ProcReplica (the Replica surface over a worker process:
                per-op RPC timeouts, EOF/CRC/timeout -> dead) + the
                capped-backoff RespawnSupervisor
- autoscale.py: trace-driven elastic control plane (ISSUE 12) — fleet
                SLO engine (windowed attainment + burn rate), traced
                queue-wait predictor behind projected-wait admission,
                and the Autoscaler that grows/retires the fleet with
                hysteresis, scale-to-zero and compile pre-warm, leaving
                an auditable `scale` trace per decision

See docs/SERVING.md for the design, the parity contract, and the
router's failover semantics.
"""

from avenir_tpu.serve.autoscale import (
    Autoscaler,
    ScaleDecision,
    SLOEngine,
    WaitPredictor,
)
from avenir_tpu.serve.engine import Engine, FinishedRequest
from avenir_tpu.serve.pages import (
    AdmitPlan,
    PageAllocator,
    PagedPool,
    init_paged_pool,
    paged_kv_ops,
)
from avenir_tpu.serve.proc import (
    ProcReplica,
    RespawnSupervisor,
    model_spec_from_model,
)
from avenir_tpu.serve.replica import (
    DEAD,
    DRAINING,
    HEALTHY,
    Replica,
    ReplicaGone,
)
from avenir_tpu.serve.router import PRIORITIES, Router, RouterFinished
from avenir_tpu.serve.scheduler import FCFSScheduler, Request
from avenir_tpu.serve.slots import SlotPool, init_slot_pool

__all__ = [
    "Autoscaler", "SLOEngine", "WaitPredictor", "ScaleDecision",
    "Engine", "FinishedRequest", "FCFSScheduler", "Request", "SlotPool",
    "init_slot_pool", "PageAllocator", "AdmitPlan", "PagedPool",
    "init_paged_pool", "paged_kv_ops", "Replica", "ReplicaGone",
    "ProcReplica", "RespawnSupervisor", "model_spec_from_model",
    "Router", "RouterFinished", "PRIORITIES", "HEALTHY", "DRAINING",
    "DEAD",
]
