"""avenir_tpu.serve — slot-based continuous-batching inference engine
(ISSUE 2).

- slots.py:     fixed (L, n_slots, T_max, H_kv, D) KV slot pool + per-slot
                decode state, donated through the jitted step
- scheduler.py: FCFS admission, power-of-2 prompt bucketing (bounded
                prefill compiles), iteration-level slot recycling
- engine.py:    submit()/step()/drain() driver over the shared
                infer/decode.py forward; per-request bit-parity with
                one-shot generate_cached

See docs/SERVING.md for the design and the parity contract.
"""

from avenir_tpu.serve.engine import Engine, FinishedRequest
from avenir_tpu.serve.scheduler import FCFSScheduler, Request
from avenir_tpu.serve.slots import SlotPool, init_slot_pool

__all__ = [
    "Engine", "FinishedRequest", "FCFSScheduler", "Request", "SlotPool",
    "init_slot_pool",
]
