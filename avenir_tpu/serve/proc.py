"""Process-isolated serve replicas: ProcReplica + respawn supervisor
(ISSUE 8 tentpole, part 3).

`ProcReplica` is the parent-side handle for one `serve/worker.py`
process. It duck-types the in-process `Replica` surface the Router
already speaks — state machine, heartbeat, `step()`, and an `engine`
proxy carrying `submit`/`T_max`/`sched.queue_depth`/`_live` — so the
Router's failover, admission and fair-share semantics are IDENTICAL
over both backends (the same tests run over both; the router changes
no logic, only which replica class it builds).

What changes is what death means. An in-process replica dies by
exception or injected silence; a process replica dies for real:

    pipe EOF / EPIPE    the worker was SIGKILLed (chaos, OOM killer,
                        a preempted node) — mark_dead, fail over
    RPC timeout         the worker is silently wedged (`worker_hang`);
                        the per-op budget is the stall-threshold rule
                        plus slack, with a compile grace while the
                        worker is still warming — mark_dead, SIGKILL
                        the corpse, fail over (`rpc_timeouts`)
    CRC mismatch        the pipe delivered corrupt bytes
                        (`frame_corrupt`); the stream offset can no
                        longer be trusted, so corruption is death,
                        never a retry (`frame_crc_errors`) — the same
                        policy as checkpoint manifests (ISSUE 5)
    op error reply      the engine raised inside the worker — the
                        process analogue of `serve_step_fail`

Retries exist ONLY for idempotent ops (`ping`): a retried `submit`
could double-enqueue, a retried `step` double-advances — non-idempotent
failures fail over instead, which the router already knows how to do.

Latency truth: TTFT/TPOT are stamped on the PARENT's clock from the
step replies' first-token lists, with the router's own `submit_t` — a
worker's clock is unrelated to the parent's, and the parity/fair-share
tests drive injectable clocks. Engine counters are mirrored into the
fleet registry as per-reply deltas, so one registry tells the whole
fleet's story either backend (docs/OBSERVABILITY.md).

`RespawnSupervisor` is the restart story the ROADMAP's phase-2 item
asks for: a dead worker is respawned with capped exponential backoff
(`utils/retry.RetryPolicy` — the same schedule shape the checkpoint
IO retries use), rejoins EMPTY (the router already requeued its work,
so re-prefill failover keeps completed outputs bit-identical), and a
crash-looping worker exhausts its budget and stays dead — at which
point `Router.drain()` stops waiting and fails loud.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np

from avenir_tpu.obs import NullSink, get_registry
from avenir_tpu.serve.frames import (
    PROTO_VERSION,
    PT_PICKLE,
    FrameCRCError,
    FrameError,
    FrameStream,
    FrameTimeout,
)
from avenir_tpu.serve.replica import DEAD, HEALTHY, ReplicaGone, \
    ReplicaHealth
from avenir_tpu.utils.faults import get_injector
from avenir_tpu.utils.retry import RetryPolicy, call_with_retry

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# the worker is launched through this bootstrap, not bare `-m`: fd 1
# must become the frame fd BEFORE the package imports run (jax/flax
# import-time chatter on a still-unredirected stdout would land in the
# frame stream ahead of the hello reply and desync the protocol).
# `python -m avenir_tpu.serve.worker` stays a valid manual entrypoint —
# worker.main() does its own dup when the env var is absent.
_WORKER_BOOTSTRAP = (
    "import os,sys;"
    "fd=os.dup(1);os.dup2(2,1);sys.stdout=sys.stderr;"
    "os.environ['AVENIR_WORKER_FRAME_FD']=str(fd);"
    "from avenir_tpu.serve.worker import main;main()"
)

# per-op reply budgets (seconds). `step` is dynamic — the stall
# threshold plus slack (see ProcReplica._step_timeout_s); `hello` is
# generous because the worker pays the jax import and model build
# inside it.
OP_TIMEOUT_S = {
    "hello": 600.0,
    "submit": 60.0,
    "ping": 10.0,
    "arm_fault": 10.0,
    "shutdown": 10.0,
    # disagg page transfer (ISSUE 13): tensor frames move page KV —
    # megabytes, not a control message — so they get the submit budget
    "fetch_pages": 60.0,
    "import_pages": 60.0,
    "pull_chain": 60.0,
    "chains": 10.0,
}
IDEMPOTENT_OPS = frozenset({"ping"})


class WorkerOpError(RuntimeError):
    """The worker replied ok=False — its engine raised (the process
    analogue of serve_step_fail) or it refused the op."""


def model_spec_from_model(model):
    """Handshake spec for a live model: (family, config dataclass,
    numpy state). Shipping the actual weights — not an init seed —
    makes worker models BIT-identical to the parent's, which is what
    the failover parity contract rests on. Deployments serving big
    checkpoints pass {"kind": "checkpoint", "out_dir": ...} instead so
    the weights ride the filesystem, not a pipe."""
    import jax
    from flax import nnx

    _, state = nnx.split(model)
    return {
        "kind": "state",
        "family": type(model).__name__.lower(),
        "config": model.config,
        "state": jax.tree.map(lambda x: np.asarray(x), state),
    }


class _SchedView:
    """The slice of FCFSScheduler the router reads, mirrored from
    worker heartbeats."""

    def __init__(self):
        self.queue_depth = 0
        self.free_slots = 0


class _EngineProxy:
    """Parent-side mirror of the worker's engine host state, refreshed
    from every reply frame's heartbeat. The router reads `T_max`,
    `sched.queue_depth`, `_live`, `tick_estimate_s()` and calls
    `submit()` — the same surface the in-process Engine exposes."""

    def __init__(self, owner):
        self._owner = owner
        self.T_max = None          # set by the handshake
        self.max_total_tokens = None   # effective submit limit (ISSUE 9)
        self.limit_name = "max_seq_len"
        self.kv_impl = "slab"
        self.role = "both"         # disagg replica class (ISSUE 13)
        self.n_slots = 0
        self.sched = _SchedView()
        self._live = {}            # engine rid -> tokens emitted so far
        self._pending = 0
        self._prefilling = 0       # paged: slots mid-chunked-prefill
        self.kv = None             # paged: page-budget heartbeat mirror
        self.chains = None         # paged: chain-summary mirror (ISSUE 16)
        self.weight_version = "0"  # versioned hello echo (ISSUE 20)
        self._tick_s = 0.0

    def tick_estimate_s(self):
        return self._tick_s

    def submit(self, *args, **kw):
        return self._owner._submit_rpc(*args, **kw)

    def update(self, hb):
        self.n_slots = int(hb.get("n_slots", self.n_slots))
        self.sched.free_slots = int(hb.get("free", 0))
        self.sched.queue_depth = int(hb.get("queue", 0))
        self._live = {int(k): int(v)
                      for k, v in (hb.get("live") or {}).items()}
        self._pending = int(hb.get("pending", 0))
        self._prefilling = int(hb.get("prefilling", 0))
        if hb.get("kv") is not None:
            self.kv = dict(hb["kv"])  # page budget rides every beat
        if hb.get("weight_version") is not None:
            # every heartbeat re-asserts the serving version — the
            # router's version-keyed cache map reads THIS mirror, so a
            # swapped worker's first reply already re-keys its chains
            self.weight_version = str(hb["weight_version"])
        self._tick_s = float(hb.get("tick_s", 0.0))

    def apply_chain_delta(self, delta):
        """Merge one step reply's chain-summary delta (ISSUE 16) into
        the parent-side mirror — the counter/sketch delta pattern:
        applying every delta in arrival order rebuilds the worker's
        direct `chain_summary()` exactly (pinned)."""
        if self.chains is None:
            self.chains = {}
        self.chains.update(delta.get("upd") or {})
        for d in delta.get("gone") or ():
            self.chains.pop(d, None)

    def clear(self):
        self.sched.free_slots = 0
        self.sched.queue_depth = 0
        self._live = {}
        self._pending = 0
        self._prefilling = 0
        self.kv = None  # a corpse's page stats must not keep feeding
        self.chains = None  # the router's fleet paging gauges / cache
        self._tick_s = 0.0  # map — its next life re-ships from scratch


class ProcReplica(ReplicaHealth):
    """One serve worker PROCESS, behind the Replica health/dispatch
    surface. Construction spawns and handshakes the worker; pass
    `defer_handshake=True` (the Router does) to spawn a whole fleet
    first and let the workers pay their jax imports concurrently."""

    def __init__(self, model_spec, replica_id, *, n_slots=4,
                 max_seq_len=None, detokenize=None, registry=None,
                 sink=None, seed=0, clock=None, stall_floor_secs=10.0,
                 stall_factor=10.0, rpc_slack_secs=5.0,
                 compile_grace_secs=300.0, env=None,
                 defer_handshake=False, engine_kwargs=None, trace=0,
                 draft_spec=None):
        super().__init__(
            replica_id,
            clock=clock if clock is not None else time.perf_counter,
            stall_floor_secs=stall_floor_secs, stall_factor=stall_factor)
        self._spec = model_spec
        # spec-decode draft weights ride the hello exactly like target
        # weights (ISSUE 11) — same spec shapes, incl. {"kind":
        # "checkpoint"} to keep a big draft off the pipe
        self._draft_spec = draft_spec
        self._ekw = {"n_slots": int(n_slots), "max_seq_len": max_seq_len,
                     "detokenize": detokenize, "seed": int(seed),
                     # paged-KV knobs ride the hello (ISSUE 9)
                     **(engine_kwargs or {})}
        if trace:
            # tracing rides the hello as the decode-tick sampling
            # interval (ISSUE 10): the worker builds its own TraceBuffer
            # and ships drained events back in every reply as clock-free
            # AGE deltas — restamped onto the parent clock in _rpc, the
            # TTFT-restamp pattern
            self._ekw["trace"] = int(trace)
        self._trace_pending = []   # restamped, engine-rid keyed
        self._trace_dropped = 0
        self._export_pending = []  # fetched page-export records (disagg)
        self._reg = registry if registry is not None else get_registry()
        self.sink = sink if sink is not None else NullSink()
        self.rpc_slack_secs = float(rpc_slack_secs)
        self.compile_grace_secs = float(compile_grace_secs)
        self._env = env
        self.engine = _EngineProxy(self)
        self._proc = None
        self._stream = None
        self._counters_seen = {}   # worker counter totals, last reply
        self._seq = 0              # request/reply alignment (see _rpc)
        self._submit_t = {}        # engine rid -> router-clock submit_t
        self._t_first = {}         # engine rid -> router-clock 1st token
        self._deadline = {}        # engine rid -> deadline_ms (or None)
        self._n_busy_steps = 0
        # compile-grace accounting: the worker compiles on its first
        # prefill of each prompt BUCKET (and its first decode step) —
        # track which buckets this worker instance has seen so the
        # step-RPC timeout grants grace exactly when a compile may be
        # in flight, not just for the first two steps of its life (a
        # late new-bucket prompt must not read as a hang)
        self._seen_buckets = set()
        self._grace_steps = 2
        self._spawn()
        if not defer_handshake:
            self.finish_handshake()

    # -- lifecycle --

    def _spawn(self):
        env = dict(os.environ if self._env is None else self._env)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep \
            + env.get("PYTHONPATH", "")
        # the worker's jax must land on the parent's platform even when
        # only the live config (not the env) was pinned to it
        env.setdefault("JAX_PLATFORMS", _parent_platform())
        self._proc = subprocess.Popen(
            [sys.executable, "-c", _WORKER_BOOTSTRAP],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=None,  # worker chatter joins the parent's stderr
            cwd=_REPO_ROOT, env=env)
        try:
            # widen the hello pipe (best effort): the model-state frame
            # can exceed the 64 KiB default, and a write past the buffer
            # blocks the parent until the worker finishes its jax import
            import fcntl

            fcntl.fcntl(self._proc.stdin.fileno(),
                        fcntl.F_SETPIPE_SZ, 1 << 20)
        except (ImportError, AttributeError, OSError, PermissionError):
            pass
        self._stream = FrameStream(self._proc.stdout.fileno(),
                                   self._proc.stdin.fileno())
        # NOTE: no hello here — _spawn only starts the process, so a
        # fleet can launch N workers and they all pay their jax imports
        # concurrently; the hello (whose pickled model state can exceed
        # the pipe buffer, blocking the writer until the worker reads)
        # goes out in finish_handshake

    def finish_handshake(self):
        """Send hello, block for the worker's reply; fail loud on a
        protocol mismatch (never guess at an incompatible peer)."""
        self._seq += 1
        hello = {"op": "hello", "seq": self._seq, "proto": PROTO_VERSION,
                 "model": self._spec, "engine": self._ekw}
        if self._draft_spec is not None:
            hello["draft"] = self._draft_spec
        self._stream.write(hello, ptype=PT_PICKLE)
        reply = self._read_reply(timeout_s=OP_TIMEOUT_S["hello"])
        if not reply.get("ok"):
            raise RuntimeError(
                f"replica {self.replica_id} worker refused handshake: "
                f"{reply.get('error')}")
        if reply.get("proto") != PROTO_VERSION:
            raise RuntimeError(
                f"replica {self.replica_id} worker speaks proto "
                f"{reply.get('proto')}, parent speaks {PROTO_VERSION}")
        self.engine.T_max = int(reply["t_max"])
        self.engine.max_total_tokens = int(
            reply.get("limit_tokens", reply["t_max"]))
        self.engine.limit_name = reply.get("limit_name", "max_seq_len")
        self.engine.kv_impl = reply.get("kv_impl", "slab")
        self.engine.role = reply.get("role", "both")
        self.engine.n_slots = int(reply["n_slots"])
        self.engine.sched.free_slots = int(reply["n_slots"])
        # compile pre-warm (ISSUE 12): when the hello's engine kwargs
        # carried `prewarm`, the worker ran one synthetic prefill +
        # decode tick per bucket BEFORE this reply — so by the time the
        # router can dispatch to this replica, its compiles are paid
        # (respawns re-send the same hello, so a supervisor-revived
        # worker pre-warms too; `prewarm_ticks` mirrors via the usual
        # counter deltas)
        self.prewarm_ticks = int(reply.get("prewarm_ticks", 0))
        self.engine.weight_version = str(reply.get("weight_version", "0"))
        self.last_beat = self._clock()
        return self

    @property
    def weight_version(self):
        """Version label of the weights the worker ACTUALLY serves —
        the hello echo, re-asserted by every heartbeat (a respawn that
        landed on a different spec is visible here, not assumed)."""
        return self.engine.weight_version

    def set_model_spec(self, spec, version=None):
        """Point every FUTURE hello at `spec` (serve/rollout.py): the
        next reload() — or a supervisor revive() after a death — will
        rebuild the worker from it. The rollout manager calls this
        BEFORE touching the worker, so a SIGKILL mid-swap respawns on
        the TARGET version instead of resurrecting the old weights
        (ISSUE 20: respawns route through the CURRENT target)."""
        self._spec = spec
        if version is not None:
            self._ekw["weight_version"] = str(version)

    def reload(self):
        """Controlled restart onto the current `self._spec` — the
        process backend's weight swap (drain -> re-hello -> prewarm ->
        rejoin, serve/rollout.py). revive()'s respawn path WITHOUT a
        death: a swap is a decision, not a failure, so `deaths` and the
        supervisor's backoff budget stay untouched. Caller drains
        first. Raises on spawn/handshake failure — the rollout manager
        marks the replica dead and the supervisor (aimed at the same
        spec by set_model_spec) takes over the retry."""
        assert not self.busy, "weight swap requires a drained replica"
        self._teardown(kill=True)
        self._counters_seen = {}
        self._submit_t = {}
        self._t_first = {}
        self._deadline = {}
        self._export_pending = []
        self._trace_pending = []
        self._trace_dropped = 0
        self._durs = []
        self._n_busy_steps = 0
        self._seen_buckets = set()
        self._grace_steps = 2
        self._stalled = False
        self.last_error = None
        self._spawn()
        try:
            self.finish_handshake()
        except Exception:
            self._teardown(kill=True)
            raise
        self.state = HEALTHY
        self.last_beat = self._clock()
        return self

    @property
    def pid(self):
        """The worker's OS pid — the chaos drill's REAL SIGKILL target
        (None once the corpse is reaped)."""
        return self._proc.pid if self._proc is not None else None

    def _teardown(self, kill):
        proc, self._proc, self._stream = self._proc, None, None
        self.engine.clear()
        if proc is None:
            return
        for f in (proc.stdin, proc.stdout):
            try:
                f.close()
            except OSError:
                pass
        try:
            if kill and proc.poll() is None:
                os.kill(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(timeout=5)  # reap — no zombies in a long fleet
        except subprocess.TimeoutExpired:
            pass

    def _on_dead(self):
        # a replica declared dead for ANY reason tears its process down
        # — a wedged worker must not linger half-alive (its pipes stay
        # readable and a later frame would desync the new stream)
        self._teardown(kill=True)
        # drop the corpse's per-request bookkeeping NOW (ISSUE 9 leak
        # audit): the router requeues its work onto OTHER replicas, so
        # these rids will never be harvested here — without this, every
        # failover leaked its submit_t/deadline/first-token entries
        # until the next revive
        self._submit_t = {}
        self._t_first = {}
        self._deadline = {}
        self._export_pending = []  # the corpse's in-flight transfers
        #                            fail over with their requests

    def close(self):
        """Graceful shutdown (drained replica, end of run)."""
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._rpc({"op": "shutdown"},
                          timeout_s=OP_TIMEOUT_S["shutdown"])
            except (FrameError, WorkerOpError, OSError, ValueError):
                pass
        self._teardown(kill=True)

    def __del__(self):  # best effort — tests and tools call close()
        try:
            self._teardown(kill=True)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # -- capacity surface the router routes on (mirrors Replica) --

    @property
    def n_slots(self):
        return self.engine.n_slots

    @property
    def free_slots(self):
        return self.engine.sched.free_slots if self.state == HEALTHY \
            else 0

    @property
    def dispatchable_slots(self):
        if self.state != HEALTHY:
            return 0
        return max(0, self.engine.sched.free_slots
                   - self.engine.sched.queue_depth)

    @property
    def busy(self):
        return bool(self.engine._live or self.engine.sched.queue_depth
                    or self.engine._pending or self.engine._prefilling)

    # -- RPC --

    def _rpc(self, msg, *, timeout_s, ptype=0, arrays=None):
        """One request/reply exchange. Every request carries a sequence
        number the worker echoes; `_read_reply` discards stale replies
        (the late answer to an op a retry already gave up on — without
        this, one retried ping would shift request/reply alignment for
        every RPC after it). Heartbeat bookkeeping rides every reply;
        callers map FrameError/WorkerOpError to death. `arrays` (numpy
        list) turns the request into a PT_KVPAGES tensor frame — the
        page-transfer wire form (ISSUE 13)."""
        if self._stream is None:
            raise ReplicaGone(f"replica {self.replica_id} has no worker")
        self._seq += 1
        msg["seq"] = self._seq
        if arrays is not None:
            from avenir_tpu.serve.frames import PT_KVPAGES

            self._stream.write((msg, arrays), ptype=PT_KVPAGES)
        else:
            self._stream.write(msg, ptype=ptype)
        reply = self._read_reply(timeout_s=timeout_s)
        if not reply.get("ok"):
            raise WorkerOpError(reply.get("error", "worker error"))
        if "hb" in reply:
            self.engine.update(reply["hb"])
        if "counters" in reply:
            self._apply_counter_deltas(reply["counters"])
        if reply.get("series"):
            # health-series sketch deltas (ISSUE 14): bucket counts
            # merge into the fleet registry's series the same way the
            # counter deltas above mirror totals — the parent-side
            # sketch equals one built from the worker's raw stream
            for key, d in reply["series"].items():
                self._reg.series(key).sketch.merge_dict(d)
        if reply.get("chains"):
            # prefix-chain summary deltas (ISSUE 16): same merge story
            self.engine.apply_chain_delta(reply["chains"])
        if reply.get("trace"):
            # restamp NOW, at arrival: age_s was measured against the
            # worker clock when the reply was built; parent_now - age is
            # the same event on the fleet clock (pipe latency shifts
            # every event of a reply equally — relative order holds,
            # and the fleet tracer's per-rid clamp absorbs the jitter)
            now = self._clock()
            for e in reply["trace"]:
                e = dict(e)
                e["t"] = now - float(e.pop("age_s", 0.0))
                self._trace_pending.append(e)
        if reply.get("trace_dropped"):
            self._trace_dropped += int(reply["trace_dropped"])
        return reply

    def take_trace(self):
        """Drain restamped worker trace events (engine-rid keyed,
        PARENT clock). Returns (events, dropped count)."""
        out, self._trace_pending = self._trace_pending, []
        dropped, self._trace_dropped = self._trace_dropped, 0
        return out, dropped

    # -- disaggregated page transfer (ISSUE 13) --

    @property
    def role(self):
        return self.engine.role

    def take_page_exports(self):
        """Drain export records fetched from the worker (step() pulls a
        PT_KVPAGES frame whenever a step reply advertises exports)."""
        out, self._export_pending = self._export_pending, []
        return out

    def _fetch_exports(self):
        """Pull the worker's queued page exports as one tensor frame
        and stage them for the router's transfer pump. Failure here is
        replica death like any other RPC failure — the requests whose
        pages were in flight fail over and re-prefill elsewhere."""
        from avenir_tpu.serve.frames import ARRAYS_PER_DTYPE

        try:
            reply = self._rpc({"op": "fetch_pages"},
                              timeout_s=OP_TIMEOUT_S["fetch_pages"])
        except FrameTimeout as e:
            self._die(e, counter="rpc_timeouts")
            return
        except FrameCRCError as e:
            self._die(e, counter="frame_crc_errors")
            return
        except (FrameError, WorkerOpError, OSError, ValueError) as e:
            self._die(e)
            return
        arrays = reply.get("arrays") or []
        off = 0
        for rec in reply.get("records", ()):
            n = ARRAYS_PER_DTYPE[rec["kv_dtype"]]
            self._export_pending.append({
                "eng_rid": int(rec["eng_rid"]),
                "tokens": rec["tokens"],
                "n_prefix": int(rec.get("n_prefix", 0)),
                "kv_dtype": rec["kv_dtype"],
                "arrays": arrays[off:off + n],
            })
            off += n

    def import_pages(self, records):
        """Ship exported page records INTO this worker over one
        PT_KVPAGES frame. Returns (pages written, payload bytes).
        Non-idempotent is fine here (a re-import dedupes on the chain
        key), but a failed transfer means a dead pipe — same death
        mapping as submit, and the router re-targets the handoff."""
        meta = {"op": "import_pages",
                "records": [{"eng_rid": r["eng_rid"],
                             "tokens": r["tokens"],
                             "n_prefix": r.get("n_prefix", 0),
                             "kv_dtype": r["kv_dtype"]}
                            for r in records]}
        flat = [a for r in records for a in r["arrays"]]
        nbytes = sum(a.nbytes for a in flat)   # tensor bytes on the wire
        try:
            reply = self._rpc(meta, arrays=flat,
                              timeout_s=OP_TIMEOUT_S["import_pages"])
        except FrameTimeout as e:
            self._die(e, counter="rpc_timeouts")
            raise ReplicaGone(str(e)) from e
        except FrameCRCError as e:
            self._die(e, counter="frame_crc_errors")
            raise ReplicaGone(str(e)) from e
        except (FrameError, WorkerOpError, OSError, ValueError) as e:
            self._die(e)
            raise ReplicaGone(str(e)) from e
        return int(reply.get("written", 0)), nbytes

    def export_chain(self, token_pages, n_prefix=0):
        """Pull-SOURCE surface of the fleet KV CDN (ISSUE 17): ask the
        worker for the live KV of the registered chain matching
        `token_pages`, delivered as one PT_KVPAGES tensor frame.
        Returns an export record (the take_page_exports shape) or None
        when the worker no longer holds anything past the receiver's
        prefix. A dead pipe, timeout, or CRC trip is replica death like
        any other RPC failure — the broker's fallback contract (local
        re-prefill) makes that safe."""
        msg = {"op": "pull_chain",
               "tokens": [[int(t) for t in p] for p in token_pages],
               "n_prefix": int(n_prefix)}
        try:
            reply = self._rpc(msg, timeout_s=OP_TIMEOUT_S["pull_chain"])
        except FrameTimeout as e:
            self._die(e, counter="rpc_timeouts")
            raise ReplicaGone(str(e)) from e
        except FrameCRCError as e:
            self._die(e, counter="frame_crc_errors")
            raise ReplicaGone(str(e)) from e
        except (FrameError, WorkerOpError, OSError, ValueError) as e:
            self._die(e)
            raise ReplicaGone(str(e)) from e
        rec = reply.get("record")
        if not rec:
            return None
        return {"eng_rid": int(rec.get("eng_rid", -1)),
                "tokens": rec["tokens"],
                "n_prefix": int(rec.get("n_prefix", 0)),
                "kv_dtype": rec["kv_dtype"],
                "arrays": list(reply.get("arrays") or [])}

    def _read_reply(self, *, timeout_s):
        """Read until the reply matching the current seq (bounded):
        stale-seq replies are drained and dropped."""
        for _ in range(16):
            reply = self._stream.read(timeout_s=timeout_s)
            if reply.get("seq") == self._seq:
                return reply
        raise FrameError(
            f"replica {self.replica_id}: no reply with seq {self._seq} "
            "within 16 frames — stream misaligned beyond recovery")

    def _die(self, err, *, counter=None):
        if counter is not None:
            self._reg.counter(counter).add(1)
        self.last_error = err
        self.mark_dead()

    def _step_timeout_s(self):
        """The hang-detection budget: the watchdog-rule stall threshold
        plus RPC slack, with a compile grace whenever the worker may be
        compiling — its first busy steps, or a step that will admit a
        prompt from a bucket this worker instance has never prefilled
        (killing a healthy worker mid-compile would cascade: the
        failed-over prompt makes the next replica compile and die the
        same way)."""
        t = self.stall_threshold_secs() + self.rpc_slack_secs
        if self._grace_steps > 0:
            t += self.compile_grace_secs
        return t

    def _submit_rpc(self, prompt, *, max_new_tokens, temperature=1.0,
                    top_k=None, stop_tokens=(), rng=None,
                    deadline_ms=None, submit_t=None, front=False):
        """The proxy's Engine.submit: ships the request (rng as raw key
        data, submit_t as an AGE — worker clocks are unrelated). The
        deadline is NOT shipped: deadline semantics belong to the
        FLEET's clock (injectable in tests), so the parent tracks it
        and names expired rids in each step request (Engine.evict). A
        submit is NOT idempotent (a blind resend could double-enqueue),
        so failure here is replica death + ReplicaGone; the router
        requeues the request on another replica."""
        import jax

        now = self._clock()
        st = now if submit_t is None else float(submit_t)
        msg = {
            "op": "submit",
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": None if top_k is None else int(top_k),
            "stop_tokens": [int(t) for t in (stop_tokens or ())],
            "rng": None if rng is None else
                   np.asarray(jax.random.key_data(rng)).tolist(),
            "age_ms": max(0.0, (now - st) * 1e3),
            "front": bool(front),
        }
        try:
            reply = self._rpc(msg, timeout_s=OP_TIMEOUT_S["submit"])
        except FrameTimeout as e:
            self._die(e, counter="rpc_timeouts")
            raise ReplicaGone(str(e)) from e
        except FrameCRCError as e:
            self._die(e, counter="frame_crc_errors")
            raise ReplicaGone(str(e)) from e
        except (FrameError, WorkerOpError, OSError, ValueError) as e:
            self._die(e)
            raise ReplicaGone(str(e)) from e
        rid = int(reply["rid"])
        self._submit_t[rid] = st
        self._deadline[rid] = (None if deadline_ms is None
                               else float(deadline_ms))
        from avenir_tpu.infer.decode import prompt_bucket

        bucket = prompt_bucket(len(msg["prompt"]), self.engine.T_max)
        if bucket not in self._seen_buckets:
            # the step that admits this prompt pays a prefill compile:
            # grant the RPC grace for the next couple of steps
            self._seen_buckets.add(bucket)
            self._grace_steps = max(self._grace_steps, 2)
        if not self._stalled:
            # a successful RPC is liveness evidence — except under the
            # injected replica_stall wedge, whose whole point is
            # SIMULATED silence (the in-process replica's submit path
            # never beats either)
            self.last_beat = self._clock()
        return rid

    def ping(self):
        """Idempotent liveness probe — the ONE retried op (transient
        timeout only; EOF/CRC mean a corpse, and retrying those would
        just re-read it)."""
        return call_with_retry(
            lambda: self._rpc({"op": "ping"},
                              timeout_s=OP_TIMEOUT_S["ping"]),
            what=f"replica {self.replica_id} ping",
            policy=RetryPolicy(attempts=3, base_s=0.05, cap_s=0.5),
            retry_on=(FrameTimeout,), registry=self._reg, sink=self.sink)

    def chain_summary(self):
        """The worker's DIRECT chain summary over RPC (ISSUE 16) — the
        parity oracle for the delta-merged `engine.chains` mirror
        (tests only; the router never takes this extra round trip)."""
        reply = self._rpc({"op": "chains"},
                          timeout_s=OP_TIMEOUT_S["chains"])
        return reply.get("chains") or {}

    def arm_fault(self, spec, seed=0):
        """Install a seeded fault injector in THIS worker (the chaos
        harness's targeted hang/corrupt arming)."""
        return self._rpc({"op": "arm_fault", "spec": spec,
                          "seed": int(seed)},
                         timeout_s=OP_TIMEOUT_S["arm_fault"])

    # -- stepping --

    def step(self):
        """One worker iteration over RPC. Same consult order as the
        in-process Replica (replica_stall, then serve_step_fail), so
        seeded fault schedules replay identically over both backends;
        the process-only paths — EOF, timeout, CRC — map to the same
        mark_dead the router already fails over from."""
        if self.state == DEAD:
            return []
        inj = get_injector()
        if not self._stalled and inj.should_fire("replica_stall"):
            self._stalled = True
        if self._stalled:
            # parent-side wedge: no RPC, no beats — indistinguishable
            # from idle until the stall threshold says otherwise
            return []
        t0 = self._clock()
        had_work = self.busy
        # serve_step_degrade (ISSUE 20): parent-side like the inproc
        # consult, so seeded poisoned-canary schedules replay on both
        # backends; each fire is a PERMANENT +2 ms per busy step
        if inj.should_fire("serve_step_degrade"):
            self._degrade_s = getattr(self, "_degrade_s", 0.0) + 0.002
        if had_work and getattr(self, "_degrade_s", 0.0):
            time.sleep(self._degrade_s)
        try:
            inj.fail("serve_step_fail", f"replica {self.replica_id}")
        except Exception as e:  # noqa: BLE001 — FaultInjected is OSError
            self._die(e)
            return []
        # parent-clock deadline sweep over THIS worker's requests:
        # queued-in-worker rids get the engine's dispatch-time tick
        # lookahead (they could not emit a token in time anyway); live
        # rids expire exactly at their deadline. The worker evicts what
        # we name (Engine.evict) — its own clock never judges deadlines
        expire = []
        tick = self.engine._tick_s
        for rid, dl in self._deadline.items():
            if dl is None:
                continue
            horizon = t0 + (0.0 if rid in self.engine._live else tick)
            if (horizon - self._submit_t.get(rid, t0)) * 1e3 >= dl:
                expire.append(rid)
        try:
            reply = self._rpc({"op": "step", "expire": expire},
                              timeout_s=self._step_timeout_s())
        except FrameTimeout as e:
            self._die(e, counter="rpc_timeouts")
            return []
        except FrameCRCError as e:
            self._die(e, counter="frame_crc_errors")
            return []
        except (FrameError, WorkerOpError, OSError, ValueError) as e:
            # FrameEOF / EPIPE: the worker was KILLED — the path a real
            # SIGKILL takes; WorkerOpError: its engine raised
            self._die(e)
            return []
        now = self._record_beat(t0, had_work)
        if had_work:
            self._n_busy_steps += 1
            if self._grace_steps > 0:
                self._grace_steps -= 1
        if reply.get("n_exports"):
            # pull the advertised page exports NOW (one tensor frame),
            # so the router's transfer pump sees them this very step —
            # the stream-while-prefilling overlap (ISSUE 13)
            self._fetch_exports()
            if self.state == DEAD:
                return []
        for rid in reply.get("first", ()):
            self._t_first[int(rid)] = now
        return [self._harvest_finished(d, now)
                for d in reply.get("finished", ())]

    # -- harvest bookkeeping --

    def _harvest_finished(self, d, now):
        """Rebuild a FinishedRequest from its wire dict, restamp
        TTFT/TPOT on the ROUTER's clock (worker clocks are unrelated,
        and injected test clocks must stay authoritative), mirror the
        latency histograms, and write the request record the in-process
        engine would have written to the fleet sink."""
        from avenir_tpu.serve.engine import FinishedRequest

        f = FinishedRequest(**d)
        rid = int(f.req_id)
        st = self._submit_t.pop(rid, None)
        self._deadline.pop(rid, None)
        t_first = self._t_first.pop(rid, None)
        if f.n_out >= 1 and t_first is None:
            t_first = now  # finished the same step its first token landed
        if f.n_out >= 1 and st is not None and t_first is not None:
            f.ttft_ms = (t_first - st) * 1e3
            self._reg.hist("ttft_ms").observe(f.ttft_ms)
        else:
            f.ttft_ms = None
        # a finished request's LAST token always landed in its finishing
        # step (stop/length by definition; deadline eviction keeps the
        # final iteration's token) — `now` is its t_last
        f.tpot_ms = ((now - t_first) / (f.n_out - 1) * 1e3
                     if f.n_out > 1 and t_first is not None else 0.0)
        if f.n_out > 1:
            self._reg.hist("tpot_ms").observe(f.tpot_ms)
        if f.finish_reason == "prefilled":
            # internal handoff marker, NOT a terminal (ISSUE 13): the
            # decode replica writes the one kind='request' row — same
            # policy as Engine._finish_prefilled on the inproc backend
            return f
        record = {
            "kind": "request", "t": time.time(), "id": rid,
            "n_prompt": f.n_prompt, "n_out": f.n_out,
            "finish_reason": f.finish_reason,
        }
        if f.ttft_ms is not None:
            record["ttft_ms"] = f.ttft_ms
        if f.n_out > 1:
            record["tpot_ms"] = f.tpot_ms
        self.sink.write(record)
        return f

    def _apply_counter_deltas(self, totals):
        """Mirror the worker registry's counter movement into the fleet
        registry (the worker process has its own registry; deltas keep
        one authoritative story parent-side without double counting)."""
        for key, total in totals.items():
            seen = self._counters_seen.get(key, 0.0)
            if total > seen:
                self._reg.counter(key).add(total - seen)
            self._counters_seen[key] = total

    # -- state transitions --

    def revive(self):
        """From `dead`: RESPAWN — a fresh worker process, handshaken,
        rejoining EMPTY (the router already requeued everything the
        corpse held, so re-prefill failover keeps completed outputs
        bit-identical). From `draining`: just un-drain. Raises if the
        spawn/handshake fails — the supervisor counts that as another
        death and backs off."""
        if self.state == DEAD:
            self._teardown(kill=True)
            self._counters_seen = {}
            self._submit_t = {}
            self._t_first = {}
            self._deadline = {}
            self._export_pending = []
            self._durs = []
            self._n_busy_steps = 0
            self._seen_buckets = set()  # a fresh process compiles anew
            self._grace_steps = 2
            self._stalled = False
            self.last_error = None
            self._spawn()
            try:
                self.finish_handshake()
            except Exception:
                self._teardown(kill=True)
                raise
        self.state = HEALTHY
        self.last_beat = self._clock()


def _parent_platform():
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — jax not imported yet: let the
        return ""      # worker pick its own default


class RespawnSupervisor:
    """Respawns dead process replicas with capped exponential backoff.

    The delay schedule is `utils/retry.RetryPolicy` — the same policy
    object the checkpoint IO retries use, injectable for tests. Each
    death schedules the next respawn attempt at `now +
    policy.delay_s(consecutive_failures)`; a respawn that itself fails
    (spawn error, handshake refusal) counts as another failure. Past
    `max_respawns` consecutive failures the supervisor GIVES UP on that
    replica — a crash-looping worker (a deterministic bug, a poisoned
    chip) must not be respawned forever, and `Router.drain()` only
    fails loud once no replica has attempts left. A replica that stays
    healthy for `reset_after_s` earns its failure budget back."""

    def __init__(self, *, policy=None, max_respawns=5, reset_after_s=60.0,
                 clock=None, registry=None, echo=print):
        self.policy = policy if policy is not None else RetryPolicy(
            attempts=max_respawns + 1, base_s=0.25, cap_s=15.0,
            jitter=0.25)
        self.max_respawns = int(max_respawns)
        self.reset_after_s = float(reset_after_s)
        self._clock = clock if clock is not None else time.perf_counter
        self._reg = registry if registry is not None else get_registry()
        self._echo = echo
        self._st = {}  # replica_id -> {failures, deaths_seen, next_t, up_t}

    def _rec(self, rep):
        return self._st.setdefault(rep.replica_id, {
            "failures": 0, "deaths_seen": rep.deaths,
            "next_t": 0.0, "up_t": None})

    def exhausted(self, rep):
        return self._rec(rep)["failures"] > self.max_respawns

    def pending(self):
        """Any dead replica with respawn budget left? (Router.drain's
        wait-vs-fail-loud decision.)"""
        return any(rep.state == DEAD and not self.exhausted(rep)
                   for rep in self._reps)

    def attach(self, replicas):
        self._reps = list(replicas)
        for rep in self._reps:
            # snapshot deaths NOW: a death between attach and the first
            # poll must read as new, not as the baseline
            self._rec(rep)
        return self

    def poll(self, now):
        """Schedule newly observed deaths, respawn what is due, refund
        the budget of replicas that stayed up. Called once per router
        step. Returns the replicas respawned this call."""
        respawned = []
        for rep in self._reps:
            st = self._rec(rep)
            if rep.state != DEAD:
                if st["up_t"] is None:
                    st["up_t"] = now
                elif (st["failures"]
                      and now - st["up_t"] >= self.reset_after_s):
                    st["failures"] = 0
                continue
            st["up_t"] = None
            if rep.deaths > st["deaths_seen"]:
                # newly observed death(s): one backoff step each
                st["deaths_seen"] = rep.deaths
                st["failures"] += 1
                if st["failures"] > self.max_respawns:
                    self._echo(
                        f"[supervisor] replica {rep.replica_id} exceeded "
                        f"{self.max_respawns} consecutive respawns — "
                        f"giving up (last error: {rep.last_error!r})")
                    continue
                st["next_t"] = now + self.policy.delay_s(st["failures"])
            if self.exhausted(rep) or now < st["next_t"]:
                continue
            try:
                rep.revive()
            except Exception as e:  # noqa: BLE001 — spawn/handshake
                st["failures"] += 1  # failure = another backoff step
                if st["failures"] > self.max_respawns:
                    self._echo(
                        f"[supervisor] replica {rep.replica_id} respawn "
                        f"failed terminally: {e!r}")
                else:
                    st["next_t"] = now + self.policy.delay_s(
                        st["failures"])
                    self._echo(
                        f"[supervisor] replica {rep.replica_id} respawn "
                        f"failed ({e!r}); retrying in "
                        f"{st['next_t'] - now:.2f}s")
                continue
            self._reg.counter("replica_respawns").add(1)
            respawned.append(rep)
            self._echo(f"[supervisor] replica {rep.replica_id} respawned "
                       f"(attempt {st['failures']}, pid {rep.pid})")
        return respawned
