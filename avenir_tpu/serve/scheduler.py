"""FCFS admission, prompt-length bucketing, slot recycling (ISSUE 2
tentpole, part 2).

Orca-style iteration-level scheduling: the unit of work is one batched
decode iteration, not one request. Between iterations the engine asks
the scheduler for admissions (free slot x queued request, FCFS order)
and returns slots the moment their occupant hits a stop token or its
length budget — a finished sequence never pins the batch to its own
tail the way static batching does.

Buckets come from `infer.decode.prompt_bucket` (the SAME rounding the
one-shot path uses, which is what makes engine prefill bit-identical to
one-shot prefill even for MoE models, where expert capacity depends on
the token count). The possible bucket set is `bucket_ladder(max seq
len)` — O(log T_max) values — and `seen_buckets` is asserted to stay
inside it, which bounds the number of prefill compiles for the whole
lifetime of the engine.
"""

import dataclasses
from collections import deque
from typing import Optional, Tuple

from avenir_tpu.infer.decode import bucket_ladder, prompt_bucket


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. `rng` is a jax PRNG key — the SAME key
    passed to a one-shot `generate_cached(model, rng, prompt[None], ...)`
    reproduces this request's tokens bit-for-bit (the engine's parity
    contract)."""

    req_id: int
    prompt: Tuple[int, ...]
    max_new_tokens: int
    temperature: float = 1.0
    top_k: Optional[int] = None
    stop_tokens: Tuple[int, ...] = ()
    rng: object = None
    submit_t: float = 0.0
    # wall-time budget from submit, in ms; None = no deadline. An
    # expired request finishes with finish_reason='timeout' — evicted
    # from its slot mid-decode, or dropped from the queue before it
    # ever burns a prefill (ISSUE 5 satellite).
    deadline_ms: Optional[float] = None

    def expired(self, now):
        return (self.deadline_ms is not None
                and (now - self.submit_t) * 1e3 >= self.deadline_ms)


class FCFSScheduler:
    """First-come-first-served queue + free-slot pool. Pure host state:
    nothing here touches the device, so admission decisions and slot
    recycling cost no dispatches and no compiles."""

    def __init__(self, n_slots, max_seq_len):
        assert n_slots >= 1
        self.n_slots = n_slots
        self.max_seq_len = max_seq_len
        self.ladder = bucket_ladder(max_seq_len)
        self.seen_buckets = set()
        self._queue = deque()
        # lowest-index-first keeps slot assignment deterministic for a
        # given arrival schedule (the parity tests replay schedules)
        self._free = sorted(range(n_slots))
        self.n_recycled = 0

    # -- queue --

    def enqueue(self, req: Request):
        self._queue.append(req)

    def enqueue_front(self, req: Request):
        """Head-of-queue enqueue (ISSUE 13): a request handed off from a
        prefill-class replica already waited its FCFS turn fleet-wide —
        its KV pages are imported and it only needs the tail chunk, so
        admitting it behind freshly dispatched work would re-impose a
        queue it already served. Fleet arrival order is preserved, just
        measured at the front door instead of per engine."""
        self._queue.appendleft(req)

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def free_slots(self):
        return len(self._free)

    # -- admission / recycling --

    def expire_queued(self, now, lookahead_s=0.0):
        """Pop (and return) every queued request whose deadline has
        passed — BEFORE admission, so a request that can no longer be
        served never burns a prefill dispatch or blocks the FCFS head.
        `lookahead_s` (the engine's decode-tick estimate) also expires
        requests whose remaining deadline cannot cover even one more
        tick: hopeless work must never occupy a slot (ISSUE 6)."""
        expired = [r for r in self._queue if r.expired(now + lookahead_s)]
        if expired:
            dead = {r.req_id for r in expired}
            self._queue = deque(r for r in self._queue
                                if r.req_id not in dead)
        return expired

    def remove(self, req_ids):
        """Pop (and return) the queued requests with these ids — the
        queue-side half of host-driven eviction (Engine.evict). The
        queue representation stays this class's business."""
        req_ids = set(req_ids)
        removed = [r for r in self._queue if r.req_id in req_ids]
        if removed:
            self._queue = deque(r for r in self._queue
                                if r.req_id not in req_ids)
        return removed

    def take_admissions(self, can_admit=None):
        """Pop (request, slot) pairs while both a queued request and a
        free slot exist. FCFS: no reordering, no lookahead — a too-long
        request blocks the queue rather than being skipped (documented
        policy; admission fairness over utilization).

        `can_admit` (ISSUE 9): optional token-budget gate consulted on
        the queue head before it is popped — the paged engine passes
        the allocator's worst-case page check here, which turns
        admission from slot-count-based into page-budget-based. A False
        return BLOCKS the head (same FCFS policy: pages free as earlier
        requests finish, so the head is served next, never starved).
        NB: a True return may commit caller-side state (the paged
        allocator reserves pages in the same call), so the pair is
        always popped after a True."""
        out = []
        while self._queue and self._free:
            if can_admit is not None and not can_admit(self._queue[0]):
                break
            out.append((self._queue.popleft(), self._free.pop(0)))
        return out

    def release(self, slot):
        assert 0 <= slot < self.n_slots and slot not in self._free
        self._free.append(slot)
        self._free.sort()
        self.n_recycled += 1

    def bucket(self, prompt_len):
        """Pad target for a prompt, recorded against the ladder bound."""
        b = prompt_bucket(prompt_len, self.max_seq_len)
        self.seen_buckets.add(b)
        assert self.seen_buckets <= set(self.ladder), (
            f"bucket {b} escaped the ladder {self.ladder}"
        )
        return b
