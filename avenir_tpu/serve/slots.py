"""Fixed-shape KV slot pool (ISSUE 2 tentpole, part 1).

The static-shape analogue of vLLM's paged KV blocks, shaped for TPU jit:
ONE pool of `n_slots` sequence slots, each a full-width KV column plus
the per-slot decode state (last logits, raw rng key data, position,
sampling params). The whole pool is a NamedTuple pytree donated through
the engine's two jitted entry points (admission-prefill and the batched
decode step), so requests swapping in and out of slots NEVER change a
shape and NEVER retrace — occupancy is a (B,) mask the host passes as a
traced argument, not part of any compiled shape.

Slot hygiene invariant (why recycling needs no cache scrub): a cache
row at position p is only attendable once a query's position reaches p,
and every code path writes position p (prefill for p < prompt_len, the
decode step at p == pos) before any query attends that far — so stale
K/V from a previous occupant is always masked (exactly-zero softmax
weight) until the moment it is overwritten.

RNG is stored as raw uint32 key data (`jax.random.key_data` layout) and
wrapped back into typed keys inside the step: raw data indexes/donates
like any other array, with bit-exact round-tripping.

This slab is the `kv_impl="slab"` default. `serve/pages.py` (ISSUE 9)
is the paged alternative: same per-slot decode state, but KV lives in
a pool of page_size-token blocks behind per-slot page tables — a slot
then pays HBM for the tokens it actually holds instead of a full
T_max column, shared prompt prefixes are stored once, and the slot
hygiene invariant above carries over page-for-row (a page is only
attendable at positions the owning sequence has already written).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SlotPool(NamedTuple):
    # k/v are bare arrays under kv_dtype='bf16' and ops/kv_quant.QuantKV
    # (int8 data + per-head fp32 scale) pytrees under kv_dtype='int8' —
    # every consumer tree-maps, so the pool shape never forks the code
    k: jax.Array            # (L, B_slots, T_max, H_kv, D)
    v: jax.Array            # (L, B_slots, T_max, H_kv, D)
    logits: jax.Array       # (B_slots, V) fp32 — last-position logits
    rng: jax.Array          # (B_slots, key_words) uint32 raw key data
    pos: jax.Array          # (B_slots,) int32 — next cache write position
    temperature: jax.Array  # (B_slots,) f32
    top_k: jax.Array        # (B_slots,) int32; V means "no top-k"


class DraftPool(NamedTuple):
    """Per-slot draft-model state for speculative decoding (ISSUE 11).
    The draft keeps a DENSE slab cache whatever the target's kv_impl /
    kv_dtype — it is small by design (that is the whole economics), so
    paging or quantizing it would buy noise. `prev`/`prev_n` carry the
    tokens the slot emitted LAST tick: each spec tick starts by
    catching the draft cache up on them (fixed (k+1)-wide forward,
    count-masked), because the draft only ever saw its own proposals —
    the correction/bonus token and any rejection live in `prev` alone."""

    k: jax.Array            # (L_d, B_slots, W_d, H_d, D_d)
    v: jax.Array
    rng: jax.Array          # (B_slots, key_words) uint32 — DRAFT keys
    pos: jax.Array          # (B_slots,) int32 — draft tokens committed
    prev: jax.Array         # (B_slots, k+1) int32 — last tick's emissions
    prev_n: jax.Array       # (B_slots,) int32 >= 1


def key_data_width():
    """Words per raw key under the process default PRNG impl (2 for
    threefry2x32)."""
    return jax.random.key_data(jax.random.key(0)).shape[-1]


def init_slot_pool(*, n_layer, n_slots, max_t, n_kv_head, head_dim,
                   vocab_size, dtype, kv_dtype="bf16"):
    """`kv_dtype` (ISSUE 11): 'bf16' stores K/V in the model compute
    dtype; 'int8' swaps the k/v leaves for ops/kv_quant.QuantKV pairs
    (per-head absmax scales ride beside the data) — same pytree
    positions, so donation and the jitted step signatures are
    untouched."""
    kv_shape = (n_layer, n_slots, max_t, n_kv_head, head_dim)
    if kv_dtype == "int8":
        from avenir_tpu.ops.kv_quant import init_quant_kv

        k = init_quant_kv(kv_shape)
        v = init_quant_kv(kv_shape)
    else:
        k = jnp.zeros(kv_shape, dtype)
        v = jnp.zeros(kv_shape, dtype)
    return SlotPool(
        k=k,
        v=v,
        logits=jnp.zeros((n_slots, vocab_size), jnp.float32),
        rng=jnp.zeros((n_slots, key_data_width()), jnp.uint32),
        pos=jnp.zeros((n_slots,), jnp.int32),
        temperature=jnp.ones((n_slots,), jnp.float32),
        top_k=jnp.full((n_slots,), vocab_size, jnp.int32),
    )


def init_draft_pool(*, n_layer, n_slots, max_t, n_kv_head, head_dim,
                    spec_k, dtype):
    """Draft-side state for spec decoding. `max_t` must already include
    the speculative scratch tail (engine passes T_max + spec_k): the
    catch-up writes a (k+1)-wide block at positions up to T_max-1 and
    proposals extend to T_max+k-1 — all masked-until-overwritten, the
    slab hygiene invariant."""
    kv_shape = (n_layer, n_slots, max_t, n_kv_head, head_dim)
    return DraftPool(
        k=jnp.zeros(kv_shape, dtype),
        v=jnp.zeros(kv_shape, dtype),
        rng=jnp.zeros((n_slots, key_data_width()), jnp.uint32),
        pos=jnp.zeros((n_slots,), jnp.int32),
        prev=jnp.zeros((n_slots, spec_k + 1), jnp.int32),
        prev_n=jnp.ones((n_slots,), jnp.int32),
    )
