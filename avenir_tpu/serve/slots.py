"""Fixed-shape KV slot pool (ISSUE 2 tentpole, part 1).

The static-shape analogue of vLLM's paged KV blocks, shaped for TPU jit:
ONE pool of `n_slots` sequence slots, each a full-width KV column plus
the per-slot decode state (last logits, raw rng key data, position,
sampling params). The whole pool is a NamedTuple pytree donated through
the engine's two jitted entry points (admission-prefill and the batched
decode step), so requests swapping in and out of slots NEVER change a
shape and NEVER retrace — occupancy is a (B,) mask the host passes as a
traced argument, not part of any compiled shape.

Slot hygiene invariant (why recycling needs no cache scrub): a cache
row at position p is only attendable once a query's position reaches p,
and every code path writes position p (prefill for p < prompt_len, the
decode step at p == pos) before any query attends that far — so stale
K/V from a previous occupant is always masked (exactly-zero softmax
weight) until the moment it is overwritten.

RNG is stored as raw uint32 key data (`jax.random.key_data` layout) and
wrapped back into typed keys inside the step: raw data indexes/donates
like any other array, with bit-exact round-tripping.

This slab is the `kv_impl="slab"` default. `serve/pages.py` (ISSUE 9)
is the paged alternative: same per-slot decode state, but KV lives in
a pool of page_size-token blocks behind per-slot page tables — a slot
then pays HBM for the tokens it actually holds instead of a full
T_max column, shared prompt prefixes are stored once, and the slot
hygiene invariant above carries over page-for-row (a page is only
attendable at positions the owning sequence has already written).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SlotPool(NamedTuple):
    k: jax.Array            # (L, B_slots, T_max, H_kv, D)
    v: jax.Array            # (L, B_slots, T_max, H_kv, D)
    logits: jax.Array       # (B_slots, V) fp32 — last-position logits
    rng: jax.Array          # (B_slots, key_words) uint32 raw key data
    pos: jax.Array          # (B_slots,) int32 — next cache write position
    temperature: jax.Array  # (B_slots,) f32
    top_k: jax.Array        # (B_slots,) int32; V means "no top-k"


def key_data_width():
    """Words per raw key under the process default PRNG impl (2 for
    threefry2x32)."""
    return jax.random.key_data(jax.random.key(0)).shape[-1]


def init_slot_pool(*, n_layer, n_slots, max_t, n_kv_head, head_dim,
                   vocab_size, dtype):
    kv_shape = (n_layer, n_slots, max_t, n_kv_head, head_dim)
    return SlotPool(
        k=jnp.zeros(kv_shape, dtype),
        v=jnp.zeros(kv_shape, dtype),
        logits=jnp.zeros((n_slots, vocab_size), jnp.float32),
        rng=jnp.zeros((n_slots, key_data_width()), jnp.uint32),
        pos=jnp.zeros((n_slots,), jnp.int32),
        temperature=jnp.ones((n_slots,), jnp.float32),
        top_k=jnp.full((n_slots,), vocab_size, jnp.int32),
    )
