"""Live weight lifecycle (ISSUE 20 tentpole): zero-downtime rolling
rollout, canary analysis, anomaly-triggered auto-rollback.

A production fleet's weights change daily; until this module, ours were
frozen at spawn. `Router.rollout(version)` arms a RolloutManager that
Router.step drives one poll per fleet iteration:

    BASELINE      collect fleet TTFT/TPOT windows under the OLD
                  version — the oldest-half reference the drift
                  detectors (obs/anomaly.py, ISSUE 14) compare against
    CANARY_SWAP   drain ONE replica, swap it to the target version
                  (drain -> re-hello/reload -> prewarm -> rejoin), and
    CANARY        stream only ITS terminal records into the same
                  series: the detector's oldest-half baseline is the
                  fleet, its recent windows are the canary, so a fire
                  IS "the canary drifted from the fleet"
    ROLLING       canary passed: swap the remaining replicas one at a
                  time, each gated on fleet health (every other
                  non-dead replica HEALTHY) and the SLO burn rate, so
                  the fleet never dips below attainment for a swap;
                  target-version replicas keep feeding the detectors
    ROLLING_BACK  a detector fired (or the version-mixing window blew
                  its bound): converge every target-version replica
                  back to the previous generation with the same
                  drain/swap machinery — no gating, rollback is the
                  emergency path
    DONE          converged (forward, or rolled back)

Robustness contract (the chaos drill pins all of these):
  * a SIGKILL'd replica mid-swap respawns on the TARGET version — its
    model spec is retargeted BEFORE its drain begins, so the
    RespawnSupervisor's revive() re-hello cannot resurrect old weights
    (during ROLLING every replica is retargeted up front, so ANY death
    lands on target and counts as its swap);
  * a rollback mid-rollout retargets every spec back first, then
    converges — deaths during rollback respawn OLD;
  * 0 accepted requests lost: swaps only ever run on a DRAINED idle
    replica, and deaths take the router's normal failover/requeue path;
  * the version-mixing window (first target-serving moment ->
    convergence) is measured, bounded by `max_mixing_s`, and a breach
    triggers rollback rather than an indefinitely mixed fleet.

KV safety: a weight swap invalidates the replica's prefix chain
(reset_host_state / worker reload) and drops its cache-map
advertisement; the version-keyed FleetCacheMap and the router's
version-fenced pull/handoff paths (ISSUE 20 satellites) guarantee no
chain ever crosses a weight-version boundary — stale KV under new
weights is silently wrong output, not a perf loss.

Every decision is an auditable `rollout` trace event with evidence
attrs plus a host-side decision log — the autoscaler's `scale`-event
discipline applied to the weight control plane.
"""

import os
import re

from avenir_tpu.obs.anomaly import AnomalyEngine, Detector
from avenir_tpu.serve.replica import DEAD, DRAINING, HEALTHY

# phases
BASELINE = "baseline"
CANARY_SWAP = "canary_swap"
CANARY = "canary"
ROLLING = "rolling"
ROLLING_BACK = "rolling_back"
DONE = "done"

_ORDINALS = {}  # version label -> ordinal, for labels with no digits


def version_number(label):
    """Numeric value for the weight_version gauge: the label's trailing
    integer (iter-00000120 -> 120), else a stable order-seen ordinal —
    gauges need numbers, version labels are strings."""
    m = re.search(r"(\d+)\s*$", str(label))
    if m:
        return int(m.group(1))
    return _ORDINALS.setdefault(str(label), len(_ORDINALS) + 1)


def resolve_generation(version, out_dir):
    """(label, worker model spec) for a committed checkpoint generation
    under `out_dir` (checkpoint/io.py generation ring). `version` is
    'latest'/None (newest), an iteration number, or a generation
    directory basename — the train->serve promotion path: a committed
    generation becomes a servable {'kind': 'checkpoint'} hello spec."""
    from avenir_tpu.checkpoint.io import list_generations

    gens = list_generations(out_dir)
    if not gens:
        raise FileNotFoundError(
            f"no committed checkpoint generations under {out_dir!r}")
    if version in (None, "latest"):
        it, form, path = gens[0]
    else:
        want = str(version)
        for it, form, path in gens:
            if want in (str(it), f"iter-{it:08d}",
                        os.path.basename(path)):
                break
        else:
            raise KeyError(
                f"no generation matching {version!r} under {out_dir!r} "
                f"(have: {[os.path.basename(p) for _, _, p in gens]})")
    return os.path.basename(path), {"kind": "checkpoint", "out_dir": path}


def canary_detectors(params=None):
    """The canary analysis panel: TTFT/TPOT oldest-half drift plus
    spec accept-rate collapse (fed only on spec-decoding fleets), with
    per-detector knob overrides ({name: {knob: value}}). cooldown_s=0
    on purpose — the first emission triggers the rollback, there is
    nothing to re-fire after. min_rel is raised to 0.5 over the fleet
    panel's 0.25: a just-swapped canary rejoins EMPTY, so fair-share
    dispatch briefly overloads it relative to its still-loaded peers —
    a few-tenths relative rise is that rebalancing bias (observed live:
    a clean canary at rel 0.34, z 4.1), while genuinely bad weights
    show up in multiples, not tenths."""
    p = dict(params or {})

    def _mk(name, **defaults):
        return Detector(name, **{**defaults, **p.get(name, {})})

    return [
        _mk("ttft_drift", cooldown_s=0.0, min_rel=0.5),
        _mk("tpot_drift", cooldown_s=0.0, min_rel=0.5),
        _mk("accept_rate_collapse", cooldown_s=0.0),
    ]


class RolloutManager:
    """One rollout campaign over a Router fleet. Construct via
    `Router.rollout(...)`; `Router.step` calls `poll()` (state machine)
    and `observe()` (terminal-record feed) each fleet iteration.

    Knobs (docs/SERVING.md "Weight lifecycle" table):
      baseline_min_requests  fleet terminal records required before the
                             canary swap begins (0 skips straight to
                             the swap — no-load maintenance rollouts)
      canary_min_requests    canary-served records required for a PASS
                             verdict (0 = health-gated swap only)
      baseline_hold_s /      minimum phase durations, in fleet-clock
      canary_hold_s          seconds — the drift detectors need whole
                             windows, not just request counts (default
                             8 x window_s each)
      window_s               detector window width (obs/series.Series)
      detector_params        per-detector overrides for the canary
                             panel ({'ttft_drift': {'sustain': 2}, ...})
      slo / hold_burn        optional SLOEngine: a forward swap waits
                             while burn_rate() > hold_burn (rollback
                             never waits — it IS the mitigation)
      max_mixing_s           version-mixing bound: first target-serving
                             moment -> convergence; a breach triggers
                             rollback with reason
                             'mixing_window_exceeded'
      settle_s               detector blackout after every swap lands
                             (default 6 x window_s): taking a replica
                             out for its swap is a SELF-INDUCED
                             capacity transient — requests that queued
                             while it drained finish with inflated
                             TTFT, and feeding them would read the
                             campaign's own mechanics as a regression
                             of the new weights (observed live: a
                             clean rollout rolling itself back on z 8.6
                             'drift' that was just the 2/3-capacity
                             window). Records produced while a swap is
                             in flight, or within settle_s after one,
                             never reach the detectors
      canary_id              replica id to canary (default: the lowest
                             healthy id)
    """

    def __init__(self, router, version, *, state=None, spec=None,
                 out_dir=None, slo=None, hold_burn=1.0,
                 baseline_min_requests=8, canary_min_requests=8,
                 baseline_hold_s=None, canary_hold_s=None,
                 window_s=0.5, detector_params=None, detectors=None,
                 max_mixing_s=120.0, settle_s=None, canary_id=None,
                 echo=print):
        self.r = router
        self._reg = router._reg
        self._clock = router._clock
        self._echo = echo
        self.slo = slo
        self.hold_burn = float(hold_burn)
        self.baseline_min_requests = int(baseline_min_requests)
        self.canary_min_requests = int(canary_min_requests)
        self.window_s = float(window_s)
        self.baseline_hold_s = (float(baseline_hold_s)
                                if baseline_hold_s is not None
                                else 8.0 * self.window_s)
        self.canary_hold_s = (float(canary_hold_s)
                              if canary_hold_s is not None
                              else 8.0 * self.window_s)
        self.max_mixing_s = float(max_mixing_s)
        self.settle_s = (float(settle_s) if settle_s is not None
                         else 6.0 * self.window_s)
        self._canary_pick = canary_id

        # -- resolve the target (and remember the old world) --
        if out_dir is not None and spec is None and state is None:
            label, spec = resolve_generation(version, out_dir)
            if version in (None, "latest"):
                version = label
        self.version = str(version)
        vers = {getattr(rep, "weight_version", "0")
                for rep in router.replicas if rep.state != DEAD}
        assert len(vers) <= 1, (
            f"fleet is version-mixed at rollout start ({sorted(vers)}) "
            "— converge (or roll back) the previous campaign first")
        self.old_version = vers.pop() if vers else "0"
        assert self.version != self.old_version, (
            f"fleet already serves {self.version!r}")
        if router.backend == "process":
            if spec is None:
                raise ValueError(
                    "process-backend rollout needs a worker model spec "
                    "— pass out_dir=<generation ring> (preferred) or "
                    "spec=<hello model spec>")
            self._target_spec, self._target_state = spec, None
            self._old_spec = router._spec
        else:
            if state is None and out_dir is not None:
                # inproc promotion from the generation ring: rebuild
                # the generation's model and take its parameter state
                from flax import nnx

                from avenir_tpu.checkpoint.io import load_checkpoint
                from avenir_tpu.sampling import model_from_checkpoint

                _, gen_spec = resolve_generation(version, out_dir)
                m, _ = model_from_checkpoint(
                    load_checkpoint(gen_spec["out_dir"]))
                state = nnx.split(m)[1]
            if state is None:
                raise ValueError(
                    "in-process rollout needs the target parameter "
                    "state — pass state=<nnx state> or out_dir=...")
            self._target_state, self._target_spec = state, None
            # numpy snapshot of the OLD weights for rollback: after the
            # canary swap the shared module holds target arrays, and
            # jax arrays in the old engines' snapshots are refs we must
            # not rely on staying alive
            import numpy as np
            from flax import nnx
            import jax

            self._old_state = jax.tree.map(
                lambda x: np.asarray(x), nnx.split(router._model)[1])
            self._old_spec = None

        # -- canary analysis engine (ISSUE 14 reused wholesale): same
        # Series/Detector/emission machinery, private store — BASELINE
        # feeds the fleet, CANARY feeds only the canary, so the drift
        # method's oldest-half baseline is by construction the
        # fleet-vs-canary comparison the verdict needs --
        self._ae = AnomalyEngine(
            registry=self._reg, sink=getattr(router, "sink", None),
            tracer=router.tracer, clock=self._clock,
            detectors=(detectors if detectors is not None
                       else canary_detectors(detector_params)),
            window_s=self.window_s, check_interval_s=self.window_s)

        self.phase = BASELINE
        self.active = True
        self.rolled_back = False
        self.rollback_reason = None
        self.decisions = []        # host-side audit log (bench artifact)
        self.canary_replica = None
        self._swapping = None      # replica_id mid-drain for its swap
        self._baseline_seen = 0
        self._canary_seen = 0
        self._t0 = self._clock()
        self._t_phase = self._t0
        self.t_mix_start = None    # first target-serving moment
        self.mixing_s = None       # measured at convergence
        self._tripped = None       # anomaly evidence awaiting poll()
        self._t_settle = None      # detector blackout end (post-swap)
        self._fired_seen = 0       # len(self._ae.fired) already handled
        self._retargeted = False   # fleet-wide spec retarget done?
        # pre-create so a clean campaign still exports all three
        self._reg.counter("rollouts")
        self._reg.counter("rollbacks")
        self._reg.counter("canary_anomalies")

    # -- audit --

    def _decide(self, action, *, reason=None, replica=None, now=None,
                **evidence):
        """One auditable lifecycle decision: trace event + host log +
        echo (counters are bumped by the callers that own them) — the
        autoscaler `scale` discipline applied to weights."""
        now = self._clock() if now is None else now
        rec = {"ts": round(now, 4), "action": action, "reason": reason,
               "replica": replica, "from_version": self.old_version,
               "to_version": self.version, "phase": self.phase,
               **{k: v for k, v in evidence.items() if v is not None}}
        self.decisions.append(rec)
        if self.r.tracer is not None:
            self.r.tracer.emit(
                None, "rollout", t=now,
                **{k: v for k, v in rec.items()
                   if k != "ts" and v is not None})
        self._echo(f"[rollout] {action}"
                   + (f" replica={replica}" if replica is not None else "")
                   + (f" reason={reason}" if reason else "")
                   + f" ({self.old_version} -> {self.version})")
        return rec

    def status(self):
        n_target = sum(
            1 for rep in self.r.replicas
            if rep.state != DEAD
            and getattr(rep, "weight_version", "0") == self.version)
        return {"phase": self.phase, "active": self.active,
                "from_version": self.old_version,
                "to_version": self.version,
                "rolled_back": self.rolled_back,
                "rollback_reason": self.rollback_reason,
                "canary_replica": self.canary_replica,
                "on_target": n_target,
                "replicas": len(self.r.replicas),
                "mixing_s": self.mixing_s,
                "decisions": len(self.decisions)}

    # -- lifecycle --

    def begin(self):
        self._reg.counter("rollouts").add(1)
        self._decide("begin", reason="requested",
                     baseline_min=self.baseline_min_requests,
                     canary_min=self.canary_min_requests,
                     max_mixing_s=self.max_mixing_s)
        return self

    # -- feeding (Router.step, after harvest) --

    def observe(self, finished, now=None):
        """Feed this step's terminal records into the canary analysis
        store. BASELINE feeds every replica (the oldest-half
        reference); CANARY feeds only the canary; ROLLING feeds every
        target-version replica (mid-rollout regressions must trip the
        same detectors). Rollback feeds nothing — the verdict is in."""
        if not self.active:
            return
        now = self._clock() if now is None else now
        if self.phase == BASELINE:
            recs = [f for f in finished
                    if getattr(f, "replica", None) is not None]
            self._baseline_seen += len(recs)
            self._ae.observe_finished(recs, t=now)
            return
        if self.phase not in (CANARY, ROLLING):
            # CANARY_SWAP drains old-version work (not the new
            # weights' records); ROLLING_BACK's verdict is already in
            return
        if self._swapping is not None or (
                self._t_settle is not None and now < self._t_settle):
            # detector blackout (see the settle_s knob): a swap in
            # flight — or its queue backlog still draining — is the
            # campaign's own capacity transient, not evidence about
            # the new weights
            return
        if self.phase == CANARY:
            recs = [f for f in finished
                    if getattr(f, "replica", None) == self.canary_replica]
            self._canary_seen += len(recs)
        else:  # ROLLING
            target_ids = {
                rep.replica_id for rep in self.r.replicas
                if rep.state != DEAD
                and getattr(rep, "weight_version", "0") == self.version}
            recs = [f for f in finished
                    if getattr(f, "replica", None) in target_ids]
        self._ae.observe_finished(recs, t=now)
        self._ae.check(now, context={"phase": self.phase,
                                     "to_version": self.version})
        fresh = self._ae.fired[self._fired_seen:]
        self._fired_seen = len(self._ae.fired)
        if fresh and self._tripped is None:
            self._tripped = fresh[0]
            if self.phase == CANARY:
                self._reg.counter("canary_anomalies").add(1)

    # -- the state machine (Router.step, before dispatch) --

    def poll(self, now=None):
        if not self.active:
            return
        now = self._clock() if now is None else now
        if self._tripped is not None and self.phase in (CANARY, ROLLING):
            self._start_rollback(now, "canary_anomaly"
                                 if self.phase == CANARY
                                 else "rollout_anomaly",
                                 anomaly=self._tripped)
        if (self.phase == ROLLING and self.t_mix_start is not None
                and now - self.t_mix_start > self.max_mixing_s):
            self._start_rollback(now, "mixing_window_exceeded",
                                 mixing_s=round(now - self.t_mix_start,
                                                3))
        if self.phase == BASELINE:
            self._poll_baseline(now)
        elif self.phase == CANARY_SWAP:
            self._poll_swap(now, self.version, on_done=self._canary_up)
        elif self.phase == CANARY:
            self._poll_canary(now)
        elif self.phase == ROLLING:
            self._poll_rolling(now, self.version, gated=True)
        elif self.phase == ROLLING_BACK:
            self._poll_rolling(now, self.old_version, gated=False)

    # -- phase bodies --

    def _poll_baseline(self, now):
        if (now - self._t_phase < self.baseline_hold_s
                and self.baseline_min_requests > 0):
            return
        if self._baseline_seen < self.baseline_min_requests:
            return
        canary = self._pick_canary()
        if canary is None:
            return  # no healthy replica right now — wait
        self.canary_replica = canary.replica_id
        # satellite: retarget the canary's respawn spec BEFORE its
        # drain — a SIGKILL anywhere mid-swap now respawns on TARGET
        self._retarget(canary, self.version)
        canary.drain()
        self._swapping = canary.replica_id
        self.phase = CANARY_SWAP
        self._t_phase = now
        self._decide("canary_start", replica=canary.replica_id, now=now,
                     baseline_requests=self._baseline_seen)

    def _canary_up(self, now):
        self.phase = CANARY
        self._t_phase = now
        if self.t_mix_start is None:
            self.t_mix_start = now  # first target-serving moment

    def _poll_canary(self, now):
        if (now - self._t_phase < self.canary_hold_s
                and self.canary_min_requests > 0):
            return
        if self._canary_seen < self.canary_min_requests:
            return
        self._decide("canary_pass", now=now, replica=self.canary_replica,
                     canary_requests=self._canary_seen,
                     held_s=round(now - self._t_phase, 3))
        # fleet-wide retarget: from here ANY death respawns on target
        # (and counts as that replica's swap) — a death mid-rollout can
        # never resurrect old weights
        self._retarget_fleet(self.version)
        self.phase = ROLLING
        self._t_phase = now

    def _poll_rolling(self, now, target, *, gated):
        if self._swapping is not None:
            self._poll_swap(now, target, on_done=None)
            if self._swapping is not None:
                return
        # converged? every non-dead replica on target and none draining
        pending = [rep for rep in self.r.replicas
                   if rep.state != DEAD
                   and getattr(rep, "weight_version", "0") != target]
        if not pending:
            if any(rep.state == DEAD and self._respawn_pending(rep)
                   for rep in self.r.replicas):
                return  # a respawn is owed; it will land on target
            self._finish(now)
            return
        nxt = self._next_victim(pending)
        if nxt is None:
            return
        if gated and not self._gate_ok(nxt):
            return
        self._retarget(nxt, target)
        nxt.drain()
        self._swapping = nxt.replica_id
        self._decide("swap_begin", replica=nxt.replica_id, now=now,
                     target=target)

    def _poll_swap(self, now, target, *, on_done):
        """Progress the in-flight swap: wait out the drain, then swap
        on the idle engine; a death mid-swap hands the replica to the
        supervisor (its spec is already retargeted) and the swap
        completes when the respawn reports the target version."""
        if self._swapping is None:
            return
        rep = self.r._rep(self._swapping)
        if rep.state == DEAD:
            # failover already requeued its work; the respawn (which
            # will hello with the retargeted spec) must land on target
            # before we move on
            if not self._respawn_pending(rep):
                # nobody will bring it back (inproc, or the supervisor
                # exhausted its budget): stop waiting on it — and if
                # this was the canary swap, fall back to BASELINE so
                # the next poll picks a fresh canary from the
                # survivors instead of polling a corpse forever
                self._decide("swap_dead", replica=rep.replica_id,
                             now=now, reason="respawn_exhausted")
                self._swapping = None
                if self.phase == CANARY_SWAP:
                    self.phase = BASELINE
            return
        if getattr(rep, "weight_version", "0") == target \
                and rep.state == HEALTHY:
            # respawned (or reloaded) onto target already
            self._swap_done(rep, now, on_done)
            return
        if rep.state != DRAINING:
            rep.drain()  # e.g. revived mid-swap: re-drain
            return
        if rep.busy:
            return  # still draining — in-flight work finishes first
        try:
            if self.r.backend == "process":
                rep.reload()
            else:
                rep.set_weights(
                    self._target_state if target == self.version
                    else self._old_state, target)
                rep.revive()  # DRAINING -> HEALTHY, work map intact
        except Exception as e:  # noqa: BLE001 — spawn/handshake refusal
            # a failed swap is a death: the supervisor (aimed at the
            # same retargeted spec) owns the retry with backoff
            self._decide("swap_failed", replica=rep.replica_id, now=now,
                         reason=repr(e))
            rep.last_error = e
            rep.mark_dead()
            self.r._failover(rep)
            return
        self._swap_done(rep, now, on_done)

    def _swap_done(self, rep, now, on_done):
        if self.r._cache_map is not None:
            # de-advertise NOW: the old version's chains are gone from
            # the engine, and the map must not hold them even until the
            # next refresh (which would re-key them anyway)
            self.r._cache_map.drop(rep.replica_id)
        self._swapping = None
        self._t_settle = now + self.settle_s
        self._decide("swap_done", replica=rep.replica_id, now=now,
                     version=getattr(rep, "weight_version", "0"))
        if self.t_mix_start is None:
            self.t_mix_start = now
        if on_done is not None:
            on_done(now)

    # -- rollback --

    def _start_rollback(self, now, reason, **evidence):
        if self.phase in (ROLLING_BACK, DONE):
            return
        self.rolled_back = True
        self.rollback_reason = reason
        self._reg.counter("rollbacks").add(1)
        self._decide("rollback_begin", reason=reason, now=now,
                     **{k: v for k, v in evidence.items()})
        # retarget the whole fleet back FIRST: any death from here
        # respawns on the old version. The inproc module is restored
        # immediately too — swapped engines keep serving target via
        # their own split snapshots until their rollback swap runs
        self._retarget_fleet(self.old_version)
        if self.r.backend != "process":
            from flax import nnx

            nnx.update(self.r._model, self._old_state)
        self._swapping = None
        self._tripped = None
        self.phase = ROLLING_BACK
        self._t_phase = now

    def _finish(self, now):
        if self.t_mix_start is not None:
            self.mixing_s = round(now - self.t_mix_start, 4)
        self.phase = DONE
        self.active = False
        if self.rolled_back:
            self._decide("rollback_done", reason=self.rollback_reason,
                         now=now, mixing_s=self.mixing_s)
        else:
            self._decide("done", now=now, mixing_s=self.mixing_s,
                         swaps=sum(1 for d in self.decisions
                                   if d["action"] == "swap_done"))

    # -- helpers --

    def _pick_canary(self):
        if self._canary_pick is not None:
            rep = self.r._rep(self._canary_pick)
            return rep if rep.state == HEALTHY else None
        cands = [rep for rep in self.r.replicas
                 if rep.state == HEALTHY]
        return min(cands, key=lambda rep: rep.replica_id) \
            if cands else None

    def _next_victim(self, pending):
        cands = [rep for rep in pending if rep.state == HEALTHY]
        return min(cands, key=lambda rep: rep.replica_id) \
            if cands else None

    def _gate_ok(self, victim):
        """SLO-floor gate for a FORWARD swap: every other non-dead
        replica healthy (taking one out must not stack on an existing
        degradation) and — when an SLOEngine is attached — the burn
        rate at or under `hold_burn`. Rollback never gates."""
        for rep in self.r.replicas:
            if rep is victim or rep.state == DEAD:
                continue
            if rep.state != HEALTHY:
                return False
        if self.slo is not None:
            burn = self.slo.burn_rate()
            if burn is not None and burn > self.hold_burn:
                return False
        return True

    def _respawn_pending(self, rep):
        sup = self.r._supervisor
        return sup is not None and not sup.exhausted(rep)

    def _retarget(self, rep, target):
        """Aim ONE replica's future hellos at `target` (process
        backend); the inproc swap needs no per-replica retarget — the
        shared module plus set_weights is the whole story."""
        if self.r.backend == "process":
            rep.set_model_spec(
                self._target_spec if target == self.version
                else self._old_spec, version=target)

    def _retarget_fleet(self, target):
        """Aim the WHOLE fleet — every replica's respawn spec and the
        router's replica-build recipe — at `target`, so deaths respawn
        onto it and autoscaler growth spawns it."""
        self._retargeted = target == self.version
        if self.r.backend == "process":
            self.r._spec = (self._target_spec
                            if target == self.version else self._old_spec)
            for rep in self.r.replicas:
                self._retarget(rep, target)
        self.r._engine_kwargs["weight_version"] = str(target)


__all__ = ["RolloutManager", "version_number", "resolve_generation",
           "canary_detectors", "BASELINE", "CANARY", "ROLLING",
           "ROLLING_BACK", "DONE"]
