"""Router-side fleet cache map (ISSUE 16 tentpole, layer 2).

The Router already mirrors every replica's page BUDGET (free pages,
util, hit rate) off the heartbeat, but stayed blind to what each cache
CONTAINS — so it cannot know that the prompt it is about to dispatch to
replica A sits fully prefilled on replica B. This module holds the
content view: per-replica bounded chain summaries (the allocator's
`chain_summary()` wire form, shipped as step-reply deltas by process
workers and read directly from in-process engines), with staleness
accounting, answering

    match(prompt)       -> {replica_id: deepest shared-chain tokens}
    best_match(prompt)  -> (replica_id, deepest shared-chain tokens)

Matching is digest-based: a summary node is keyed by the blake2b digest
of its full root token path (`pages.chain_digest`), so the map compares
a prompt against a REMOTE replica's cache by digesting the prompt's own
prefixes — no raw token chains ever cross the wire. Depths are the
summary's `n_tokens` values (whole registered pages), so a match may
overstate the attach an actual admission would get by up to one page
(`plan()` caps `shared_len` at len(prompt)-1 and can extend into a
partially matching page) — this is TELEMETRY, feeding the counterfactual
reuse auditor (serve/router.py), never routing; PR 17's affinity router
is the consumer that must tolerate exactly this approximation.

Staleness: each update stamps the fleet clock; a dead replica's summary
is dropped by the router's failover path, so a corpse's cache content
never keeps advertising itself (the `_EngineProxy.clear()` rule).

Version keying (ISSUE 20): each update also carries the advertising
replica's `weight_version`, and match()/best_match() take the fleet's
current version view — an advertisement recorded under a different
version than its replica NOW serves never matches, and a consumer can
restrict matches to its own version. KV is only reusable under the
exact weights that produced it: attaching (or pulling) a chain across a
weight-version boundary would decode new weights against old-weights KV
— silently wrong output, not a perf loss. The pre-ISSUE-20 map was
version-blind, which made every weight swap a correctness hazard.
"""

import time

from avenir_tpu.serve.pages import chain_digest


def merge_chain_delta(state, delta):
    """Apply one `take_chain_delta()` wire dict to a summary dict —
    THE merge rule (shared by `_EngineProxy.apply_chain_delta` and the
    parity tests): apply every delta in order onto {} and you have the
    direct `chain_summary()`, exactly."""
    state.update(delta.get("upd") or {})
    for d in delta.get("gone") or ():
        state.pop(d, None)
    return state


class FleetCacheMap:
    """Per-replica chain summaries + staleness, the router's content
    view of fleet cache state. Pure host dict bookkeeping — update()
    cost is one dict swap per replica per step, match() cost is one
    digest per DISTINCT advertised depth <= len(prompt)."""

    def __init__(self, clock=None):
        self._clock = clock if clock is not None else time.perf_counter
        self._nodes = {}   # replica_id -> {digest: [n_tok, depth, ref,
        #                                            hits, last_use]}
        self._stamp = {}   # replica_id -> fleet-clock update time
        self._ver = {}     # replica_id -> weight_version at update time

    def update(self, replica_id, nodes, now=None, version=None):
        """Replace one replica's summary (inproc replicas hand the
        direct summary; process replicas hand the delta-merged mirror).
        `version` records the weight version the advertising replica
        served when the summary was taken — the key match() compares
        against the fleet's CURRENT version view (ISSUE 20)."""
        self._nodes[replica_id] = dict(nodes or {})
        self._stamp[replica_id] = (self._clock() if now is None
                                   else float(now))
        self._ver[replica_id] = (None if version is None
                                 else str(version))

    def drop(self, replica_id):
        """Forget a replica (death/retire/weight swap): a corpse's —
        or a previous weight version's — cache content must not keep
        winning best_match."""
        self._nodes.pop(replica_id, None)
        self._stamp.pop(replica_id, None)
        self._ver.pop(replica_id, None)

    def version(self, replica_id):
        """Weight version this replica's summary was recorded under
        (None when unversioned — pre-swap updates or tests)."""
        return self._ver.get(replica_id)

    def replicas(self):
        return sorted(self._nodes)

    def nodes(self, replica_id):
        return self._nodes.get(replica_id, {})

    def staleness_s(self, replica_id, now=None):
        """Seconds since this replica's summary was refreshed (None if
        unknown) — the consumer's freshness check."""
        t = self._stamp.get(replica_id)
        if t is None:
            return None
        return (self._clock() if now is None else float(now)) - t

    def match(self, prompt, versions=None):
        """{replica_id: deepest matching chain depth in TOKENS} for
        `prompt` against every tracked summary. Each distinct advertised
        depth is digested at most once per call.

        `versions` (ISSUE 20): {replica_id: current weight_version} —
        the fleet's live view. When given, a replica whose summary was
        recorded under a DIFFERENT version than it now serves (or whose
        current version is unknown) scores 0: a post-swap replica's old
        advertisement must never win placement or source a pull. None
        preserves the version-blind behavior for single-version fleets
        and telemetry-only consumers."""
        prompt = [int(t) for t in prompt]
        dig = {}  # depth -> digest of prompt[:depth], computed lazily
        out = {}
        for rid, nodes in self._nodes.items():
            if versions is not None and (
                    versions.get(rid) is None
                    or self._ver.get(rid) != str(versions[rid])):
                out[rid] = 0
                continue
            best = 0
            for d, node in nodes.items():
                n = int(node[0])
                if n <= best or n > len(prompt):
                    continue
                got = dig.get(n)
                if got is None:
                    got = dig[n] = chain_digest(prompt[:n])
                if got == d:
                    best = n
            out[rid] = best
        return out

    def best_match(self, prompt, versions=None):
        """(replica_id, deepest shared-chain tokens) — the fleet-best
        placement for `prompt`, or (None, 0) when no tracked replica
        shares any prefix. Deterministic tie-break on replica id.
        `versions` filters exactly as in match()."""
        m = self.match(prompt, versions=versions)
        best_rid, best_n = None, 0
        for rid in sorted(m, key=str):
            if m[rid] > best_n:
                best_rid, best_n = rid, m[rid]
        return best_rid, best_n
