"""Slot-based continuous-batching inference engine (ISSUE 2 tentpole,
part 3).

Synchronous and network-free (the sandbox has no sockets): callers
drive `Engine.submit()` / `step()` / `drain()` directly — a transport
in front of this would own no generation logic. One `step()` is one
scheduler iteration:

  1. admission — for every (queued request, free slot) pair, ONE jitted
     prefill-into-slot dispatch per request: forward the bucketed
     prompt through a temp single-sequence cache, then splice K/V, last
     logits, rng, position and sampling params into the donated pool at
     a *traced* slot index (no retrace per slot).
  2. decode — ONE batched dispatch across all slots: per-slot sampling
     (each slot consumes only its own rng key -> bit-identical to B=1),
     then the shared `_forward_cached` single-token step at per-slot
     positions.
  3. harvest — the per-iteration device-to-host token fetch (the only
     fence), incremental per-slot detokenization, stop/budget checks,
     and slot recycling the moment a sequence finishes.

Parity contract (pinned by tests/test_serve.py): every request's token
stream is bit-identical to `generate_cached(model, req.rng,
prompt[None], ...)` run alone, regardless of arrival order, co-tenants,
slot eviction or bucketing. This holds because (a) sampling is per-row
with per-slot keys, (b) attention over a longer masked cache tail is
exact on this backend (established by the one-shot parity tests), and
(c) prefill uses the SAME prompt bucket as the one-shot path — which
also makes MoE expert-capacity behavior identical at prefill. (c) has
one clamp-region exception: when max_seq_len < block_size AND a
prompt's power-of-2 bucket exceeds max_seq_len, the engine pads to
max_seq_len while one-shot pads wider — harmless for dense models (pad
rows are masked to exactly-zero weight at any length), but MoE prefill
capacity counts padded tokens, so Mixtral parity there needs the
non-binding regime. Which is also the one genuine batching caveat at
decode: Mixtral with a *binding* capacity (ceil(K*B*cf/E) < B) is
batch-composition-dependent by construction — the engine warns once;
with cf*K >= E (capacity >= batch) decode never drops and parity is
exact (docs/SERVING.md).

Compile budget: one prefill trace per prompt bucket ever seen plus ONE
decode-step trace for the engine's lifetime, asserted against the
bucket ladder after every step. Admission and recycling are host-side
bookkeeping plus traced arguments — occupancy changes never retrace.
"""

import dataclasses
import functools
import statistics
import time
import warnings
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from avenir_tpu.infer.decode import (
    KVCache,
    _forward_cached,
    _sample_rows,
    _normalize_stop,
    bucket_ladder,
    init_cache,
    prompt_bucket,
)
from avenir_tpu.infer.spec import draft_key, ngram_propose, \
    ngram_q_logits, spec_accept
from avenir_tpu.obs import NullSink, get_registry, span
from avenir_tpu.ops.kv_quant import init_quant_kv, quant_slab_kv_ops
from avenir_tpu.serve.pages import PagedHost, PagedPool, \
    init_paged_pool, paged_kv_ops
from avenir_tpu.serve.scheduler import FCFSScheduler, Request
from avenir_tpu.serve.slots import SlotPool, init_draft_pool, \
    init_slot_pool


def _splice_slot(dst, src, slot):
    """Tree-mapped per-slot splice: update `dst`'s slot column (axis 1
    after the layer axis) with `src`'s single-sequence column. Serves
    dense arrays and QuantKV (data, scale) pairs with one code path —
    each leaf's start-index tuple is rank-matched."""
    return jax.tree.map(
        lambda d, s: jax.lax.dynamic_update_slice(
            d, s.astype(d.dtype), (0, slot) + (0,) * (d.ndim - 2)),
        dst, src)


def _seed_spec_slot(pool, dpool, dtmp, slot, logits_row, key_data,
                    dkey_data, temp, top_k, dpos):
    """The spec-admission tail, shared by the slab admit and the paged
    chunk fn (one behavior, one site): sample the request's FIRST token
    from its prefill logits with the slot's own key — the same split
    the first sequential tick would consume, which is what makes greedy
    spec output bit-identical from token one — then splice the draft's
    prefilled cache, keys, and catch-up seed (prev=[tail], prev_n=1)
    into the slot. Idempotent given the ORIGINAL request key, so the
    uniform paged chunk fn can run it every chunk and only the final
    chunk's values survive. Returns (pool, dpool, tail scalar)."""
    keys1 = jax.random.wrap_key_data(key_data[None])
    keys1, tail = _sample_rows(keys1, logits_row, temp[None], top_k[None])
    upd = jax.lax.dynamic_update_slice
    prev_row = jnp.zeros((1, dpool.prev.shape[1]), jnp.int32).at[
        0, 0].set(tail[0].astype(jnp.int32))
    pool = pool._replace(
        rng=upd(pool.rng, jax.random.key_data(keys1), (slot, 0)))
    dpool = dpool._replace(
        k=_splice_slot(dpool.k, dtmp.k, slot),
        v=_splice_slot(dpool.v, dtmp.v, slot),
        rng=upd(dpool.rng, dkey_data[None], (slot, 0)),
        pos=upd(dpool.pos, dpos[None].astype(jnp.int32), (slot,)),
        prev=upd(dpool.prev, prev_row, (slot, 0)),
        prev_n=upd(dpool.prev_n, jnp.ones((1,), jnp.int32), (slot,)),
    )
    return pool, dpool, tail[0]


@dataclasses.dataclass
class FinishedRequest:
    req_id: int
    tokens: List[int]          # prompt + emitted (stop token included)
    n_prompt: int
    n_out: int
    finish_reason: str         # 'stop' | 'length' | 'timeout' | 'rejected'
    text: Optional[str]        # detokenized, when a codec was given
    ttft_ms: Optional[float]   # None: timed out before the first token
    tpot_ms: float
    # which limit a 'rejected' refusal hit: 'max_seq_len' (slab / model
    # positions) or 'page_budget' (paged: max_pages_per_seq * page_size)
    reject_limit: Optional[str] = None


class _Live:
    """Host-side per-slot record while a request occupies a slot."""

    def __init__(self, req):
        self.req = req
        self.emitted = []
        self.text = "" if req is not None else None
        self.t_first = None
        self.t_last = None
        # spec decoding (ISSUE 11): the request's first token is
        # sampled at admission (inside the prefill dispatch, consuming
        # the slot rng exactly like the first sequential tick) and
        # harvested — prepended — with the slot's first verify tick
        self.pending = []
        # adaptive spec_k (ISSUE 18): this slot's current effective k
        # (a rung of the engine's k ladder; the full cap unless
        # spec_k='auto' walks it) and its accept-rate EWMA
        self.k_eff = None
        self.acc_ewma = None
        # ngram self-draft (ISSUE 18): the request's full host-side
        # token context — prompt + every token sampled so far — the
        # suffix-match proposer scans each tick (None = model draft).
        # `tail` carries the last SAMPLED token separately: it is the
        # verify block's first input (decode-critical), while ctx only
        # ever feeds the proposer — a desynced/corrupt lookup context
        # must cost speed, never correctness
        self.ctx = None
        self.tail = None


class Engine:
    """Continuous-batching driver over the jitted KV-cache decode path.

    Works for GPT / Llama / Mixtral in both layer layouts — everything
    model-specific lives in `infer.decode._forward_cached`, which the
    engine reuses rather than forking.
    """

    def __init__(self, model, *, n_slots=4, max_seq_len=None,
                 detokenize: Optional[Callable] = None, registry=None,
                 sink=None, seed=0, clock=None, kv_impl="slab",
                 page_size=16, n_pages=None, max_pages_per_seq=None,
                 prefill_chunk=None, prefix_sharing=True,
                 paged_attn_impl="auto", tracer=None, kv_dtype="bf16",
                 spec_decode="off", spec_k=4, draft_model=None,
                 role="both", health_series=False, chain_topk=0,
                 weight_version="0"):
        """`kv_impl` (ISSUE 9, the attn_impl/loss_impl pattern):
        'slab' keeps the fixed per-slot KV columns (serve/slots.py);
        'paged' stores KV in a pool of `n_pages` blocks of `page_size`
        tokens behind per-slot page tables (serve/pages.py) — prompts
        prefill in `prefill_chunk`-token chunks, shared prefixes attach
        by refcount (`prefix_sharing`) with copy-on-write, and
        admission is page-budget-based instead of slot-count-based.
        `n_pages` defaults to the slab's KV footprint (n_slots * T_max
        tokens); `max_pages_per_seq` (default ceil(T_max/page_size))
        fixes the page-table width so allocation never retraces.
        `paged_attn_impl` = reference | pallas | auto (pallas on TPU).

        `kv_dtype` (ISSUE 11, beside kv_impl/attn_impl): 'bf16' stores
        KV in the model compute dtype; 'int8' quantizes on write with
        per-(position, head) absmax scales (ops/kv_quant.py) — half the
        decode-attend bandwidth and, per byte of HBM, twice the paged
        token capacity. Numerics contract: logits-close to bf16, not
        bitwise (the attn_impl tolerance pattern; tests pin all three
        families in both layouts).

        `spec_decode` (ISSUE 11): 'off' = sequential (one token per
        tick); 'draft' = speculative — `draft_model` (same vocab;
        fail-loud here, which IS the worker's hello) proposes `spec_k`
        tokens per tick and the target verifies all of them in ONE
        batched jitted step, harvesting 1..spec_k+1 tokens per slot
        per tick. Rejection sampling (infer/spec.py) keeps emissions
        exactly target-distributed, and top_k=1 (greedy) outputs are
        BIT-identical to sequential `generate_cached` for any draft.
        The draft's own KV rides a dense slab (`serve/slots.DraftPool`)
        whatever this engine's kv_impl/kv_dtype.

        `role` (ISSUE 13, disaggregated prefill/decode): 'both' (the
        default) serves the full request lifecycle; 'prefill' turns
        this engine into a prefill-class worker — it chunk-prefills
        prompts, EXPORTS each KV page the moment prompt tokens fully
        cover it (`take_page_exports`, shipped over serve/frames.py
        PT_KVPAGES frames by the router), and finishes the request
        with finish_reason='prefilled' instead of ever decoding; its
        page reservations cover the prompt only. Requires kv_impl=
        'paged' (pages ARE the transfer unit) and spec_decode='off'.
        Any paged engine can IMPORT pages (`import_kv_pages`): the
        chain splices into the local allocator as cached prefix nodes,
        so the handoff submit prefix-hits them and only computes the
        sub-page tail — bit-identical to a full local prefill because
        attached shared pages already are (the ISSUE 9 exactness
        argument, now crossing a process boundary).

        `tracer` (ISSUE 10): an obs/trace.py TraceBuffer (or Tracer)
        receiving per-request lifecycle events — engine_admit, prefill
        chunks, prefix hits, COW, first token, sampled decode ticks,
        evict, finish. None (the default) disables tracing: every
        emission site is a single `is not None` branch, so the hot
        decode tick pays nothing measurable (tests/test_trace.py).

        `health_series` (ISSUE 14): collect this engine's busy-step
        walls into a mergeable obs/series.QuantileSketch
        (`take_series_delta()` drains the bucket DELTAS — the wire
        form a process worker ships in its step replies, merged
        parent-side like the counter deltas). Off by default: the
        disabled path is one `is None` branch per step.

        `chain_topk` (ISSUE 16): > 0 arms prefix-chain telemetry —
        `take_chain_delta()` drains the allocator's bounded top-K chain
        summary as incremental deltas (the ISSUE 14 wire pattern), so
        the router's FleetCacheMap can see what this engine's cache
        contains. 0 (the default) ships nothing; paged engines only."""
        # one clock for submit timestamps, TTFT/TPOT, and deadline
        # expiry — injectable so the deadline tests drive time instead
        # of sleeping through it
        self._clock = clock if clock is not None else time.perf_counter
        cfg = model.config
        self.model = model
        self.n_slots = int(n_slots)
        self.T_max = int(max_seq_len or cfg.block_size)
        assert self.T_max <= cfg.block_size, (
            f"max_seq_len {self.T_max} > model block_size {cfg.block_size}"
        )
        assert kv_impl in ("slab", "paged"), f"unknown kv_impl {kv_impl!r}"
        self.kv_impl = kv_impl
        assert kv_dtype in ("bf16", "int8"), f"unknown kv_dtype {kv_dtype!r}"
        self.kv_dtype = kv_dtype
        assert spec_decode in ("off", "draft"), (
            f"unknown spec_decode {spec_decode!r}")
        self.spec_decode = spec_decode
        assert role in ("both", "prefill"), f"unknown role {role!r}"
        if role == "prefill":
            # fail LOUD at construction — in a process worker this is
            # the hello (the spec-decode fail-loud policy): a prefill
            # worker without pages has no transferable unit, and spec
            # decoding's draft state cannot ride a page transfer
            if kv_impl != "paged":
                raise ValueError(
                    "role='prefill' requires kv_impl='paged' — KV pages "
                    "are the unit a prefill-class replica ships")
            if spec_decode != "off":
                raise ValueError(
                    "role='prefill' is incompatible with spec_decode: a "
                    "prefill-class replica never decodes, and the draft "
                    "slab cannot ride a page transfer")
        self.role = role
        # weight_version (ISSUE 20): opaque label naming the weights
        # this engine serves (a checkpoint generation, e.g.
        # 'iter-00000120'). Pure bookkeeping — the engine never
        # interprets it; the rollout manager rewrites it at swap time
        # and it rides every stats() heartbeat so the router can
        # version-key KV reuse (stale-KV-under-new-weights is a
        # silent-wrongness bug, not a perf bug).
        self.weight_version = str(weight_version)
        # spec_k (ISSUE 18): an int fixes k; 'auto' makes k per-request
        # ADAPTIVE — each live slot walks the k bucket ladder
        # (bucket_ladder(cap, floor=1)) on its measured accept-rate
        # EWMA, so a collapsing draft shrinks its verify width instead
        # of burning k rejected proposals per tick. The default cap
        # under 'auto' is the same k=4 the fixed default uses.
        self.spec_k_auto = spec_k == "auto"
        self.spec_k = 4 if self.spec_k_auto else int(spec_k)
        assert self.spec_k >= 1
        # draft-free self-draft (ISSUE 18): draft_model='ngram' swaps
        # the second model for host-side prompt-lookup proposals
        # (infer.spec.ngram_propose) verified through the SAME batched
        # (B, k+1) verify block — no draft pool, no draft weights, no
        # model in the hello
        self.ngram = draft_model == "ngram"
        if isinstance(draft_model, str) and not self.ngram:
            raise ValueError(
                f"unknown draft_model {draft_model!r} — pass a model "
                "or the string 'ngram' (prompt-lookup self-draft)")
        self.draft_model = draft_model
        spec_on = spec_decode == "draft"
        if spec_on:
            # fail LOUD at construction — in a process worker this is
            # the hello, so a draft/target mismatch refuses the
            # handshake instead of emitting garbage under load
            # (docs/OPERATIONS.md failure matrix)
            if draft_model is None:
                raise ValueError(
                    "spec_decode='draft' needs a draft_model (a small "
                    "same-vocab model, or 'ngram' for the draft-free "
                    "prompt-lookup self-draft)")
            if not self.ngram:
                dcfg = draft_model.config
                if dcfg.vocab_size != cfg.vocab_size:
                    raise ValueError(
                        f"draft/target vocab mismatch: draft "
                        f"{dcfg.vocab_size} != target {cfg.vocab_size} — "
                        "speculative verification compares token "
                        "distributions, the vocabularies must be the same "
                        "model version (fail-loud at hello)")
                if dcfg.block_size < self.T_max:
                    raise ValueError(
                        f"draft block_size {dcfg.block_size} < engine "
                        f"max_seq_len {self.T_max} — the draft must cover "
                        "every position the target serves (fail-loud at "
                        "hello)")
        # the verify-width ladder adaptive k rides (ISSUE 18): per-tick
        # width is the bucket of the largest live k_eff, so steady
        # state with fixed spec_k stays ONE step trace and 'auto' is
        # bounded by len(k_ladder) traces ever (asserted each step)
        self._k_ladder = bucket_ladder(self.spec_k, floor=1) \
            if spec_on else (1,)
        self.detokenize = detokenize
        self._reg = registry if registry is not None else get_registry()
        self.sink = sink if sink is not None else NullSink()
        self.sched = FCFSScheduler(self.n_slots, self.T_max)
        self._live = {}  # slot -> _Live
        self._pending = []  # rejected-at-submit records, flushed by step()
        self._tick_s = []   # recent decode-tick durations (clock secs)
        self._tr = tracer   # None = tracing off (the near-zero path)
        self.chain_topk = int(chain_topk)  # 0 = chain telemetry off
        self._hs = None     # None = health series off (ISSUE 14)
        if health_series:
            from avenir_tpu.obs.series import QuantileSketch

            self._hs = QuantileSketch()
        self._tick_n = 0    # decode ticks ever, for trace sampling
        self._next_id = 0
        self._base_rng = jax.random.key(seed)
        self.traces = {"prefill": [], "step": [], "cow": [], "import": [],
                       "seed": [], "draft_prefill": []}
        # finished-page export queue (role='prefill'): records the
        # router drains each step and streams to the decode class —
        # already-materialized numpy, so a SIGKILL mid-transfer loses
        # nothing the failover re-prefill cannot recompute
        self._page_exports = []

        n_kv = getattr(cfg, "n_kv_head", cfg.n_head)
        head_dim = cfg.n_embd // cfg.n_head
        from avenir_tpu.models.common import resolve_dtype

        pool_dtype = resolve_dtype(cfg.compute_dtype)
        self._pool_dtype = pool_dtype
        # spec verify writes [tail, d_1..d_k] at pos..pos+k, so both KV
        # layouts carry a spec_k-position scratch tail past T_max —
        # masked until overwritten, never attended past the accepted
        # point (the slot-hygiene invariant covers rejected drafts)
        self._spec_pad = self.spec_k if spec_on else 0
        self._reg.gauge("kv_dtype").set(8 if kv_dtype == "int8" else 16)
        if spec_on and self.ngram:
            # register at construction so obs_report can tell the
            # draft source apart even before the first lookup lands
            self._reg.counter("ngram_hits").add(0)
        if kv_impl == "paged":
            # spec × prefix sharing (ISSUE 18, tearing down the PR 10
            # wall): a prefix HIT skips computing the shared prompt
            # region for the TARGET (the attached pages ARE its KV) —
            # and the draft, which has no shared-page store, catches up
            # with DRAFT-ONLY chunks over the shared region
            # (`_draft_chunk_fn`, charged to the same prefill budget).
            # The draft is tiny by construction, so the catch-up costs
            # a sliver of the shared-region savings; chunk-split
            # invariance of `_forward_cached` makes its proposals
            # bit-identical to a full joint prefill, so output stays a
            # pure function of (prompt, rng) and the failover-replay
            # contract survives. The ngram self-draft has no draft KV
            # at all and composes for free.
            self.page_size = int(page_size)
            assert self.page_size >= 1
            # equal-HBM default: the paged pool spends exactly the KV
            # bytes the slab would have — the capacity win is layout
            self.n_pages = int(n_pages if n_pages is not None
                               else max(1, (self.n_slots * self.T_max)
                                        // self.page_size))
            self.max_pages_per_seq = int(
                max_pages_per_seq if max_pages_per_seq is not None
                else -(-(self.T_max + self._spec_pad) // self.page_size))
            self.prefill_chunk = int(prefill_chunk or 4 * self.page_size)
            self._paged = PagedHost(
                n_pages=self.n_pages, page_size=self.page_size,
                n_slots=self.n_slots,
                max_pages_per_seq=self.max_pages_per_seq,
                prefill_chunk=self.prefill_chunk,
                prefix_sharing=prefix_sharing,
                spec_pad=self._spec_pad,
                prefill_only=(role == "prefill"))
            self.pool = init_paged_pool(
                n_layer=cfg.n_layer, n_slots=self.n_slots,
                n_pages=self.n_pages, page_size=self.page_size,
                n_kv_head=n_kv, head_dim=head_dim,
                vocab_size=cfg.vocab_size, dtype=pool_dtype,
                kv_dtype=kv_dtype,
            )
        else:
            self._paged = None
            self.pool = init_slot_pool(
                n_layer=cfg.n_layer, n_slots=self.n_slots,
                max_t=self.T_max + self._spec_pad, n_kv_head=n_kv,
                head_dim=head_dim,
                vocab_size=cfg.vocab_size, dtype=pool_dtype,
                kv_dtype=kv_dtype,
            )
        # slab int8: KV reads/writes route through the quantized kv_ops
        # pair; the single-token decode attend takes the fused Pallas
        # int8 kernel on TPU (HBM moves int8 — the bandwidth win) and
        # the dequant + dense reference elsewhere (CPU-testable)
        self._slab_kv_ops = None
        if kv_impl == "slab" and kv_dtype == "int8":
            attend_fn = None
            if jax.default_backend() == "tpu":
                from avenir_tpu.ops.pallas.flash_attention import \
                    decode_attention_int8

                def attend_fn(q, kc, vc, q_pos):
                    lengths = (q_pos[:, -1] + 1).astype(jnp.int32)
                    return decode_attention_int8(
                        q[:, 0], kc.data, kc.scale, vc.data, vc.scale,
                        lengths)[:, None]

            self._slab_kv_ops = quant_slab_kv_ops(pool_dtype, attend_fn)
        self._dpool = None
        if spec_on and not self.ngram:
            dcfg = draft_model.config
            self._dpool = init_draft_pool(
                n_layer=dcfg.n_layer, n_slots=self.n_slots,
                max_t=self.T_max + self.spec_k,
                n_kv_head=getattr(dcfg, "n_kv_head", dcfg.n_head),
                head_dim=dcfg.n_embd // dcfg.n_head,
                spec_k=self.spec_k,
                dtype=resolve_dtype(dcfg.compute_dtype),
            )
        if getattr(cfg, "n_experts", 0):
            cap = max(1, int(-(-cfg.n_experts_per_tok * self.n_slots
                               * cfg.capacity_factor // cfg.n_experts)))
            if cap < self.n_slots:
                warnings.warn(
                    "MoE decode capacity binds at this batch "
                    f"(capacity {cap} < {self.n_slots} slots): token drops "
                    "depend on batch composition, so engine output can "
                    "diverge from one-shot decoding under load "
                    "(docs/SERVING.md)", stacklevel=2)

        # split ONCE: unlike generate_cached (which re-splits per call to
        # pick up in-place weight mutations), serving weights are static
        # for the engine's lifetime — a per-iteration re-split would put
        # a full parameter-pytree traversal on the per-token hot path.
        # Call refresh_state() after mutating weights in place.
        graphdef, self._state = nnx.split(model)
        self._dgraphdef = self._dstate = None
        if spec_on and not self.ngram:
            self._dgraphdef, self._dstate = nnx.split(draft_model)
        traces = self.traces
        if kv_impl == "paged":
            self._build_paged_fns(graphdef, traces, paged_attn_impl)
        else:
            self._build_slab_fns(graphdef, traces)
        if spec_on and self.ngram:
            # ngram first-token seed: the sequential admit/chunk path
            # prefills the target, then this tiny pool-only fn samples
            # the request's first token from the spliced prefill logits
            # with the slot's own key — the same split the first
            # sequential tick would consume, so greedy ngram output is
            # bit-identical from token one. ONE trace ever ("seed"),
            # shared by both KV layouts; no model forward inside.
            @functools.partial(jax.jit, donate_argnums=(0,))
            def _seed_tail(pool, slot):
                traces["seed"].append(True)
                sl = lambda a: jax.lax.dynamic_slice_in_dim(
                    a, slot, 1, axis=0)
                keys1 = jax.random.wrap_key_data(sl(pool.rng))
                keys1, tail = _sample_rows(
                    keys1, sl(pool.logits), sl(pool.temperature),
                    sl(pool.top_k))
                pool = pool._replace(rng=jax.lax.dynamic_update_slice(
                    pool.rng, jax.random.key_data(keys1), (slot, 0)))
                return pool, tail[0]

            self._seed_tail = _seed_tail

    def _spec_core(self, m, dm, pool, dpool, active, kv_ops, k_eff,
                   k_tick):
        """The speculative tick, shared by both KV layouts — runs
        INSIDE the jitted step (one dispatch): draft catch-up on last
        tick's emissions, k autoregressive draft proposals, the ONE
        batched target verify over [tail, d_1..d_k], then rejection-
        sampling acceptance (infer/spec.py). Returns (toks (B, k_cap+1),
        counts (B,), new_pool, new_dpool) — fixed shapes; the variable
        1..k+1 harvest is host bookkeeping over `counts`.

        `k_tick` (ISSUE 18, adaptive spec_k) is the tick's VERIFY WIDTH
        — a static rung of the k ladder (trace-time python int), the
        bucket of the largest live k_eff, so shrinking k genuinely
        shrinks the draft scan and the verify forward instead of just
        masking rows. `k_eff` (B,) int32 masks acceptance per row below
        that (spec_accept force-rejects positions >= k_eff). With fixed
        spec_k both pin at the cap: one step trace, as ever."""
        K1 = dpool.prev.shape[1]
        K = min(int(k_tick), K1 - 1)
        # 1. draft catch-up: the draft saw only its own proposals last
        # tick — feed it what was actually EMITTED (count-masked width
        # k+1; padding rows land past every query position this tick
        # and are overwritten by the proposals before ever attended)
        dkeys = jax.random.wrap_key_data(dpool.rng)
        q_all, dcache = _forward_cached(
            dm, dpool.prev, KVCache(dpool.k, dpool.v), dpool.pos,
            return_all=True)
        q0 = jnp.take_along_axis(
            q_all, (dpool.prev_n - 1)[:, None, None], axis=1)[:, 0]
        dpos = dpool.pos + dpool.prev_n

        # 2. k draft proposals, each sampled with the slot's OWN
        # sampling params from the slot's draft key stream
        def body(carry, mm):
            dkeys, qlog, kc, vc, p = carry
            dkeys, d = _sample_rows(dkeys, qlog, pool.temperature,
                                    pool.top_k)
            logits2, cache2 = _forward_cached(mm, d[:, None],
                                              KVCache(kc, vc), p)
            return (dkeys, logits2, cache2.k, cache2.v, p + 1), (d, qlog)

        (dkeys, _, dk_new, dv_new, _), (drafts, q_logits) = nnx.scan(
            body, in_axes=(nnx.Carry, None), out_axes=(nnx.Carry, 0),
            length=K,
        )((dkeys, q0, dcache.k, dcache.v, dpos), dm)
        drafts = drafts.T                          # (B, K)
        q_logits = jnp.moveaxis(q_logits, 0, 1)    # (B, K, V)

        # 3. ONE batched target verify over [tail, d_1..d_k]: index i
        # of the returned logits is p(.|prefix, d_1..d_i)
        tail = jnp.take_along_axis(dpool.prev, (dpool.prev_n - 1)[:, None],
                                   axis=1)
        vin = jnp.concatenate([tail, drafts], axis=1)   # (B, K+1)
        p_logits, cache = _forward_cached(
            m, vin, KVCache(pool.k, pool.v), pool.pos, kv_ops=kv_ops,
            return_all=True)

        # 4. accept/reject: bit-greedy, distribution-exact otherwise
        tkeys = jax.random.wrap_key_data(pool.rng)
        tkeys, toks, counts = spec_accept(
            tkeys, p_logits, q_logits, drafts, pool.temperature,
            pool.top_k, k_eff=jnp.minimum(k_eff, K))
        # pad the emission block back to the pool's fixed k_cap+1 width
        # (dead columns — counts never reaches them) so prev and the
        # host harvest keep ONE shape across k_tick rungs
        B = toks.shape[0]
        if K < K1 - 1:
            toks = jnp.concatenate(
                [toks, jnp.zeros((B, K1 - 1 - K), jnp.int32)], axis=1)
        new_pool = pool._replace(
            k=cache.k, v=cache.v,
            rng=jax.random.key_data(tkeys),
            pos=jnp.where(active, pool.pos + counts, pool.pos),
        )
        new_dpool = dpool._replace(
            k=dk_new, v=dv_new,
            rng=jax.random.key_data(dkeys),
            pos=jnp.where(active, dpos, dpool.pos),
            prev=jnp.where(active[:, None], toks, dpool.prev),
            prev_n=jnp.where(active, counts, dpool.prev_n),
        )
        return toks, counts, new_pool, new_dpool

    def _init_tmp_cache(self, width):
        """Single-sequence temp cache for an admission prefill, in this
        engine's kv_dtype (quantize-on-write starts at prefill — the
        pool never holds a bf16 copy of anything)."""
        cfg = self.model.config
        n_kv = getattr(cfg, "n_kv_head", cfg.n_head)
        head_dim = cfg.n_embd // cfg.n_head
        shape = (cfg.n_layer, 1, width, n_kv, head_dim)
        if self.kv_dtype == "int8":
            return KVCache(init_quant_kv(shape), init_quant_kv(shape))
        return KVCache(jnp.zeros(shape, self._pool_dtype),
                       jnp.zeros(shape, self._pool_dtype))

    def _build_slab_fns(self, graphdef, traces):
        """The slab pool's jitted entry points: admission prefill and
        the batched step (sequential or speculative). Compile budget
        unchanged: one prefill trace per bucket + ONE step trace."""
        dgraphdef = self._dgraphdef
        spec_on = self.spec_decode == "draft"
        model_draft = spec_on and not self.ngram
        slab_kv = self._slab_kv_ops
        init_tmp = self._init_tmp_cache
        dcfg = self.draft_model.config if model_draft else None

        def _admit_body(state, pool, idx_pad, slot, last_index, key_data,
                        temp, top_k):
            traces["prefill"].append(idx_pad.shape)
            m = nnx.merge(graphdef, state)
            tmp = init_tmp(idx_pad.shape[1])
            logits, tmp = _forward_cached(m, idx_pad, tmp, 0,
                                          last_index=last_index,
                                          kv_ops=slab_kv)
            upd = jax.lax.dynamic_update_slice
            pool = pool._replace(
                k=_splice_slot(pool.k, tmp.k, slot),
                v=_splice_slot(pool.v, tmp.v, slot),
                logits=upd(pool.logits, logits, (slot, 0)),
                rng=upd(pool.rng, key_data[None], (slot, 0)),
                pos=upd(pool.pos, (last_index + 1)[None].astype(jnp.int32),
                        (slot,)),
                temperature=upd(pool.temperature, temp[None], (slot,)),
                top_k=upd(pool.top_k, top_k[None], (slot,)),
            )
            return pool

        if model_draft:
            # spec admission = the sequential one PLUS: the draft
            # prefills the same prompt into its slab column, and the
            # request's FIRST token (the "tail") is sampled here from
            # the prefill logits — consuming the slot's rng exactly as
            # the first sequential decode tick would, which is what
            # keeps greedy spec output bit-identical from token one
            @functools.partial(jax.jit, donate_argnums=(1, 2))
            def _admit_spec(state, pool, dpool, dstate, idx_pad, slot,
                            last_index, key_data, dkey_data, temp, top_k):
                pool = _admit_body(state, pool, idx_pad, slot, last_index,
                                   key_data, temp, top_k)
                dm = nnx.merge(dgraphdef, dstate)
                n_kv_d = getattr(dcfg, "n_kv_head", dcfg.n_head)
                dshape = (dcfg.n_layer, 1, idx_pad.shape[1], n_kv_d,
                          dcfg.n_embd // dcfg.n_head)
                dtmp = KVCache(jnp.zeros(dshape, dpool.k.dtype),
                               jnp.zeros(dshape, dpool.v.dtype))
                _, dtmp = _forward_cached(dm, idx_pad, dtmp, 0,
                                          last_index=last_index)
                logits_row = jax.lax.dynamic_slice_in_dim(
                    pool.logits, slot, 1, axis=0)
                return _seed_spec_slot(pool, dpool, dtmp, slot,
                                       logits_row, key_data, dkey_data,
                                       temp, top_k, last_index + 1)

            self._admit = _admit_spec

            @functools.partial(jax.jit, static_argnums=(6,),
                               donate_argnums=(2, 3))
            def _spec_step(state, dstate, pool, dpool, active, k_eff,
                           k_tick):
                traces["step"].append(True)
                m = nnx.merge(graphdef, state)
                dm = nnx.merge(dgraphdef, dstate)
                return self._spec_core(m, dm, pool, dpool, active,
                                       slab_kv, k_eff, k_tick)

            self._step_fn = _spec_step
            return

        self._admit = functools.partial(jax.jit, donate_argnums=(1,))(
            _admit_body)

        if spec_on:  # ngram self-draft (ISSUE 18): no draft pool/state
            # — the host proposes via suffix match, the target verifies
            # the (B, k_tick+1) block exactly as the model-draft path
            # does, and q is the point-mass one-hot at the proposals so
            # spec_accept's exactness guarantees carry over verbatim.
            # k_tick rides in as the DRAFTS WIDTH (shape-keyed retrace
            # per k-ladder rung; budget asserted), no static arg needed.
            @functools.partial(jax.jit, donate_argnums=(1,))
            def _ngram_step(state, pool, active, drafts, tail, k_eff):
                traces["step"].append(True)
                m = nnx.merge(graphdef, state)
                vin = jnp.concatenate([tail[:, None], drafts], axis=1)
                p_logits, cache = _forward_cached(
                    m, vin, KVCache(pool.k, pool.v), pool.pos,
                    kv_ops=slab_kv, return_all=True)
                q_logits = ngram_q_logits(drafts, p_logits.shape[-1])
                tkeys = jax.random.wrap_key_data(pool.rng)
                tkeys, toks, counts = spec_accept(
                    tkeys, p_logits, q_logits, drafts, pool.temperature,
                    pool.top_k, k_eff=k_eff)
                return toks, counts, pool._replace(
                    k=cache.k, v=cache.v,
                    rng=jax.random.key_data(tkeys),
                    pos=jnp.where(active, pool.pos + counts, pool.pos))

            self._step_fn = _ngram_step
            return

        # ONE step variant on purpose: the engine's compile budget
        # (buckets + 1 decode step, asserted) is the contract we keep.
        # Slots with top_k=None (and every EMPTY slot — the pool default)
        # carry k=V, an exactly-no-op mask; _sample_rows now skips the
        # per-row full-vocab sort at RUNTIME via a batch-level lax.cond
        # whenever no live row carries a real top-k, inside the same
        # compiled step — so all-no-top-k batches (and idle padding-only
        # ones) stop paying the sort without a second compile.
        @functools.partial(jax.jit, donate_argnums=(1,))
        def _step(state, pool, active):
            traces["step"].append(True)
            m = nnx.merge(graphdef, state)
            keys = jax.random.wrap_key_data(pool.rng)
            keys, toks = _sample_rows(keys, pool.logits, pool.temperature,
                                      pool.top_k)
            logits, cache = _forward_cached(m, toks[:, None],
                                            KVCache(pool.k, pool.v),
                                            pool.pos, kv_ops=slab_kv)
            pos = jnp.where(active, pool.pos + 1, pool.pos)
            return toks, pool._replace(
                k=cache.k, v=cache.v, logits=logits,
                rng=jax.random.key_data(keys), pos=pos,
            )

        self._step_fn = _step

    def _build_paged_fns(self, graphdef, traces, paged_attn_impl):
        """The paged pool's three jitted entry points (ISSUE 9):
        chunk-prefill (the ONLY prefill form — a short prompt is one
        chunk), the batched decode step over page tables, and the COW
        page copy. Compile budget: one trace per chunk bucket + one
        decode step + one COW copy for the engine's lifetime — page
        tables and the chunk's start/length/valid-count are all traced
        arguments, so pages allocating and freeing never retrace."""
        resolved = paged_attn_impl
        if resolved == "auto":
            resolved = ("pallas" if jax.default_backend() == "tpu"
                        else "reference")
        assert resolved in ("reference", "pallas"), paged_attn_impl
        self.paged_attn_impl = resolved
        kv_dtype = self.kv_dtype
        compute_dtype = self._pool_dtype
        attend_fn = None
        if resolved == "pallas" and kv_dtype == "int8":
            from avenir_tpu.ops.pallas.paged_attention import \
                paged_attention_int8

            def attend_fn(q, kc, vc, q_pos, tables):
                lengths = (q_pos[:, -1] + 1).astype(jnp.int32)
                return paged_attention_int8(
                    q[:, 0], kc.data, kc.scale, vc.data, vc.scale,
                    tables, lengths)[:, None]

        elif resolved == "pallas":
            from avenir_tpu.ops.pallas.paged_attention import \
                paged_attention

            def attend_fn(q, kc, vc, q_pos, tables):
                # decode-only fast path: q_pos is the (B, 1) per-row
                # position vector, so row b may attend pos+1 tokens
                lengths = (q_pos[:, -1] + 1).astype(jnp.int32)
                return paged_attention(q[:, 0], kc, vc, tables,
                                       lengths)[:, None]

        n_pg, ps, P = self.n_pages, self.page_size, self.max_pages_per_seq
        dgraphdef = self._dgraphdef
        spec_on = self.spec_decode == "draft"
        model_draft = spec_on and not self.ngram
        dcfg = self.draft_model.config if model_draft else None

        def _kv(tables, **kw):
            return paged_kv_ops(tables, n_pages=n_pg, page_size=ps,
                                kv_dtype=kv_dtype,
                                compute_dtype=compute_dtype, **kw)

        def _chunk_body(state, pool, idx, table_row, slot, start, n_real,
                        key_data, temp, top_k):
            traces["prefill"].append(idx.shape)
            m = nnx.merge(graphdef, state)
            kv = _kv(table_row[None], n_real=n_real)
            logits, cache = _forward_cached(
                m, idx, KVCache(pool.k, pool.v), start,
                last_index=n_real - 1, kv_ops=kv)
            # one UNIFORM chunk fn — no is-final flag: logits/rng/pos/
            # sampling params splice every chunk (idempotent until the
            # final chunk, whose splice is the one decode samples from),
            # so a prompt of any length costs ladder-bounded compiles
            upd = jax.lax.dynamic_update_slice
            return pool._replace(
                k=cache.k, v=cache.v,
                logits=upd(pool.logits, logits, (slot, 0)),
                rng=upd(pool.rng, key_data[None], (slot, 0)),
                pos=upd(pool.pos,
                        (start + n_real)[None].astype(jnp.int32), (slot,)),
                temperature=upd(pool.temperature, temp[None], (slot,)),
                top_k=upd(pool.top_k, top_k[None], (slot,)),
            ), logits

        if model_draft:
            # the chunk fn stays UNIFORM across chunks: the draft
            # forwards the same chunk into its slab column, and the
            # tail/prev/rng splices recompute idempotently from the
            # ORIGINAL request key every chunk — only the final chunk's
            # values survive, so chunk count never forks the compile
            @functools.partial(jax.jit, donate_argnums=(1, 2))
            def _chunk_spec(state, pool, dpool, dstate, idx, table_row,
                            slot, start, n_real, key_data, dkey_data,
                            temp, top_k):
                pool, logits = _chunk_body(state, pool, idx, table_row,
                                           slot, start, n_real, key_data,
                                           temp, top_k)
                dm = nnx.merge(dgraphdef, dstate)
                # draft chunk: read-modify-write the slot's draft slab
                # column at a traced index (dynamic_slice, not [slot])
                dk = jax.lax.dynamic_slice_in_dim(dpool.k, slot, 1,
                                                  axis=1)
                dv = jax.lax.dynamic_slice_in_dim(dpool.v, slot, 1,
                                                  axis=1)
                _, dtmp = _forward_cached(dm, idx, KVCache(dk, dv), start,
                                          last_index=n_real - 1)
                return _seed_spec_slot(pool, dpool, dtmp, slot, logits,
                                       key_data, dkey_data, temp, top_k,
                                       start + n_real)

            self._chunk_fn = _chunk_spec

            # spec × prefix sharing (ISSUE 18): DRAFT-ONLY chunk over a
            # region the target skipped — a prefix hit attaches the
            # target's shared pages as-is, and this fn walks the draft
            # through the same prompt tokens so its proposals condition
            # on exactly the state a full prefill would have built
            # (chunk-split invariance of _forward_cached ⇒ bit-equal).
            # Same chunk-bucket ladder as the combined fn, own trace
            # key ("draft_prefill", ladder-bounded, asserted).
            @functools.partial(jax.jit, donate_argnums=(0,))
            def _draft_chunk(dpool, dstate, idx, slot, start, n_real):
                traces["draft_prefill"].append(idx.shape)
                dm = nnx.merge(dgraphdef, dstate)
                dk = jax.lax.dynamic_slice_in_dim(dpool.k, slot, 1,
                                                  axis=1)
                dv = jax.lax.dynamic_slice_in_dim(dpool.v, slot, 1,
                                                  axis=1)
                _, dtmp = _forward_cached(dm, idx, KVCache(dk, dv), start,
                                          last_index=n_real - 1)
                return dpool._replace(
                    k=_splice_slot(dpool.k, dtmp.k, slot),
                    v=_splice_slot(dpool.v, dtmp.v, slot))

            self._draft_chunk_fn = _draft_chunk

            @functools.partial(jax.jit, static_argnums=(8,),
                               donate_argnums=(2, 3))
            def _spec_step(state, dstate, pool, dpool, active, tables,
                           write_limit, k_eff, k_tick):
                traces["step"].append(True)
                m = nnx.merge(graphdef, state)
                dm = nnx.merge(dgraphdef, dstate)
                # verify is a MULTI-token write: the per-row write_limit
                # drops scratch positions past the slot's allocated page
                # coverage (a clipped page_slot would corrupt a page the
                # 0-padded table names); attend_fn only serves width-1
                # queries, so verify reads take the gather reference
                kv = _kv(tables, write_mask=active,
                         write_limit=write_limit, attend_fn=attend_fn)
                return self._spec_core(m, dm, pool, dpool, active, kv,
                                       k_eff, k_tick)

            self._step_fn = _spec_step
        elif spec_on:
            # ngram self-draft, paged: the SEQUENTIAL chunk fn prefills
            # the target (no draft KV exists to keep in lockstep — the
            # self-draft composes with prefix sharing and page imports
            # for free), and the verify step mirrors the slab ngram
            # step over page tables with the multi-token write_limit
            @functools.partial(jax.jit, donate_argnums=(1,))
            def _chunk(state, pool, idx, table_row, slot, start, n_real,
                       key_data, temp, top_k):
                pool, _ = _chunk_body(state, pool, idx, table_row, slot,
                                      start, n_real, key_data, temp,
                                      top_k)
                return pool

            self._chunk_fn = _chunk

            @functools.partial(jax.jit, donate_argnums=(1,))
            def _ngram_step(state, pool, active, drafts, tail, k_eff,
                            tables, write_limit):
                traces["step"].append(True)
                m = nnx.merge(graphdef, state)
                vin = jnp.concatenate([tail[:, None], drafts], axis=1)
                kv = _kv(tables, write_mask=active,
                         write_limit=write_limit, attend_fn=attend_fn)
                p_logits, cache = _forward_cached(
                    m, vin, KVCache(pool.k, pool.v), pool.pos,
                    kv_ops=kv, return_all=True)
                q_logits = ngram_q_logits(drafts, p_logits.shape[-1])
                tkeys = jax.random.wrap_key_data(pool.rng)
                tkeys, toks, counts = spec_accept(
                    tkeys, p_logits, q_logits, drafts, pool.temperature,
                    pool.top_k, k_eff=k_eff)
                return toks, counts, pool._replace(
                    k=cache.k, v=cache.v,
                    rng=jax.random.key_data(tkeys),
                    pos=jnp.where(active, pool.pos + counts, pool.pos))

            self._step_fn = _ngram_step
        else:
            @functools.partial(jax.jit, donate_argnums=(1,))
            def _chunk(state, pool, idx, table_row, slot, start, n_real,
                       key_data, temp, top_k):
                pool, _ = _chunk_body(state, pool, idx, table_row, slot,
                                      start, n_real, key_data, temp,
                                      top_k)
                return pool

            self._chunk_fn = _chunk

            @functools.partial(jax.jit, donate_argnums=(1,))
            def _step(state, pool, active, tables):
                traces["step"].append(True)
                m = nnx.merge(graphdef, state)
                keys = jax.random.wrap_key_data(pool.rng)
                keys, toks = _sample_rows(keys, pool.logits,
                                          pool.temperature, pool.top_k)
                kv = _kv(tables, write_mask=active, attend_fn=attend_fn)
                logits, cache = _forward_cached(m, toks[:, None],
                                                KVCache(pool.k, pool.v),
                                                pool.pos, kv_ops=kv)
                pos = jnp.where(active, pool.pos + 1, pool.pos)
                return toks, pool._replace(
                    k=cache.k, v=cache.v, logits=logits,
                    rng=jax.random.key_data(keys), pos=pos,
                )

            self._step_fn = _step

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _cow(pool, src, dst):
            traces["cow"].append(True)
            cp = lambda a: a.at[:, dst].set(a[:, src])
            return pool._replace(k=jax.tree.map(cp, pool.k),
                                 v=jax.tree.map(cp, pool.v))

        self._cow_fn = _cow

        # page import (ISSUE 13): scatter transferred page KV into the
        # pool at the physical pages import_chain allocated. `phys` is
        # padded to a ladder width with n_pages, which jax's
        # out-of-bounds scatter DROPS — the same masking mechanism as
        # chunk padding — so import width never retraces beyond the
        # ladder (asserted like every other compile budget).
        from avenir_tpu.infer.decode import bucket_ladder as _bl

        self._import_ladder = _bl(self.max_pages_per_seq, floor=1)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _import(pool, phys, k_in, v_in):
            traces["import"].append(jax.tree.leaves(k_in)[0].shape)

            def scat(c, d):
                return c.at[:, phys].set(d.astype(c.dtype), mode="drop")

            return pool._replace(k=jax.tree.map(scat, pool.k, k_in),
                                 v=jax.tree.map(scat, pool.v, v_in))

        self._import_fn = _import

    # ---- API ----

    @property
    def max_total_tokens(self):
        """The submit-time length limit: prompt + max_new_tokens must
        fit this. Slab: T_max. Paged: also the per-sequence page budget
        (max_pages_per_seq * page_size) AND the whole pool (a request
        whose worst case exceeds n_pages could block the FCFS head
        forever waiting on pages that cannot exist) — whichever binds.
        Spec decoding shaves its scratch tail (spec_k positions) off
        the paged budget: the reservation must cover verify writes past
        the last real token."""
        if self._paged is None:
            return self.T_max
        return min(self.T_max,
                   min(self.max_pages_per_seq, self.n_pages)
                   * self.page_size - self._spec_pad)

    @property
    def limit_name(self):
        """Which limit `max_total_tokens` is — carried on rejection
        records so a caller knows WHAT to raise (ISSUE 9 satellite)."""
        if (self._paged is not None
                and min(self.max_pages_per_seq, self.n_pages)
                * self.page_size - self._spec_pad <= self.T_max):
            return "page_budget"
        return "max_seq_len"

    @property
    def open_work(self):
        """Admitted-or-queued work this engine still owes output for
        (mid-chunked-prefill slots included — they hold pages and a
        slot but are not yet in the live map)."""
        return bool(self._live or self.sched.queue_depth or self._pending
                    or (self._paged is not None and self._paged.prefill))

    def refresh_state(self):
        """Re-snapshot the model's parameters (after in-place weight
        mutation, e.g. loading a new checkpoint into the same module)."""
        self._state = nnx.split(self.model)[1]

    def tick_estimate_s(self):
        """Median recent decode-tick wall time in engine-clock seconds.
        The MEDIAN — watchdog-style — so the first compiling tick cannot
        inflate the dispatch-time expiry lookahead into spuriously
        expiring short-deadline work; with fewer than two samples the
        only measurement IS that compile spike, so the estimate stays
        0.0 (no lookahead) until a steady-state tick lands."""
        if len(self._tick_s) < 2:
            return 0.0
        return statistics.median_low(self._tick_s)

    def stats(self):
        """Host-state heartbeat snapshot — what a process worker ships
        back in every reply frame so its parent-side ProcReplica can
        mirror the scheduler surface the router routes on
        (serve/proc.py) without a second RPC."""
        s = {
            "n_slots": self.n_slots,
            "free": self.sched.free_slots,
            "queue": self.sched.queue_depth,
            "live": {int(lv.req.req_id): len(lv.emitted)
                     for lv in self._live.values()},
            "pending": len(self._pending),
            "tick_s": self.tick_estimate_s(),
            "weight_version": self.weight_version,
        }
        if self._paged is not None:
            # the heartbeat carries the page budget (ISSUE 9 satellite):
            # a parent-side ProcReplica mirrors these so the router and
            # the obs surface see fleet paging pressure without an RPC
            a = self._paged.alloc.stats()
            s["prefilling"] = len(self._paged.prefill)
            s["kv"] = {
                "impl": "paged",
                "n_pages": a["n_pages"],
                "pages_free": a["free"] + a["cached"],
                "page_util": a["util"],
                "prefix_hit_rate": self._paged.prefix_hit_rate(),
                # the rate's WEIGHT (ISSUE 16 satellite): admitted
                # prompt tokens — the fleet gauge averages per-replica
                # rates weighted by this, so an idle replica's 0.0
                # cannot drag the fleet number
                "prefix_attempts": self._paged.prompt_tokens,
            }
        return s

    def submit(self, prompt, *, max_new_tokens, temperature=1.0,
               top_k=None, stop_tokens=(), rng=None, deadline_ms=None,
               submit_t=None, front=False):
        """Enqueue a request; returns its id. `rng` defaults to
        fold_in(engine seed, id) — pass an explicit key to reproduce a
        one-shot `generate_cached` run. `deadline_ms` (None = none): a
        wall-time budget from submission; past it the request finishes
        with finish_reason='timeout' — evicted from its slot (partial
        tokens returned) or dropped from the queue before prefill.
        `submit_t` (engine-clock seconds) backdates the request — the
        router's failover path uses it so TTFT and the deadline keep
        counting from the ORIGINAL submission, not the resubmission.
        `front=True` enqueues at the head (the disaggregated handoff:
        the request already served its fleet-wide FCFS wait on the
        prefill class; scheduler.enqueue_front).

        A prompt+budget that cannot fit the engine's limit is NOT an
        engine crash (ISSUE 6 satellite): it finishes immediately with
        finish_reason='rejected' (`serve_rejected` counter) — bad user
        input on a shared engine must never take the fleet down. The
        limit is budget-aware (ISSUE 9 satellite): `max_seq_len` under
        the slab, `max_pages_per_seq * page_size` under paged KV —
        the rejection record's `reject_limit` names which one fired."""
        prompt = tuple(int(t) for t in prompt)
        assert prompt, "empty prompt"
        assert max_new_tokens >= 1
        assert deadline_ms is None or deadline_ms > 0
        rid = self._next_id
        self._next_id += 1
        if len(prompt) + max_new_tokens > self.max_total_tokens:
            self._reg.counter("serve_rejected").add(1)
            rec = FinishedRequest(
                req_id=rid, tokens=list(prompt), n_prompt=len(prompt),
                n_out=0, finish_reason="rejected",
                text="" if self.detokenize is not None else None,
                ttft_ms=None, tpot_ms=0.0, reject_limit=self.limit_name,
            )
            self.sink.write({
                "kind": "request", "t": time.time(), "id": rid,
                "n_prompt": len(prompt), "n_out": 0,
                "finish_reason": "rejected",
                "reject_limit": self.limit_name,
                "limit_tokens": self.max_total_tokens,
            })
            if self._tr is not None:
                self._tr.emit(rid, "finish", reason="rejected",
                              n_out=0, reject_limit=self.limit_name)
            self._pending.append(rec)
            return rid
        if rng is None:
            rng = jax.random.fold_in(self._base_rng, rid)
        req = Request(
            req_id=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), top_k=top_k,
            stop_tokens=_normalize_stop(stop_tokens) or (), rng=rng,
            submit_t=self._clock() if submit_t is None else float(submit_t),
            deadline_ms=None if deadline_ms is None else float(deadline_ms),
        )
        if front:
            self.sched.enqueue_front(req)
        else:
            self.sched.enqueue(req)
        self._reg.gauge("queue_depth").set(self.sched.queue_depth)
        return rid

    def step(self):
        """One scheduler iteration: expire, admit, one batched decode
        dispatch, harvest. Returns the requests that finished this
        iteration (including timeouts)."""
        hs = self._hs
        if hs is None:  # the disabled-by-default cheap path (ISSUE 14)
            if self._paged is not None:
                return self._step_paged()
            return self._step_slab()
        had_work = self.open_work
        t0 = self._clock()
        out = (self._step_paged() if self._paged is not None
               else self._step_slab())
        if had_work:
            # busy steps only — the _record_beat rule: idle no-ops
            # would drag the sketch's median toward zero
            hs.observe((self._clock() - t0) * 1e3)
        return out

    def take_series_delta(self):
        """Health-series sketch deltas since the last take (ISSUE 14):
        {series key: bucket-delta dict}, or None when the series is off
        or nothing new landed — the per-step-reply wire form
        (serve/worker.py ships it, serve/proc.py merges it into the
        fleet registry's series exactly like counter deltas)."""
        if self._hs is None:
            return None
        d = self._hs.take_delta()
        return {"step_time_ms": d} if d else None

    def take_chain_delta(self):
        """Prefix-chain summary delta since the last take (ISSUE 16):
        {"upd": {digest: node}, "gone": [digest]}, or None when chain
        telemetry is off (`chain_topk=0`), this engine is not paged, or
        nothing changed — the step-reply wire form (serve/worker.py
        ships it, serve/proc.py applies it to the parent-side mirror
        exactly like counter/sketch deltas)."""
        if self.chain_topk <= 0 or self._paged is None:
            return None
        return self._paged.alloc.take_chain_delta(self.chain_topk)

    def chain_summary(self):
        """Direct (non-incremental) chain summary — the parity oracle
        the merged deltas are pinned against, and what an in-process
        replica reads instead of merging its own heartbeats."""
        if self.chain_topk <= 0 or self._paged is None:
            return {}
        return self._paged.alloc.chain_summary(self.chain_topk)

    def _tick_k(self):
        """Per-tick adaptive-k inputs (ISSUE 18): the (n_slots,) int32
        effective-k vector (the cap for empty and non-auto slots) and
        this tick's VERIFY WIDTH — the k-ladder bucket of the largest
        live k_eff. Fixed spec_k pins every slot at the cap, so the
        width never moves and the step stays one trace; under 'auto'
        the width only shrinks when EVERY live slot has shrunk."""
        keff = np.full((self.n_slots,), self.spec_k, np.int32)
        kmax = 1
        for slot, live in self._live.items():
            keff[slot] = live.k_eff or self.spec_k
            kmax = max(kmax, int(keff[slot]))
        return keff, prompt_bucket(kmax, self.spec_k, floor=1)

    def _ngram_proposals(self, k_tick):
        """Host-side prompt-lookup proposals for every live slot
        (ISSUE 18): suffix-match each slot's full context (prompt +
        emitted so far) and propose the k_tick tokens that literally
        followed the previous occurrence. Returns ((n_slots, k_tick)
        drafts, (n_slots,) tails — each slot's last sampled token, the
        verify block's first input). Pure host arithmetic on ints; the
        `ngram_hits` counter tallies per-slot-tick lookup hits."""
        drafts = np.zeros((self.n_slots, k_tick), np.int32)
        tails = np.zeros((self.n_slots,), np.int32)
        hits = 0
        for slot, live in self._live.items():
            props, hit = ngram_propose(live.ctx, k_tick)
            drafts[slot] = props
            tails[slot] = live.tail
            hits += int(hit)
        self._reg.counter("ngram_hits").add(hits)
        return drafts, tails

    def _step_slab(self):
        state = self._state
        V = self.pool.logits.shape[-1]
        finished = self._pending
        self._pending = []
        # dispatch-time expiry lookahead (ISSUE 6 satellite): a queued
        # request whose remaining deadline cannot cover even ONE decode
        # tick would time out before its first token — expire it now
        # instead of letting hopeless work burn a prefill and a slot
        for req in self.sched.expire_queued(self._clock(),
                                            lookahead_s=self.tick_estimate_s()):
            finished.append(self._finish_queued_timeout(req))
        spec_on = self.spec_decode == "draft"
        for req, slot in self.sched.take_admissions():
            t0 = len(req.prompt)
            t_pad = self.sched.bucket(t0)
            if self._tr is not None:
                self._tr.emit(req.req_id, "engine_admit", slot=slot,
                              bucket=t_pad)
                # the slab prefills in one shot: one chunk, the prompt
                self._tr.emit(req.req_id, "prefill_chunk", start=0,
                              n=t0, slot=slot)
            idx = np.zeros((1, t_pad), np.int32)
            idx[0, :t0] = req.prompt
            k_eff = V if req.top_k is None else max(1, min(int(req.top_k), V))
            live = _Live(req)
            with span("serve_prefill", registry=self._reg):
                if spec_on and not self.ngram:
                    self.pool, self._dpool, tail = self._admit(
                        state, self.pool, self._dpool, self._dstate,
                        jnp.asarray(idx), jnp.int32(slot),
                        jnp.int32(t0 - 1), jax.random.key_data(req.rng),
                        jax.random.key_data(draft_key(req.rng)),
                        jnp.float32(req.temperature), jnp.int32(k_eff),
                    )
                    live.pending = [int(tail)]
                    self._stamp_admission_first_token(live, slot)
                elif spec_on:
                    # ngram: the SEQUENTIAL admit prefills the target,
                    # then the pool-only seed fn samples the first
                    # token (same rng split as the first sequential
                    # tick — greedy bit-parity from token one)
                    self.pool = self._admit(
                        state, self.pool, jnp.asarray(idx), jnp.int32(slot),
                        jnp.int32(t0 - 1), jax.random.key_data(req.rng),
                        jnp.float32(req.temperature), jnp.int32(k_eff),
                    )
                    self.pool, tail = self._seed_tail(self.pool,
                                                      jnp.int32(slot))
                    live.pending = [int(tail)]
                    live.ctx = list(req.prompt) + live.pending
                    live.tail = int(tail)
                    self._stamp_admission_first_token(live, slot)
                else:
                    self.pool = self._admit(
                        state, self.pool, jnp.asarray(idx), jnp.int32(slot),
                        jnp.int32(t0 - 1), jax.random.key_data(req.rng),
                        jnp.float32(req.temperature), jnp.int32(k_eff),
                    )
            if spec_on:
                live.k_eff = self.spec_k
            self._live[slot] = live

        if self._live:
            active = np.zeros((self.n_slots,), bool)
            active[list(self._live)] = True
            t_tick = self._clock()
            counts = keff_arr = None
            with span("serve_decode", registry=self._reg):
                if spec_on:
                    keff_arr, k_tick = self._tick_k()
                    if self.ngram:
                        drafts, tails = self._ngram_proposals(k_tick)
                        toks, counts, self.pool = self._step_fn(
                            state, self.pool, jnp.asarray(active),
                            jnp.asarray(drafts), jnp.asarray(tails),
                            jnp.asarray(keff_arr))
                    else:
                        toks, counts, self.pool, self._dpool = \
                            self._step_fn(
                                state, self._dstate, self.pool,
                                self._dpool, jnp.asarray(active),
                                jnp.asarray(keff_arr), k_tick)
                    toks = np.asarray(toks)   # the per-iteration D2H fence
                    counts = np.asarray(counts)
                else:
                    toks, self.pool = self._step_fn(state, self.pool,
                                                    jnp.asarray(active))
                    toks = np.asarray(toks)  # the per-iteration D2H fence
            self._harvest_tokens(toks, t_tick, finished, counts=counts,
                                 k_eff=keff_arr)
        self._set_gauges()
        assert len(self.traces["prefill"]) <= len(self.sched.ladder), (
            "prefill compiles escaped the bucket ladder"
        )
        assert len(self.traces["step"]) <= len(self._k_ladder), (
            "the decode step retraced past the k ladder — a slot-pool "
            "shape leaked"
        )
        assert len(self.traces["seed"]) <= 1, "the ngram seed retraced"
        return finished

    def _step_paged(self):
        """One paged-KV scheduler iteration (ISSUE 9): expire, admit
        (page-budget-based), advance chunked prefills within this
        tick's token budget, one batched decode dispatch over the page
        tables, harvest. The decode dispatch is identical in shape
        every tick no matter how pages moved — tables and the live mask
        are traced arguments."""
        state = self._state
        pg = self._paged
        V = self.pool.logits.shape[-1]
        finished = self._pending
        self._pending = []
        now = self._clock()
        for req in self.sched.expire_queued(
                now, lookahead_s=self.tick_estimate_s()):
            finished.append(self._finish_queued_timeout(req))
        # deadline expiry for mid-prefill slots BEFORE spending another
        # chunk on them — a hopeless prefill must not burn compute
        for slot in sorted(pg.prefill):
            if pg.prefill[slot].req.expired(now):
                finished.append(self._finish_prefilling_timeout(slot))
        # token-budget admission: pages, not slot count, are the scarce
        # resource — the scheduler's FCFS head blocks until the
        # allocator can cover its worst case (prompt + max_new, minus
        # attached prefix pages)
        for req, slot in self.sched.take_admissions(can_admit=pg.try_admit):
            if self._tr is not None:
                plan = pg._plans[req.req_id]
                self._tr.emit(req.req_id, "engine_admit", slot=slot,
                              new_pages=plan.new_pages)
                if plan.shared_len:
                    self._tr.emit(req.req_id, "prefix_hit",
                                  shared_tokens=plan.shared_len,
                                  pages=len(plan.shared_pages))
            pg.start_prefill(slot, req)
        # chunked prefill: at most `prefill_chunk` prompt tokens
        # computed per tick across all prefilling slots (oldest
        # admission first), so a long prompt spreads over ticks and can
        # never stall the co-tenants' decode dispatch below
        budget = self.prefill_chunk
        model_draft = self.spec_decode == "draft" and not self.ngram
        for slot in list(pg.prefill):
            if budget <= 0:
                break
            st = pg.prefill[slot]
            req = st.req
            # spec × prefix sharing (ISSUE 18): a prefix hit starts the
            # TARGET at plan.shared_len but the draft owns no shared
            # pages — walk it through the skipped region with
            # draft-only chunks first (charged to the same prefill
            # budget; the draft is tiny, so this is a sliver of the
            # shared-region savings). Combined chunks resume once the
            # draft has caught up, keeping both models in lockstep.
            while (model_draft and st.draft_next < st.next
                   and budget > 0):
                d_start = st.draft_next
                d_n = min(budget, self.prefill_chunk,
                          st.next - d_start)
                if self._tr is not None:
                    self._tr.emit(req.req_id, "prefill_chunk",
                                  start=d_start, n=d_n, slot=slot,
                                  draft=True)
                t_pad = pg.chunk_bucket(d_n)
                idx = np.zeros((1, t_pad), np.int32)
                idx[0, :d_n] = req.prompt[d_start:d_start + d_n]
                with span("serve_prefill", registry=self._reg):
                    self._dpool = self._draft_chunk_fn(
                        self._dpool, self._dstate, jnp.asarray(idx),
                        jnp.int32(slot), jnp.int32(d_start),
                        jnp.int32(d_n))
                self._reg.counter("prefill_chunks").add(1)
                st.draft_next = d_start + d_n
                budget -= d_n
            if budget <= 0:
                break
            start = st.next
            n_real = min(budget, st.n_prompt - start)
            cow = pg.prepare_chunk(req.req_id, start, n_real)
            if cow is not None:
                if self._tr is not None:
                    self._tr.emit(req.req_id, "cow", src=cow[0],
                                  dst=cow[1])
                self.pool = self._cow_fn(self.pool, jnp.int32(cow[0]),
                                         jnp.int32(cow[1]))
            if self._tr is not None:
                self._tr.emit(req.req_id, "prefill_chunk", start=start,
                              n=n_real, slot=slot)
            t_pad = pg.chunk_bucket(n_real)
            idx = np.zeros((1, t_pad), np.int32)
            idx[0, :n_real] = req.prompt[start:start + n_real]
            k_eff = V if req.top_k is None else max(1, min(int(req.top_k),
                                                           V))
            spec_on = self.spec_decode == "draft"
            tail = None
            with span("serve_prefill", registry=self._reg):
                if model_draft:
                    self.pool, self._dpool, tail = self._chunk_fn(
                        state, self.pool, self._dpool, self._dstate,
                        jnp.asarray(idx),
                        jnp.asarray(pg.table_row(req.req_id)),
                        jnp.int32(slot), jnp.int32(start),
                        jnp.int32(n_real),
                        jax.random.key_data(req.rng),
                        jax.random.key_data(draft_key(req.rng)),
                        jnp.float32(req.temperature), jnp.int32(k_eff),
                    )
                else:
                    self.pool = self._chunk_fn(
                        state, self.pool, jnp.asarray(idx),
                        jnp.asarray(pg.table_row(req.req_id)),
                        jnp.int32(slot), jnp.int32(start),
                        jnp.int32(n_real),
                        jax.random.key_data(req.rng),
                        jnp.float32(req.temperature), jnp.int32(k_eff),
                    )
            self._reg.counter("prefill_chunks").add(1)
            st.next = start + n_real
            st.draft_next = st.next   # combined chunks advance both
            budget -= n_real
            pg.register_progress(slot)
            if self.role == "prefill":
                # export every page the chunk just finished covering —
                # AS it finishes, not at the end, so the router streams
                # pages to the decode class WHILE later chunks compute
                # (handoff latency hides behind the remaining prefill)
                self._collect_exports(slot)
                if st.next >= st.n_prompt:
                    finished.append(self._finish_prefilled(slot))
                continue
            if st.next >= st.n_prompt:
                # prefill done — the slot joins THIS tick's decode (the
                # slab engine's admission->decode-same-tick semantics)
                pg.finish_prefill(slot)
                live = _Live(req)
                if spec_on:
                    if self.ngram:
                        # sample the first token from the final chunk's
                        # spliced logits (pool-only seed fn, one trace)
                        self.pool, tail = self._seed_tail(
                            self.pool, jnp.int32(slot))
                    # only the FINAL chunk's tail is real (earlier
                    # chunks' samples were idempotent overwrites) — one
                    # small D2H per finished prefill, never per token
                    live.pending = [int(tail)]
                    if self.ngram:
                        live.ctx = list(req.prompt) + live.pending
                        live.tail = int(tail)
                    live.k_eff = self.spec_k
                    self._stamp_admission_first_token(live, slot)
                self._live[slot] = live
        if self._live:
            spec_on = self.spec_decode == "draft"
            for slot in sorted(self._live):
                live = self._live[slot]
                # spec verify writes tail..tail+spec_k — pages must
                # cover the whole scratch window (the admission
                # reservation's spec_pad guarantees they can)
                next_pos = (len(live.req.prompt) + len(live.emitted)
                            + len(live.pending) - 1 + self._spec_pad
                            if spec_on else
                            len(live.req.prompt) + len(live.emitted))
                cow = pg.ensure_decode_page(live.req.req_id, next_pos)
                if cow is not None:
                    if self._tr is not None:
                        self._tr.emit(live.req.req_id, "cow",
                                      src=cow[0], dst=cow[1])
                    self.pool = self._cow_fn(self.pool, jnp.int32(cow[0]),
                                             jnp.int32(cow[1]))
            active = np.zeros((self.n_slots,), bool)
            active[list(self._live)] = True
            t_tick = self._clock()
            counts = keff_arr = None
            with span("serve_decode", registry=self._reg):
                if spec_on:
                    # per-slot allocated token coverage: the write mask
                    # for scratch positions past the last owned page
                    limit = np.zeros((self.n_slots,), np.int32)
                    for slot, rid in pg.rid_of.items():
                        limit[slot] = (len(pg.alloc.table(rid))
                                       * self.page_size)
                    keff_arr, k_tick = self._tick_k()
                    if self.ngram:
                        drafts, tails = self._ngram_proposals(k_tick)
                        toks, counts, self.pool = self._step_fn(
                            state, self.pool, jnp.asarray(active),
                            jnp.asarray(drafts), jnp.asarray(tails),
                            jnp.asarray(keff_arr),
                            jnp.asarray(pg.tables_array()),
                            jnp.asarray(limit))
                    else:
                        toks, counts, self.pool, self._dpool = \
                            self._step_fn(
                                state, self._dstate, self.pool,
                                self._dpool, jnp.asarray(active),
                                jnp.asarray(pg.tables_array()),
                                jnp.asarray(limit),
                                jnp.asarray(keff_arr), k_tick)
                    toks = np.asarray(toks)
                    counts = np.asarray(counts)
                else:
                    toks, self.pool = self._step_fn(
                        state, self.pool, jnp.asarray(active),
                        jnp.asarray(pg.tables_array()))
                    toks = np.asarray(toks)  # the per-iteration D2H fence
            self._harvest_tokens(toks, t_tick, finished, counts=counts,
                                 k_eff=keff_arr)
        self._set_gauges()
        a = pg.alloc.stats()
        self._reg.gauge("kv_pages_free").set(a["free"] + a["cached"])
        self._reg.gauge("kv_page_util").set(a["util"])
        self._reg.gauge("prefix_hit_rate").set(pg.prefix_hit_rate())
        assert len(self.traces["prefill"]) <= len(pg.chunk_ladder), (
            "prefill-chunk compiles escaped the chunk ladder"
        )
        assert len(self.traces["draft_prefill"]) <= len(pg.chunk_ladder), (
            "draft-catch-up compiles escaped the chunk ladder"
        )
        assert len(self.traces["step"]) <= len(self._k_ladder), (
            "the paged decode step retraced past the k ladder — a "
            "shape leaked (page tables must ride as traced arguments)"
        )
        assert len(self.traces["seed"]) <= 1, "the ngram seed retraced"
        assert len(self.traces["cow"]) <= 1, "the COW copy retraced"
        assert len(self.traces["import"]) <= len(
            getattr(self, "_import_ladder", ())), (
            "page-import compiles escaped the import ladder")
        return finished

    # ---- disaggregated prefill/decode (ISSUE 13) ----

    def _collect_exports(self, slot):
        """Queue export records for every page slot of `slot`'s request
        newly covered END-TO-END by prompt tokens. The gather reads the
        CURRENT table — a partially attached page that was COWed reads
        the COWed copy, a locally prefix-hit page reads the shared page
        (same bytes this prompt's KV would be) — and materializes to
        numpy immediately, so later page churn cannot corrupt a queued
        export."""
        pg = self._paged
        st = pg.prefill[slot]
        ps = self.page_size
        covered = min(st.next, st.n_prompt)
        last_excl = covered // ps          # page slots fully covered
        if last_excl <= st.exported_upto:
            return
        rid = st.req.req_id
        table = pg.alloc.table(rid)
        idxs = list(range(st.exported_upto, last_excl))
        phys = np.asarray([table[i].page for i in idxs], np.int32)
        # tokens carry the FULL chain from ROOT; `n_prefix` marks where
        # this segment's NEW pages (the shipped arrays) start. KV pages
        # are only meaningful under the exact prefix that produced them
        # (position + context dependence), so the importer anchors each
        # segment on the already-imported chain instead of registering
        # it at the root — an unanchored segment could falsely match a
        # DIFFERENT prompt's prefix (import_chain docstring)
        n_prefix = st.exported_upto
        tokens = [list(st.req.prompt[i * ps:(i + 1) * ps])
                  for i in range(last_excl)]
        if self.kv_dtype == "int8":
            arrays = [np.asarray(self.pool.k.data[:, phys]),
                      np.asarray(self.pool.k.scale[:, phys]),
                      np.asarray(self.pool.v.data[:, phys]),
                      np.asarray(self.pool.v.scale[:, phys])]
        else:
            arrays = [np.asarray(self.pool.k[:, phys]),
                      np.asarray(self.pool.v[:, phys])]
        st.exported_upto = last_excl
        pg.alloc.pages_exported += len(idxs)
        self._reg.counter("kv_pages_exported").add(len(idxs))
        self._page_exports.append({
            "eng_rid": int(rid), "tokens": tokens, "n_prefix": n_prefix,
            "kv_dtype": self.kv_dtype, "arrays": arrays,
        })

    def take_page_exports(self):
        """Drain queued page-export records (role='prefill'). Each is
        {eng_rid, tokens: [page-token lists, FULL chain from ROOT],
        n_prefix: how many of those are anchor-only (already shipped),
        kv_dtype, arrays: [k, v] or [k_data, k_scale, v_data, v_scale]
        covering tokens[n_prefix:]} — the exact (meta, arrays) shape
        serve/frames.encode_kv_pages ships."""
        out, self._page_exports = self._page_exports, []
        return out

    def export_chain(self, token_pages, n_prefix=0):
        """Pull-SOURCE side of the fleet KV CDN (ISSUE 17): gather the
        KV of the registered chain matching `token_pages` (full-page
        token lists from ROOT), skipping the first `n_prefix` pages the
        receiver already holds. Returns an export record in the
        `take_page_exports` shape (eng_rid -1: pulls are request-less),
        or None when nothing beyond the receiver's own prefix survives
        locally — the chain was evicted since the map advertised it,
        and the router just falls back to local prefill.

        The gather walks the allocator's LIVE chain (not the advertised
        summary), so a stale or overstated map entry degrades to a
        shorter — still exact — export, never a wrong one."""
        assert self._paged is not None, "chain export needs kv_impl='paged'"
        pages = self._paged.alloc.lookup_chain(token_pages)
        n = len(pages)
        n_prefix = int(n_prefix)
        if n <= n_prefix:
            return None
        # pad the gather index to a power-of-2 bucket (same rule as the
        # import scatter) so XLA compiles one gather per bucket, not
        # one per chain length — page 0 repeats as harmless filler and
        # the slice below drops it
        from avenir_tpu.infer.decode import prompt_bucket

        L = n - n_prefix
        width = prompt_bucket(L, self.max_pages_per_seq, floor=1)
        phys = np.zeros((width,), np.int32)
        phys[:L] = pages[n_prefix:]
        if self.kv_dtype == "int8":
            arrays = [np.asarray(self.pool.k.data[:, phys])[:, :L],
                      np.asarray(self.pool.k.scale[:, phys])[:, :L],
                      np.asarray(self.pool.v.data[:, phys])[:, :L],
                      np.asarray(self.pool.v.scale[:, phys])[:, :L]]
        else:
            arrays = [np.asarray(self.pool.k[:, phys])[:, :L],
                      np.asarray(self.pool.v[:, phys])[:, :L]]
        self._paged.alloc.pages_exported += n - n_prefix
        self._reg.counter("kv_pages_exported").add(n - n_prefix)
        tokens = [[int(t) for t in token_pages[i]] for i in range(n)]
        return {"eng_rid": -1, "tokens": tokens, "n_prefix": n_prefix,
                "kv_dtype": self.kv_dtype, "arrays": arrays}

    def import_kv_pages(self, tokens, arrays, kv_dtype="bf16",
                        n_prefix=0):
        """Splice transferred KV pages into this engine's pool +
        allocator (decode-class side of the handoff). `tokens` is the
        chain identity (full-page token lists from ROOT — the first
        `n_prefix` are anchors whose KV already landed in an earlier
        segment), `arrays` the page KV for tokens[n_prefix:]. Already-
        known chain nodes are deduped (their KV is bit-identical by the
        exact-token key — nothing to write); new nodes get physical
        pages from the allocator and ONE padded scatter writes their
        KV. Returns the number of pages actually written. A partial
        import (pool pressure, or a missing anchor) is fine: the
        handoff submit's plan() attaches whatever prefix landed and
        recomputes the rest — exactness never depends on the import."""
        assert self._paged is not None, "page import needs kv_impl='paged'"
        assert kv_dtype == self.kv_dtype, (
            f"kv transfer dtype {kv_dtype!r} != engine kv_dtype "
            f"{self.kv_dtype!r} — a disaggregated fleet must serve one "
            "KV dtype (fail-loud, the handshake policy)")
        pairs = self._paged.alloc.import_chain(tokens, n_prefix=n_prefix)
        new = [(i, p) for i, (p, is_new) in enumerate(pairs) if is_new]
        if not new:
            return 0
        from avenir_tpu.infer.decode import prompt_bucket
        self._reg.counter("kv_pages_imported").add(len(new))
        width = prompt_bucket(len(new), self.max_pages_per_seq, floor=1)
        phys = np.full((width,), self.n_pages, np.int32)
        phys[:len(new)] = [p for _, p in new]
        sel = [i - n_prefix for i, _ in new]

        def pad(a):
            out = np.zeros((a.shape[0], width) + a.shape[2:], a.dtype)
            out[:, :len(new)] = a[:, sel]
            return out

        if self.kv_dtype == "int8":
            from avenir_tpu.ops.kv_quant import QuantKV

            kd, ks, vd, vs = arrays
            k_in = QuantKV(jnp.asarray(pad(kd)), jnp.asarray(pad(ks)))
            v_in = QuantKV(jnp.asarray(pad(vd)), jnp.asarray(pad(vs)))
        else:
            k, v = arrays
            k_in, v_in = jnp.asarray(pad(k)), jnp.asarray(pad(v))
        self.pool = self._import_fn(self.pool, jnp.asarray(phys),
                                    k_in, v_in)
        return len(new)

    def _finish_prefilled(self, slot):
        """Prefill-class completion: the prompt's KV is computed and
        every full page exported — finish with reason='prefilled'
        (n_out=0; the ROUTER owns the handoff and the request's real
        terminal record comes from the decode-class replica, so no
        serve_requests bump and no terminal trace event here — exactly
        one `finish` per fleet request is the trace lint's contract)."""
        pg = self._paged
        st = pg.prefill[slot]
        req = st.req
        pg.release(slot)      # pops prefill state, frees/caches pages
        self.sched.release(slot)
        # NO sink record either: kind='request' JSONL rows are
        # one-per-terminal-request (obs_report counts them), and the
        # terminal row comes from the decode-class replica
        return FinishedRequest(
            req_id=req.req_id, tokens=list(req.prompt),
            n_prompt=len(req.prompt), n_out=0,
            finish_reason="prefilled",
            text="" if self.detokenize is not None else None,
            ttft_ms=None, tpot_ms=0.0,
        )

    def _stamp_admission_first_token(self, live, slot):
        """Spec decoding samples the request's FIRST token INSIDE the
        admission prefill (`_seed_spec_slot`; the `int(tail)` above is
        a host-visible fetch) — TTFT truth anchors here, not at the
        verify tick that happens to harvest the `pending` token, which
        for an engine's first request would silently fold the decode-
        step COMPILE into prefill attribution. Stamping at admission
        keeps the trace partition exact: queue + prefill (+ failover)
        ends where the token actually landed (ISSUE 12 satellite;
        regression-pinned in tests/test_spec_decode.py)."""
        now = self._clock()
        live.t_first = live.t_last = now
        self._reg.hist("ttft_ms").observe(
            (now - live.req.submit_t) * 1e3)
        if self._tr is not None:
            self._tr.emit(live.req.req_id, "first_token", t=now,
                          slot=slot, admission=True)

    # adaptive spec_k (ISSUE 18): per-slot accept-rate EWMA weight and
    # the rung-walk thresholds — shrink a rung when the smoothed accept
    # rate can't keep the wider verify worthwhile, grow one back when
    # nearly everything is accepted. The floor is the ladder's first
    # rung (k=1): speculation never turns OFF, it degrades to the
    # cheapest width — which is what the accept_rate_collapse runbook
    # row means by "the adaptive-k floor" (docs/OPERATIONS.md).
    _K_EWMA = 0.3
    _K_SHRINK_BELOW = 0.35
    _K_GROW_ABOVE = 0.8

    def _harvest_tokens(self, toks, t_tick, finished, counts=None,
                        k_eff=None):
        """Post-decode harvest shared by both KV impls: per-slot token
        append/detokenize, stop/budget checks, then deadline eviction
        AFTER harvest — this iteration's token is kept (the request
        pays for it either way), then the slot is recycled; surviving
        co-tenants are untouched, so their streams stay bit-identical
        to a one-shot run (the same argument as stop-token recycling;
        parity-tested).

        `counts` (spec decoding, ISSUE 11): toks is (B, spec_k+1) and
        each live slot harvests its first counts[slot] entries — plus
        any admission-sampled pending first token — IN ORDER, with the
        stop/budget check after every token, so a mid-block stop or a
        budget edge truncates exactly where sequential decoding would
        have stopped (the device may have verified further; those
        tokens are discarded with the slot, like any over-advanced
        speculative state)."""
        now = self._clock()
        self._tick_s.append(now - t_tick)
        if len(self._tick_s) > 64:
            del self._tick_s[:32]
        tr = self._tr
        n_live = len(self._live)
        spec_accepted = spec_proposed = 0
        if counts is not None:
            # accepted DRAFT tokens this tick (the bonus/correction
            # token is target-sampled, not a draft acceptance);
            # proposed = the sum of per-slot EFFECTIVE k (ISSUE 18) —
            # with fixed spec_k that is spec_k * n_live, as ever
            spec_accepted = int(sum(int(counts[s]) - 1
                                    for s in self._live))
            spec_proposed = int(sum(int(k_eff[s]) for s in self._live))
            self._reg.counter("spec_proposed").add(spec_proposed)
            self._reg.counter("spec_accepted").add(spec_accepted)
            prop = self._reg.counter("spec_proposed").total
            acc = self._reg.counter("spec_accepted").total
            self._reg.gauge("spec_accept_rate").set(
                acc / prop if prop else 0.0)
            self._reg.gauge("spec_k_effective").set(
                spec_proposed / n_live if n_live else 0.0)
            if self.spec_k_auto:
                # rung walk BEFORE any slot finishes below: each live
                # slot smooths its own accept rate and moves one ladder
                # rung at most per tick (floor k=1, cap spec_k)
                for s in self._live:
                    live = self._live[s]
                    rate = (int(counts[s]) - 1) / max(int(k_eff[s]), 1)
                    live.acc_ewma = (
                        rate if live.acc_ewma is None else
                        (1 - self._K_EWMA) * live.acc_ewma
                        + self._K_EWMA * rate)
                    i = self._k_ladder.index(live.k_eff)
                    if (live.acc_ewma < self._K_SHRINK_BELOW and i > 0):
                        live.k_eff = self._k_ladder[i - 1]
                    elif (live.acc_ewma > self._K_GROW_ABOVE
                          and i + 1 < len(self._k_ladder)):
                        live.k_eff = self._k_ladder[i + 1]
        # decode ticks ever == batched model passes (the denominator of
        # the effective tokens-per-model-pass headline, tools/
        # bench_decode.py) — counted with or without tracing
        self._tick_n += 1
        if tr is not None:
            # SAMPLED: one event per decode_sample batched iterations —
            # tracing on must not write an event per token either
            if self._tick_n % tr.decode_sample == 0:
                tr.emit(None, "decode_tick", t=now,
                        n_live=n_live, tick=self._tick_n)
                if counts is not None:
                    tr.emit(None, "spec_verify", t=now,
                            proposed=spec_proposed,
                            accepted=spec_accepted, tick=self._tick_n,
                            spec_draft_source=(
                                "ngram" if self.ngram else "model"),
                            k_eff=(spec_proposed / n_live
                                   if n_live else 0.0))
        emitted_total = 0
        for slot in sorted(self._live):
            live = self._live[slot]
            if counts is None:
                seq = [int(toks[slot])]
            else:
                seq = list(live.pending)
                live.pending = []
                new = [int(t) for t in toks[slot][:int(counts[slot])]]
                if live.ctx is not None:
                    # ngram: the lookup context tracks every sampled
                    # token (pending tokens are already in it), while
                    # `tail` — the next verify block's first input —
                    # advances from the harvest itself, so ctx stays a
                    # pure proposer hint
                    live.ctx.extend(new)
                    if new:
                        live.tail = new[-1]
                seq += new
            for tok in seq:
                live.emitted.append(tok)
                emitted_total += 1
                if live.t_first is None:
                    live.t_first = now
                    self._reg.hist("ttft_ms").observe(
                        (now - live.req.submit_t) * 1e3)
                    if tr is not None:
                        tr.emit(live.req.req_id, "first_token", t=now,
                                slot=slot)
                live.t_last = now
                if self.detokenize is not None:
                    live.text += self.detokenize([tok])
                hit_stop = tok in live.req.stop_tokens
                if hit_stop or len(live.emitted) >= live.req.max_new_tokens:
                    finished.append(self._finish(
                        slot, live, "stop" if hit_stop else "length"))
                    break
        self._reg.counter("tokens_out").add(emitted_total)
        now = self._clock()
        for slot in sorted(self._live):
            live = self._live[slot]
            if live.req.expired(now):
                finished.append(self._finish(slot, live, "timeout"))

    def _set_gauges(self):
        self._reg.gauge("queue_depth").set(self.sched.queue_depth)
        occupied = len(self._live)
        if self._paged is not None:
            occupied += len(self._paged.prefill)
        self._reg.gauge("slot_occupancy").set(occupied / self.n_slots)

    def evict(self, rids):
        """Host-driven expiry (ISSUE 8): a process worker's PARENT owns
        the deadline clock (worker clocks are unrelated to the fleet's,
        injectable test clocks doubly so), so it names the expired
        requests and the engine evicts them with timeout semantics — a
        queued one finishes without ever taking a slot, a live one
        finishes with its partial tokens and frees the slot for this
        step's admissions. Returns the finished records."""
        rids = set(rids)
        out = []
        if not rids:
            return out
        for slot in sorted(self._live):
            live = self._live[slot]
            if live.req.req_id in rids:
                out.append(self._finish(slot, live, "timeout"))
        if self._paged is not None:
            for slot in sorted(self._paged.prefill):
                if self._paged.prefill[slot].req.req_id in rids:
                    out.append(self._finish_prefilling_timeout(slot))
        out.extend(self._finish_queued_timeout(r)
                   for r in self.sched.remove(rids))
        if self._paged is not None:
            # eviction is the page-leak-prone path (ISSUE 9 satellite):
            # every eviction re-proves the allocator's refcount/freed
            # partition from the live tables
            self._paged.audit()
        return out

    def drain(self):
        """Run steps until queue and slots are empty; returns every
        request finished along the way. Under paged KV the drained
        allocator is AUDITED: refcounts must sum to zero live pages and
        the free/cached lists must account for the whole pool — a page
        leak fails loud here, not as slow capacity loss (ISSUE 9)."""
        open_reqs = ([lv.req for lv in self._live.values()]
                     + list(self.sched._queue))
        prefill_ticks = 0
        if self._paged is not None:
            open_reqs += [st.req for st in self._paged.prefill.values()]
            chunk = self.prefill_chunk
            # chunked prefill spreads each prompt over ceil(len/chunk)
            # ticks, and budget-blocked admission can wait behind every
            # earlier request's ticks — double the linear bound
            prefill_ticks = sum(-(-len(r.prompt) // chunk) + 1
                                for r in open_reqs)
        bound = 2 + len(self._pending) + self.sched.queue_depth + 2 * (
            prefill_ticks
            + sum(r.max_new_tokens for r in open_reqs))
        out = []
        steps = 0
        while self.open_work:
            out.extend(self.step())
            steps += 1
            if steps > bound:
                raise RuntimeError(
                    f"engine failed to drain within {bound} iterations")
        if self._paged is not None:
            self._paged.audit(expect_empty=True)
        return out

    def reset_host_state(self):
        """Rejoin-empty reset (serve/replica.py revive): fresh
        scheduler, live map and prefill state cleared, paged allocator
        re-initialized. KV contents are NOT scrubbed — stale rows/pages
        stay masked until overwritten (the slot-hygiene invariant)."""
        self._live.clear()
        self._pending = []
        self._page_exports = []   # a revived replica's old exports are
        #                           for requests that already failed over
        self.sched = FCFSScheduler(self.n_slots, self.T_max)
        if self._paged is not None:
            self._paged.reset()

    def prewarm(self):
        """Compile pre-warm (ISSUE 12): one synthetic prefill + decode
        tick per prompt bucket (slab) / chunk bucket (paged), run at
        spawn — inside the worker hello for the process backend —
        BEFORE the replica is dispatchable, so a fresh replica never
        serves its first compile to a user (the p99 cliff the trace
        reports attributed to fresh workers).

        Muted: the synthetic requests run against a throwaway registry,
        a NullSink and no tracer — only `prewarm_ticks` lands on the
        real registry, so prewarmed and cold engines tell identical
        serving stories. The request-id counter is restored afterwards
        so default per-rid rng streams match an un-warmed engine's.
        Returns the tick count."""
        assert not self.open_work, "prewarm needs an idle engine"
        from avenir_tpu.infer.decode import prompt_bucket
        from avenir_tpu.obs.metrics import MetricsRegistry

        reg, self._reg = self._reg, MetricsRegistry()
        sink, self.sink = self.sink, NullSink()
        tr, self._tr = self._tr, None
        next_id = self._next_id
        ticks = 0
        try:
            if self._paged is not None:
                ladder, cap = (self._paged.chunk_ladder,
                               self.prefill_chunk)
            else:
                ladder, cap = self.sched.ladder, self.T_max
            V = self.model.config.vocab_size
            for bi, b in enumerate(ladder):
                n = min(b, self.max_total_tokens - 1)
                if n < 1 or prompt_bucket(n, cap) != b:
                    continue  # token budget cannot reach this bucket
                # distinct token content per bucket: identical prompts
                # would prefix-hit under paged sharing and the shared
                # chunk would skip the very compile being warmed
                self.submit([(bi + 1) % V] * n, max_new_tokens=1,
                            rng=jax.random.key(0))
                while self.open_work:
                    self.step()
                    ticks += 1
        finally:
            self._reg, self.sink, self._tr = reg, sink, tr
            self._next_id = next_id
        self._reg.counter("prewarm_ticks").add(ticks)
        return ticks

    # ---- internals ----

    def _finish(self, slot, live, reason):
        req = live.req
        if live.pending:
            # spec decoding: an admission-sampled first token that was
            # never harvested (evicted between admission and its first
            # verify tick) is still a PRODUCED token — its t_first is
            # already stamped, so dropping it here would finish a
            # request with ttft_ms set and n_out=0; deliver it instead
            for tok in live.pending:
                live.emitted.append(tok)
                if self.detokenize is not None:
                    live.text += self.detokenize([tok])
            self._reg.counter("tokens_out").add(len(live.pending))
            live.pending = []
        del self._live[slot]
        self.sched.release(slot)
        if self._paged is not None:
            # deref this request's pages: owned unregistered ones free,
            # registered prefix pages it held become cached/evictable,
            # shared pages just drop a refcount; the reservation tail
            # (stop-token early finishes) is returned too
            self._paged.release(slot)
        # restore the slot's sampling params to the pool default (k=V =
        # "no top-k") — a recycled-but-empty slot must not keep its last
        # request's finite k, or the _sample_rows runtime sort-skip
        # (all rows >= V) would never fire again after the first top-k
        # request. One tiny host-driven update per FINISHED request,
        # nowhere near the per-token path.
        V = self.pool.logits.shape[-1]
        self.pool = self.pool._replace(
            top_k=self.pool.top_k.at[slot].set(V))
        n_out = len(live.emitted)
        ttft_ms = ((live.t_first - req.submit_t) * 1e3
                   if live.t_first is not None else None)
        tpot_ms = ((live.t_last - live.t_first) / (n_out - 1) * 1e3
                   if n_out > 1 else 0.0)
        self._reg.counter("serve_requests").add(1)
        if reason == "timeout":
            self._reg.counter("serve_timeouts").add(1)
        if n_out > 1:  # tpot is undefined for single-token requests
            self._reg.hist("tpot_ms").observe(tpot_ms)
        rec = FinishedRequest(
            req_id=req.req_id, tokens=list(req.prompt) + live.emitted,
            n_prompt=len(req.prompt), n_out=n_out, finish_reason=reason,
            text=live.text if self.detokenize is not None else None,
            ttft_ms=ttft_ms, tpot_ms=tpot_ms,
        )
        record = {
            "kind": "request", "t": time.time(), "id": req.req_id,
            "n_prompt": rec.n_prompt, "n_out": n_out,
            "finish_reason": reason,
        }
        if ttft_ms is not None:
            record["ttft_ms"] = ttft_ms
        if n_out > 1:  # omitted (not 0.0) so report percentiles stay honest
            record["tpot_ms"] = tpot_ms
        self.sink.write(record)
        if self._tr is not None:
            if reason == "timeout":
                self._tr.emit(req.req_id, "evict", slot=slot)
            self._tr.emit(req.req_id, "finish", reason=reason,
                          n_out=n_out)
        return rec

    def _finish_prefilling_timeout(self, slot):
        """Deadline death mid-chunked-prefill (paged only): no token was
        ever produced, so the record is the queued-timeout shape — but
        the slot and every page (including the unspent reservation)
        free immediately."""
        st = self._paged.prefill[slot]
        self._paged.release(slot)   # pops the prefill state + pages
        self.sched.release(slot)
        if self._tr is not None:
            # it HELD a slot and burned prefill compute — trace it as an
            # eviction, not a queued death (the record shape stays the
            # queued-timeout one: no token was ever produced)
            self._tr.emit(st.req.req_id, "evict", slot=slot,
                          prefilling=True)
        return self._finish_queued_timeout(st.req, queued=False)

    def _finish_queued_timeout(self, req, queued=True):
        """A request whose deadline passed while it was still QUEUED: it
        never held a slot and emitted nothing — no pool state to touch.
        (`queued=False` from the mid-prefill eviction path, which shares
        the record shape but DID hold a slot — its trace says so.)"""
        self._reg.counter("serve_requests").add(1)
        self._reg.counter("serve_timeouts").add(1)
        rec = FinishedRequest(
            req_id=req.req_id, tokens=list(req.prompt),
            n_prompt=len(req.prompt), n_out=0, finish_reason="timeout",
            text="" if self.detokenize is not None else None,
            ttft_ms=None, tpot_ms=0.0,
        )
        self.sink.write({
            "kind": "request", "t": time.time(), "id": req.req_id,
            "n_prompt": rec.n_prompt, "n_out": 0,
            "finish_reason": "timeout",
        })
        if self._tr is not None:
            self._tr.emit(req.req_id, "finish", reason="timeout",
                          n_out=0, queued=queued)
        return rec
