"""Serve worker process: one Engine behind a frame-RPC loop (ISSUE 8
tentpole, part 2).

    python -m avenir_tpu.serve.worker

docs/SERVING.md promised "one process per chip in a deployment"; this
is that process. It owns exactly one `serve.Engine` and speaks the
`serve/frames.py` protocol over its stdin/stdout pipes — it makes NO
scheduling, failover or admission decisions (those stay in the parent's
Router, which is why the router's semantics are identical over both
backends). The parent is `serve/proc.ProcReplica`.

Protocol (one request frame in, one reply frame out, strictly serial —
the parent's per-op timeout is the liveness check, so a worker that
cannot reply IS a dead worker):

    hello      (pickle) proto version + model spec + engine kwargs.
               The model arrives as (family, config dataclass, numpy
               state) and is rebuilt with `nnx.update`, so worker
               weights are BIT-identical to the parent's — the fleet's
               failover parity contract depends on it. A `checkpoint`
               spec loads ckpt.pt from disk instead (big models should
               not ride a pipe). Replies {ok, proto, t_max, pid}.
    submit     enqueue one request; rng rides as raw uint32 key data.
               `age_ms` backdates submit_t onto THIS process's clock
               (pipes do not share a clock with the parent).
    step       one engine iteration; replies finished records, the
               engine heartbeat (`Engine.stats()`), which requests got
               their FIRST token this step (the parent stamps latency
               on its own clock), and the worker's counter totals (the
               parent mirrors deltas into the fleet registry).
    ping       liveness probe (the only idempotent op — the only one
               the parent ever retries).
    arm_fault  install a seeded FaultInjector spec in THIS worker
               (chaos harness targeting; env AVENIR_FAULTS also works
               but applies to every worker spawned with it).
    shutdown   reply, then exit 0.

Fault sites consulted here (the chaos drill's production paths):

    worker_kill   SIGKILL this process mid-step — the real thing, not
                  an injected exception; the parent sees pipe EOF
    worker_hang   stop replying forever (a wedged collective); only
                  the parent's RPC timeout can tell
    frame_corrupt flip a byte of an outgoing payload after its CRC is
                  computed (serve/frames.py writer) — trips the
                  parent's CRC check

Every human-readable byte goes to stderr: fd 1 is dup'd for frames and
then redirected to stderr, so a stray print() (jax warnings, model
chatter) can never desync the frame stream.
"""

import os
import signal
import sys
import time

from avenir_tpu.serve.frames import PROTO_VERSION, FrameEOF, FrameStream


def _build_model(spec):
    """Model from a handshake spec. Imports live here, after the frame
    fds are secured, so import-time chatter lands on stderr."""
    import jax
    from flax import nnx

    kind = spec.get("kind")
    if kind == "state":
        family = spec["family"]
        if family == "gpt":
            from avenir_tpu.models.gpt import GPT as cls
        elif family == "llama":
            from avenir_tpu.models.llama import Llama as cls
        elif family == "mixtral":
            from avenir_tpu.models.mixtral import Mixtral as cls
        else:
            raise ValueError(f"unknown model family {family!r}")
        model = cls(spec["config"], rngs=nnx.Rngs(0))
        # the parent's weights, bit-for-bit — init seed is irrelevant
        nnx.update(model, jax.tree.map(jax.numpy.asarray, spec["state"]))
        return model
    if kind == "checkpoint":
        from avenir_tpu.checkpoint.io import load_checkpoint
        from avenir_tpu.sampling import model_from_checkpoint

        model, _family = model_from_checkpoint(
            load_checkpoint(spec["out_dir"]))
        return model
    raise ValueError(f"unknown model spec kind {kind!r}")


def _serve(stream):
    """Handshake, then the op loop. Returns the exit code."""
    from avenir_tpu.utils.faults import FaultInjector, get_injector, \
        set_injector

    hello = stream.read(timeout_s=600.0)
    hseq = hello.get("seq")
    if hello.get("op") != "hello":
        stream.write({"ok": False, "seq": hseq,
                      "error": f"expected hello, got {hello.get('op')!r}"})
        return 2
    if hello.get("proto") != PROTO_VERSION:
        # the frame layer already rejects a mismatched frame VERSION;
        # this op-level echo catches a peer whose frames parse but whose
        # message vocabulary moved — same policy: refuse loudly
        stream.write({
            "ok": False, "seq": hseq,
            "error": (f"hello proto {hello.get('proto')} != worker proto "
                      f"{PROTO_VERSION} — upgrade both sides together"),
        })
        return 2

    import jax  # noqa: F401  (engine import below needs the runtime up)

    from avenir_tpu.obs import get_registry
    from avenir_tpu.serve.engine import Engine

    ekw = dict(hello.get("engine") or {})
    reg = get_registry()
    # paged-KV + decode-speed knobs ride the handshake (ISSUEs 9 + 11):
    # the parent decides kv_impl/kv_dtype/spec geometry, the worker only
    # obeys — None values fall back to the Engine's own defaults
    kv_kw = {k: ekw[k] for k in
             ("kv_impl", "page_size", "n_pages", "max_pages_per_seq",
              "prefill_chunk", "prefix_sharing", "paged_attn_impl",
              "kv_dtype", "spec_decode", "spec_k", "role",
              "health_series", "chain_topk", "weight_version")
             if ekw.get(k) is not None}
    # request tracing (ISSUE 10): the parent's hello flips this flag;
    # the engine collects lifecycle events in a bounded buffer and every
    # reply ships the drained events as clock-free AGE deltas (pipes do
    # not share clocks — the parent restamps on ITS clock, the same
    # pattern submit_t already rides as age_ms)
    tbuf = None
    if ekw.get("trace"):
        from avenir_tpu.obs.trace import TraceBuffer

        # the hello's trace value IS the decode-tick sampling interval
        tbuf = TraceBuffer(decode_sample=int(ekw["trace"]))
    # the DRAFT model ships in the hello exactly like the target (ISSUE
    # 11): same (family, config, numpy state) spec, rebuilt bit-identical
    # — so fleet spec decoding needs zero router/proc semantic changes.
    # An Engine that refuses the pair (vocab/width mismatch) becomes an
    # error REPLY: the parent's handshake fails loud with the reason
    # instead of a pipe EOF (docs/OPERATIONS.md failure matrix)
    try:
        # draft_model='ngram' (ISSUE 18) is a STRING riding the engine
        # kwargs — the draft-free self-draft ships no second model in
        # the hello at all, which is the point
        draft = (_build_model(hello["draft"])
                 if hello.get("draft") is not None
                 else ekw.get("draft_model"))
        engine = Engine(
            _build_model(hello["model"]),
            n_slots=int(ekw.get("n_slots", 4)),
            max_seq_len=ekw.get("max_seq_len"),
            detokenize=ekw.get("detokenize"),
            seed=int(ekw.get("seed", 0)),
            registry=reg,
            tracer=tbuf,
            draft_model=draft,
            **kv_kw,
        )
    except (ValueError, AssertionError) as e:
        stream.write({"ok": False, "seq": hseq,
                      "error": f"{type(e).__name__}: {e}"})
        return 2
    if tbuf is not None:
        tbuf.clock = engine._clock  # ages measured on the event clock
    # compile pre-warm (ISSUE 12): the hello triggers one synthetic
    # prefill + decode tick per bucket BEFORE the ok reply goes out —
    # the parent's ProcReplica is not dispatchable until the handshake
    # returns, so a fresh worker never serves its first compile to a
    # user. The tick count rides the hello reply; the worker-registry
    # `prewarm_ticks` counter mirrors to the fleet via the usual
    # per-reply counter deltas.
    prewarm_ticks = 0
    if ekw.get("prewarm"):
        prewarm_ticks = engine.prewarm()

    def drain_trace():
        if tbuf is None:
            return {}
        dropped, tbuf.dropped = tbuf.dropped, 0
        out = {"trace": tbuf.drain_aged()}
        if dropped:
            out["trace_dropped"] = dropped
        return out
    stream.write({"ok": True, "seq": hseq, "proto": PROTO_VERSION,
                  "t_max": engine.T_max, "n_slots": engine.n_slots,
                  "limit_tokens": engine.max_total_tokens,
                  "limit_name": engine.limit_name,
                  "kv_impl": engine.kv_impl,
                  "kv_dtype": engine.kv_dtype,
                  "spec_decode": engine.spec_decode,
                  "role": engine.role,
                  "weight_version": engine.weight_version,
                  "prewarm_ticks": prewarm_ticks,
                  "pid": os.getpid()})

    def hb():
        return engine.stats()

    while True:
        req = stream.read(timeout_s=None)  # the parent paces the loop
        op = req.get("op")
        seq = req.get("seq")

        def send(obj):
            # every reply echoes its request's seq, so a parent that
            # retried a timed-out op (ping) can discard the late reply
            # to the first attempt instead of desyncing request/reply
            # alignment for every RPC after it
            obj["seq"] = seq
            stream.write(obj)

        try:
            if op == "step":
                inj = get_injector()
                if inj.should_fire("worker_kill"):
                    # the REAL failure: no goodbye frame, no flush — the
                    # parent learns from pipe EOF, exactly like an OOM
                    # kill or a preempted node
                    os.kill(os.getpid(), signal.SIGKILL)
                if inj.should_fire("worker_hang"):
                    while True:  # a wedge, not an exit: the process
                        time.sleep(3600)  # lives on, silently useless
                pre = {int(lv.req.req_id): len(lv.emitted)
                       for lv in engine._live.values()}
                # parent-named expiry FIRST: deadline clocks live in
                # the parent (Engine.evict docstring), and an evicted
                # slot is free for this very step's admissions
                finished = engine.evict(req.get("expire") or ())
                finished += engine.step()
                post = {int(lv.req.req_id): len(lv.emitted)
                        for lv in engine._live.values()}
                first = [rid for rid, n in post.items()
                         if n >= 1 and pre.get(rid, 0) == 0]
                first += [int(f.req_id) for f in finished
                          if f.n_out >= 1 and pre.get(int(f.req_id), 0) == 0]
                # health-series sketch deltas (ISSUE 14): mergeable
                # bucket counts since the last reply — the parent
                # merges them into the fleet series exactly like the
                # counter deltas below (None when the series is off)
                series = engine.take_series_delta()
                # prefix-chain summary deltas (ISSUE 16): same wire
                # pattern — incremental, absent when nothing changed,
                # merged parent-side into the _EngineProxy mirror
                chains = engine.take_chain_delta()
                send({
                    "ok": True,
                    "finished": [_fin_dict(f) for f in finished],
                    "first": first,
                    "hb": hb(),
                    **({"series": series} if series else {}),
                    **({"chains": chains} if chains else {}),
                    "counters": reg.counters(),
                    # disagg (ISSUE 13): queued page exports stay here
                    # (tensors never ride a JSON reply) — the parent
                    # sees the count and fetches a PT_KVPAGES frame
                    "n_exports": len(engine._page_exports),
                    **drain_trace(),
                })
            elif op == "submit":
                rng = None
                if req.get("rng") is not None:
                    rng = jax.random.wrap_key_data(
                        jax.numpy.asarray(req["rng"], jax.numpy.uint32))
                submit_t = None
                if req.get("age_ms") is not None:
                    submit_t = engine._clock() - float(req["age_ms"]) / 1e3
                rid = engine.submit(
                    req["prompt"],
                    max_new_tokens=int(req["max_new_tokens"]),
                    temperature=float(req.get("temperature", 1.0)),
                    top_k=req.get("top_k"),
                    stop_tokens=tuple(req.get("stop_tokens") or ()),
                    rng=rng,
                    deadline_ms=req.get("deadline_ms"),
                    submit_t=submit_t,
                    front=bool(req.get("front")),
                )
                send({"ok": True, "rid": int(rid), "hb": hb(),
                      "counters": reg.counters(), **drain_trace()})
            elif op == "fetch_pages":
                # drain queued exports into ONE PT_KVPAGES tensor frame
                # (ISSUE 13): meta carries the token-chain ids per
                # record, arrays carry the raw page KV (+ int8 scales)
                from avenir_tpu.serve.frames import PT_KVPAGES

                recs = engine.take_page_exports()
                meta = {"ok": True, "seq": seq,
                        "records": [{"eng_rid": r["eng_rid"],
                                     "tokens": r["tokens"],
                                     "n_prefix": r.get("n_prefix", 0),
                                     "kv_dtype": r["kv_dtype"]}
                                    for r in recs]}
                flat = [a for r in recs for a in r["arrays"]]
                stream.write((meta, flat), ptype=PT_KVPAGES)
            elif op == "pull_chain":
                # fleet KV CDN pull source (ISSUE 17): gather the
                # requested chain's surviving pages into ONE PT_KVPAGES
                # tensor frame. record=None means the chain was evicted
                # since the map advertised it — the router falls back
                # to local prefill (pulls are never a correctness
                # dependency)
                from avenir_tpu.serve.frames import PT_KVPAGES

                rec = engine.export_chain(
                    req["tokens"], n_prefix=int(req.get("n_prefix", 0)))
                meta = {"ok": True, "seq": seq, "record": None}
                flat = []
                if rec is not None:
                    meta["record"] = {"eng_rid": rec["eng_rid"],
                                      "tokens": rec["tokens"],
                                      "n_prefix": rec["n_prefix"],
                                      "kv_dtype": rec["kv_dtype"]}
                    flat = list(rec["arrays"])
                stream.write((meta, flat), ptype=PT_KVPAGES)
            elif op == "import_pages":
                # inbound PT_KVPAGES frame: splice the chains into the
                # local allocator + pool (decode-class side)
                from avenir_tpu.serve.frames import ARRAYS_PER_DTYPE

                arrays = req["arrays"]
                written = 0
                off = 0
                for rec in req.get("records", ()):
                    n = ARRAYS_PER_DTYPE[rec["kv_dtype"]]
                    written += engine.import_kv_pages(
                        rec["tokens"], arrays[off:off + n],
                        kv_dtype=rec["kv_dtype"],
                        n_prefix=int(rec.get("n_prefix", 0)))
                    off += n
                send({"ok": True, "written": int(written), "hb": hb(),
                      "counters": reg.counters()})
            elif op == "ping":
                send({"ok": True, "hb": hb(), "pid": os.getpid()})
            elif op == "chains":
                # debug/parity op (ISSUE 16): the DIRECT summary on this
                # worker's own allocator — the oracle the parent's
                # delta-merged mirror is pinned against in tests
                send({"ok": True, "chains": engine.chain_summary()})
            elif op == "arm_fault":
                # CONSTRUCT (validate) first — a bad spec must become an
                # error reply, not raise after an ok was already written
                # (one reply per request, always); INSTALL after the
                # reply goes out, so an armed frame_corrupt hits a real
                # production frame (the next step reply), not the ack of
                # its own arming
                inj_new = FaultInjector(req.get("spec", ""),
                                        seed=int(req.get("seed", 0)))
                send({"ok": True})
                set_injector(inj_new)
            elif op == "shutdown":
                send({"ok": True})
                return 0
            else:
                send({"ok": False, "error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 — a step failure is a
            # ROUTABLE event: report it and let the parent decide (it
            # marks this replica dead and fails the work over); only
            # protocol-level breakage should kill the loop itself
            send({"ok": False, "error": f"{type(e).__name__}: {e}"})


def _fin_dict(f):
    import dataclasses

    return dataclasses.asdict(f)


def main():
    # frames own fd 1; anything that prints (jax, warnings, the model)
    # is redirected to stderr so it cannot desync the stream. When
    # spawned by serve/proc.py the BOOTSTRAP did this before ANY
    # package import (import-time stdout chatter would otherwise land
    # on the frame pipe) and left the frame fd in the env; a manual
    # `python -m avenir_tpu.serve.worker` falls back to doing it here.
    fd_env = os.environ.get("AVENIR_WORKER_FRAME_FD")
    if fd_env is not None:
        frame_out = int(fd_env)
    else:
        frame_out = os.dup(1)
        os.dup2(2, 1)
        sys.stdout = sys.stderr
    from avenir_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    stream = FrameStream(0, frame_out)
    try:
        sys.exit(_serve(stream))
    except (FrameEOF, BrokenPipeError):
        # the parent closed the pipes (teardown of a replica it already
        # declared dead, or the parent itself died) — nothing left to
        # serve and nobody to tell: exit quietly, not with a traceback
        sys.exit(0)


if __name__ == "__main__":
    main()
