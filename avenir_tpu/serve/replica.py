"""Health-checked serve replica: one Engine plus a failure story
(ISSUE 6 tentpole, part 1).

A replica is an in-process handle wrapping one `serve.Engine`, so the
whole fleet is CPU-testable and fault-injectable — in a deployment each
replica is one process on one chip and this object is the router's view
of it (state, heartbeat, step driver). Two failure modes are modeled,
both through `utils/faults.py` sites so the PRODUCTION failover path is
exactly what the tests and the chaos harness exercise:

    serve_step_fail   the engine step raises (XLA abort, HBM OOM, the
                      process dying under the driver) -> the replica
                      marks itself `dead` the moment the exception
                      surfaces
    replica_stall     the replica silently stops making progress (a
                      wedged collective, a hung host thread — the same
                      silence obs/watchdog.py exists for). A stalled
                      replica keeps "running" but never heartbeats; the
                      ROUTER's health check declares it dead once the
                      stall threshold passes.

Heartbeats are derived from step progress: every completed `step()`
stamps `last_beat` and records its duration, and the stall threshold is
the obs/watchdog.py pattern — `max(floor, factor x median completed
step)` — so one knob stays meaningful from a tiny CPU test (ms steps)
to a real chip (tens of ms).

State machine:

    healthy --- step raises / stall threshold passed ---> dead
    healthy --- drain() ------------------------------> draining
    draining -- step raises / stall ------------------> dead
    dead ------ revive() -----------------------------> healthy

`draining` stops NEW admissions (the router checks state before
dispatch) while in-flight work finishes — the graceful half of a
restart. `revive()` hard-resets the engine's host state (queue, live
map, free list); the KV pool is NOT scrubbed — the overwrite-before-
attend invariant (serve/slots.py) makes stale K/V from the previous
life unreachable until overwritten, the same argument slot recycling
already rests on.
"""

import statistics
import time

from avenir_tpu.serve.engine import Engine
from avenir_tpu.utils.faults import get_injector

HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"


class ReplicaGone(RuntimeError):
    """Raised by a replica's dispatch surface when the backing worker
    died mid-operation (process backend) — the router requeues the
    request and moves on instead of crashing the fleet step."""


class ReplicaHealth:
    """The router-facing health surface shared by the in-process
    `Replica` and the process-isolated `serve/proc.ProcReplica`
    (ISSUE 8): state machine, heartbeat bookkeeping, and the
    obs/watchdog.py stall-threshold rule. Subclasses provide `busy`,
    `step()` and the engine/dispatch surface; `_on_dead()` is the
    death hook (ProcReplica SIGKILLs its worker corpse there)."""

    def __init__(self, replica_id, *, clock, stall_floor_secs=10.0,
                 stall_factor=10.0):
        self.replica_id = int(replica_id)
        self._clock = clock
        self.state = HEALTHY
        self.stall_floor_secs = float(stall_floor_secs)
        self.stall_factor = float(stall_factor)
        self.last_beat = self._clock()
        self._durs = []       # completed-step durations, clock seconds
        self._stalled = False  # fault-injected wedge (no beats, no work)
        self.deaths = 0
        self.last_error = None  # the exception that killed us, if any

    def median_step_secs(self):
        return statistics.median_low(self._durs) if self._durs else 0.0

    def _record_beat(self, t0, had_work):
        """Stamp a heartbeat after a completed step; busy steps also
        enter the duration stats (idle no-ops must not — a mostly-idle
        replica's ~0 median would degrade the stall threshold to its
        bare floor and make slow replicas look fast to the router's
        deadline-slack placement penalty)."""
        now = self._clock()
        self.last_beat = now
        if had_work:
            self._durs.append(now - t0)
            if len(self._durs) > 64:
                del self._durs[:32]
        return now

    # -- health --

    def stall_threshold_secs(self):
        """The shared stall-threshold rule — max(floor, factor x median
        completed-step time), scale-free across model sizes. ONE home
        (obs/series.stall_threshold_secs, ISSUE 14) shared with
        obs/watchdog.py so the two stall tiers can never drift apart."""
        from avenir_tpu.obs.series import stall_threshold_secs

        return stall_threshold_secs(self.stall_floor_secs,
                                    self.median_step_secs(),
                                    factor=self.stall_factor)

    def check_health(self, now):
        """Declare a silent stall: HOLDING WORK with no heartbeat within
        the threshold. An idle replica is exempt — with nothing admitted
        there is no progress to expect (and another replica's long
        compile delaying the fleet loop must not read as this one's
        death); a wedged-but-idle replica is caught the moment work
        lands on it and fails to move. Returns the (updated) state."""
        if (self.state != DEAD and self.busy
                and now - self.last_beat > self.stall_threshold_secs()):
            self.mark_dead()
        return self.state

    # -- state transitions --

    def drain(self):
        """Stop new admissions; in-flight work keeps stepping."""
        if self.state == HEALTHY:
            self.state = DRAINING

    def mark_dead(self):
        """Abrupt death (step failure, declared stall, or a chaos kill)."""
        if self.state != DEAD:
            self.state = DEAD
            self.deaths += 1
            self._on_dead()

    def _on_dead(self):
        """Death hook for subclasses (the in-process replica leaves its
        engine state readable; a process replica reaps its corpse)."""


class Replica(ReplicaHealth):
    """One engine in the fleet, with the router-facing health surface."""

    def __init__(self, model, replica_id, *, n_slots=4, max_seq_len=None,
                 detokenize=None, registry=None, sink=None, seed=0,
                 clock=None, stall_floor_secs=10.0, stall_factor=10.0,
                 engine_kwargs=None, trace=0, draft_model=None):
        # per-replica trace buffer (ISSUE 10): engine events keyed by
        # ENGINE-local rids collect here and the router drains+translates
        # them each step (take_trace) — the same drain-per-step shape the
        # process backend uses over its reply frames, so one fleet trace
        # tree covers both backends. `trace` is the decode-tick sampling
        # interval (0/False = tracing off; the Router passes its
        # Tracer's decode_sample so the knob reaches every engine)
        self._trace_buf = None
        if trace:
            from avenir_tpu.obs.trace import TraceBuffer

            self._trace_buf = TraceBuffer(clock=clock,
                                          decode_sample=int(trace))
        ekw = dict(engine_kwargs or {})
        # compile pre-warm (ISSUE 12): rides engine_kwargs — the same
        # key a process worker's hello consumes — so the autoscaler's
        # spawn path is one flag on either backend
        prewarm = ekw.pop("prewarm", False)
        self.engine = Engine(
            model, n_slots=n_slots, max_seq_len=max_seq_len,
            detokenize=detokenize, registry=registry, sink=sink,
            seed=seed, clock=clock, tracer=self._trace_buf,
            draft_model=draft_model,
            **ekw,
        )
        if self._trace_buf is not None:
            # share the engine's resolved clock (clock=None means the
            # engine picked perf_counter; events must ride that too)
            self._trace_buf.clock = self.engine._clock
        if prewarm:
            # a fresh replica compiles BEFORE it is dispatchable — the
            # router only sees it once construction returns
            self.engine.prewarm()
        super().__init__(replica_id, clock=self.engine._clock,
                         stall_floor_secs=stall_floor_secs,
                         stall_factor=stall_factor)

    def take_trace(self):
        """Drain this replica's trace events (engine-rid keyed, fleet
        clock — no restamp needed in-process). Returns (events,
        dropped-since-last-drain)."""
        if self._trace_buf is None:
            return [], 0
        dropped, self._trace_buf.dropped = self._trace_buf.dropped, 0
        return self._trace_buf.drain(), dropped

    # -- disaggregated page transfer (ISSUE 13) --

    @property
    def role(self):
        return getattr(self.engine, "role", "both")

    def take_page_exports(self):
        """Drain finished-page export records (role='prefill')."""
        if self.state == DEAD:
            return []
        return self.engine.take_page_exports()

    def import_pages(self, records):
        """Splice exported page records into this replica's engine.
        In-process transfers still ROUND-TRIP the PT_KVPAGES frame
        codec — the wire format is the contract both backends share, so
        the inproc fleet (and its benches) exercises — and pays for —
        exactly the serialization the process fleet ships, not a
        zero-cost shortcut. Returns (pages written, payload bytes)."""
        from avenir_tpu.serve.frames import ARRAYS_PER_DTYPE, \
            decode_kv_pages, encode_kv_pages

        meta = {"records": [{"eng_rid": r["eng_rid"],
                             "tokens": r["tokens"],
                             "n_prefix": r.get("n_prefix", 0),
                             "kv_dtype": r["kv_dtype"]}
                            for r in records]}
        flat = [a for r in records for a in r["arrays"]]
        payload = encode_kv_pages(meta, flat)
        decoded = decode_kv_pages(payload)
        written = 0
        off = 0
        for rec in decoded["records"]:
            n = ARRAYS_PER_DTYPE[rec["kv_dtype"]]
            written += self.engine.import_kv_pages(
                rec["tokens"], decoded["arrays"][off:off + n],
                kv_dtype=rec["kv_dtype"],
                n_prefix=int(rec.get("n_prefix", 0)))
            off += n
        return written, len(payload)

    def export_chain(self, token_pages, n_prefix=0):
        """Pull-SOURCE surface of the fleet KV CDN (ISSUE 17): gather
        the live KV of the registered chain matching `token_pages`
        (export-record shape, or None when the chain was evicted since
        the map advertised it). A dead replica exports nothing — raise
        ReplicaGone so the router's pull broker takes the same
        src-death fallback path as the process backend."""
        if self.state == DEAD:
            raise ReplicaGone(f"replica {self.replica_id} is dead")
        return self.engine.export_chain(token_pages, n_prefix=n_prefix)

    # -- live weight lifecycle (ISSUE 20) --

    @property
    def weight_version(self):
        """The version label of the weights this replica serves — the
        router's version-keying input for KV reuse and the rollout
        manager's convergence check."""
        return getattr(self.engine, "weight_version", "0")

    def set_weights(self, state, version):
        """In-place weight swap (serve/rollout.py): load `state` into
        the model module, re-snapshot the engine's parameter split, and
        HARD-RESET host state — the previous version's prefix chain,
        queue, and page refcounts must not survive into the new one
        (stale-KV-under-new-weights is silent wrongness, which is why
        this is not optional). Caller drains first: an idle engine is
        the precondition, exactly like prewarm."""
        assert not self.busy, "weight swap requires a drained replica"
        from flax import nnx

        nnx.update(self.engine.model, state)
        self.engine.refresh_state()
        self.engine.reset_host_state()
        self.engine.weight_version = str(version)

    # -- capacity surface the router routes on --

    @property
    def n_slots(self):
        return self.engine.n_slots

    @property
    def free_slots(self):
        return self.engine.sched.free_slots if self.state == HEALTHY else 0

    @property
    def dispatchable_slots(self):
        """Free slots minus work already submitted but not yet admitted
        (engine `free_slots` only drops at admission, one engine step
        later) — the router's dispatch budget, so a fleet step never
        over-commits a replica and fair-share stays a ROUTER decision
        instead of decaying into per-engine FCFS backlogs."""
        if self.state != HEALTHY:
            return 0
        return max(0, self.engine.sched.free_slots
                   - self.engine.sched.queue_depth)

    @property
    def busy(self):
        """Holds admitted-but-unfinished work (any state) — including
        paged-KV slots still mid-chunked-prefill, which hold pages and
        a slot and must count for stall detection."""
        return self.engine.open_work

    # -- stepping --

    def step(self):
        """One engine iteration under fault consult. Returns the engine's
        finished list; on a step failure the replica is `dead` and the
        return is empty — the ROUTER owns requeueing what was in flight
        (it knows the original prompts; this object only knows slots)."""
        if self.state == DEAD:
            return []
        inj = get_injector()
        if not self._stalled and inj.should_fire("replica_stall"):
            self._stalled = True
        if self._stalled:
            # a wedged replica does no work and — the defining symptom —
            # does NOT heartbeat; only the router's threshold check can
            # tell this silence from an idle replica
            return []
        t0 = self._clock()
        had_work = self.busy
        # serve_step_degrade (ISSUE 20): each fire adds a PERMANENT
        # +2 ms of host latency to every subsequent busy step — the
        # poisoned canary. The sleep is real wall time so TTFT/TPOT
        # measured on the engine clock actually inflate; nothing but
        # the drift detectors can tell (the train_step_degrade idiom)
        if inj.should_fire("serve_step_degrade"):
            self._degrade_s = getattr(self, "_degrade_s", 0.0) + 0.002
        if had_work and getattr(self, "_degrade_s", 0.0):
            time.sleep(self._degrade_s)
        try:
            inj.fail("serve_step_fail", f"replica {self.replica_id}")
            finished = self.engine.step()
        except Exception as e:
            # a dead replica is a ROUTABLE event, not a fleet crash —
            # but keep the corpse's cause of death inspectable (a
            # deterministic bug kills every replica it fails over to,
            # and drain()'s all-dead error is where an operator looks)
            self.last_error = e
            self.mark_dead()
            return []
        self._record_beat(t0, had_work)
        return finished

    # -- state transitions --

    def revive(self):
        """From `dead`: a restarted replica rejoins empty — fresh
        scheduler (all slots free, empty queue), live map cleared; the
        KV pool is deliberately NOT scrubbed (stale rows stay masked
        until overwritten — the slot-hygiene invariant, serve/slots.py).
        From `draining`: just un-drain — in-flight work is live and must
        NOT be dropped."""
        if self.state == DEAD:
            # paged engines also re-init their allocator here: the page
            # CONTENTS are stale-but-masked like slab rows, but the old
            # life's prefix chain and refcounts must not survive into
            # the new one (its pages are about to be reallocated)
            self.engine.reset_host_state()
            self._stalled = False
            self._durs = []
            self.last_error = None
        self.state = HEALTHY
        self.last_beat = self._clock()
