"""Paged KV subsystem (ISSUE 9 tentpole): block allocator, shared-prefix
pages with copy-on-write, chunked prefill, paged attention.

The slab pool (`serve/slots.py`) charges every slot a full `T_max` KV
column — a 30-token request pays for thousands of positions it never
writes, which caps concurrent users per chip far below what HBM allows.
This module replaces the slab with the TPU-discipline version of vLLM's
PagedAttention plus SGLang-style shared-prefix reuse:

  - **pages**: ONE pool of `n_pages` KV blocks of `page_size` tokens,
    shape (L, n_pages, page_size, H_kv, D). A sequence's KV lives in
    whichever pages its page table names — near-zero fragmentation
    (any free page serves any request; the only waste is the tail of
    the last page, < page_size tokens per sequence).
  - **page tables**: per-slot rows padded to a fixed `max_pages_per_seq`
    width and passed to the jitted step as a TRACED argument (like the
    live mask), so pages allocating and freeing never changes a
    compiled shape and never retraces — the same never-retrace
    discipline as every other slot array.
  - **host allocator** (`PageAllocator`): pure host state — free list,
    per-page refcounts, reservation accounting (admission is refused
    unless the worst-case page need is covered, so decode can never
    hit an out-of-pages wall mid-request), and the prefix registry.
  - **shared prefixes**: full pages of prompt tokens register in a
    rolling-hash chain (dict-keyed by (parent node, page tokens), so a
    chain node IS the exact token prefix — no hash collisions). A new
    prompt walks the chain and attaches matching pages by refcount
    instead of recomputing/rewriting them; a partially matching page
    can also be attached (the masked-tail-exactness argument makes the
    divergent tail unattendable) and is **copied on the first
    divergent write** (COW). Freed registered pages stay cached and
    evictable (LRU) until the pool needs them — a fleet of users
    sharing one system prompt pays for its KV once.
  - **chunked prefill**: admission forwards a long prompt at most
    `prefill_chunk` tokens per engine tick, so prefill can never stall
    a decode tick for the co-tenant slots. Chunked prefill is
    BIT-IDENTICAL to one-shot prefill on this backend (per-position
    computations are row-independent; pinned by tests/test_pages.py),
    which is what lets attached shared pages — computed under someone
    else's chunk boundaries — stand in for recomputation exactly.
  - **paged attention**: the reference implementation gathers the
    table's pages back into a (B, P*page_size, H_kv, D) view and
    reuses the dense `_attend_cached` (bit-identical to the slab path;
    CPU-testable); the TPU path is the Pallas kernel in
    `ops/pallas/paged_attention.py` (numerically equivalent, not
    bitwise — same contract as `attn_impl='pallas'`).

Engine wiring lives in `serve/engine.py` behind the `kv_impl={slab,
paged}` knob (the `attn_impl`/`loss_impl` pattern). The correctness
oracle is unchanged: per-request bit-parity with one-shot
`generate_cached`, prefix sharing on or off.
"""

import dataclasses
import hashlib
from bisect import insort
from collections import OrderedDict
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.infer.decode import _attend_cached, bucket_ladder, \
    prompt_bucket
from avenir_tpu.serve.slots import key_data_width

ROOT = -1  # the prefix chain's root node id (no parent page)


def chain_digest(tokens):
    """Stable 8-byte digest of a token path (root -> chain node), as a
    hex string. This is the WIRE identity of a chain node (ISSUE 16):
    two allocators in different processes — or a worker and the router
    — computing the digest of the same token prefix get the same value,
    which is what lets the fleet cache map compare cache content across
    replicas without shipping raw token chains every heartbeat. Python's
    builtin hash() is salted per process and cannot serve here."""
    h = hashlib.blake2b(digest_size=8)
    h.update(b"".join(int(t).to_bytes(4, "little") for t in tokens))
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Host-side block allocator
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PageRef:
    """One page-table entry. `owned` pages are writable; a shared
    (attached) page must be COWed before its first divergent write."""

    page: int
    owned: bool


@dataclasses.dataclass(frozen=True)
class AdmitPlan:
    """What admission decided for one request: which prefix pages it
    attaches, how many prompt positions they cover (`shared_len` — the
    chunked prefill starts there), and the worst-case new-page need the
    reservation covers."""

    shared_len: int
    shared_pages: Tuple[int, ...]   # full-page chain matches, in order
    partial: Optional[int]          # partially matching page, if any
    total_pages: int                # ceil((prompt + max_new) / page_size)
    new_pages: int                  # total_pages - len(shared_pages)


class PageAllocator:
    """Ref-counted fixed-pool page allocator with prefix sharing + COW.

    Pure host state (no jax): allocation decisions cost no dispatches,
    and the device only ever sees the resulting page-table arrays.

    Accounting model (the leak-audit contract, `audit()`):

      every page is in exactly ONE of three states —
        free       on the free list, content garbage
        cached     refcount 0 but still registered in the prefix chain
                   (evictable LRU; reused for prefix hits until evicted)
        live       refcount >= 1 — referenced by that many live page
                   tables (shared pages count once per table)

    Admission reserves the WORST-CASE new-page need (prompt + max_new,
    minus fully attached prefix pages) against `available()` = free +
    cached - outstanding reservations, so `alloc()` during prefill or
    decode can never fail mid-request — the paged engine has no
    preemption path and must never need one.
    """

    def __init__(self, n_pages, page_size, prefix_sharing=True):
        assert n_pages >= 1 and page_size >= 1
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.prefix_sharing = bool(prefix_sharing)
        self._free = list(range(self.n_pages))  # sorted: deterministic
        self._ref = {}            # page -> refcount (absent == 0)
        self._evictable = OrderedDict()  # registered ref-0 pages, LRU
        self._node = {}           # page -> (parent, tokens) while registered
        self._children = {}       # parent -> {tokens: page}
        self._tables = {}         # rid -> [PageRef, ...]
        self._reserved = {}       # rid -> pages still owed to this request
        self._chain = {}          # rid -> current chain node (registration)
        self.cow_copies = 0
        self.prefix_hits = 0      # requests that attached >= 1 page
        # disaggregated transfer accounting (ISSUE 13 satellite):
        # `_imported` tracks pages spliced in from ANOTHER allocator
        # (a prefill-class replica's) while they stay registered, so
        # COW activity on transferred chains is attributable
        self._imported = set()
        self.pages_exported = 0    # bumped by the engine's export path
        self.pages_imported = 0
        self.imported_cow_copies = 0
        # `imported_live` maintained incrementally on ref transitions
        # (ISSUE 16 satellite) — stats() rides every heartbeat, and a
        # scan of `_imported` per beat scaled with transfer volume;
        # audit() asserts counter == scan
        self._imported_live = 0
        # chain telemetry (ISSUE 16 tentpole): per-node hotness for the
        # bounded top-K summary — hits = admissions that attached this
        # node, last_use = the monotone admit tick of the latest (a
        # COUNTER, not a clock: summaries must be deterministic and
        # cross-process comparable)
        self._meta = {}           # page -> [hits, last_use_tick]
        self._tick = 0
        self._chains_dirty = True  # True: a take_chain_delta is due
        self._last_summary = {}    # digest -> node, as of the last take

    # -- capacity --

    def available(self):
        """Pages an admission may still promise: free + evictable
        cached, minus what outstanding reservations already own."""
        return (len(self._free) + len(self._evictable)
                - sum(self._reserved.values()))

    def stats(self):
        live = self.n_pages - len(self._free) - len(self._evictable)
        return {
            "n_pages": self.n_pages,
            "free": len(self._free),
            "cached": len(self._evictable),
            "live": live,
            "util": live / self.n_pages,
            "reserved": sum(self._reserved.values()),
            "cow_copies": self.cow_copies,
            # transfer-oriented stats (ISSUE 13 satellite): page flow
            # across the disaggregation boundary, plus how much COW
            # activity landed on chains another allocator computed
            "pages_exported": self.pages_exported,
            "pages_imported": self.pages_imported,
            "imported_live": self._imported_live,
            "imported_cow_copies": self.imported_cow_copies,
        }

    # -- prefix matching --

    def plan(self, prompt, max_new):
        """Match `prompt` against the prefix chain (no state change).
        Full pages match exactly along the chain; the first non-matching
        position may still land inside a registered page whose tokens
        share a prefix — that page attaches PARTIALLY (its divergent
        tail stays masked, exactly like slab padding) and is COWed on
        the request's first write into it. `shared_len` is capped at
        len(prompt) - 1: at least one prompt position must be computed
        to produce the last-token logits decode samples from."""
        prompt = tuple(int(t) for t in prompt)
        ps = self.page_size
        total = -(-(len(prompt) + int(max_new)) // ps)
        shared, i, cur = [], 0, ROOT
        partial = None
        if self.prefix_sharing:
            while i + ps <= len(prompt) - 1:
                page = self._children.get(cur, {}).get(prompt[i:i + ps])
                if page is None:
                    break
                shared.append(page)
                cur = page
                i += ps
            cap = len(prompt) - 1 - i
            best_m = 0
            for toks, page in self._children.get(cur, {}).items():
                m = 0
                for a, b in zip(toks, prompt[i:]):
                    if a != b:
                        break
                    m += 1
                m = min(m, cap)
                if m > best_m:
                    best_m, partial = m, page
            if best_m == 0:
                partial = None
            shared_len = i + best_m
        else:
            shared_len = 0
        return AdmitPlan(
            shared_len=shared_len, shared_pages=tuple(shared),
            partial=partial, total_pages=total,
            new_pages=total - len(shared),
        )

    # -- admission / release --

    def admit(self, rid, prompt, max_new):
        """Try to admit: returns the AdmitPlan (prefix pages attached,
        reservation taken, table seeded) or None when the worst-case
        page need is not covered — the scheduler's token-budget
        admission check. A False path mutates nothing."""
        assert rid not in self._tables, f"rid {rid} already admitted"
        plan = self.plan(prompt, max_new)
        # attaching a CACHED (ref-0) prefix page revives it to live,
        # shrinking the reclaimable pool by one without consuming a
        # reservation — the admission check must charge for those too,
        # or outstanding reservations could exceed free+cached and a
        # later alloc() for an already-admitted request would crash
        attach = list(plan.shared_pages)
        if plan.partial is not None:
            attach.append(plan.partial)
        cached_attached = sum(1 for p in attach if p in self._evictable)
        if self.available() < plan.new_pages + cached_attached:
            return None
        self._reserved[rid] = plan.new_pages
        table = []
        self._tick += 1
        for page in plan.shared_pages:
            self._incref(page)
            table.append(PageRef(page, owned=False))
            m = self._meta.get(page)
            if m is not None:   # hotness: one hit per attaching admit
                m[0] += 1
                m[1] = self._tick
        if plan.partial is not None:
            self._incref(plan.partial)
            table.append(PageRef(plan.partial, owned=False))
        self._tables[rid] = table
        self._chain[rid] = plan.shared_pages[-1] if plan.shared_pages \
            else ROOT
        if plan.shared_len:
            self.prefix_hits += 1
        return plan

    def free_seq(self, rid):
        """Release a finished/evicted request: every table entry is
        dereferenced (registered pages whose refcount hits 0 become
        cached/evictable, unregistered ones go straight to the free
        list) and the unused tail of its reservation is returned."""
        for entry in self._tables.pop(rid, []):
            self._decref(entry.page)
        self._reserved.pop(rid, None)
        self._chain.pop(rid, None)

    def table(self, rid):
        return self._tables[rid]

    # -- page movement --

    def alloc(self, rid):
        """One fresh owned page for `rid`, appended to its table. Always
        succeeds for an admitted request (the reservation guarantees
        it — an AssertionError here is an accounting bug, not load)."""
        page = self._take(rid)
        self._ref[page] = 1
        self._tables[rid].append(PageRef(page, owned=True))
        return page

    def ensure_writable(self, rid, slot_idx):
        """COW: make table entry `slot_idx` writable. Owned entries are
        a no-op (None); a shared entry is replaced by a fresh page and
        the (src, dst) physical pair is returned — the caller must copy
        the page's KV on device before the next write."""
        entry = self._tables[rid][slot_idx]
        if entry.owned:
            return None
        src = entry.page
        dst = self._take(rid)
        self._ref[dst] = 1
        self._tables[rid][slot_idx] = PageRef(dst, owned=True)
        self._decref(src)
        self.cow_copies += 1
        if src in self._imported:
            # COW against a chain another allocator computed — the
            # transfer boundary is invisible to the sharing machinery,
            # which is the point; this counter proves it happened
            self.imported_cow_copies += 1
        return (src, dst)

    def import_chain(self, token_pages, n_prefix=0):
        """Splice a chain of FULL prompt pages from ANOTHER allocator
        (a prefill-class replica shipped them over frames, ISSUE 13)
        into this allocator's prefix chain as CACHED (ref-0, registered,
        LRU-evictable) nodes. `token_pages` is the chain identity —
        page_size-token tuples in chain order FROM ROOT; exact-token
        keying means a transferred page and a locally computed page of
        the same tokens are literally the same chain node, so prefix
        attach + COW work across the transfer boundary unchanged.

        `n_prefix`: the first `n_prefix` entries are ANCHOR nodes — a
        streamed transfer ships its chain in segments, and a segment's
        pages are only meaningful UNDER the exact prefix that produced
        them (KV content is position- and context-dependent). Anchors
        must already exist in this chain; a missing anchor (the earlier
        segment was evicted, or never landed) STOPS the import — an
        unanchored segment registered at the wrong depth could falsely
        match a different prompt's prefix, which would be a correctness
        bug, not a cache miss.

        Returns [(page, is_new), ...] — `is_new` False for anchors and
        deduped nodes (a previous transfer, or local computation:
        nothing to write). Pages come from the free list first, then
        LRU eviction of cached nodes; when neither can yield a page
        (everything live/reserved) the import STOPS and returns the
        prefix it managed — a partial chain is still a valid prefix,
        and the decode-side plan() just recomputes the missing tail
        (exactness never depends on the import landing).

        State accounting: free -> cached keeps `available()` unchanged
        (cached pages are reclaimable), so outstanding reservations are
        never endangered by an import."""
        out = []
        parent = ROOT
        for i, toks in enumerate(token_pages):
            toks = tuple(int(t) for t in toks)
            assert len(toks) == self.page_size, (
                f"import_chain page of {len(toks)} tokens != page_size "
                f"{self.page_size} — only FULL pages have chain identity")
            kids = self._children.setdefault(parent, {})
            page = kids.get(toks)
            if page is not None:
                out.append((page, False))
                parent = page
                continue
            if i < n_prefix:
                return out  # anchor missing: segment unanchorable
            if self._free:
                page = self._free.pop(0)
            elif self._evictable:
                # reclaim the LRU cached node, then take the freed page
                self._evict(next(iter(self._evictable)))
                if (not self._free
                        or (parent != ROOT and parent not in self._node)):
                    # eviction freed nothing usable — or it reclaimed an
                    # ancestor of the very chain being imported (a tiny
                    # pool), deregistering our parent: registering under
                    # a stale node could resurrect as a wrong-prefix
                    # match once the id is reused. Stop (partial chain).
                    break
                page = self._free.pop(0)
            else:
                break  # pool fully live/reserved: partial chain stands
            self._node[page] = (parent, toks)
            kids[toks] = page
            self._evictable[page] = None   # cached: ref 0, registered
            self._imported.add(page)
            self.pages_imported += 1
            self._meta[page] = [0, self._tick]
            self._chains_dirty = True
            out.append((page, True))
            parent = page
        return out

    def lookup_chain(self, token_pages):
        """Walk the registered prefix chain along `token_pages` (FULL
        page_size-token pages in chain order from ROOT) and return the
        physical pages of the longest registered prefix — the pull-
        SOURCE side of `import_chain` (ISSUE 17 KV CDN). A partial walk
        is a valid answer: the map that advertised this chain is a
        bounded, possibly stale summary, and eviction may have raced
        the pull; the caller exports what survives and the receiver's
        prefill recomputes the rest (exactness never depends on it).

        Matched nodes get a hit + recency touch (and an LRU
        `move_to_end` for cached ref-0 nodes): a fleet pull IS reuse,
        and the LRU must not evict a chain peers are actively pulling."""
        out = []
        parent = ROOT
        for toks in token_pages:
            toks = tuple(int(t) for t in toks)
            if len(toks) != self.page_size:
                break  # only FULL pages have chain identity
            page = self._children.get(parent, {}).get(toks)
            if page is None:
                break
            meta = self._meta.get(page)
            if meta is not None:
                meta[0] += 1
                meta[1] = self._tick
                self._chains_dirty = True
            if page in self._evictable:
                self._evictable.move_to_end(page)
            out.append(page)
            parent = page
        return out

    def register(self, rid, slot_idx, tokens):
        """Register table entry `slot_idx` — a page now fully covered
        by prompt tokens — as a prefix-chain node under `rid`'s current
        chain position. If an identical node already exists (two equal
        prompts racing), the chain advances through the existing page
        and the duplicate stays private. Registered pages are immutable
        by construction: requests only ever write at their sequence
        tail, which lies beyond every fully-covered prompt page."""
        if not self.prefix_sharing:
            return
        tokens = tuple(int(t) for t in tokens)
        assert len(tokens) == self.page_size
        parent = self._chain.get(rid, ROOT)
        if parent != ROOT and parent not in self._node:
            # the chain node this request was riding is gone: a dedup
            # hop landed it on a CACHED page (ref 0, not in this
            # request's table) that eviction reclaimed mid-prefill.
            # Registering under the stale id could resurrect as a
            # wrong-prefix match once the page id is reused and
            # re-registered — stop chaining this request instead (a
            # conservative miss, never a wrong hit)
            return
        kids = self._children.setdefault(parent, {})
        existing = kids.get(tokens)
        if existing is not None:
            self._chain[rid] = existing
            return
        entry = self._tables[rid][slot_idx]
        if not entry.owned:
            # a fully attached shared page IS the chain node already
            self._chain[rid] = entry.page
            return
        self._node[entry.page] = (parent, tokens)
        kids[tokens] = entry.page
        self._chain[rid] = entry.page
        self._meta[entry.page] = [0, self._tick]
        self._chains_dirty = True

    # -- chain telemetry (ISSUE 16 tentpole) --

    def _path_tokens(self, page):
        """The full token path ROOT -> `page` (a registered node)."""
        parts = []
        cur = page
        while cur != ROOT:
            parent, toks = self._node[cur]
            parts.append(toks)
            cur = parent
        out = []
        for toks in reversed(parts):
            out.extend(toks)
        return out

    def chain_summary(self, top_k=32):
        """Bounded summary of the registered prefix chains: the top-K
        nodes by (hits, recency), each keyed by the `chain_digest` of
        its full root path and valued `[n_tokens, depth_pages, ref,
        hits, last_use_tick]`. The cap bounds the heartbeat wire form:
        at most K entries of a 16-hex-char digest plus five small ints
        (~60 bytes/node JSON-ish, so K=32 is ~2 KB worst case)."""
        top_k = int(top_k)
        if top_k <= 0 or not self._node:
            return {}
        pages = sorted(
            self._node,
            key=lambda p: (self._meta[p][0], self._meta[p][1], p),
            reverse=True)[:top_k]
        out = {}
        for page in pages:
            path = self._path_tokens(page)
            hits, last = self._meta[page]
            out[chain_digest(path)] = [
                len(path), len(path) // self.page_size,
                self._ref.get(page, 0), hits, last]
        return out

    def take_chain_delta(self, top_k=32):
        """Incremental wire form of `chain_summary`: what changed since
        the previous take, as {"upd": {digest: node}, "gone": [digest]}
        — or None when nothing did (the common idle heartbeat ships
        zero extra bytes). Applying every delta in order onto an empty
        dict rebuilds `chain_summary(top_k)` EXACTLY (the counter/sketch
        merge-of-deltas contract, pinned by tests/test_cache_obs.py)."""
        if not self._chains_dirty:
            return None
        self._chains_dirty = False
        cur = self.chain_summary(top_k)
        prev = self._last_summary
        upd = {d: v for d, v in cur.items() if prev.get(d) != v}
        gone = [d for d in prev if d not in cur]
        self._last_summary = cur
        if not upd and not gone:
            return None
        return {"upd": upd, "gone": gone}

    # -- internals --

    def _incref(self, page):
        n = self._ref.get(page, 0)
        if n == 0:
            self._evictable.pop(page, None)  # cached -> live
            if page in self._imported:
                self._imported_live += 1
        self._ref[page] = n + 1
        self._chains_dirty = True  # a registered node's ref moved

    def _decref(self, page):
        n = self._ref.get(page, 0)
        assert n >= 1, f"double free of page {page}"
        self._chains_dirty = True
        if n > 1:
            self._ref[page] = n - 1
            return
        self._ref.pop(page)
        if page in self._imported:
            self._imported_live -= 1
        if page in self._node:
            self._evictable[page] = None   # keep for future prefix hits
        else:
            insort(self._free, page)

    def _take(self, rid):
        assert self._reserved.get(rid, 0) > 0, (
            f"page alloc for rid {rid} without reservation — admission "
            "under-counted its worst case (allocator bug)")
        if not self._free:
            assert self._evictable, (
                "no free or evictable page despite a live reservation — "
                "reservation accounting is broken")
            self._evict(next(iter(self._evictable)))  # LRU victim
        self._reserved[rid] -= 1
        return self._free.pop(0)

    def _evict(self, page):
        """Reclaim a cached (ref-0, registered) page: drop it and its
        whole registered subtree from the chain — a chain with a hole
        in the middle must not match past it — freeing any cached
        descendants along the way (live descendants just lose their
        registration and free normally later)."""
        self._evictable.pop(page)
        parent, toks = self._node.pop(page)
        self._imported.discard(page)   # no longer a transferred chain node
        self._meta.pop(page, None)
        self._chains_dirty = True
        self._children.get(parent, {}).pop(toks, None)
        for child in list(self._children.pop(page, {}).values()):
            self._deregister_subtree(child)
        insort(self._free, page)

    def _deregister_subtree(self, page):
        self._node.pop(page)
        if page in self._imported and page not in self._evictable:
            # a LIVE imported page losing its registration also leaves
            # the imported set — the incremental counter must follow
            self._imported_live -= 1
        self._imported.discard(page)
        self._meta.pop(page, None)
        self._chains_dirty = True
        for child in list(self._children.pop(page, {}).values()):
            self._deregister_subtree(child)
        if page in self._evictable:
            self._evictable.pop(page)
            insort(self._free, page)

    # -- the leak audit --

    def audit(self):
        """Recompute every invariant from first principles and assert it
        (drain()/evict call this — a page leak must fail loud, not
        slowly strangle capacity). Returns the stats dict."""
        want = {}
        for table in self._tables.values():
            for entry in table:
                want[entry.page] = want.get(entry.page, 0) + 1
        for page in range(self.n_pages):
            assert self._ref.get(page, 0) == want.get(page, 0), (
                f"page {page}: refcount {self._ref.get(page, 0)} != "
                f"{want.get(page, 0)} live table references — page leak")
        live = set(want)
        free, cached = set(self._free), set(self._evictable)
        assert len(free) == len(self._free), "duplicate free-list entry"
        assert not (free & cached) and not (free & live) \
            and not (cached & live), "page in two states at once"
        assert free | cached | live == set(range(self.n_pages)), (
            f"pages vanished: {set(range(self.n_pages)) - free - cached - live}")
        for page in cached:
            assert page in self._node, "cached page lost its registration"
        for page, (parent, toks) in self._node.items():
            assert self._children[parent][toks] == page, (
                "prefix chain linkage broken")
        # cross-allocator splice validity (ISSUE 13 satellite): every
        # still-tracked imported page must be a REGISTERED chain node
        # (cached or live via attach) — an imported page on the free
        # list would mean the import path leaked identity, and a later
        # reuse of that id could alias a wrong prefix
        for page in self._imported:
            assert page in self._node, (
                f"imported page {page} lost its chain registration "
                "without leaving the imported set")
            assert page not in free, (
                f"imported page {page} is simultaneously registered and "
                "free — splice accounting broken")
        # ISSUE 16 satellite: the incrementally maintained imported-live
        # counter must equal the scan it replaced on the heartbeat path
        scan = sum(1 for p in self._imported if self._ref.get(p, 0) > 0)
        assert self._imported_live == scan, (
            f"imported_live counter {self._imported_live} != scan {scan}"
            " — a ref transition missed its increment")
        assert set(self._meta) == set(self._node), (
            "chain hotness meta out of sync with registered nodes")
        assert sum(self._reserved.values()) <= len(free) + len(cached), (
            "outstanding reservations exceed reclaimable pages")
        return self.stats()


# ---------------------------------------------------------------------------
# Device-side paged pool + KV ops
# ---------------------------------------------------------------------------


class PagedPool(NamedTuple):
    """The paged analogue of `slots.SlotPool`, donated through the
    jitted step exactly the same way: KV lives in pages instead of
    per-slot columns, everything else is per-slot decode state. Page
    tables are NOT part of the pool — the host passes them as a traced
    argument each dispatch (they are tiny, change on every allocation,
    and a traced arg can never retrace)."""

    k: jax.Array            # (L, n_pages, page_size, H_kv, D)
    v: jax.Array            # (L, n_pages, page_size, H_kv, D)
    logits: jax.Array       # (n_slots, V) fp32
    rng: jax.Array          # (n_slots, key_words) uint32
    pos: jax.Array          # (n_slots,) int32
    temperature: jax.Array  # (n_slots,) f32
    top_k: jax.Array        # (n_slots,) int32; V means "no top-k"


def init_paged_pool(*, n_layer, n_slots, n_pages, page_size, n_kv_head,
                    head_dim, vocab_size, dtype, kv_dtype="bf16"):
    kv_shape = (n_layer, n_pages, page_size, n_kv_head, head_dim)
    if kv_dtype == "int8":
        from avenir_tpu.ops.kv_quant import init_quant_kv

        return PagedPool(
            k=init_quant_kv(kv_shape),
            v=init_quant_kv(kv_shape),
            logits=jnp.zeros((n_slots, vocab_size), jnp.float32),
            rng=jnp.zeros((n_slots, key_data_width()), jnp.uint32),
            pos=jnp.zeros((n_slots,), jnp.int32),
            temperature=jnp.ones((n_slots,), jnp.float32),
            top_k=jnp.full((n_slots,), vocab_size, jnp.int32),
        )
    return PagedPool(
        k=jnp.zeros(kv_shape, dtype),
        v=jnp.zeros(kv_shape, dtype),
        logits=jnp.zeros((n_slots, vocab_size), jnp.float32),
        rng=jnp.zeros((n_slots, key_data_width()), jnp.uint32),
        pos=jnp.zeros((n_slots,), jnp.int32),
        temperature=jnp.ones((n_slots,), jnp.float32),
        top_k=jnp.full((n_slots,), vocab_size, jnp.int32),
    )


def paged_kv_ops(tables, *, n_pages, page_size, n_real=None,
                 write_mask=None, attend_fn=None, kv_dtype="bf16",
                 compute_dtype=None, write_limit=None):
    """(write, attend) pair for `infer.decode._forward_cached` over a
    paged layer cache of shape (n_pages, page_size, H_kv, D).

    `tables` (B, P) int32 maps logical page slot -> physical page; pad
    entries may be anything (their positions are masked by q_pos).
    Writes route position p to (tables[b, p // page_size], p %
    page_size); invalid rows are scattered to page index `n_pages`,
    which jax's out-of-bounds scatter DROPS — the masking mechanism for
    chunk padding (`n_real`) and inactive decode rows (`write_mask`).
    Reads gather the table's pages into a (B, P*page_size, ...) view
    and reuse the dense `_attend_cached` — bit-identical to the slab
    path (tests pin it); `attend_fn`, when given, replaces the gather
    for single-token queries (the Pallas decode kernel).

    ISSUE 11 additions:
      - a THIRD write form, (B, T>1) at per-row positions — the spec-
        decode verify forward writes [tail, d_1..d_k] per slot in one
        dispatch; `write_limit` (B,) drops any position >= the row's
        allocated token coverage (a clipped page_slot on an unallocated
        position would silently corrupt whatever page the table's 0-pad
        names), and `write_mask` drops inactive rows whole.
      - `kv_dtype='int8'`: kc/vc are ops/kv_quant.QuantKV pairs;
        writes quantize per (position, head) before the scatter and the
        gather path dequantizes into `compute_dtype` before the dense
        attend (the parity-tolerance reference; `attend_fn` gets the
        QuantKV halves for the fused Pallas int8 kernel)."""
    B, P = tables.shape
    ps = page_size
    quant = kv_dtype == "int8"
    if quant:
        from avenir_tpu.ops.kv_quant import QuantKV, dequantize, quantize

    def _scatter(c, data, scale, phys, off):
        if quant:
            return QuantKV(
                c.data.at[phys, off].set(data, mode="drop"),
                c.scale.at[phys, off].set(scale, mode="drop"))
        return c.at[phys, off].set(data.astype(c.dtype), mode="drop")

    def _prep(c, x):
        """Quantize (or cast) the new K/V block for scattering."""
        if quant:
            d, s = quantize(x)
            return d, s
        return x, None

    def write(kc, vc, k, v, pos):
        if getattr(pos, "ndim", 0) == 1 and k.shape[1] == 1:
            # decode: (B, 1, H_kv, D) at per-row positions
            page_slot = jnp.clip(pos // ps, 0, P - 1)
            phys = jnp.take_along_axis(tables, page_slot[:, None],
                                       axis=1)[:, 0]
            if write_mask is not None:
                phys = jnp.where(write_mask, phys, n_pages)  # dropped
            off = pos % ps
            kd, ks = _prep(kc, k[:, 0])
            vd, vs = _prep(vc, v[:, 0])
            return (_scatter(kc, kd, ks, phys, off),
                    _scatter(vc, vd, vs, phys, off))
        if getattr(pos, "ndim", 0) == 1:
            # spec verify: (B, T) tokens at per-row start positions
            T = k.shape[1]
            offs = pos[:, None] + jnp.arange(T)[None]        # (B, T)
            page_slot = jnp.clip(offs // ps, 0, P - 1)
            phys = jnp.take_along_axis(tables, page_slot, axis=1)
            if write_mask is not None:
                phys = jnp.where(write_mask[:, None], phys, n_pages)
            if write_limit is not None:
                phys = jnp.where(offs < write_limit[:, None], phys,
                                 n_pages)
            kd, ks = _prep(kc, k)
            vd, vs = _prep(vc, v)
            return (_scatter(kc, kd, ks, phys, offs % ps),
                    _scatter(vc, vd, vs, phys, offs % ps))
        # chunk prefill: B == 1, scalar start position
        T = k.shape[1]
        offs = pos + jnp.arange(T)
        page_slot = jnp.clip(offs // ps, 0, P - 1)
        phys = tables[0][page_slot]
        if n_real is not None:
            phys = jnp.where(jnp.arange(T) < n_real, phys, n_pages)
        kd, ks = _prep(kc, k[0])
        vd, vs = _prep(vc, v[0])
        return (_scatter(kc, kd, ks, phys, offs % ps),
                _scatter(vc, vd, vs, phys, offs % ps))

    def _gather(c):
        if quant:
            # gather FIRST, dequantize the (B, P*ps, ...) view — never
            # materialize a dense copy of the whole pool (the reference
            # path serves every multi-token spec verify, so its traffic
            # must stay proportional to the attended window)
            g = QuantKV(
                c.data[tables].reshape(B, P * ps, *c.data.shape[-2:]),
                c.scale[tables].reshape(B, P * ps, c.scale.shape[-1]))
            return dequantize(g, compute_dtype or jnp.float32)
        return c[tables].reshape(B, P * ps, *c.shape[-2:])

    def attend(q, kc, vc, q_pos):
        if attend_fn is not None and q.shape[1] == 1:
            return attend_fn(q, kc, vc, q_pos, tables)
        return _attend_cached(q, _gather(kc), _gather(vc), q_pos)

    return write, attend


# ---------------------------------------------------------------------------
# Engine-side host driver
# ---------------------------------------------------------------------------


class _PrefillState:
    """Per-slot chunked-prefill progress. `next` is the next prompt
    position to compute (admission starts it at the plan's shared_len —
    the prefix hit IS skipped compute); `reg_upto` the next page slot
    to register once fully covered by prompt tokens."""

    def __init__(self, req, plan):
        self.req = req
        self.n_prompt = len(req.prompt)
        self.next = plan.shared_len
        self.reg_upto = len(plan.shared_pages)
        # spec × prefix sharing (ISSUE 18): next prompt position the
        # DRAFT model has computed. Starts at 0, not shared_len — the
        # draft has no shared-page store, so on a prefix hit the engine
        # walks it through the skipped region with draft-only chunks
        # before combined chunks resume (ngram drafts have no KV and
        # ignore this cursor entirely)
        self.draft_next = 0
        # disaggregated export progress (role='prefill' engines): next
        # page slot to SHIP once fully covered by prompt tokens. Starts
        # at 0, not shared_len — locally prefix-hit pages still ship
        # (their content is exactly this prompt's KV, whoever computed
        # it), so a prefill replica's warm cache accelerates transfers
        self.exported_upto = 0


class PagedHost:
    """Host bookkeeping between the engine driver and the allocator:
    admission plans, per-slot prefill progress, page-table staging, and
    the paging metrics. Owns NO device state — the engine owns the pool
    and the jitted functions; this object tells it which pages to touch.
    """

    def __init__(self, *, n_pages, page_size, n_slots, max_pages_per_seq,
                 prefill_chunk, prefix_sharing=True, spec_pad=0,
                 prefill_only=False):
        self.alloc = PageAllocator(n_pages, page_size,
                                   prefix_sharing=prefix_sharing)
        # role='prefill' engines (ISSUE 13): admission reserves pages
        # for the PROMPT only — the request never decodes here (its
        # pages ship to a decode-class replica and free at handoff), so
        # charging max_new would idle most of the prefill pool
        self.prefill_only = bool(prefill_only)
        self.page_size = int(page_size)
        self.n_slots = int(n_slots)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.prefill_chunk = int(prefill_chunk)
        # speculative-decode scratch (ISSUE 11): the verify forward
        # writes up to spec_k positions PAST the request's last real
        # token, so admission reserves ceil((prompt + max_new +
        # spec_pad) / page_size) pages — the out-of-pages-wall guarantee
        # must cover the scratch tail too (a per-request capacity cost
        # of at most ceil(spec_k/page_size)+1 pages, docs/SERVING.md)
        self.spec_pad = int(spec_pad)
        self.chunk_ladder = bucket_ladder(self.prefill_chunk)
        self.prefill = {}     # slot -> _PrefillState (admission order)
        self.rid_of = {}      # slot -> rid (prefilling or live)
        self._plans = {}      # rid -> AdmitPlan (until prefill starts)
        self.shared_tokens = 0
        self.prompt_tokens = 0

    # -- admission --

    def try_admit(self, req):
        """The scheduler's token-budget admission check (FCFS: a False
        return blocks the queue head). True COMMITS allocator state —
        the scheduler hands the request a slot in the same call."""
        max_new = 0 if self.prefill_only \
            else req.max_new_tokens + self.spec_pad
        plan = self.alloc.admit(req.req_id, req.prompt, max_new)
        if plan is None:
            return False
        self._plans[req.req_id] = plan
        self.shared_tokens += plan.shared_len
        self.prompt_tokens += len(req.prompt)
        return True

    def start_prefill(self, slot, req):
        plan = self._plans.pop(req.req_id)
        self.prefill[slot] = _PrefillState(req, plan)
        self.rid_of[slot] = req.req_id

    # -- chunked prefill --

    def chunk_bucket(self, n):
        """Pad target for a chunk of n real tokens — the chunk-size
        analogue of the prompt-bucket ladder, bounding prefill compiles
        at O(log prefill_chunk) for the engine's lifetime."""
        return prompt_bucket(n, self.prefill_chunk)

    def prepare_chunk(self, rid, start, n_real):
        """Allocate the pages positions [start, start+n_real) need and
        make the first written page owned. Returns the (src, dst) COW
        copy to perform on device, or None — at most one per request,
        on its first divergent write into a partially attached page."""
        ps = self.page_size
        first = start // ps
        last = (start + n_real - 1) // ps
        table = self.alloc.table(rid)
        for _ in range(len(table), last + 1):
            self.alloc.alloc(rid)
        return self.alloc.ensure_writable(rid, first)

    def register_progress(self, slot):
        """Register every page slot newly covered end-to-end by prompt
        tokens (chain order — parents before children)."""
        st = self.prefill[slot]
        ps = self.page_size
        covered = min(st.next, st.n_prompt)
        while (st.reg_upto + 1) * ps <= covered:
            s = st.reg_upto
            self.alloc.register(st.req.req_id, s,
                                st.req.prompt[s * ps:(s + 1) * ps])
            st.reg_upto += 1

    def finish_prefill(self, slot):
        del self.prefill[slot]  # rid_of persists while the slot is live

    # -- decode --

    def ensure_decode_page(self, rid, pos):
        """Page coverage for a decode write at `pos`: allocate on a
        page boundary; `ensure_writable` is a defensive no-op here (a
        decode position's page was always written during prefill or
        freshly allocated — both owned)."""
        slot_idx = pos // self.page_size
        table = self.alloc.table(rid)
        while len(table) <= slot_idx:
            self.alloc.alloc(rid)
        return self.alloc.ensure_writable(rid, slot_idx)

    # -- table staging --

    def table_row(self, rid):
        row = np.zeros((self.max_pages_per_seq,), np.int32)
        for i, entry in enumerate(self.alloc.table(rid)):
            row[i] = entry.page
        return row

    def tables_array(self):
        out = np.zeros((self.n_slots, self.max_pages_per_seq), np.int32)
        for slot, rid in self.rid_of.items():
            for i, entry in enumerate(self.alloc.table(rid)):
                out[slot, i] = entry.page
        return out

    # -- release / reset / metrics --

    def release(self, slot):
        rid = self.rid_of.pop(slot)
        self.prefill.pop(slot, None)
        self.alloc.free_seq(rid)

    def reset(self):
        """Rejoin-empty reset (replica revive): fresh allocator — the
        page CONTENTS are stale-but-masked exactly like slab rows, but
        the prefix chain must not survive into the new life (its pages
        are about to be reallocated arbitrarily)."""
        self.alloc = PageAllocator(self.alloc.n_pages, self.page_size,
                                   prefix_sharing=self.alloc.prefix_sharing)
        self.prefill.clear()
        self.rid_of.clear()
        self._plans.clear()

    def prefix_hit_rate(self):
        if not self.prompt_tokens:
            return 0.0
        return self.shared_tokens / self.prompt_tokens

    def audit(self, *, expect_empty=False):
        stats = self.alloc.audit()
        if expect_empty:
            assert stats["live"] == 0 and stats["reserved"] == 0, (
                f"pages still live after drain: {stats} — page leak")
        return stats
