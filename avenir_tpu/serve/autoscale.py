"""Trace-driven elastic control plane: fleet SLO engine + autoscaler
(ISSUE 12 tentpole).

PR 9 made every millisecond of TTFT attributable and PR 7 made replicas
spawnable/killable OS processes — but nothing ACTED on what the
observability layer sees: fleet size was static, admission shed on a
static projected-wait heuristic, and a fresh worker served its first
compile to a user. This module closes the observe -> decide -> act loop
while keeping every decision itself observable:

- **SLOEngine** — windowed (ring-buffer, injectable-clock) per-priority
  SLO attainment and burn rate computed from the SAME finished-request
  stream serve_bench already scores (`slo_attainment`): a request meets
  its SLO iff it was SERVED (stop/length) within the TTFT target and,
  where defined, the TPOT target; shed and timed-out requests are
  violations (they are exactly the user-visible symptom of an
  under-provisioned fleet), door rejections (impossible shapes) are
  excluded. Burn rate is the SRE error-budget form: with a target
  attainment A*, burn = (1 - attainment) / (1 - A*) — 1.0 means the
  error budget is being spent exactly at its sustainable rate, above it
  the fleet is burning reserve. Exported as schema-pinned gauges
  (`slo_attainment_interactive`/`_batch`, `slo_burn_rate`).

- **WaitPredictor** — per-class queue-wait predictor fit on traced
  dispatch history (the submit -> dispatch deltas the PR 9 tracer
  stamps as `submit`/`dispatch` events; the router feeds it the same
  (depth-at-submit, wait) pairs those events carry, and only builds it
  when tracing is armed). `Router.projected_wait_ms` consults it so
  admission shedding tracks MEASURED queue behavior under shifting load
  instead of the static median-slot-hold rule — which remains the
  fallback when tracing is off or the predictor is not yet fit.

- **Autoscaler** — watches burn rate and queue-wait attribution and
  spawns/retires replicas through the router's fleet surface (process
  backend: real worker processes via the ProcReplica/RespawnSupervisor
  machinery; new replicas pre-warm their compile caches before taking
  work — `Engine.prewarm`). No flapping by construction: scale-up needs
  the up-condition SUSTAINED for `up_stable_s`, scale-down needs the
  down-condition (burn low AND the shrunken fleet would still be
  comfortably utilized) sustained for `down_stable_s`, and every action
  starts a `cooldown_s` window in which no further decision fires
  (tests pin zero decisions under steady load). Scale-to-zero
  (`scale_to_zero=True`, the batch-class mode) retires the whole fleet
  after `idle_to_zero_s` of no work and wakes it the moment work
  arrives — the wake bypasses the cooldown (an empty fleet with queued
  work is an outage, not an oscillation), paying spawn + pre-warm
  latency once per burst (docs/OPERATIONS.md).

Every decision is simultaneously (a) a `scale_up`/`scale_down` counter
bump, (b) a `scale` trace event carrying the evidence that triggered it
(burn rate, per-class attainment, queue wait, utilization, evidence
window, before/after fleet size) into the PR 9 tracer — and therefore
the flight recorder and the Perfetto export, where scale decisions
render as their own track with a fleet-size counter — and (c) a row in
`tools/fleet_report.py`'s decision log. An operator can answer "why did
the fleet grow at 14:03" from the artifacts alone.
"""

import dataclasses
import math
import time
from collections import deque

from avenir_tpu.obs import get_registry
from avenir_tpu.serve.replica import DEAD, HEALTHY

# literal gauge keys (METRIC_SCHEMA-pinned); a dict lookup rather than
# an f-string so the schema lint's source scan sees only declared keys
_ATT_GAUGE = {
    "interactive": "slo_attainment_interactive",
    "batch": "slo_attainment_batch",
}

SERVED = ("stop", "length")


def request_met_slo(f, *, slo_ttft_ms, slo_tpot_ms):
    """The ONE definition of 'this request met its SLO' — shared with
    serve_bench's slo_attainment so the autoscaler optimizes exactly
    the number the bench scores: served (tokens delivered, not shed or
    timed out), TTFT within target, TPOT within target where defined."""
    return (f.finish_reason in SERVED
            and f.ttft_ms is not None and f.ttft_ms <= slo_ttft_ms
            and (f.n_out <= 1 or f.tpot_ms <= slo_tpot_ms))


class SLOEngine:
    """Windowed per-priority-class SLO attainment + burn rate over the
    finished-request stream (ring buffer, injectable clock).

    `observe(finished)` ingests terminal records (engine or router
    FinishedRequests); door rejections are excluded (bad input, not
    capacity), everything else scores against the TTFT/TPOT targets.
    `attainment(cls)` / `burn_rate()` answer over the trailing
    `window_s` seconds; gauges are refreshed on every burn_rate()."""

    def __init__(self, *, slo_ttft_ms, slo_tpot_ms,
                 target_attainment=0.9, window_s=30.0, clock=None,
                 registry=None):
        assert 0.0 < target_attainment < 1.0, (
            "target_attainment must be in (0, 1) — 1.0 makes the error "
            "budget zero and the burn rate undefined")
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.slo_tpot_ms = float(slo_tpot_ms)
        self.target_attainment = float(target_attainment)
        self.window_s = float(window_s)
        self.clock = clock if clock is not None else time.perf_counter
        self._reg = registry if registry is not None else get_registry()
        self._obs = deque()   # (t, priority, ok) — evicted past window_s
        self.n_observed = 0

    def observe(self, finished):
        now = self.clock()
        for f in finished:
            if f.finish_reason == "rejected":
                continue  # impossible shape: user error, not capacity
            ok = request_met_slo(f, slo_ttft_ms=self.slo_ttft_ms,
                                 slo_tpot_ms=self.slo_tpot_ms)
            cls = getattr(f, "priority", "interactive")
            served = f.finish_reason in SERVED
            # per-COMPONENT verdicts (ISSUE 13): TTFT misses point at
            # the prefill class, TPOT misses at the decode class — the
            # signals a disaggregated fleet scales its classes on
            ttft_ok = (served and f.ttft_ms is not None
                       and f.ttft_ms <= self.slo_ttft_ms)
            tpot_ok = (served
                       and (f.n_out <= 1 or f.tpot_ms <= self.slo_tpot_ms))
            self._obs.append((now, cls, bool(ok), ttft_ok, tpot_ok))
            self.n_observed += 1
        self._evict(now)

    def _evict(self, now):
        horizon = now - self.window_s
        while self._obs and self._obs[0][0] < horizon:
            self._obs.popleft()

    def attainment(self, priority=None):
        """Fraction of windowed observations meeting the SLO (None with
        no samples). `priority=None` pools every class."""
        self._evict(self.clock())
        obs = [o[2] for o in self._obs
               if priority is None or o[1] == priority]
        if not obs:
            return None
        return sum(obs) / len(obs)

    def component_attainments(self):
        """Windowed attainment per SLO COMPONENT, pooled over priority
        classes: 'ttft' (queue + prefill latency — the prefill class's
        resource under disaggregation) and 'tpot' (decode bandwidth —
        the decode class's). None per key with no windowed samples.
        These are what let the autoscaler grow the RIGHT replica class
        (ISSUE 13 satellite)."""
        self._evict(self.clock())
        out = {}
        for key, idx in (("ttft", 3), ("tpot", 4)):
            vals = [o[idx] for o in self._obs]
            out[key] = sum(vals) / len(vals) if vals else None
        return out

    def attainments(self):
        """Per-class windowed attainment ({cls: fraction or None}).
        Gauge convention: an EMPTY window writes 1.0 (no observed
        violations) — otherwise a gauge frozen at the last crisis
        value would report an SLO fire on an idle fleet forever; the
        returned None still tells control logic idle from healthy."""
        out = {}
        for cls, key in _ATT_GAUGE.items():
            a = self.attainment(cls)
            out[cls] = a
            self._reg.gauge(key).set(1.0 if a is None else a)
        return out

    def burn_rate(self):
        """Worst-class error-budget burn over the window: with target
        attainment A*, burn = (1 - attainment) / (1 - A*). 1.0 = the
        budget is being spent exactly at its sustainable rate; None
        with no windowed samples (an idle fleet burns nothing)."""
        return self.burn_from(self.attainments())

    def burn_from(self, atts):
        """Burn rate from an attainments() snapshot — the poll loop
        computes the snapshot once and derives both from it (the
        window scan is per-poll hot-path work)."""
        budget = 1.0 - self.target_attainment
        burns = [(1.0 - a) / budget for a in atts.values()
                 if a is not None]
        if not burns:
            # idle fleet burns nothing: the gauge must not stay frozen
            # at the last crisis value after the window empties
            self._reg.gauge("slo_burn_rate").set(0.0)
            return None
        burn = max(burns)
        self._reg.gauge("slo_burn_rate").set(burn)
        return burn


class WaitPredictor:
    """Per-class queue-wait predictor fit on traced dispatch history
    (ISSUE 12 tentpole, part 3).

    Observations are the submit -> dispatch deltas the PR 9 trace
    events stamp, paired with the class queue depth at submit; the
    router feeds them only on a request's FIRST dispatch (failover
    requeues measure replica death, not queue behavior). The model is
    a small online least squares `wait ~= a + b * depth` over a bounded
    ring — depth is the one admission-time observable, and the fitted
    slope IS the measured drain rate the static rule only guesses at
    (median slot hold / fair-share capacity). Until `min_samples`
    observations land, `predict_ms` returns None and the router keeps
    the static rule — tracing off means no predictor at all."""

    # below this fitted slope (ms of wait per unit of queue depth) the
    # model has learned no drain-rate information — outside its
    # observed depth support it abstains and the static rule answers
    MIN_SLOPE_MS = 1.0
    SUPPORT_SLACK = 2.0
    RESYNC = 4096

    def __init__(self, cap=256, min_samples=8):
        self._obs = deque(maxlen=int(cap))   # (depth, wait_s)
        self.min_samples = int(min_samples)
        # running sums — the fit is O(1) per call, not an O(cap)
        # rescan on the per-submit admission hot path; re-synced
        # exactly every RESYNC observes so eviction drift cannot
        # accumulate over a long-lived fleet
        self._sx = self._sy = self._sxx = self._sxy = 0.0
        self._n_observed = 0
        self._max_depth = 0.0   # lifetime support bound (monotone)

    def observe(self, depth, wait_s):
        d, w = float(depth), max(0.0, float(wait_s))
        if len(self._obs) == self._obs.maxlen:
            od, ow = self._obs[0]   # deque eviction, mirrored in sums
            self._sx -= od
            self._sy -= ow
            self._sxx -= od * od
            self._sxy -= od * ow
        self._obs.append((d, w))
        self._sx += d
        self._sy += w
        self._sxx += d * d
        self._sxy += d * w
        self._max_depth = max(self._max_depth, d)
        self._n_observed += 1
        if self._n_observed % self.RESYNC == 0:
            self._sx = sum(x for x, _ in self._obs)
            self._sy = sum(y for _, y in self._obs)
            self._sxx = sum(x * x for x, _ in self._obs)
            self._sxy = sum(x * y for x, y in self._obs)

    @property
    def n_samples(self):
        return len(self._obs)

    def predict_ms(self, depth):
        """Predicted queue wait (ms) for a request arriving at this
        class queue depth; None until the predictor is fit — and None
        again when the fit carries no drain-rate information (flat or
        single-depth samples) and the queried depth sits outside its
        observed support: a calm-period fit of '~0 ms at depth 0-1'
        must not blind shedding (or the predictive scale-up trigger)
        to a sudden 50-deep burst — the static rule answers instead."""
        n = len(self._obs)
        if n < self.min_samples:
            return None
        depth = float(depth)
        mx = self._sx / n
        my = self._sy / n
        var = max(0.0, self._sxx - n * mx * mx)
        outside = depth > self._max_depth + self.SUPPORT_SLACK
        if var < 1e-9:
            # every sample at one depth: the mean speaks only nearby
            return my * 1e3 if abs(depth - mx) <= 1.0 else None
        b = max(0.0, (self._sxy - n * mx * my) / var)
        #       deeper queues never predict SHORTER waits ^
        if outside and b * 1e3 < self.MIN_SLOPE_MS:
            return None
        a = my - b * mx
        return max(0.0, a + b * depth) * 1e3


@dataclasses.dataclass
class ScaleDecision:
    """One autoscale decision, as recorded in the host-side log (the
    trace event carries the same fields as attrs)."""

    t: float
    action: str            # 'up' | 'down' | 'wake' | 'replace_dead'
    reason: str
    from_size: int
    to_size: int
    evidence: dict


class Autoscaler:
    """Observes the SLO engine + router queue state, spawns/retires
    replicas with hysteresis + cooldown, and leaves an auditable trail.

    Drive it from the serving loop:

        fins = router.step()
        scaler.observe(fins)
        scaler.poll()            # decisions happen here

    (or `scaler.run_step()`, which does all three). Decisions actuate
    through `Router.add_replica` / `Router.retire_replica`: inproc
    replicas are built in place; process-backend replicas spawn a real
    worker through the ProcReplica machinery, whose hello pre-warms the
    compile caches (`prewarm=True` default) so a fresh replica is never
    dispatchable until a synthetic prefill + decode tick per bucket has
    compiled — a user never eats a fresh worker's first compile.

    Knobs (docs/SERVING.md table):
      min_replicas/max_replicas  fleet bounds (scale_to_zero forces
                                 min to 0)
      up_burn / down_burn        burn-rate hysteresis band: up above,
                                 down below — never both
      up_queue_wait_ms           queue-wait trigger (default: half the
                                 SLO TTFT) — predictive scale-up BEFORE
                                 attainment is lost, when tracing feeds
                                 the wait predictor
      up_stable_s/down_stable_s  how long a condition must hold
      cooldown_s                 dead time after any action
      down_util                  scale-down only if the SHRUNKEN fleet
                                 would still sit below this busy
                                 fraction (surplus must be provable)
      scale_to_zero/idle_to_zero_s  batch-class mode: retire the whole
                                 fleet when idle, wake on queued work
      prewarm                    pre-warm compile caches on every spawn
    """

    def __init__(self, router, slo: SLOEngine, *, min_replicas=1,
                 max_replicas=4, up_burn=1.0, down_burn=0.3,
                 up_queue_wait_ms=None, up_stable_s=2.0,
                 down_stable_s=10.0, cooldown_s=5.0, down_util=0.6,
                 scale_to_zero=False, idle_to_zero_s=10.0, prewarm=True,
                 spawn_async=False, spawn_parallelism=1, registry=None,
                 clock=None, echo=print):
        self.router = router
        self.slo = slo
        self.scale_to_zero = bool(scale_to_zero)
        self.min_replicas = 0 if scale_to_zero else int(min_replicas)
        self.max_replicas = int(max_replicas)
        assert self.max_replicas >= max(1, self.min_replicas)
        self.up_burn = float(up_burn)
        self.down_burn = float(down_burn)
        assert self.down_burn < self.up_burn, (
            "hysteresis band inverted: down_burn must sit below up_burn "
            "or the fleet flaps between the two thresholds")
        self.up_queue_wait_ms = (float(up_queue_wait_ms)
                                 if up_queue_wait_ms is not None
                                 else slo.slo_ttft_ms / 2.0)
        self.up_stable_s = float(up_stable_s)
        self.down_stable_s = float(down_stable_s)
        self.cooldown_s = float(cooldown_s)
        self.down_util = float(down_util)
        self.idle_to_zero_s = float(idle_to_zero_s)
        self.prewarm = bool(prewarm)
        # spawn_async: grow via Router.begin_add_replica on a
        # background thread — the fleet keeps serving while the
        # newcomer pays its spawn + pre-warm, and it joins at the first
        # poll() that finds it ready. One spawn in flight at a time; no
        # other decision fires while one is warming (fresh capacity
        # must land before the stale evidence window can demand more).
        # Default off: synchronous spawns keep tests deterministic;
        # real serving loops (serve_bench --autoscale) turn it on.
        self.spawn_async = bool(spawn_async)
        # how many newcomers may warm CONCURRENTLY: on a many-host
        # deployment each spawn compiles on its own machine, but on a
        # shared host every warming replica steals compute from the
        # serving loop — default 1 (serial), raise it only when spawn
        # compute is actually elsewhere
        self.spawn_parallelism = max(1, int(spawn_parallelism))
        self._spawns = []           # in-flight background builds
        self._util_hist = deque()   # (t, busy_frac) samples per poll
        self._reg = registry if registry is not None else router._reg
        self._clock = clock if clock is not None else router._clock
        # wake-on-shed baseline: at fleet zero, deadline-carrying
        # submits are refused at the door (projected wait is infinite)
        # and never enter the queues — a rising serve_shed count is
        # then the ONLY evidence that traffic wants the fleet back
        self._shed_seen = self._reg.counter("serve_shed").total
        self._echo = echo
        self.decisions = []       # host-side ScaleDecision log
        self._last_action_t = -math.inf
        # pacing for the wake/replace_dead branches, which bypass the
        # normal cooldown: only a FAILED spawn arms it, so a healthy
        # wake stays instant but a persistently failing spawn (fd or
        # process limit) retries at cooldown cadence, not every poll
        self._last_spawn_fail_t = -math.inf
        self._up_since = None
        self._down_since = None
        self._idle_since = None
        self._last_poll_t = None

    # -- the loop surface --

    def run_step(self):
        """One elastic fleet iteration: step the router, feed the SLO
        engine, make any due decision. Returns the finished requests."""
        fins = self.router.step()
        self.observe(fins)
        self.poll()
        return fins

    def observe(self, finished):
        self.slo.observe(finished)

    def poll(self, now=None):
        """Account replica-seconds, refresh the SLO gauges, and make at
        most ONE scale decision if its condition has been sustained and
        the cooldown allows. Returns the decision (or None)."""
        now = self._clock() if now is None else now
        r = self.router
        # a draining (retiring) replica still holds its chip until
        # reaped, and in-flight background spawns hold theirs while
        # they warm — all bill like serving replicas
        billable = (sum(rep.state != DEAD for rep in r.replicas)
                    + len(self._spawns))
        if self._last_poll_t is not None and now > self._last_poll_t:
            self._reg.counter("fleet_replica_seconds").add(
                (now - self._last_poll_t) * billable)
        self._last_poll_t = now
        for spawn in [s for s in self._spawns if s.ready()]:
            self._spawns.remove(spawn)
            try:
                rep = r.finish_add_replica(spawn)
                self._echo(f"[autoscale] replica {rep.replica_id} "
                           "warmed and joined the fleet")
            except Exception as e:  # noqa: BLE001 — spawn failure is
                # an event, not a fleet crash; the next poll's
                # conditions decide whether to try again (paced by
                # the spawn-fail clock for the cooldown-free branches)
                self._echo(f"[autoscale] background spawn failed: "
                           f"{e!r}")
                self._last_spawn_fail_t = now
                # COMPENSATING audit record: the up decision's to_size
                # never materialized — without this, the trace/
                # fleet_report/Perfetto fleet-size trail (and every
                # replica-second integral over it) would overstate the
                # fleet forever on exactly the failure case
                actual = r.fleet_size
                if r.tracer is not None:
                    r.tracer.emit(None, "scale", t=now,
                                  action="spawn_failed",
                                  reason=repr(e)[:160],
                                  from_size=actual, to_size=actual,
                                  replica=spawn.replica_id)
                self.decisions.append(ScaleDecision(
                    t=now, action="spawn_failed", reason=repr(e)[:160],
                    from_size=actual, to_size=actual,
                    evidence={"replica": spawn.replica_id}))
        if self._spawns:
            # capacity is already on its way: no further decision until
            # it lands — stale window evidence must not stack replicas
            # the warming ones will already answer
            self._reg.gauge("fleet_size").set(r.fleet_size)
            return None
        alive = r.fleet_size
        self._reg.gauge("fleet_size").set(alive)
        atts = self.slo.attainments()
        burn = self.slo.burn_from(atts)
        qw = self._queue_wait_ms()
        # utilization is sampled per poll and averaged over the
        # down-stability window: an instantaneous sample flickers with
        # every lone arrival (one request on an otherwise idle replica
        # reads as util=1/slots for a service time), and the
        # scale-down check must see sustained occupancy, not noise
        util = self._busy_frac()
        self._util_hist.append((now, util))
        horizon = now - max(self.down_stable_s, 1.0)
        while self._util_hist and self._util_hist[0][0] < horizon:
            self._util_hist.popleft()
        util_avg = (sum(u for _, u in self._util_hist)
                    / len(self._util_hist))
        evidence = {
            "burn_rate": None if burn is None else round(burn, 4),
            "queue_wait_ms": None if qw is None else round(qw, 2),
            "busy_frac": round(util_avg, 4),
            "queue_depth": r.queue_depth,
            "window_s": self.slo.window_s,
        }
        for cls, a in atts.items():
            evidence[f"attainment_{cls}"] = (None if a is None
                                             else round(a, 4))

        has_work = bool(r.open_requests or r.queue_depth)
        # 1) burst wake: an empty fleet with queued work — or with
        # fresh door sheds: an all-deadline class never queues at zero
        # capacity (every submit is refused with projected wait
        # infinite), so the shed counter movement IS the burst — is an
        # OUTAGE, not an oscillation: bypass stability and cooldown.
        # The requests shed before the wake are already refused; the
        # wake restores capacity for the next ones (docs/OPERATIONS.md
        # wake-latency row).
        # ... unless a RespawnSupervisor still owns revival of the
        # dead fleet (same deference as replace_dead below): waking
        # on top of its pending respawns would double-provision
        shed_total = self._reg.counter("serve_shed").total
        fresh_sheds = shed_total > self._shed_seen
        self._shed_seen = shed_total
        sup = getattr(r, "_supervisor", None)
        # both floor-restoring branches bypass the normal cooldown
        # (waiting out a scale-down's dead time on an OUTAGE would be
        # absurd) but still pace RETRIES after a failed spawn — without
        # this gate a persistent spawn failure re-forks on every poll
        spawn_ok = now - self._last_spawn_fail_t >= self.cooldown_s
        if (alive == 0 and (has_work or fresh_sheds) and spawn_ok
                and (sup is None or not sup.pending())):
            return self._scale_up(now, "wake", evidence)
        # 2) replace-dead: under the process backend the respawn
        # supervisor owns revival (same replica id, backoff schedule);
        # without one, the autoscaler restores the floor itself
        if (alive < self.min_replicas and spawn_ok
                and getattr(r, "_supervisor", None) is None):
            return self._scale_up(now, "replace_dead", evidence)

        # rollout coordination (ISSUE 20): while a weight rollout (or
        # its rollback) converges the fleet, scale-DOWN decisions are
        # suppressed — retiring mid-campaign would thrash the version
        # accounting and could dip attainment exactly when a replica is
        # out for its swap. Scale-UP stays allowed: extra capacity only
        # helps the rollout hold the SLO floor
        rolling = getattr(r, "rollout_active", False)

        # 3) scale-to-zero idle retirement (batch-class mode)
        if (self.scale_to_zero and alive > 0 and not has_work
                and not rolling):
            if self._idle_since is None:
                self._idle_since = now
            elif (now - self._idle_since >= self.idle_to_zero_s
                  and now - self._last_action_t >= self.cooldown_s):
                return self._scale_down(now, "idle_to_zero", evidence)
        else:
            self._idle_since = None

        # 4) scale up: burn above the band, or measured queue wait past
        # the predictive trigger — sustained
        up = ((burn is not None and burn >= self.up_burn)
              or (qw is not None and qw >= self.up_queue_wait_ms))
        if up and alive < self.max_replicas:
            if self._up_since is None:
                self._up_since = now
            elif (now - self._up_since >= self.up_stable_s
                  and now - self._last_action_t >= self.cooldown_s):
                reason = ("burn_rate"
                          if burn is not None and burn >= self.up_burn
                          else "queue_wait")
                return self._scale_up(now, reason, evidence)
        else:
            self._up_since = None

        # 5) scale down: burn below the band AND the shrunken fleet
        # would still sit below the utilization ceiling — sustained
        surplus = (not rolling
                   and (burn is None or burn <= self.down_burn)
                   and alive > max(1, self.min_replicas)
                   and util_avg * alive / (alive - 1) <= self.down_util)
        if surplus:
            if self._down_since is None:
                self._down_since = now
            elif (now - self._down_since >= self.down_stable_s
                  and now - self._last_action_t >= self.cooldown_s):
                return self._scale_down(now, "surplus", evidence)
        else:
            self._down_since = None
        return None

    # -- evidence --

    def _queue_wait_ms(self):
        """Queue-wait evidence: the router's projected wait at the
        CURRENT class queue depth — which is the traced predictor's
        forward-looking answer when tracing is armed (it reacts the
        poll a backlog forms, where a trailing mean of finished waits
        lags by its window) and the static rule otherwise. Worst class
        wins; an infinite projection (no healthy replica) is the wake
        path's business, not a number."""
        waits = []
        for cls in self.router.weights:
            w = self.router.projected_wait_ms(cls)
            if w is not None and math.isfinite(w):
                waits.append(w)
        return max(waits) if waits else None

    def _busy_frac(self):
        """Occupied-slot fraction across the non-dead fleet (the
        scale-down surplus check's utilization)."""
        total = occupied = 0
        for rep in self.router.replicas:
            if (rep.state == DEAD
                    or rep.replica_id in self.router._retiring):
                # the surplus projection divides by the SERVING fleet
                # (`alive`); counting a draining retiree's mostly-empty
                # slots in the denominator would dilute utilization and
                # enable cascade retirements right at the threshold
                continue
            total += rep.n_slots
            occupied += len(rep.engine._live)
            # mid-chunked-prefill slots hold a slot and burn compute:
            # inproc paged engines expose them as pg.prefill, a process
            # replica's heartbeat mirrors the count as _prefilling —
            # missing either would understate utilization and let the
            # surplus check retire a replica the fleet still needs
            paged = getattr(rep.engine, "_paged", None)
            if paged is not None:
                occupied += len(paged.prefill)
            else:
                occupied += getattr(rep.engine, "_prefilling", 0)
        return occupied / total if total else 0.0

    # -- disaggregated class choice (ISSUE 13 satellite) --

    def _disagg(self):
        """Is the router's fleet split into prefill/decode classes?"""
        return (hasattr(self.router, "fleet_size_by_class")
                and any(v == "prefill"
                        for v in getattr(self.router, "_role",
                                         {}).values()))

    def _queued_long_frac(self):
        """Fraction of router-queued requests that would route to the
        prefill class (prompt >= disagg_min_prompt); None with nothing
        queued. This is what distinguishes 'TTFT burns because prefill
        is short' from 'TTFT burns because the decode class has no free
        slots' — both show as queue wait + TTFT misses, but only the
        queued work's composition names the starved class."""
        thr = getattr(self.router, "disagg_min_prompt", 0)
        n = n_long = 0
        for q in getattr(self.router, "_queues", {}).values():
            for req in q:
                n += 1
                n_long += len(req.prompt) >= thr
        return (n_long / n) if n else None

    def _pick_up_class(self, reason):
        """Which replica class a scale-up should grow. Queue wait and
        TTFT-dominated burn follow the QUEUED WORK's composition
        (_queued_long_frac): a long-dominated queue is waiting on
        prefill-class capacity, a short-dominated one on decode slots —
        growing prefill under a short-prompt flood would spend the
        fleet budget on replicas that can never serve the backlog. A
        TPOT-dominated burn is decode bandwidth. Wake / replace_dead
        restore a decode-class replica first — it serves the full
        lifecycle standalone, so the fleet is never alive yet unable
        to finish anything."""
        if reason in ("wake", "replace_dead"):
            return "both"
        if reason == "queue_wait":
            lf = self._queued_long_frac()
            return "prefill" if lf is None or lf >= 0.5 else "both"
        comp = self.slo.component_attainments()
        budget = 1.0 - self.slo.target_attainment
        burn_ttft = (None if comp["ttft"] is None
                     else (1.0 - comp["ttft"]) / budget)
        burn_tpot = (None if comp["tpot"] is None
                     else (1.0 - comp["tpot"]) / budget)
        if (burn_ttft or 0.0) > (burn_tpot or 0.0):
            lf = self._queued_long_frac()
            # an empty queue + TTFT burn = prefill latency itself
            return "both" if lf is not None and lf < 0.5 else "prefill"
        return "both"

    def _class_evidence(self, evidence):
        """Per-class sizes + component burn, folded into the decision's
        audit evidence when the fleet is disaggregated."""
        if not self._disagg():
            return evidence
        comp = self.slo.component_attainments()
        by = self.router.fleet_size_by_class()
        return {**evidence,
                "prefill_replicas": by["prefill"],
                "decode_replicas": by["decode"],
                "attainment_ttft": (None if comp["ttft"] is None
                                    else round(comp["ttft"], 4)),
                "attainment_tpot": (None if comp["tpot"] is None
                                    else round(comp["tpot"], 4))}

    # -- actuation + audit trail --

    def _scale_up(self, now, reason, evidence):
        before = self.router.fleet_size
        action = reason if reason in ("wake", "replace_dead") else "up"
        role = self._pick_up_class(reason) if self._disagg() else "both"
        evidence = self._class_evidence(evidence)
        if role != "both":
            evidence = {**evidence, "class": role}
        if self.spawn_async:
            # STEP SIZE follows the measured need: a queue wait at N x
            # the trigger threshold asks for ~N replicas' worth of
            # drain, and a fleet caught small by a fast ramp must not
            # climb one serial spawn at a time (the newcomers warm
            # CONCURRENTLY and join as each is ready). Wake/replace
            # restore exactly one.
            k = 1
            qw = evidence.get("queue_wait_ms")
            if action == "up" and qw:
                k = max(1, math.ceil(qw / self.up_queue_wait_ms))
            k = min(k, self.spawn_parallelism,
                    self.max_replicas - before)
            for _ in range(k):
                self._spawns.append(self.router.begin_add_replica(
                    prewarm=self.prewarm, role=role))
            return self._decide(
                now, action, reason, before, before + k,
                {**evidence,
                 "replica": [s.replica_id for s in self._spawns[-k:]],
                 "n_spawn": k, "spawn_async": True})
        t0 = self._clock()
        try:
            rep = self.router.add_replica(prewarm=self.prewarm,
                                          role=role)
        except Exception as e:  # noqa: BLE001 — same policy as the
            # async join: a spawn failure is an event, not a reason to
            # crash a loop that is still serving on the healthy fleet.
            # Nothing is recorded as a decision (the fleet never grew);
            # both retry clocks back off — _last_action_t paces the
            # sustained-condition branches, _last_spawn_fail_t paces
            # the cooldown-bypassing wake/replace_dead branches
            self._echo(f"[autoscale] spawn failed: {e!r}")
            self._last_action_t = now
            self._last_spawn_fail_t = now
            return None
        spawn_s = self._clock() - t0
        return self._decide(now, action, reason, before, before + 1,
                            {**evidence, "replica": rep.replica_id,
                             "spawn_s": round(spawn_s, 4)})

    def _scale_down(self, now, reason, evidence):
        before = self.router.fleet_size
        if reason == "idle_to_zero":
            # the documented contract: the WHOLE idle fleet retires in
            # one decision after idle_to_zero_s, not one replica per
            # idle window (the fleet has no work — every drain is a
            # no-op — so retiring serially would just bill
            # ~fleet_size x (idle_to_zero_s + cooldown_s) of extra
            # replica-seconds per idle period)
            victims = [rep for rep in self.router.replicas
                       if rep.state == HEALTHY
                       and rep.replica_id not in self.router._retiring]
            if not victims:
                return None
            for rep in victims:
                self.router.retire_replica(rep.replica_id)
            return self._decide(
                now, "down", reason, before, before - len(victims),
                {**evidence,
                 "replica": [rep.replica_id for rep in victims]})
        victim = self._pick_victim()
        if victim is None:
            return None
        evidence = self._class_evidence(evidence)
        self.router.retire_replica(victim.replica_id)
        return self._decide(now, "down", reason, before, before - 1,
                            {**evidence, "replica": victim.replica_id})

    def _pick_victim(self):
        """Retire the least-loaded healthy replica; ties retire the
        newest (LIFO keeps the longest-warmed caches serving).

        Disagg (ISSUE 13): the victim comes from the class with the
        LOWER component burn (surplus lives where the SLO is safest),
        and neither class is ever retired to zero while the other
        serves — a fleet with prefill replicas but no decode class
        could prefill forever and finish nothing."""
        cands = [rep for rep in self.router.replicas
                 if rep.state == HEALTHY
                 and rep.replica_id not in self.router._retiring]
        if self._disagg() and cands:
            role_of = self.router._role
            by = {"prefill": [r for r in cands
                              if role_of.get(r.replica_id) == "prefill"],
                  "decode": [r for r in cands
                             if role_of.get(r.replica_id) != "prefill"]}
            comp = self.slo.component_attainments()
            # shrink the class whose SLO component is SAFEST; a class
            # down to its last healthy replica is off the table
            order = ["decode", "prefill"]
            if (comp["ttft"] is not None and comp["tpot"] is not None
                    and comp["ttft"] > comp["tpot"]):
                order = ["prefill", "decode"]
            for cls in order:
                if len(by[cls]) > 1 or not by["prefill" if cls ==
                                              "decode" else "decode"]:
                    cands = by[cls]
                    break
            else:
                return None
        if not cands:
            return None
        return min(cands, key=lambda rep: (len(rep.engine._live),
                                           -rep.replica_id))

    def _decide(self, now, action, reason, from_size, to_size,
                evidence):
        """The audit trail: counter bump + trace event (-> flight
        recorder + Perfetto `autoscaler` track + fleet_report) + host
        log, atomically per decision."""
        grew = to_size > from_size
        self._reg.counter("scale_up" if grew else "scale_down").add(1)
        self._reg.gauge("fleet_size").set(self.router.fleet_size)
        tracer = self.router.tracer
        if tracer is not None:
            tracer.emit(None, "scale", t=now, action=action,
                        reason=reason, from_size=from_size,
                        to_size=to_size,
                        **{k: v for k, v in evidence.items()
                           if v is not None})
        d = ScaleDecision(t=now, action=action, reason=reason,
                          from_size=from_size, to_size=to_size,
                          evidence=dict(evidence))
        self.decisions.append(d)
        self._last_action_t = now
        self._up_since = self._down_since = self._idle_since = None
        self._echo(f"[autoscale] {action} {from_size} -> {to_size} "
                   f"(reason={reason}, burn={evidence.get('burn_rate')}"
                   f", queue_wait={evidence.get('queue_wait_ms')} ms)")
        return d

    def close(self):
        """Reap in-flight background spawns (join the build, shut the
        finished replica down without joining it to the fleet) — call
        BEFORE Router.close() at end of run, or a warming worker
        process outlives the fleet it was meant to join."""
        for spawn in self._spawns:
            try:
                spawn.thread.join()
                if spawn.result is not None and hasattr(spawn.result,
                                                        "close"):
                    spawn.result.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._spawns = []

    # -- convenience for benches/tests --

    def drain(self, max_steps=None):
        """Router.drain with the autoscaler in the loop (a zero fleet
        with queued work wakes instead of failing loud)."""
        out = []
        steps = 0
        bound = max_steps or 200_000
        while (self.router.open_requests or self.router._pending):
            out.extend(self.run_step())
            steps += 1
            if steps > bound:
                raise RuntimeError("autoscaled fleet failed to drain")
        return out


def mean_fleet_size(decisions, *, t0, t1, initial_size):
    """Time-weighted mean fleet size over [t0, t1] from a decision log
    (each decision switches the size at its timestamp) — the
    fleet_report summary's cheap integral."""
    if t1 <= t0:
        return float(initial_size)
    size = initial_size
    t = t0
    area = 0.0
    for d in sorted(decisions, key=lambda d: d.t if hasattr(d, "t")
                    else d["t"]):
        dt_ = d.t if hasattr(d, "t") else d["t"]
        to = d.to_size if hasattr(d, "to_size") else d["to_size"]
        if dt_ <= t0:
            size = to
            continue
        if dt_ >= t1:
            break
        area += size * (dt_ - t)
        size, t = to, dt_
    area += size * (t1 - t)
    return area / (t1 - t0)


def steady_window_s(decisions, *, t0, t1):
    """Longest decision-free stretch in [t0, t1] — the no-flapping
    number fleet_report prints."""
    ts = sorted([t0] + [d.t if hasattr(d, "t") else d["t"]
                        for d in decisions] + [t1])
    return max(b - a for a, b in zip(ts, ts[1:])) if len(ts) > 1 else 0.0


__all__ = [
    "SLOEngine", "WaitPredictor", "Autoscaler", "ScaleDecision",
    "request_met_slo", "mean_fleet_size", "steady_window_s",
]
