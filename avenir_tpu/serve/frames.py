"""Length-prefixed, CRC-checked, versioned frame protocol for the
process-isolated serve fleet (ISSUE 8 tentpole, part 1).

The sandbox has no sockets, so a serve worker process talks to its
parent over stdin/stdout pipes. Pipes deliver a byte stream with none
of the message framing, integrity or liveness guarantees an RPC layer
needs, and a fleet that SIGKILLs workers on purpose (tools/
chaos_serve.py) will routinely read half-written frames from corpses —
so every message rides in one self-describing frame:

    MAGIC "AVFR" | u8 proto version | u8 payload type | u32 payload len
    | u32 CRC-32 of payload | payload bytes

and every failure mode is a DISTINCT, loud exception:

    FrameProtocolError  bad magic (stream desync — a worker printed to
                        the frame fd) or a proto version this side does
                        not speak: fail fast, never guess
    FrameCRCError       payload bytes did not survive the pipe (or the
                        `frame_corrupt` fault site flipped one). Never
                        retried — like the checkpoint manifests
                        (ISSUE 5), corruption is fallback territory,
                        not retry territory: the reader's stream offset
                        can no longer be trusted, so the peer is dead
    FrameEOF            the peer closed the pipe (worker SIGKILLed,
                        parent gone) — possibly mid-frame
    FrameTimeout        no (complete) frame within the caller's per-op
                        budget: a silently wedged peer

Payloads are JSON (`PT_JSON`, the control plane), pickle
(`PT_PICKLE`, the model-state handshake: config dataclass + numpy
weight arrays — parent and worker run the same trusted codebase, and
the handshake is the only pickle frame either side ever sends), or the
KV-page tensor form (`PT_KVPAGES`, ISSUE 13): a JSON meta header —
op/seq plus per-record token-chain ids and per-array dtype/shape —
followed by the raw page bytes (K/V page data, and the per-head int8
scale sidecars when the fleet serves kv_dtype='int8'). This is the
wire format disaggregated prefill ships finished KV pages over: the
token chain IS the page identity (serve/pages.py's exact-prefix
registration), so the receiving allocator can splice the pages into
its own prefix chain and shared-prefix COW keeps working across the
transfer boundary.

Deliberately stdlib-only: the codec imports no jax, so the protocol
unit tests (tests/test_serve_proc.py, tier-1) cost nothing, and a
future transport (sockets, shared memory) swaps the fd layer without
touching the frame format. The `frame_corrupt` fault site lives in the
WRITER — the CRC is computed first, then the flip — so what the tests
exercise is the reader's production detection path.
"""

import json
import os
import pickle
import select
import struct
import time
import zlib

MAGIC = b"AVFR"
PROTO_VERSION = 1
PT_JSON = 0
PT_PICKLE = 1
PT_KVPAGES = 2   # KV-page tensor payload (disaggregated prefill, ISSUE 13)

# arrays per record on the PT_KVPAGES wire: bf16 ships (k, v); int8
# ships (k_data, k_scale, v_data, v_scale). Every marshalling site —
# worker, proxy, in-process replica — slices record arrays by this
# count; one table so a new kv dtype cannot silently desync them.
ARRAYS_PER_DTYPE = {"bf16": 2, "int8": 4}

_KVMETA = struct.Struct(">I")  # meta-JSON byte length prefix

_HEADER = struct.Struct(">4sBBII")  # magic, version, ptype, len, crc
HEADER_SIZE = _HEADER.size

# a frame bigger than this is a desynced stream, not a message (the
# largest legitimate frame is the model-state handshake; 1 GiB covers
# any model whose weights a pipe handshake makes sense for at all)
MAX_FRAME_BYTES = 1 << 30


class FrameError(RuntimeError):
    """Base of every frame-layer failure."""


class FrameProtocolError(FrameError):
    """Bad magic or a protocol version this side does not speak."""


class FrameCRCError(FrameError):
    """Payload failed its CRC — corruption, never retried."""


class FrameEOF(FrameError):
    """Peer closed the pipe (possibly mid-frame)."""


class FrameTimeout(FrameError):
    """No complete frame within the caller's per-op budget."""


def _np_dtype(name):
    """numpy dtype from its string name. bf16 lives in ml_dtypes (the
    jax numpy extension); imported lazily so this module stays
    stdlib-only for every frame that carries no tensors."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_kv_pages(meta, arrays):
    """Serialize one KV-page payload: `meta` (a JSON-able dict — op,
    seq, per-record token chains) + `arrays` (numpy arrays: page K/V
    data and, for int8 KV, the per-head scale sidecars). Layout:

        u32 meta length | meta JSON (with per-array dtype/shape
        appended under "_arrays") | raw array bytes, C-order, in order

    The token-chain ids ride in `meta` — they ARE the pages' identity
    (the exact-prefix chain key), which is what lets the importing
    allocator register the pages for shared-prefix reuse + COW."""
    import numpy as np

    meta = dict(meta)
    meta["_arrays"] = [{"dtype": str(a.dtype), "shape": list(a.shape)}
                       for a in arrays]
    head = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    parts = [_KVMETA.pack(len(head)), head]
    parts += [np.ascontiguousarray(a).tobytes() for a in arrays]
    return b"".join(parts)


def decode_kv_pages(payload):
    """Inverse of encode_kv_pages: returns the meta dict with the
    reconstructed numpy arrays under "arrays" (the "_arrays" shape
    manifest is consumed)."""
    import numpy as np

    (head_len,) = _KVMETA.unpack_from(payload, 0)
    off = _KVMETA.size
    meta = json.loads(payload[off:off + head_len].decode("utf-8"))
    off += head_len
    arrays = []
    for spec in meta.pop("_arrays"):
        dt = _np_dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape)) if shape else dt.itemsize
        if off + n > len(payload):
            # a SHORT tear must land in the frame-error taxonomy too —
            # a bare numpy ValueError would escape callers' FrameError
            # classification
            raise FrameProtocolError(
                f"kv-page payload length mismatch: manifest wants "
                f"{off + n} of {len(payload)} bytes — torn or desynced "
                "tensor frame")
        arrays.append(np.frombuffer(payload[off:off + n],
                                    dtype=dt).reshape(shape))
        off += n
    if off != len(payload):
        raise FrameProtocolError(
            f"kv-page payload length mismatch: manifest consumed {off} "
            f"of {len(payload)} bytes — torn or desynced tensor frame")
    meta["arrays"] = arrays
    return meta


def encode_frame(obj, ptype=PT_JSON):
    """One wire-ready frame. The CRC covers the payload as SERIALIZED;
    the `frame_corrupt` fault site flips a payload byte AFTER the CRC
    is computed, so an armed injector produces exactly the torn frame
    the reader's CRC check exists to catch. For PT_KVPAGES, `obj` is a
    (meta, arrays) pair."""
    if ptype == PT_JSON:
        payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    elif ptype == PT_PICKLE:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    elif ptype == PT_KVPAGES:
        payload = encode_kv_pages(*obj)
    else:
        raise ValueError(f"unknown payload type {ptype!r}")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    from avenir_tpu.utils.faults import get_injector

    payload = get_injector().corrupt("frame_corrupt", payload)
    return _HEADER.pack(MAGIC, PROTO_VERSION, ptype, len(payload), crc) \
        + payload


def decode_header(header):
    """-> (ptype, length, crc); raises FrameProtocolError loudly on bad
    magic or a version mismatch (the handshake's fail-fast path)."""
    magic, version, ptype, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameProtocolError(
            f"bad frame magic {magic!r} — stream desync (did something "
            "print to the frame fd?)")
    if version != PROTO_VERSION:
        raise FrameProtocolError(
            f"frame protocol version mismatch: peer speaks v{version}, "
            f"this side speaks v{PROTO_VERSION} — refusing to guess at "
            "an incompatible wire format (upgrade both sides together)")
    if length > MAX_FRAME_BYTES:
        raise FrameProtocolError(
            f"frame length {length} exceeds MAX_FRAME_BYTES — desynced "
            "stream or a hostile peer")
    return ptype, length, crc


def decode_payload(ptype, payload, crc):
    """CRC-check and deserialize one payload."""
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise FrameCRCError(
            f"frame payload failed CRC ({len(payload)} bytes) — the pipe "
            "delivered corrupt bytes; the stream is no longer trustworthy")
    if ptype == PT_JSON:
        return json.loads(payload.decode("utf-8"))
    if ptype == PT_PICKLE:
        return pickle.loads(payload)
    if ptype == PT_KVPAGES:
        return decode_kv_pages(payload)
    raise FrameProtocolError(f"unknown payload type {ptype}")


class FrameStream:
    """Frame reader/writer over a pair of pipe fds.

    Reads are select()-driven with a wall-clock deadline shared across
    the header and payload of one frame — a peer that trickles half a
    frame and wedges still trips FrameTimeout. A FrameTimeout is
    RECOVERABLE: the buffer still holds a clean frame prefix (nothing
    is consumed until a whole frame arrived), so a later read resumes
    correctly. After a CRC/protocol error the stream is dead by policy;
    callers never resynchronize.
    """

    def __init__(self, read_fd, write_fd):
        self._rfd = read_fd
        self._wfd = write_fd
        self._buf = bytearray()  # bytearray: += on bytes is quadratic
        #                          over a GiB-scale handshake frame

    def write(self, obj, ptype=PT_JSON):
        """Serialize and write one frame; OSError (EPIPE when the peer
        is a corpse) propagates to the caller's dead-peer handling."""
        data = encode_frame(obj, ptype)
        view = memoryview(data)
        while view:
            n = os.write(self._wfd, view)
            view = view[n:]

    def read(self, timeout_s=None):
        """Read one frame; returns the decoded object. `timeout_s` is
        the whole-frame budget (None = block forever).

        Atomic over the buffer (ISSUE 13 satellite): nothing is CONSUMED
        until header AND payload are both complete, so a FrameTimeout
        mid-frame — the deadline landing between a partial header (or a
        parsed header and a partial payload) — leaves `self._buf`
        holding a clean frame PREFIX. A caller whose op layer retries
        (the idempotent ping) then resumes at the right offset, instead
        of the old behavior where the consumed header was gone and the
        leftover payload bytes were parsed as a new header — a short
        read surfacing as FrameProtocolError (garbage magic) or, on an
        unlucky byte pattern, FrameCRCError: both UNRECOVERABLE where
        the truth (a slow peer) was the recoverable FrameTimeout."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        self._fill(HEADER_SIZE, deadline)
        ptype, length, crc = decode_header(
            bytes(self._buf[:HEADER_SIZE]))  # peek — not yet consumed
        self._fill(HEADER_SIZE + length, deadline)
        payload = bytes(self._buf[HEADER_SIZE:HEADER_SIZE + length])
        del self._buf[:HEADER_SIZE + length]  # whole frame, atomically
        return decode_payload(ptype, payload, crc)

    def _fill(self, n, deadline):
        """Grow the buffer to >= n bytes without consuming any."""
        while len(self._buf) < n:
            if deadline is not None:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    raise FrameTimeout(
                        "no complete frame within the per-op timeout")
            else:
                wait = None
            ready, _, _ = select.select([self._rfd], [], [], wait)
            if not ready:
                raise FrameTimeout(
                    "no complete frame within the per-op timeout")
            chunk = os.read(self._rfd, 1 << 16)
            if not chunk:
                raise FrameEOF("peer closed the pipe")
            self._buf.extend(chunk)
