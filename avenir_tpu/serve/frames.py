"""Length-prefixed, CRC-checked, versioned frame protocol for the
process-isolated serve fleet (ISSUE 8 tentpole, part 1).

The sandbox has no sockets, so a serve worker process talks to its
parent over stdin/stdout pipes. Pipes deliver a byte stream with none
of the message framing, integrity or liveness guarantees an RPC layer
needs, and a fleet that SIGKILLs workers on purpose (tools/
chaos_serve.py) will routinely read half-written frames from corpses —
so every message rides in one self-describing frame:

    MAGIC "AVFR" | u8 proto version | u8 payload type | u32 payload len
    | u32 CRC-32 of payload | payload bytes

and every failure mode is a DISTINCT, loud exception:

    FrameProtocolError  bad magic (stream desync — a worker printed to
                        the frame fd) or a proto version this side does
                        not speak: fail fast, never guess
    FrameCRCError       payload bytes did not survive the pipe (or the
                        `frame_corrupt` fault site flipped one). Never
                        retried — like the checkpoint manifests
                        (ISSUE 5), corruption is fallback territory,
                        not retry territory: the reader's stream offset
                        can no longer be trusted, so the peer is dead
    FrameEOF            the peer closed the pipe (worker SIGKILLed,
                        parent gone) — possibly mid-frame
    FrameTimeout        no (complete) frame within the caller's per-op
                        budget: a silently wedged peer

Payloads are JSON (`PT_JSON`, the control plane) or pickle
(`PT_PICKLE`, the model-state handshake: config dataclass + numpy
weight arrays — parent and worker run the same trusted codebase, and
the handshake is the only pickle frame either side ever sends).

Deliberately stdlib-only: the codec imports no jax, so the protocol
unit tests (tests/test_serve_proc.py, tier-1) cost nothing, and a
future transport (sockets, shared memory) swaps the fd layer without
touching the frame format. The `frame_corrupt` fault site lives in the
WRITER — the CRC is computed first, then the flip — so what the tests
exercise is the reader's production detection path.
"""

import json
import os
import pickle
import select
import struct
import time
import zlib

MAGIC = b"AVFR"
PROTO_VERSION = 1
PT_JSON = 0
PT_PICKLE = 1

_HEADER = struct.Struct(">4sBBII")  # magic, version, ptype, len, crc
HEADER_SIZE = _HEADER.size

# a frame bigger than this is a desynced stream, not a message (the
# largest legitimate frame is the model-state handshake; 1 GiB covers
# any model whose weights a pipe handshake makes sense for at all)
MAX_FRAME_BYTES = 1 << 30


class FrameError(RuntimeError):
    """Base of every frame-layer failure."""


class FrameProtocolError(FrameError):
    """Bad magic or a protocol version this side does not speak."""


class FrameCRCError(FrameError):
    """Payload failed its CRC — corruption, never retried."""


class FrameEOF(FrameError):
    """Peer closed the pipe (possibly mid-frame)."""


class FrameTimeout(FrameError):
    """No complete frame within the caller's per-op budget."""


def encode_frame(obj, ptype=PT_JSON):
    """One wire-ready frame. The CRC covers the payload as SERIALIZED;
    the `frame_corrupt` fault site flips a payload byte AFTER the CRC
    is computed, so an armed injector produces exactly the torn frame
    the reader's CRC check exists to catch."""
    if ptype == PT_JSON:
        payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    elif ptype == PT_PICKLE:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    else:
        raise ValueError(f"unknown payload type {ptype!r}")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    from avenir_tpu.utils.faults import get_injector

    payload = get_injector().corrupt("frame_corrupt", payload)
    return _HEADER.pack(MAGIC, PROTO_VERSION, ptype, len(payload), crc) \
        + payload


def decode_header(header):
    """-> (ptype, length, crc); raises FrameProtocolError loudly on bad
    magic or a version mismatch (the handshake's fail-fast path)."""
    magic, version, ptype, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameProtocolError(
            f"bad frame magic {magic!r} — stream desync (did something "
            "print to the frame fd?)")
    if version != PROTO_VERSION:
        raise FrameProtocolError(
            f"frame protocol version mismatch: peer speaks v{version}, "
            f"this side speaks v{PROTO_VERSION} — refusing to guess at "
            "an incompatible wire format (upgrade both sides together)")
    if length > MAX_FRAME_BYTES:
        raise FrameProtocolError(
            f"frame length {length} exceeds MAX_FRAME_BYTES — desynced "
            "stream or a hostile peer")
    return ptype, length, crc


def decode_payload(ptype, payload, crc):
    """CRC-check and deserialize one payload."""
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise FrameCRCError(
            f"frame payload failed CRC ({len(payload)} bytes) — the pipe "
            "delivered corrupt bytes; the stream is no longer trustworthy")
    if ptype == PT_JSON:
        return json.loads(payload.decode("utf-8"))
    if ptype == PT_PICKLE:
        return pickle.loads(payload)
    raise FrameProtocolError(f"unknown payload type {ptype}")


class FrameStream:
    """Frame reader/writer over a pair of pipe fds.

    Reads are select()-driven with a wall-clock deadline shared across
    the header and payload of one frame — a peer that trickles half a
    frame and wedges still trips FrameTimeout. After any FrameError the
    stream's buffer can hold a partial frame; callers treat the peer as
    dead (the fleet's policy) rather than resynchronize.
    """

    def __init__(self, read_fd, write_fd):
        self._rfd = read_fd
        self._wfd = write_fd
        self._buf = bytearray()  # bytearray: += on bytes is quadratic
        #                          over a GiB-scale handshake frame

    def write(self, obj, ptype=PT_JSON):
        """Serialize and write one frame; OSError (EPIPE when the peer
        is a corpse) propagates to the caller's dead-peer handling."""
        data = encode_frame(obj, ptype)
        view = memoryview(data)
        while view:
            n = os.write(self._wfd, view)
            view = view[n:]

    def read(self, timeout_s=None):
        """Read one frame; returns the decoded object. `timeout_s` is
        the whole-frame budget (None = block forever)."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        header = self._read_exact(HEADER_SIZE, deadline)
        ptype, length, crc = decode_header(header)
        payload = self._read_exact(length, deadline)
        return decode_payload(ptype, payload, crc)

    def _read_exact(self, n, deadline):
        while len(self._buf) < n:
            if deadline is not None:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    raise FrameTimeout(
                        "no complete frame within the per-op timeout")
            else:
                wait = None
            ready, _, _ = select.select([self._rfd], [], [], wait)
            if not ready:
                raise FrameTimeout(
                    "no complete frame within the per-op timeout")
            chunk = os.read(self._rfd, 1 << 16)
            if not chunk:
                raise FrameEOF("peer closed the pipe")
            self._buf.extend(chunk)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out
