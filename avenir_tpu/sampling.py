"""Sampling entry for sample.py --backend=tpu (SURVEY.md §2a R5, §3.5).

Loads a ckpt.pt (written by EITHER backend — the container is shared,
§3.4) and generates with temperature + top-k, mirroring sample_cuda's
behavior (sample.py:53-78)."""

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.checkpoint.io import _strip_compile_prefix, load_checkpoint
from avenir_tpu.models.gpt import GPT, GPTConfig


def run_sampling(*, out_dir, init_from, start, num_samples, max_new_tokens,
                 temperature, top_k, seed, set_ckpt_config, load_codec):
    if init_from == "resume":
        ckpt = load_checkpoint(out_dir)
        set_ckpt_config(ckpt.get("config", {}))
        args = {
            k: ckpt["model_args"][k]
            for k in ("n_layer", "n_head", "n_embd", "block_size", "bias",
                      "vocab_size")
        }
        model = GPT(GPTConfig(**args), rngs=nnx.Rngs(seed))
        load_torch_state_dict(model, _strip_compile_prefix(dict(ckpt["model"])))
    elif init_from.startswith("gpt2"):
        from avenir_tpu.tools.hf_import import gpt2_from_hf

        model = gpt2_from_hf(init_from)
    else:
        raise ValueError(f"init_from={init_from!r}")

    encode, decode = load_codec()
    x = jnp.asarray(encode(start), dtype=jnp.int32)[None, :]
    rng = jax.random.key(seed)
    # jitted KV-cache decoder when the total length fits the position
    # table; recompute-full-prefix (parity path) otherwise
    use_cache = x.shape[1] + max_new_tokens <= model.config.block_size
    for s in range(num_samples):
        rng, sub = jax.random.split(rng)
        if use_cache:
            from avenir_tpu.infer.decode import generate_cached

            y = generate_cached(model, sub, x, max_new_tokens,
                                temperature=temperature, top_k=top_k)
        else:
            y = model.generate(sub, x, max_new_tokens,
                               temperature=temperature, top_k=top_k)
        print(decode([int(t) for t in y[0]]))
        print("---------------")
