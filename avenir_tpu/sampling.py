"""Sampling entry for sample.py --backend=tpu (SURVEY.md §2a R5, §3.5).

Loads a ckpt.pt (written by EITHER backend — the container is shared,
§3.4) and generates with temperature + top-k, mirroring sample_cuda's
behavior (sample.py:53-78). Family-aware: the checkpoint's
`model_family` field (checkpoint/io.py save path) selects GPT, Llama or
Mixtral; all three decode through the same KV-cache path
(infer/decode.py)."""

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.checkpoint.io import _strip_compile_prefix, load_checkpoint
from avenir_tpu.models.gpt import GPT, GPTConfig


def model_from_checkpoint(ckpt, *, seed=0):
    """Build the right model family from a loaded checkpoint dict and load
    its weights. Returns (model, family)."""
    family = str(ckpt.get("model_family", "gpt"))
    cfg = dict(ckpt.get("config", {}))
    margs = ckpt["model_args"]
    if family == "gpt":
        args = {
            k: margs[k]
            for k in ("n_layer", "n_head", "n_embd", "block_size", "bias",
                      "vocab_size")
        }
        model = GPT(GPTConfig(**args), rngs=nnx.Rngs(seed))
    elif family in ("llama", "mixtral"):
        if family == "llama":
            from avenir_tpu.models.llama import Llama, LlamaConfig

            model = Llama(LlamaConfig.from_train_config(cfg, margs),
                          rngs=nnx.Rngs(seed))
        else:
            from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

            model = Mixtral(MixtralConfig.from_train_config(cfg, margs),
                            rngs=nnx.Rngs(seed))
    else:
        raise ValueError(f"unknown model_family {family!r} in checkpoint")
    load_torch_state_dict(
        model, _strip_compile_prefix(dict(ckpt["model"])),
        tied_lm_head=(family == "gpt"),
    )
    return model, family


def run_sampling(*, out_dir, init_from, start, num_samples, max_new_tokens,
                 temperature, top_k, seed, set_ckpt_config, load_codec):
    if init_from == "resume":
        ckpt = load_checkpoint(out_dir)
        set_ckpt_config(ckpt.get("config", {}))
        model, _family = model_from_checkpoint(ckpt, seed=seed)
    elif init_from.startswith("gpt2"):
        from avenir_tpu.tools.hf_import import gpt2_from_hf

        model = gpt2_from_hf(init_from)
    else:
        raise ValueError(f"init_from={init_from!r}")

    encode, decode = load_codec()
    x = jnp.asarray(encode(start), dtype=jnp.int32)[None, :]
    rng = jax.random.key(seed)
    # jitted KV-cache decoder when the total length fits the position
    # table; recompute-full-prefix (parity path) otherwise
    use_cache = x.shape[1] + max_new_tokens <= model.config.block_size
    if use_cache:
        # Batched calls over the samples (one prefill + one decode
        # dispatch per CHUNK instead of num_samples of each). The
        # per-sample keys are the SAME split chain the old sequential
        # loop produced, and per-row sampling is bit-identical to a B=1
        # call per row (infer/decode._sample_rows), so the printed
        # samples are unchanged — tests/test_decode.py pins both
        # properties, and chunking cannot change them either. The chunk
        # bounds peak memory: one KV cache ROW per in-flight sample, so
        # an unbounded num_samples must not scale device memory with it.
        from avenir_tpu.infer.decode import generate_cached

        chunk = 16
        subs = []
        for _ in range(num_samples):
            rng, sub = jax.random.split(rng)
            subs.append(sub)
        for lo in range(0, num_samples, chunk):
            part = subs[lo:lo + chunk]
            keys = jax.random.wrap_key_data(
                jnp.stack([jax.random.key_data(k) for k in part]))
            y = generate_cached(model, keys, jnp.tile(x, (len(part), 1)),
                                max_new_tokens, temperature=temperature,
                                top_k=top_k)
            for s in range(len(part)):
                print(decode([int(t) for t in y[s]]))
                print("---------------")
    else:
        for _ in range(num_samples):
            rng, sub = jax.random.split(rng)
            y = model.generate(sub, x, max_new_tokens,
                               temperature=temperature, top_k=top_k)
            print(decode([int(t) for t in y[0]]))
            print("---------------")
