"""HF GPT-2 weight import for the TPU backend (SURVEY.md §7 PR3 "HF GPT-2
import through the same key-map"; mirrors model.py:210-254 from_pretrained).

The HF checkpoint stores Conv1D projection weights as (in, out) — already
the nnx kernel layout — but we deliberately route through the torch-layout
bridge (transpose to (out, in), then let load_torch_state_dict transpose
back) so HF import exercises the EXACT key-map the checkpoint format uses.

No torch import: weights are read from the local HF cache via safetensors
(numpy) when available, falling back to transformers' torch loader only if
the safetensors file is absent. The sandbox has no egress, so all paths use
local_files_only and fail with a clear message when the cache is cold.
"""

import numpy as np
from flax import nnx

from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.models.gpt import GPT, GPTConfig

HF_CONFIGS = {
    "gpt2": dict(n_layer=12, n_head=12, n_embd=768),
    "gpt2-medium": dict(n_layer=24, n_head=16, n_embd=1024),
    "gpt2-large": dict(n_layer=36, n_head=20, n_embd=1280),
    "gpt2-xl": dict(n_layer=48, n_head=25, n_embd=1600),
}

# HF uses Conv1D ((in, out) storage) for these; torch-Linear layout is (out, in)
_CONV1D_SUFFIXES = (
    "attn.c_attn.weight", "attn.c_proj.weight",
    "mlp.c_fc.weight", "mlp.c_proj.weight",
)


def gpt2_config(model_type, dropout=0.0, compute_dtype="float32",
                attn_impl="auto"):
    assert model_type in HF_CONFIGS, (
        f"unknown HF model {model_type!r}; one of {sorted(HF_CONFIGS)}"
    )
    return GPTConfig(
        vocab_size=50257, block_size=1024, bias=True, dropout=dropout,
        compute_dtype=compute_dtype, attn_impl=attn_impl,
        **HF_CONFIGS[model_type],
    )


def hf_sd_to_torch_layout(hf_sd):
    """Normalize a raw HF GPT-2 state dict (numpy arrays) to the torch
    reference layout our bridge key-map consumes:
      - ensure the `transformer.` prefix (the hub gpt2 files omit it),
      - drop attention mask buffers and the tied lm_head alias,
      - transpose Conv1D weights to torch Linear (out, in)."""
    out = {}
    for key, arr in hf_sd.items():
        if key.startswith("transformer."):
            key = key[len("transformer."):]
        if key.endswith((".attn.bias", ".attn.masked_bias")):
            continue  # causal-mask buffers, not params
        if key == "lm_head.weight":
            continue  # tied to wte (model.py:149-151)
        arr = np.asarray(arr)
        if any(key.endswith(s) for s in _CONV1D_SUFFIXES):
            arr = np.ascontiguousarray(arr.T)
        out["transformer." + key] = arr
    return out


def _load_hf_numpy_sd(model_type):
    """Read the HF checkpoint from the local cache as {key: numpy}."""
    try:
        from safetensors.numpy import load_file
        from transformers.utils import cached_file

        path = cached_file(model_type, "model.safetensors",
                           local_files_only=True)
        return load_file(path)
    except Exception:
        pass
    # fallback: the torch loader (e.g. cache only has pytorch_model.bin)
    try:
        from transformers import GPT2LMHeadModel

        hf = GPT2LMHeadModel.from_pretrained(model_type,
                                             local_files_only=True)
        return {k: v.numpy() for k, v in hf.state_dict().items()}
    except Exception as e:
        raise RuntimeError(
            f"could not load {model_type!r} from the local HF cache "
            "(this sandbox has no network egress; populate the cache "
            f"first): {e}"
        ) from e


def load_hf_gpt2_sd(model, hf_sd):
    """Load a raw HF GPT-2 state dict into an nnx GPT via the bridge."""
    return load_torch_state_dict(model, hf_sd_to_torch_layout(hf_sd))


def gpt2_from_hf(model_type, *, dropout=0.0, compute_dtype="float32",
                 attn_impl="auto", seed=0):
    """Build an nnx GPT and load HF GPT-2 weights (model.py:210-254)."""
    cfg = gpt2_config(model_type, dropout=dropout,
                      compute_dtype=compute_dtype, attn_impl=attn_impl)
    model = GPT(cfg, rngs=nnx.Rngs(seed))
    return load_hf_gpt2_sd(model, _load_hf_numpy_sd(model_type))


# ---------------------------------------------------------------------------
# Llama / Mixtral (VERDICT r2 missing #7). HF stores these as torch Linear
# (out, in) — exactly what the bridge key-map consumes, no Conv1D transposes.
# `name_or_dir` may be a hub id (resolved from the local cache only; the
# sandbox has no egress) or a local directory from save_pretrained.
# ---------------------------------------------------------------------------


def _hf_file(name_or_dir, filename, required=True):
    import os

    if os.path.isdir(name_or_dir):
        path = os.path.join(name_or_dir, filename)
        if not os.path.exists(path):
            if required:
                raise FileNotFoundError(
                    f"{name_or_dir!r} has no {filename} (expected an HF "
                    "save_pretrained directory)"
                )
            return None
        return path
    try:
        from transformers.utils import cached_file

        return cached_file(name_or_dir, filename, local_files_only=True)
    except Exception:
        if required:
            raise
        return None


def _load_hf_numpy_sd_any(name_or_dir):
    """{key: numpy} from single-file or sharded safetensors, local only."""
    import json

    from safetensors.numpy import load_file

    single = _hf_file(name_or_dir, "model.safetensors", required=False)
    if single is not None:
        return load_file(single)
    index = _hf_file(name_or_dir, "model.safetensors.index.json",
                     required=False)
    if index is None:
        raise RuntimeError(
            f"no model.safetensors[.index.json] for {name_or_dir!r} in the "
            "local HF cache (this sandbox has no network egress)"
        )
    with open(index) as f:
        shard_map = json.load(f)["weight_map"]
    sd = {}
    for shard in sorted(set(shard_map.values())):
        sd.update(load_file(_hf_file(name_or_dir, shard)))
    return sd


def _llama_config_kwargs(hf_cfg, compute_dtype, attn_impl):
    """Map an HF LlamaConfig/MixtralConfig dict to our config kwargs."""
    return dict(
        vocab_size=hf_cfg["vocab_size"],
        block_size=hf_cfg["max_position_embeddings"],
        n_layer=hf_cfg["num_hidden_layers"],
        n_head=hf_cfg["num_attention_heads"],
        n_kv_head=hf_cfg.get("num_key_value_heads",
                             hf_cfg["num_attention_heads"]),
        n_embd=hf_cfg["hidden_size"],
        ffn_hidden=hf_cfg["intermediate_size"],
        rope_theta=hf_cfg.get("rope_theta", 10000.0),
        norm_eps=hf_cfg.get("rms_norm_eps", 1e-5),
        compute_dtype=compute_dtype, attn_impl=attn_impl,
    )


def _family_from_hf(name_or_dir, family, *, compute_dtype, attn_impl, seed,
                    block_size=None, capacity_factor=None):
    import json

    with open(_hf_file(name_or_dir, "config.json")) as f:
        hf_cfg = json.load(f)
    kwargs = _llama_config_kwargs(hf_cfg, compute_dtype, attn_impl)
    if block_size is not None:  # crop the position budget (memory)
        kwargs["block_size"] = block_size
    if family == "mixtral":
        import warnings

        from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

        kwargs.update(
            n_experts=hf_cfg["num_local_experts"],
            n_experts_per_tok=hf_cfg["num_experts_per_tok"],
            router_aux_loss_coef=hf_cfg.get("router_aux_loss_coef", 0.02),
        )
        if capacity_factor is not None:
            # runtime-only knob, not an HF config field: HF's dense MoE
            # never drops, so exact-parity use wants E/K (capacity == N)
            kwargs["capacity_factor"] = capacity_factor
        if hf_cfg.get("sliding_window") not in (None, 0):
            warnings.warn(
                f"HF config declares sliding_window="
                f"{hf_cfg['sliding_window']} but this implementation "
                "attends over the full context; logits will diverge from "
                "HF beyond the window", stacklevel=2,
            )
        cfg = MixtralConfig(**kwargs)
        model = Mixtral(cfg, rngs=nnx.Rngs(seed))
    else:
        from avenir_tpu.models.llama import Llama, LlamaConfig

        cfg = LlamaConfig(**kwargs)
        model = Llama(cfg, rngs=nnx.Rngs(seed))
    sd = {k: np.asarray(v) for k, v in _load_hf_numpy_sd_any(name_or_dir).items()}
    if hf_cfg.get("tie_word_embeddings", False) and "lm_head.weight" not in sd:
        # our Llama keeps lm_head untied (Llama-3 convention); tied HF
        # checkpoints (e.g. 3.2-1B) just omit the alias — materialize it
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    return load_torch_state_dict(model, sd, tied_lm_head=False)


def llama_from_hf(name_or_dir, *, compute_dtype="float32", attn_impl="auto",
                  seed=0, block_size=None):
    """Build an nnx Llama from an HF Llama checkpoint (cache or local dir)."""
    return _family_from_hf(name_or_dir, "llama", compute_dtype=compute_dtype,
                           attn_impl=attn_impl, seed=seed,
                           block_size=block_size)


def mixtral_from_hf(name_or_dir, *, compute_dtype="float32",
                    attn_impl="auto", seed=0, block_size=None,
                    capacity_factor=None):
    """Build an nnx Mixtral from an HF Mixtral checkpoint.
    `capacity_factor` (runtime-only, not an HF field): E/K gives
    capacity == all tokens, matching HF's dense routing exactly."""
    return _family_from_hf(name_or_dir, "mixtral",
                           compute_dtype=compute_dtype, attn_impl=attn_impl,
                           seed=seed, block_size=block_size,
                           capacity_factor=capacity_factor)
