"""HF GPT-2 weight import for the TPU backend (SURVEY.md §7 PR3 "HF GPT-2
import through the same key-map"; mirrors model.py:210-254 from_pretrained).

The HF checkpoint stores Conv1D projection weights as (in, out) — already
the nnx kernel layout — but we deliberately route through the torch-layout
bridge (transpose to (out, in), then let load_torch_state_dict transpose
back) so HF import exercises the EXACT key-map the checkpoint format uses.

No torch import: weights are read from the local HF cache via safetensors
(numpy) when available, falling back to transformers' torch loader only if
the safetensors file is absent. The sandbox has no egress, so all paths use
local_files_only and fail with a clear message when the cache is cold.
"""

import numpy as np
from flax import nnx

from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.models.gpt import GPT, GPTConfig

HF_CONFIGS = {
    "gpt2": dict(n_layer=12, n_head=12, n_embd=768),
    "gpt2-medium": dict(n_layer=24, n_head=16, n_embd=1024),
    "gpt2-large": dict(n_layer=36, n_head=20, n_embd=1280),
    "gpt2-xl": dict(n_layer=48, n_head=25, n_embd=1600),
}

# HF uses Conv1D ((in, out) storage) for these; torch-Linear layout is (out, in)
_CONV1D_SUFFIXES = (
    "attn.c_attn.weight", "attn.c_proj.weight",
    "mlp.c_fc.weight", "mlp.c_proj.weight",
)


def gpt2_config(model_type, dropout=0.0, compute_dtype="float32",
                attn_impl="auto"):
    assert model_type in HF_CONFIGS, (
        f"unknown HF model {model_type!r}; one of {sorted(HF_CONFIGS)}"
    )
    return GPTConfig(
        vocab_size=50257, block_size=1024, bias=True, dropout=dropout,
        compute_dtype=compute_dtype, attn_impl=attn_impl,
        **HF_CONFIGS[model_type],
    )


def hf_sd_to_torch_layout(hf_sd):
    """Normalize a raw HF GPT-2 state dict (numpy arrays) to the torch
    reference layout our bridge key-map consumes:
      - ensure the `transformer.` prefix (the hub gpt2 files omit it),
      - drop attention mask buffers and the tied lm_head alias,
      - transpose Conv1D weights to torch Linear (out, in)."""
    out = {}
    for key, arr in hf_sd.items():
        if key.startswith("transformer."):
            key = key[len("transformer."):]
        if key.endswith((".attn.bias", ".attn.masked_bias")):
            continue  # causal-mask buffers, not params
        if key == "lm_head.weight":
            continue  # tied to wte (model.py:149-151)
        arr = np.asarray(arr)
        if any(key.endswith(s) for s in _CONV1D_SUFFIXES):
            arr = np.ascontiguousarray(arr.T)
        out["transformer." + key] = arr
    return out


def _load_hf_numpy_sd(model_type):
    """Read the HF checkpoint from the local cache as {key: numpy}."""
    try:
        from safetensors.numpy import load_file
        from transformers.utils import cached_file

        path = cached_file(model_type, "model.safetensors",
                           local_files_only=True)
        return load_file(path)
    except Exception:
        pass
    # fallback: the torch loader (e.g. cache only has pytorch_model.bin)
    try:
        from transformers import GPT2LMHeadModel

        hf = GPT2LMHeadModel.from_pretrained(model_type,
                                             local_files_only=True)
        return {k: v.numpy() for k, v in hf.state_dict().items()}
    except Exception as e:
        raise RuntimeError(
            f"could not load {model_type!r} from the local HF cache "
            "(this sandbox has no network egress; populate the cache "
            f"first): {e}"
        ) from e


def load_hf_gpt2_sd(model, hf_sd):
    """Load a raw HF GPT-2 state dict into an nnx GPT via the bridge."""
    return load_torch_state_dict(model, hf_sd_to_torch_layout(hf_sd))


def gpt2_from_hf(model_type, *, dropout=0.0, compute_dtype="float32",
                 attn_impl="auto", seed=0):
    """Build an nnx GPT and load HF GPT-2 weights (model.py:210-254)."""
    cfg = gpt2_config(model_type, dropout=dropout,
                      compute_dtype=compute_dtype, attn_impl=attn_impl)
    model = GPT(cfg, rngs=nnx.Rngs(seed))
    return load_hf_gpt2_sd(model, _load_hf_numpy_sd(model_type))
