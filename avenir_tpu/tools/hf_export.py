"""HF-format export: an avenir model (or ckpt.pt) -> a directory that
`transformers.*ForCausalLM.from_pretrained` loads directly
(config.json + model.safetensors). The inverse of tools/hf_import.py —
together they close the ecosystem round trip: import HF weights, train
on TPU, export back for anyone downstream.

Layout notes (mirror of the import path):
  - Llama/Mixtral: HF stores torch-Linear (out, in) — exactly what
    checkpoint/bridge.py's export_torch_state_dict emits. Keys match the
    HF module tree by construction (the models were named for it).
  - GPT-2: HF uses Conv1D ((in, out) storage) for the four projection
    weights, the transpose of the torch reference layout — re-transposed
    here (inverse of hf_import.hf_sd_to_torch_layout).

CLI: python -m avenir_tpu.tools.hf_export --out_dir=<train out_dir> \
        --dest=<hf dir>
reads out_dir/ckpt.pt (either backend's) and writes the HF directory.
"""

import json
import os

import numpy as np

from avenir_tpu.checkpoint.bridge import export_torch_state_dict

# inverse of hf_import._CONV1D_SUFFIXES (GPT-2 only)
_CONV1D_SUFFIXES = (
    "attn.c_attn.weight", "attn.c_proj.weight",
    "mlp.c_fc.weight", "mlp.c_proj.weight",
)


def _gpt2_hf_config(ma):
    return {
        "architectures": ["GPT2LMHeadModel"],
        "model_type": "gpt2",
        "vocab_size": ma["vocab_size"],
        "n_positions": ma["block_size"], "n_ctx": ma["block_size"],
        "n_embd": ma["n_embd"], "n_layer": ma["n_layer"],
        "n_head": ma["n_head"],
        "activation_function": "gelu_new",
        "layer_norm_epsilon": 1e-5,
        "tie_word_embeddings": True,
    }


def _llama_hf_config(ma, family):
    cfg = {
        "architectures": ["LlamaForCausalLM" if family == "llama"
                          else "MixtralForCausalLM"],
        "model_type": family,
        "vocab_size": ma["vocab_size"],
        "max_position_embeddings": ma["block_size"],
        "hidden_size": ma["n_embd"],
        "intermediate_size": ma["ffn_hidden"],
        "num_hidden_layers": ma["n_layer"],
        "num_attention_heads": ma["n_head"],
        "num_key_value_heads": ma["n_kv_head"],
        "rope_theta": ma.get("rope_theta", 10000.0),
        "rms_norm_eps": ma.get("norm_eps", 1e-5),
        "hidden_act": "silu",
        "tie_word_embeddings": False,
        "attention_bias": False, "mlp_bias": False,
    }
    if family == "mixtral":
        cfg.update(
            num_local_experts=ma["n_experts"],
            num_experts_per_tok=ma["n_experts_per_tok"],
            router_aux_loss_coef=ma.get("router_aux_loss_coef", 0.02),
            sliding_window=None,
        )
    return cfg


def export_hf(dest, *, params_or_model, model_args, model_family="gpt"):
    """Write `dest/config.json` + `dest/model.safetensors` from nnx params
    (Module, Param State, or a host-numpy state dict in torch layout)."""
    from safetensors.numpy import save_file

    os.makedirs(dest, exist_ok=True)
    if isinstance(params_or_model, dict):
        sd = dict(params_or_model)  # already torch-layout {key: np}
    else:
        sd = export_torch_state_dict(
            params_or_model, model_family=model_family,
            tied_lm_head=(model_family == "gpt"),
        )
    if model_family == "gpt":
        hf_cfg = _gpt2_hf_config(model_args)
        out = {}
        for k, v in sd.items():
            v = np.asarray(v)
            if k == "lm_head.weight":
                continue  # tied: HF re-derives the alias from wte
            if k.startswith("transformer."):
                k = k[len("transformer."):]
            if any(k.endswith(s) for s in _CONV1D_SUFFIXES):
                v = np.ascontiguousarray(v.T)  # torch Linear -> HF Conv1D
            out["transformer." + k] = v
        sd = out
    else:
        hf_cfg = _llama_hf_config(model_args, model_family)
        sd = {k: np.ascontiguousarray(np.asarray(v)) for k, v in sd.items()}

    with open(os.path.join(dest, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)
    save_file(sd, os.path.join(dest, "model.safetensors"))
    return dest


def export_hf_from_ckpt(out_dir, dest):
    """Convert out_dir/ckpt.pt (either backend's) to an HF directory."""
    from avenir_tpu.checkpoint.io import load_checkpoint

    ckpt = load_checkpoint(out_dir)
    family = ckpt.get("model_family", "gpt")
    ma = dict(ckpt["model_args"])
    if family in ("llama", "mixtral"):
        # the family extras live in the train config, not model_args
        # (sampling.py reconstructs configs the same way); resolve exactly
        # as LlamaConfig.from_train_config does
        from avenir_tpu.models.llama import default_ffn_hidden

        cfg = ckpt.get("config", {})
        ma.setdefault("n_kv_head", cfg.get("n_kv_head", 0) or ma["n_head"])
        ma.setdefault("ffn_hidden", cfg.get("ffn_hidden", 0)
                      or default_ffn_hidden(ma["n_embd"]))
        ma.setdefault("rope_theta", cfg.get("rope_theta", 10000.0))
        if family == "mixtral":
            ma.setdefault("n_experts", cfg.get("n_experts", 8))
            ma.setdefault("n_experts_per_tok", cfg.get("n_experts_per_tok", 2))
            ma.setdefault("router_aux_loss_coef",
                          cfg.get("router_aux_loss_coef", 0.02))
    sd = {k: np.asarray(v) for k, v in ckpt["model"].items()}
    return export_hf(dest, params_or_model=sd, model_args=ma,
                     model_family=family)


if __name__ == "__main__":
    import sys

    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    assert "out_dir" in args and "dest" in args, (
        "usage: python -m avenir_tpu.tools.hf_export --out_dir=<dir> "
        "--dest=<hf dir>"
    )
    export_hf_from_ckpt(args["out_dir"], args["dest"])
    print(f"wrote {args['dest']}/config.json + model.safetensors")
