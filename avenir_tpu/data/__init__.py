from avenir_tpu.data.loader import DataLoader
