from avenir_tpu.data.loader import DataLoader, read_wire_format, write_token_file
from avenir_tpu.data.streaming import (
    load_manifest,
    parse_data_mix,
    write_token_shards,
)

__all__ = [
    "DataLoader",
    "load_manifest",
    "parse_data_mix",
    "read_wire_format",
    "write_token_file",
    "write_token_shards",
]
