"""Sharded streaming dataset layer (ISSUE 19 tentpole).

The config ladder's upper rungs (GPT-2 1.5B, Llama-3 8B, Mixtral) need
corpora that no single memmapped `train.bin` can hold or feed. This
module grows the on-disk contract from "one token file per split" to
"one DIRECTORY of v2-wire shard files per split plus a small manifest",
and gives `DataLoader` the three pieces the pod path needs:

  * `write_token_shards` / `load_manifest` — the sharded layout.
    `<split>.shards/` holds `shard-00000.bin ...` (each a v2
    header + raw token array, self-describing per file) and a
    `MANIFEST.json` naming every shard, its token count, and the
    corpus-wide dtype. The dtype is chosen ONCE for the whole corpus
    (narrowest that fits the vocab) so every crop leaves the disk in
    the same wire dtype.

  * `SplitSource` — one corpus split resolved to whichever layout is
    on disk: the sharded directory, or the legacy single `<split>.bin`
    (headerless uint16 or v2, unchanged byte-for-byte). Sharded
    sources are PER-HOST LOCAL: process p of P deterministically owns
    the contiguous shard range [p*S/P, (p+1)*S/P) — the same
    arithmetic as the checkpoint restore's `local_shard_ranges`
    locality filter — so a pod host never reads a peer's files. Crop
    positions are flat indices into the concatenation of this
    process's sampleable shard ranges; crops never span a shard
    boundary.

  * `Prefetcher` — the deep background pipeline behind
    `--prefetch_depth > 1`. A single persistent daemon worker stages
    batches into a bounded FIFO (up to depth x window batches ahead),
    so the consumed rng stream stays bit-identical to the unprefetched
    loader's (one producer, one consumer, strict FIFO — the same
    contract the depth-1 double buffer pins in
    tests/test_loader.py::test_prefetch_preserves_stream_order).
    Worker failures are stored and re-raised at the NEXT consume, never
    swallowed: the worker has already advanced the rng for its partial
    draws, so continuing would silently desync the kill-resume stream.

Weighted multi-corpus mixing (`--data_mix='owt:0.7,code:0.3'`) lives in
DataLoader itself (avenir_tpu/data/loader.py) on top of SplitSource;
`parse_data_mix` / `resolve_corpus_dir` here own the spec syntax.
"""

import collections
import json
import os
import threading
import time
import zlib

import numpy as np

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_KIND = "avenir-token-shards"
MANIFEST_VERSION = 1
_SHARD_FMT = "shard-{:05d}.bin"


# ---- sharded writer -------------------------------------------------------

def write_token_shards(path, tokens, shard_tokens=1 << 22, vocab_size=None):
    """Write `tokens` as a directory of v2-wire shard files + MANIFEST.json.

    `path` is the shards directory (convention: `<data_dir>/<split>.shards`).
    The wire dtype is chosen once for the WHOLE corpus — narrowest that
    fits `vocab_size` (or max token + 1) — so mixing/streaming never sees
    a dtype change mid-corpus. Every shard carries the v2 header (magic +
    dtype code), making each file self-describing on its own.

    Atomicity matches the checkpoint discipline: shard bodies are written
    first, the manifest last via .part-then-rename — a directory without
    a committed manifest is not a corpus yet, so a killed prep job can
    simply be re-run. Returns the numpy dtype written."""
    from avenir_tpu.data.loader import (
        WIRE_MAGIC, WIRE_V2, WIRE_VOCAB_CAP, _CODE_FOR_DTYPE)

    tokens = np.asarray(tokens)
    shard_tokens = int(shard_tokens)
    assert shard_tokens > 0, "shard_tokens must be positive"
    hi = int(vocab_size) if vocab_size is not None else (
        int(tokens.max()) + 1 if tokens.size else 0)
    assert tokens.size == 0 or (int(tokens.max()) < hi
                                and int(tokens.min()) >= 0), (
        f"token ids outside [0, {hi}) — a vocab_size/tokenizer mismatch "
        "(same gate as write_token_file)")
    if hi <= WIRE_VOCAB_CAP:
        dtype = np.dtype(np.uint16)
    else:
        assert hi <= int(np.iinfo(np.uint32).max) + 1, (
            f"vocab_size={hi} does not fit uint32")
        dtype = np.dtype(np.uint32)
    os.makedirs(path, exist_ok=True)
    header = WIRE_MAGIC + bytes([WIRE_V2, _CODE_FOR_DTYPE[dtype], 0, 0])
    shards = []
    for s, start in enumerate(range(0, max(len(tokens), 1), shard_tokens)):
        chunk = tokens[start:start + shard_tokens]
        fname = _SHARD_FMT.format(s)
        with open(os.path.join(path, fname), "wb") as f:
            f.write(header)
            chunk.astype(dtype).tofile(f)
        shards.append({"file": fname, "tokens": int(len(chunk))})
    manifest = {
        "kind": MANIFEST_KIND, "version": MANIFEST_VERSION,
        "dtype": dtype.name, "shard_tokens": shard_tokens,
        "total_tokens": int(len(tokens)), "shards": shards,
    }
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath + ".part", "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(mpath + ".part", mpath)
    return dtype


def load_manifest(shards_dir):
    """Parse + validate a shard manifest. Fails loud on a foreign or
    future layout instead of guessing (the wire-format discipline)."""
    with open(os.path.join(shards_dir, MANIFEST_NAME)) as f:
        m = json.load(f)
    assert m.get("kind") == MANIFEST_KIND, (
        f"{shards_dir}: manifest kind {m.get('kind')!r} is not "
        f"{MANIFEST_KIND!r}")
    assert int(m.get("version", -1)) == MANIFEST_VERSION, (
        f"{shards_dir}: manifest version {m.get('version')} (this build "
        f"reads v{MANIFEST_VERSION}) — refusing to guess the layout")
    assert m.get("shards"), f"{shards_dir}: manifest lists no shards"
    return m


# ---- mix spec -------------------------------------------------------------

def parse_data_mix(spec):
    """'owt:0.7,code:0.3' -> [(name, weight), ...] with weights
    normalized to sum 1. Weights are parsed off the LAST colon so corpus
    names may be paths containing colons-free... absolute paths are fine
    (rsplit)."""
    out = []
    for entry in str(spec).split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, w = entry.rpartition(":")
        assert name, (
            f"data_mix entry {entry!r} has no 'name:weight' form")
        w = float(w)
        assert w > 0, f"data_mix weight for {name!r} must be > 0 (got {w})"
        out.append((name, w))
    assert len(out) >= 1, f"data_mix spec {spec!r} names no corpora"
    names = [n for n, _ in out]
    assert len(set(names)) == len(names), (
        f"data_mix names a corpus twice: {names}")
    total = sum(w for _, w in out)
    return [(n, w / total) for n, w in out]


def resolve_corpus_dir(name, base_dir):
    """A mix entry names a corpus directory: an absolute/relative path
    that exists, a sibling of `base_dir` (the common `data/owt`,
    `data/code` layout), or `base_dir` itself by basename."""
    cands = [name,
             os.path.join(os.path.dirname(base_dir.rstrip(os.sep)), name)]
    if os.path.basename(base_dir.rstrip(os.sep)) == name:
        cands.insert(0, base_dir)
    for c in cands:
        if os.path.isdir(c):
            return c
    raise FileNotFoundError(
        f"data_mix corpus {name!r} not found (tried {cands})")


def corpus_seed_tag(name):
    """Stable 32-bit tag for seeding a corpus/split rng stream: part of
    the SeedSequence entropy, so streams stay decorrelated per corpus
    without an ordering dependence on the mix spec."""
    return zlib.crc32(str(name).encode()) & 0xFFFFFFFF


# ---- split sources --------------------------------------------------------

class SplitSource:
    """One corpus split resolved to its on-disk layout.

    Exposes the two things sampling needs — `n_positions` (how many
    crop start positions THIS PROCESS may draw from; the rng bound) and
    `gather(ix)` (vectorized crop reads) — identically for both
    layouts, so the mixing/sharding code above never branches on disk
    format. The legacy single file is re-opened per gather (the
    np.memmap leak defense the reference loader always had); shard
    mappings are CACHED and recycled every _RECYCLE_EVERY gathers —
    per-batch np.memmap opens across many small shard files would cost
    more than the fused gather saves, while a periodic full drop keeps
    the same leak bound (mappings never live unboundedly long)."""

    _RECYCLE_EVERY = 64

    def __init__(self, data_dir, split, block_size, *, vocab_size=None,
                 process_index=None, process_count=None):
        from avenir_tpu.data.loader import read_wire_format

        import jax

        self.data_dir = data_dir
        self.split = split
        self.block_size = int(block_size)
        pidx = jax.process_index() if process_index is None else process_index
        pcnt = jax.process_count() if process_count is None else process_count
        shards_dir = os.path.join(data_dir, f"{split}.shards")
        legacy = os.path.join(data_dir, f"{split}.bin")
        if os.path.isdir(shards_dir):
            self.kind = "sharded"
            self.path = shards_dir
            self.what = f"{split}.shards"
            m = load_manifest(shards_dir)
            self.dtype = np.dtype(m["dtype"])
            all_shards = m["shards"]
            n_shards = len(all_shards)
            assert n_shards >= pcnt, (
                f"{shards_dir}: {n_shards} shard(s) cannot give "
                f"{pcnt} processes disjoint non-empty shard ranges — "
                "re-shard the corpus with a smaller shard_tokens"
            )
            # per-host locality: process p of P owns the contiguous
            # shard range [p*S/P, (p+1)*S/P) — the checkpoint restore's
            # local_shard_ranges arithmetic. Disjoint by construction,
            # covers every shard, and stable across relaunches at the
            # same process_count.
            lo = pidx * n_shards // pcnt
            hi = (pidx + 1) * n_shards // pcnt
            self.local_shards = all_shards[lo:hi]
            self.local_range = (lo, hi)
            # sampleable crop starts per local shard: a crop reads
            # block_size+1 tokens and never spans shards, so shard s
            # contributes max(0, tokens_s - block_size) start positions
            pos = np.array(
                [max(0, int(s["tokens"]) - self.block_size)
                 for s in self.local_shards], dtype=np.int64)
            self._cum = np.cumsum(pos)
            self._starts = self._cum - pos  # flat position where shard begins
            self.n_positions = int(self._cum[-1]) if len(pos) else 0
            assert self.n_positions > 0, (
                f"{shards_dir}: shards {lo}..{hi - 1} hold no crop of "
                f"block_size={self.block_size} for process {pidx} — "
                "shards must be longer than block_size"
            )
            self._offset = None  # per-file, sniffed at open
            self._maps = {}  # shard idx -> open memmap (recycled)
            self._gathers = 0
        elif os.path.exists(legacy):
            self.kind = "file"
            self.path = legacy
            self.what = f"{split}.bin"
            self.dtype, self._offset = read_wire_format(legacy)
            nbytes = os.path.getsize(legacy) - self._offset
            # the LEGACY bound, bit-for-bit: len(arr) - block_size
            self.n_positions = nbytes // self.dtype.itemsize - self.block_size
            self.local_shards = None
            self.local_range = None
        else:
            raise FileNotFoundError(
                f"no {split}.bin or {split}.shards/ under {data_dir}")
        cap = int(np.iinfo(self.dtype).max) + 1
        assert vocab_size is None or vocab_size <= cap, (
            f"vocab_size={vocab_size} does not fit {self.what}'s "
            f"{self.dtype.name} wire/on-disk token format (max {cap}); "
            "token ids would wrap silently — regenerate the corpus with "
            "write_token_file/write_token_shards before such a vocab "
            "can run"
        )

    def gather(self, ix):
        """Vectorized crop reads: (x, y) arrays of shape (len(ix),
        block_size) in the wire dtype, y shifted one token. One fused
        (n, block_size+1) gather per file replaces the legacy
        per-crop python slice loop (~3x less host CPU per staged batch
        on the bench host — the data_bench headline)."""
        steps = np.arange(self.block_size + 1)
        if self.kind == "file":
            arr = np.memmap(self.path, dtype=self.dtype, mode="r",
                            offset=self._offset)
            w = arr[np.asarray(ix)[:, None] + steps]
            return w[:, :-1], w[:, 1:]
        from avenir_tpu.data.loader import read_wire_format

        ix = np.asarray(ix)
        self._gathers += 1
        if self._gathers % self._RECYCLE_EVERY == 0:
            self._maps.clear()  # drop mappings; kernel reclaims pages
        sh = np.searchsorted(self._cum, ix, side="right")
        off = ix - self._starts[sh]
        w = np.empty((len(ix), self.block_size + 1), dtype=self.dtype)
        for s in np.unique(sh):
            s = int(s)
            arr = self._maps.get(s)
            if arr is None:
                f = os.path.join(self.path, self.local_shards[s]["file"])
                dtype, offset = read_wire_format(f)
                assert dtype == self.dtype, (
                    f"{f}: shard dtype {dtype.name} disagrees with "
                    f"manifest {self.dtype.name} — the corpus directory "
                    "is torn")
                arr = np.memmap(f, dtype=dtype, mode="r", offset=offset)
                self._maps[s] = arr
            m = sh == s
            w[m] = arr[off[m][:, None] + steps]
        return w[:, :-1], w[:, 1:]


# ---- deep prefetch --------------------------------------------------------

class Prefetcher:
    """Persistent single-worker background stager for prefetch_depth > 1.

    One daemon thread repeatedly calls `sample_fn()` (which owns the rng
    and appends its own consumption stats) and appends to a bounded FIFO;
    the consumer pops in order. Exactly ONE producer means the staged
    stream is the same sequence a synchronous loader would draw, so the
    bit-identical-stream contract survives any depth. The buffer bound is
    depth x (latest window size) batches — the host-RAM knob
    docs/PERFORMANCE.md's "Feeding the pod" section sizes."""

    def __init__(self, sample_fn, depth):
        assert depth >= 2, "Prefetcher is the deep path (depth >= 2)"
        self.depth = int(depth)
        self._sample = sample_fn
        self._buf = collections.deque()
        self._cv = threading.Condition()
        self._target = 0
        self._stop = False
        self.error = None
        self._thread = None

    def ensure(self, k):
        """(Re)arm the worker with a buffer target of depth*k batches.
        Window size k may shrink at eval boundaries; the target follows."""
        with self._cv:
            self._target = self.depth * int(k)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._work, name="avenir-data-prefetch-deep",
                    daemon=True)
                self._thread.start()
            self._cv.notify_all()

    def _work(self):
        from avenir_tpu.obs.metrics import get_registry

        reg = get_registry()
        while True:
            with self._cv:
                while not self._stop and len(self._buf) >= self._target:
                    self._cv.wait()
                if self._stop:
                    return
            t0 = time.perf_counter()
            try:
                item = self._sample()
            except BaseException as e:  # surfaced at the next pop
                with self._cv:
                    self.error = e
                    self._cv.notify_all()
                return
            finally:
                reg.counter("data_stage_ms").add(
                    (time.perf_counter() - t0) * 1e3)
            with self._cv:
                self._buf.append(item)
                self._cv.notify_all()

    def staged(self):
        with self._cv:
            return len(self._buf)

    def pop(self, k):
        """Pop `k` staged batches in FIFO order. Returns (items, hit,
        waited_ms): hit means the whole window was already buffered
        (the data_prefetch_hit contract); waited_ms is the blocked time
        (input stall — the device outpaced host staging)."""
        waited = 0.0
        with self._cv:
            if self.error is not None:
                raise_prefetch_error(self.error)
            hit = len(self._buf) >= k
            while len(self._buf) < k:
                if self.error is not None:
                    raise_prefetch_error(self.error)
                assert not self._stop, "pop() after stop()"
                t0 = time.perf_counter()
                self._cv.wait(timeout=0.5)
                waited += time.perf_counter() - t0
            out = [self._buf.popleft() for _ in range(k)]
            self._cv.notify_all()
        return out, hit, waited * 1e3

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


def raise_prefetch_error(err):
    """The one fail-loud for a dead prefetch stage (satellite: a stored
    error must raise at the NEXT get_batch, never be joined away): the
    worker already advanced the rng for its partial draws, so continuing
    would silently desync the bit-identical kill-resume stream."""
    raise RuntimeError(
        "background batch prefetch failed (rng draws for the staged "
        "window are already consumed, so the stream cannot be resumed "
        "consistently)"
    ) from err
