"""Multi-host memmap data loader (SURVEY.md §2b T8).

Same on-disk contract as the torch trainer's get_batch (train.py:144-161):
uint16 token memmaps, random crops of block_size+1. Made multi-host aware
the jax way: every process samples its OWN disjoint stream of crops from
the full local file (the corpus is replicated on each host's disk), and
`jax.make_array_from_process_local_data` assembles the per-process shards
into one global jax.Array laid out by the batch sharding — no host ever
materializes the global batch.

The memmap is re-opened per batch, matching the reference's defense against
the np.memmap leak (train.py:145-147).
"""

import os

import jax
import numpy as np


class DataLoader:
    def __init__(self, data_dir, block_size, batch_size, *, sharding=None,
                 grad_accum=1, seed=0, flat=False):
        """`batch_size` is the GLOBAL batch size in sequences per micro-step;
        each call to get_batch returns (grad_accum, B, T) stacked micro
        batches as a sharded global array (leading accum dim unsharded).
        `flat=True` (eval): grad_accum must be 1 and batches are (B, T)."""
        self.data_dir = data_dir
        self.block_size = block_size
        self.batch_size = batch_size
        self.grad_accum = grad_accum
        self.sharding = sharding
        self.flat = flat
        assert not (flat and grad_accum != 1)
        n_proc = jax.process_count()
        assert batch_size % n_proc == 0, (
            f"global batch {batch_size} must divide over {n_proc} processes"
        )
        self.local_batch = batch_size // n_proc
        # disjoint per-process stream
        self.rng = np.random.default_rng(seed + 1000 * jax.process_index())

    def _sample_local(self, split):
        arr = np.memmap(
            os.path.join(self.data_dir, f"{split}.bin"), dtype=np.uint16, mode="r"
        )
        n = self.grad_accum * self.local_batch
        ix = self.rng.integers(0, len(arr) - self.block_size, size=n)
        # tokens stay uint16 ON THE WIRE (the .bin dtype; every vocab here
        # fits) — the jit'd step casts to int32 on device (train/step.py),
        # halving H2D bytes per batch. Measured r5 on the tunneled bench
        # chip: ~230ms of per-window transfer serialization at int32, the
        # dominant loop-vs-step-harness gap; pods pay the same halving on
        # DCN-attached hosts.
        x = np.stack([arr[i : i + self.block_size] for i in ix])
        y = np.stack([arr[i + 1 : i + 1 + self.block_size] for i in ix])
        if self.flat:
            shape = (self.local_batch, self.block_size)
        else:
            shape = (self.grad_accum, self.local_batch, self.block_size)
        return x.reshape(shape), y.reshape(shape)

    def get_batch(self, split):
        x, y = self._sample_local(split)
        if self.sharding is None:
            return jax.numpy.asarray(x), jax.numpy.asarray(y)
        if self.flat:
            global_shape = (self.batch_size, self.block_size)
        else:
            global_shape = (self.grad_accum, self.batch_size, self.block_size)
        gx = jax.make_array_from_process_local_data(self.sharding, x, global_shape)
        gy = jax.make_array_from_process_local_data(self.sharding, y, global_shape)
        return gx, gy

    def get_batch_window(self, split, k):
        """`k` consecutive batches stacked on a leading (unsharded) step
        axis — (k, grad_accum, B, T) — for the windowed multi-step
        dispatch (train/step.jit_windowed_train_step). Draws from the SAME
        per-process stream as get_batch, so k window calls and k·1 single
        calls yield the identical batch sequence."""
        assert not self.flat, "windowed batches are a train-path concept"
        xs, ys = zip(*(self._sample_local(split) for _ in range(k)))
        x, y = np.stack(xs), np.stack(ys)
        if self.sharding is None:
            return jax.numpy.asarray(x), jax.numpy.asarray(y)
        from jax.sharding import NamedSharding, PartitionSpec as P

        wsh = NamedSharding(self.sharding.mesh, P(None, *self.sharding.spec))
        gshape = (k, self.grad_accum, self.batch_size, self.block_size)
        gx = jax.make_array_from_process_local_data(wsh, x, gshape)
        gy = jax.make_array_from_process_local_data(wsh, y, gshape)
        return gx, gy
