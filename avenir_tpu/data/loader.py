"""Multi-host memmap data loader (SURVEY.md §2b T8).

Same on-disk contract as the torch trainer's get_batch (train.py:144-161):
token memmaps, random crops of block_size+1. Made multi-host aware
the jax way: every process samples its OWN disjoint stream of crops from
the full local file (the corpus is replicated on each host's disk), and
`jax.make_array_from_process_local_data` assembles the per-process shards
into one global jax.Array laid out by the batch sharding — no host ever
materializes the global batch.

Wire formats (ISSUE 15 satellite — the config ladder's upper rungs):
  - legacy: a raw headerless uint16 memmap (the nanoGPT .bin contract;
    half the H2D bytes of int32 — the r5 win). Any vocab > 65536 against
    this form fails loud at construction (ids would wrap silently).
  - v2: an 8-byte header (magic 'AVNR', version byte, dtype code byte,
    2 reserved zeros) followed by the raw token array — selected per
    FILE by the header, so a mixed directory of legacy and v2 files
    just works. dtype code 2 = uint32: the >65536-vocab form Llama-3's
    128k vocab needs (write_token_file picks the narrowest dtype that
    fits). The 8-byte offset keeps the uint32 memmap aligned.
Both forms ride the H2D wire in their on-disk dtype; the jit'd step
widens to int32 on device (train/step.py).

The memmap is re-opened per batch, matching the reference's defense against
the np.memmap leak (train.py:145-147).
"""

import os
import threading
import time

import jax
import numpy as np

from avenir_tpu.obs.metrics import get_registry
from avenir_tpu.utils.faults import get_injector
from avenir_tpu.utils.retry import call_with_retry

# the legacy on-disk .bin format AND its H2D wire format (headerless raw
# uint16); v2 files carry their own dtype in the header below
WIRE_DTYPE = np.uint16
WIRE_VOCAB_CAP = int(np.iinfo(WIRE_DTYPE).max) + 1  # 65536

# v2 container: 8-byte header then the raw token array
WIRE_MAGIC = b"AVNR"
WIRE_V2 = 2
WIRE_HEADER_BYTES = 8
_DTYPE_CODES = {1: np.uint16, 2: np.uint32}
_CODE_FOR_DTYPE = {np.dtype(v): k for k, v in _DTYPE_CODES.items()}


def write_token_file(path, tokens, vocab_size=None):
    """Write a token array in the narrowest wire form that fits:
    legacy raw uint16 when the vocab does (bit-compatible with every
    existing .bin consumer incl. the torch trainer), the v2
    header + uint32 form otherwise. Returns the numpy dtype written."""
    tokens = np.asarray(tokens)
    hi = int(vocab_size) if vocab_size is not None else (
        int(tokens.max()) + 1 if tokens.size else 0)
    assert tokens.size == 0 or (int(tokens.max()) < hi
                                and int(tokens.min()) >= 0), (
        f"token ids outside [0, {hi}) (max {int(tokens.max())}) — a "
        "vocab_size/tokenizer mismatch; writing would silently wrap ids "
        "into the narrow wire dtype (the exact corruption the wire gate "
        "exists to prevent)"
    )
    if hi <= WIRE_VOCAB_CAP:
        tokens.astype(np.uint16).tofile(path)
        return np.dtype(np.uint16)
    assert hi <= int(np.iinfo(np.uint32).max) + 1, (
        f"vocab_size={hi} does not fit uint32")
    with open(path, "wb") as f:
        f.write(WIRE_MAGIC + bytes([WIRE_V2,
                                    _CODE_FOR_DTYPE[np.dtype(np.uint32)],
                                    0, 0]))
        tokens.astype(np.uint32).tofile(f)
    return np.dtype(np.uint32)


def read_wire_format(path):
    """(dtype, byte offset) of a token file: header-sniffed v2, else the
    legacy raw-uint16 contract.

    Collision discipline: a legacy corpus could in principle START with
    tokens whose bytes spell the magic (0x5641, 0x524E as uint16 LE).
    The reserved-zero bytes are therefore part of the sniff — a magic
    match whose reserved bytes are nonzero reads as legacy, so the
    silent-misparse window needs FIVE specific leading values
    (~2^-64 for real corpora). A magic+reserved match with a bad
    version/dtype byte fails LOUD rather than guessing: loud-on-
    astronomically-rare beats silent garbage, and a future v3 writer
    bumps the version byte into exactly this error."""
    with open(path, "rb") as f:
        head = f.read(WIRE_HEADER_BYTES)
    if (len(head) < WIRE_HEADER_BYTES or head[:4] != WIRE_MAGIC
            or head[6:8] != b"\x00\x00"):
        return np.dtype(WIRE_DTYPE), 0
    version, code = head[4], head[5]
    assert version == WIRE_V2, (
        f"{path}: unknown token-file version {version} (this build reads "
        f"v{WIRE_V2}) — refusing to guess the layout")
    assert code in _DTYPE_CODES, (
        f"{path}: unknown token dtype code {code}")
    return np.dtype(_DTYPE_CODES[code]), WIRE_HEADER_BYTES


class DataLoader:
    def __init__(self, data_dir, block_size, batch_size, *, sharding=None,
                 grad_accum=1, seed=0, flat=False, vocab_size=None):
        """`batch_size` is the GLOBAL batch size in sequences per micro-step;
        each call to get_batch returns (grad_accum, B, T) stacked micro
        batches as a sharded global array (leading accum dim unsharded).
        `flat=True` (eval): grad_accum must be 1 and batches are (B, T).
        `vocab_size` (when known) is validated against the uint16 wire
        format — a Llama-3-sized 128k vocab must fail loud HERE instead of
        silently wrapping ids modulo 65536 (ADVICE r5)."""
        self.data_dir = data_dir
        self.block_size = block_size
        self.batch_size = batch_size
        self.grad_accum = grad_accum
        self.sharding = sharding
        self.flat = flat
        self._reg = get_registry()
        assert not (flat and grad_accum != 1)
        self.vocab_size = vocab_size
        self._wire = {}  # split -> (dtype, byte offset), header-sniffed once
        if vocab_size is not None:
            # fail loud HERE, not mid-run: the train file's wire format
            # must fit the vocab (ADVICE r5). The v2 uint32 form is what
            # lets Llama-3's 128k vocab pass this gate.
            train_bin = os.path.join(data_dir, "train.bin")
            if os.path.exists(train_bin):
                self._wire_format("train")
        n_proc = jax.process_count()
        assert batch_size % n_proc == 0, (
            f"global batch {batch_size} must divide over {n_proc} processes"
        )
        self.local_batch = batch_size // n_proc
        # disjoint per-process stream
        self.rng = np.random.default_rng(seed + 1000 * jax.process_index())
        # background prefetch (ISSUE 3 satellite): after each window the
        # loader stages the NEXT window's memmap crops on a daemon
        # thread, so the fancy-indexing overlaps device compute instead
        # of running on the dispatch edge. The buffer is FIFO and every
        # _sample_local draw happens in consumption order (the thread is
        # joined before any pop), so the rng stream a run CONSUMES is
        # bit-identical to the unprefetched loader's — pinned by
        # tests/test_loader.py::test_prefetch_preserves_stream_order.
        self._buf = []  # staged (x, y) micro batches, oldest first
        self._buf_split = None
        self._prefetch_thread = None
        self._prefetch_error = None

    def _wire_format(self, split):
        """Header-sniffed (dtype, offset) of one split's token file,
        cached (the file's layout cannot change mid-run), with the
        vocab-fits-the-wire fail-loud applied on first sight."""
        cached = self._wire.get(split)
        if cached is not None:
            return cached
        dtype, offset = read_wire_format(
            os.path.join(self.data_dir, f"{split}.bin"))
        cap = int(np.iinfo(dtype).max) + 1
        assert self.vocab_size is None or self.vocab_size <= cap, (
            f"vocab_size={self.vocab_size} does not fit {split}.bin's "
            f"{dtype.name} wire/on-disk token format (max {cap}); token "
            "ids would wrap silently — regenerate the corpus with "
            "write_token_file (the v2 uint32 form) before such a vocab "
            "can run"
        )
        self._wire[split] = (dtype, offset)
        return dtype, offset

    def _sample_local(self, split):
        n = self.grad_accum * self.local_batch
        # the rng draw happens ONCE, before the (retryable) file reads:
        # a flaky read retried by call_with_retry must re-read the SAME
        # crops, or the consumed rng stream would depend on how flaky
        # the storage was (breaking the deterministic-resume contract)
        ix = None
        dtype, offset = self._wire_format(split)

        def read():
            nonlocal ix
            get_injector().fail("data_read_fail", what=f"{split}.bin")
            arr = np.memmap(
                os.path.join(self.data_dir, f"{split}.bin"),
                dtype=dtype, mode="r", offset=offset,
            )
            if ix is None:
                ix = self.rng.integers(0, len(arr) - self.block_size,
                                       size=n)
            # tokens stay in the file's narrow dtype ON THE WIRE (uint16
            # legacy, uint32 for >65536 vocabs) — the jit'd step casts to
            # int32 on device (train/step.py), halving H2D bytes per
            # batch at uint16. Measured r5 on the tunneled bench chip:
            # ~230ms of per-window transfer serialization at int32, the
            # dominant loop-vs-step-harness gap; pods pay the same
            # halving on DCN-attached hosts.
            x = np.stack([arr[i : i + self.block_size] for i in ix])
            y = np.stack([arr[i + 1 : i + 1 + self.block_size] for i in ix])
            return x, y

        x, y = call_with_retry(read, what=f"data read {split}.bin")
        if self.flat:
            shape = (self.local_batch, self.block_size)
        else:
            shape = (self.grad_accum, self.local_batch, self.block_size)
        return x.reshape(shape), y.reshape(shape)

    def fast_forward(self, plan):
        """Advance the sampling rng as if the draws had already happened:
        `plan` is [(split, n_batches), ...] replayed in order. Resume
        support (ISSUE 5): a relaunched run fast-forwards its fresh
        loader past the batches the killed run consumed, making the
        post-resume batch stream bit-identical to an uninterrupted
        run's. The replay must use each split's REAL sampling bound —
        numpy's bounded-integer rejection sampling consumes a
        bound-dependent amount of the bit stream, so a dummy bound
        would desync it."""
        assert not self._buf and self._prefetch_thread is None, (
            "fast_forward must run on a fresh loader (before any draw "
            "or prefetch)"
        )
        n = self.grad_accum * self.local_batch
        for split, count in plan:
            dtype, offset = self._wire_format(split)
            nbytes = os.path.getsize(
                os.path.join(self.data_dir, f"{split}.bin")) - offset
            hi = nbytes // dtype.itemsize - self.block_size
            for _ in range(int(count)):
                self.rng.integers(0, hi, size=n)

    def _count(self, x, t0):
        """Batch-staging telemetry: wall time spent sampling + assembling
        on this process, batches staged, input tokens moved."""
        self._reg.counter("data_stage_ms").add((time.perf_counter() - t0) * 1e3)
        self._reg.counter("data_batches").add(1)
        self._reg.counter("data_tokens").add(int(np.prod(x.shape)))

    def _join_prefetch(self):
        """Wait out an in-flight background stage (counting the blocked
        time — a nonzero data_prefetch_wait_ms means the window finished
        before the host did). After the join only the calling thread
        touches the buffer/rng. A stage() failure re-raises HERE: the
        thread has already advanced the rng for its partial draws, so
        continuing would silently desync the bit-identical-stream
        contract — fail loud instead."""
        t = self._prefetch_thread
        if t is None:
            return
        t0 = time.perf_counter()
        was_running = t.is_alive()
        t.join()
        self._prefetch_thread = None
        if was_running:
            self._reg.counter("data_prefetch_wait_ms").add(
                (time.perf_counter() - t0) * 1e3)
        if self._prefetch_error is not None:
            err, self._prefetch_error = self._prefetch_error, None
            raise RuntimeError(
                "background batch prefetch failed (rng draws for the "
                "staged window are already consumed, so the stream "
                "cannot be resumed consistently)"
            ) from err

    def _take(self, split, k, count_hit=True):
        """Pop `k` staged batches (topping up synchronously on a miss) in
        strict FIFO order. `split` must match what was staged — one
        DataLoader serves one split once prefetch is engaged (the loop's
        train/eval loaders are separate instances). `count_hit=False` for
        non-window callers: data_prefetch_hit counts whole WINDOWS served
        from the buffer (the METRIC_SCHEMA contract), not stray
        single-batch drains."""
        self._join_prefetch()
        if self._buf:
            assert self._buf_split == split, (
                f"prefetch buffer holds {self._buf_split!r} batches but "
                f"{split!r} was requested — a prefetching DataLoader "
                "serves a single split (use a second loader)"
            )
        if count_hit and len(self._buf) >= k:
            self._reg.counter("data_prefetch_hit").add(1)
        while len(self._buf) < k:
            self._buf.append(self._sample_local(split))
        out, self._buf = self._buf[:k], self._buf[k:]
        return out

    def _spawn_prefetch(self, split, k):
        """Stage the next `k` batches in the background (double buffer:
        at most one window in flight). The thread's sampling time lands
        in data_stage_ms (thread-safe counter) so the memmap cost stays
        visible even though it no longer blocks the loop; its exceptions
        are re-raised by the next _join_prefetch."""

        def stage():
            t0 = time.perf_counter()
            try:
                for _ in range(k):
                    self._buf.append(self._sample_local(split))
            except BaseException as e:  # surfaced at the next join
                self._prefetch_error = e
            finally:
                self._reg.counter("data_stage_ms").add(
                    (time.perf_counter() - t0) * 1e3)

        self._buf_split = split
        self._prefetch_error = None
        self._prefetch_thread = threading.Thread(
            target=stage, name="avenir-data-prefetch", daemon=True)
        self._prefetch_thread.start()

    def get_batch(self, split):
        t0 = time.perf_counter()
        if self._buf or self._prefetch_thread is not None:
            # a windowed caller left staged batches behind: consume them
            # in order so the stream stays bit-identical
            x, y = self._take(split, 1, count_hit=False)[0]
        else:
            x, y = self._sample_local(split)
        if self.sharding is None:
            out = jax.numpy.asarray(x), jax.numpy.asarray(y)
            self._count(x, t0)
            return out
        if self.flat:
            global_shape = (self.batch_size, self.block_size)
        else:
            global_shape = (self.grad_accum, self.batch_size, self.block_size)
        gx = jax.make_array_from_process_local_data(self.sharding, x, global_shape)
        gy = jax.make_array_from_process_local_data(self.sharding, y, global_shape)
        self._count(x, t0)
        return gx, gy

    def get_batch_window(self, split, k):
        """`k` consecutive batches stacked on a leading (unsharded) step
        axis — (k, grad_accum, B, T) — for the windowed multi-step
        dispatch (train/step.jit_windowed_train_step). Draws from the SAME
        per-process stream as get_batch, so k window calls and k·1 single
        calls yield the identical batch sequence."""
        assert not self.flat, "windowed batches are a train-path concept"
        t0 = time.perf_counter()
        xs, ys = zip(*self._take(split, k))
        # double-buffer: stage the NEXT window on a background thread
        # while this one's device window runs
        self._spawn_prefetch(split, k)
        x, y = np.stack(xs), np.stack(ys)
        if self.sharding is None:
            out = jax.numpy.asarray(x), jax.numpy.asarray(y)
            self._count(x, t0)
            return out
        from jax.sharding import NamedSharding, PartitionSpec as P

        wsh = NamedSharding(self.sharding.mesh, P(None, *self.sharding.spec))
        gshape = (k, self.grad_accum, self.batch_size, self.block_size)
        gx = jax.make_array_from_process_local_data(wsh, x, gshape)
        gy = jax.make_array_from_process_local_data(wsh, y, gshape)
        self._count(x, t0)
        return gx, gy
