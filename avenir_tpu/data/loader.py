"""Multi-host memmap data loader (SURVEY.md §2b T8 + ISSUE 19 streaming).

Same on-disk contract as the torch trainer's get_batch (train.py:144-161):
token memmaps, random crops of block_size+1. Made multi-host aware
the jax way: every process samples its OWN disjoint stream of crops,
and `jax.make_array_from_process_local_data` assembles the per-process
shards into one global jax.Array laid out by the batch sharding — no
host ever materializes the global batch.

Corpus layouts (resolved per split by data/streaming.SplitSource):
  - legacy single file `<split>.bin` — replicated on every host's disk,
    every process samples the full file. Byte-identical behavior to the
    pre-streaming loader (same rng stream, same crops).
  - sharded directory `<split>.shards/` — many v2-wire shard files plus
    a MANIFEST.json; process p of P owns the contiguous shard range
    [p*S/P, (p+1)*S/P) (the checkpoint `local_shard_ranges` locality
    design), so a pod host never reads a peer's files.

Wire formats (ISSUE 15 satellite — the config ladder's upper rungs):
  - legacy: a raw headerless uint16 memmap (the nanoGPT .bin contract;
    half the H2D bytes of int32 — the r5 win). Any vocab > 65536 against
    this form fails loud at construction (ids would wrap silently).
  - v2: an 8-byte header (magic 'AVNR', version byte, dtype code byte,
    2 reserved zeros) followed by the raw token array — selected per
    FILE by the header, so a mixed directory of legacy and v2 files
    just works. dtype code 2 = uint32: the >65536-vocab form Llama-3's
    128k vocab needs (write_token_file picks the narrowest dtype that
    fits). The 8-byte offset keeps the uint32 memmap aligned.
Both forms ride the H2D wire in their on-disk dtype; the jit'd step
widens to int32 on device (train/step.py).

Weighted multi-corpus mixing (`mix='owt:0.7,code:0.3'`, ISSUE 19): each
crop picks its corpus from a DEDICATED per-process selection stream
(fixed consumption: n uniform doubles per batch, independent of the
weights), then draws its position from that corpus's OWN rng — so
mixture weights can change across a relaunch without desyncing any
corpus's stream, and kill-resume replays from the checkpointed
per-corpus draw counts (`resume_state`/`fast_forward_state`).

Files are re-opened per batch, matching the reference's defense against
the np.memmap leak (train.py:145-147).
"""

import collections
import os
import threading
import time

import jax
import numpy as np

from avenir_tpu.obs.metrics import get_registry
from avenir_tpu.utils.faults import get_injector
from avenir_tpu.utils.retry import call_with_retry

# the legacy on-disk .bin format AND its H2D wire format (headerless raw
# uint16); v2 files carry their own dtype in the header below
WIRE_DTYPE = np.uint16
WIRE_VOCAB_CAP = int(np.iinfo(WIRE_DTYPE).max) + 1  # 65536

# v2 container: 8-byte header then the raw token array
WIRE_MAGIC = b"AVNR"
WIRE_V2 = 2
WIRE_HEADER_BYTES = 8
_DTYPE_CODES = {1: np.uint16, 2: np.uint32}
_CODE_FOR_DTYPE = {np.dtype(v): k for k, v in _DTYPE_CODES.items()}


def write_token_file(path, tokens, vocab_size=None):
    """Write a token array in the narrowest wire form that fits:
    legacy raw uint16 when the vocab does (bit-compatible with every
    existing .bin consumer incl. the torch trainer), the v2
    header + uint32 form otherwise. Returns the numpy dtype written.
    The sharded twin for streaming corpora is
    data/streaming.write_token_shards (same dtype policy, one manifest
    per split directory)."""
    tokens = np.asarray(tokens)
    hi = int(vocab_size) if vocab_size is not None else (
        int(tokens.max()) + 1 if tokens.size else 0)
    assert tokens.size == 0 or (int(tokens.max()) < hi
                                and int(tokens.min()) >= 0), (
        f"token ids outside [0, {hi}) (max {int(tokens.max())}) — a "
        "vocab_size/tokenizer mismatch; writing would silently wrap ids "
        "into the narrow wire dtype (the exact corruption the wire gate "
        "exists to prevent)"
    )
    if hi <= WIRE_VOCAB_CAP:
        tokens.astype(np.uint16).tofile(path)
        return np.dtype(np.uint16)
    assert hi <= int(np.iinfo(np.uint32).max) + 1, (
        f"vocab_size={hi} does not fit uint32")
    with open(path, "wb") as f:
        f.write(WIRE_MAGIC + bytes([WIRE_V2,
                                    _CODE_FOR_DTYPE[np.dtype(np.uint32)],
                                    0, 0]))
        tokens.astype(np.uint32).tofile(f)
    return np.dtype(np.uint32)


def read_wire_format(path):
    """(dtype, byte offset) of a token file: header-sniffed v2, else the
    legacy raw-uint16 contract.

    Collision discipline: a legacy corpus could in principle START with
    tokens whose bytes spell the magic (0x5641, 0x524E as uint16 LE).
    The reserved-zero bytes are therefore part of the sniff — a magic
    match whose reserved bytes are nonzero reads as legacy, so the
    silent-misparse window needs FIVE specific leading values
    (~2^-64 for real corpora). A magic+reserved match with a bad
    version/dtype byte fails LOUD rather than guessing: loud-on-
    astronomically-rare beats silent garbage, and a future v3 writer
    bumps the version byte into exactly this error."""
    with open(path, "rb") as f:
        head = f.read(WIRE_HEADER_BYTES)
    if (len(head) < WIRE_HEADER_BYTES or head[:4] != WIRE_MAGIC
            or head[6:8] != b"\x00\x00"):
        return np.dtype(WIRE_DTYPE), 0
    version, code = head[4], head[5]
    assert version == WIRE_V2, (
        f"{path}: unknown token-file version {version} (this build reads "
        f"v{WIRE_V2}) — refusing to guess the layout")
    assert code in _DTYPE_CODES, (
        f"{path}: unknown token dtype code {code}")
    return np.dtype(_DTYPE_CODES[code]), WIRE_HEADER_BYTES


# a replay chunk bound: fast-forward draws in slices of this many crops
# so resuming a long run never materializes a giant index array
_REPLAY_CHUNK = 1 << 20


class DataLoader:
    def __init__(self, data_dir, block_size, batch_size, *, sharding=None,
                 grad_accum=1, seed=0, flat=False, vocab_size=None,
                 mix=None, prefetch_depth=1):
        """`batch_size` is the GLOBAL batch size in sequences per micro-step;
        each call to get_batch returns (grad_accum, B, T) stacked micro
        batches as a sharded global array (leading accum dim unsharded).
        `flat=True` (eval): grad_accum must be 1 and batches are (B, T).
        `vocab_size` (when known) is validated against the wire format of
        every corpus — a Llama-3-sized 128k vocab must fail loud HERE
        instead of silently wrapping ids modulo 65536 (ADVICE r5).
        `mix` ('name:weight,...' or [(name, weight), ...]) blends crops
        from several corpus dirs, resolved relative to `data_dir`'s
        parent. `prefetch_depth` >= 2 replaces the depth-1 double buffer
        with a persistent background pipeline staging up to
        depth x window batches ahead."""
        from avenir_tpu.data.streaming import parse_data_mix, resolve_corpus_dir

        self.data_dir = data_dir
        self.block_size = block_size
        self.batch_size = batch_size
        self.grad_accum = grad_accum
        self.sharding = sharding
        self.flat = flat
        self.seed = seed
        self._reg = get_registry()
        assert not (flat and grad_accum != 1)
        self.vocab_size = vocab_size
        self.prefetch_depth = int(prefetch_depth)
        assert self.prefetch_depth >= 1, "prefetch_depth must be >= 1"
        self._sources = {}  # (corpus name | None, split) -> SplitSource
        n_proc = jax.process_count()
        assert batch_size % n_proc == 0, (
            f"global batch {batch_size} must divide over {n_proc} processes"
        )
        self.local_batch = batch_size // n_proc
        # disjoint per-process stream (single-corpus path: UNCHANGED
        # seeding, the bit-identity anchor for every legacy data/ dir)
        self.rng = np.random.default_rng(seed + 1000 * jax.process_index())
        if mix:
            parsed = (parse_data_mix(mix) if isinstance(mix, str)
                      else [(str(n), float(w)) for n, w in mix])
            total = sum(w for _, w in parsed)
            self._mix = [(n, w / total) for n, w in parsed]
            self._mix_dirs = {n: resolve_corpus_dir(n, data_dir)
                              for n, _ in self._mix}
            self._cuts = np.cumsum([w for _, w in self._mix])
            # the selection stream: ITS consumption is n doubles per
            # batch whatever the weights, so replay needs only the count
            self._sel_rng = np.random.default_rng(
                [seed, jax.process_index(), 0x5E1EC7ED])
            self._rngs = {}  # (name, split) -> per-corpus sampling rng
        else:
            self._mix = None
        if vocab_size is not None:
            # fail loud HERE, not mid-run: every corpus's train wire
            # format must fit the vocab (ADVICE r5). The v2 uint32 form
            # is what lets Llama-3's 128k vocab pass this gate.
            if self._mix is not None:
                for name, _ in self._mix:
                    self._source("train", name)
            elif (os.path.exists(os.path.join(data_dir, "train.bin"))
                  or os.path.isdir(os.path.join(data_dir, "train.shards"))):
                self._source("train")
        # background prefetch (ISSUE 3 satellite): after each window the
        # loader stages the NEXT window's memmap crops on a daemon
        # thread, so the fancy-indexing overlaps device compute instead
        # of running on the dispatch edge. The buffer is FIFO and every
        # _sample_local draw happens in consumption order (the thread is
        # joined before any pop), so the rng stream a run CONSUMES is
        # bit-identical to the unprefetched loader's — pinned by
        # tests/test_loader.py::test_prefetch_preserves_stream_order.
        self._buf = []  # staged (x, y) micro batches, oldest first
        self._buf_split = None
        self._prefetch_thread = None
        self._prefetch_error = None
        # deep pipeline (prefetch_depth >= 2): a persistent worker
        # (data/streaming.Prefetcher), engaged by the first window call
        self._deep = None
        self._deep_split = None
        # pop-time consumption accounting for checkpointed resume
        # (ISSUE 19): prefetch stages rng draws AHEAD of consumption,
        # so the resume point is what was POPPED, not the rng position.
        # _sample_local pushes one stats entry per staged batch; _account
        # pops one per batch handed to the caller.
        self._stats_fifo = collections.deque()
        self._consumed = {"batches": {}, "sel_draws": 0, "crops": {}}

    # ---- sources & rngs ---------------------------------------------------

    def _source(self, split, corpus=None):
        """SplitSource for (corpus, split), built once (a file's layout
        cannot change mid-run) with the vocab-fits-the-wire fail-loud
        applied on first sight."""
        from avenir_tpu.data.streaming import SplitSource

        key = (corpus, split)
        src = self._sources.get(key)
        if src is None:
            d = self.data_dir if corpus is None else self._mix_dirs[corpus]
            src = SplitSource(d, split, self.block_size,
                              vocab_size=self.vocab_size)
            self._sources[key] = src
        return src

    def _corpus_rng(self, name, split):
        """Each corpus split keeps its OWN sampling rng (seeded off the
        corpus name, not the mix position), so adding/reweighting
        corpora never desyncs another corpus's stream."""
        from avenir_tpu.data.streaming import corpus_seed_tag

        key = (name, split)
        r = self._rngs.get(key)
        if r is None:
            r = np.random.default_rng(
                [self.seed, jax.process_index(),
                 corpus_seed_tag(name), corpus_seed_tag(split)])
            self._rngs[key] = r
        return r

    def _mix_parts(self, split):
        return [(name, self._source(split, name),
                 self._corpus_rng(name, split))
                for name, _ in self._mix]

    # ---- sampling ---------------------------------------------------------

    def _sample_local(self, split):
        n = self.grad_accum * self.local_batch
        if self._mix is not None:
            return self._sample_mixed(split, n)
        src = self._source(split)
        # the rng draw happens ONCE, before the (retryable) file reads:
        # a flaky read retried by call_with_retry must re-read the SAME
        # crops, or the consumed rng stream would depend on how flaky
        # the storage was (breaking the deterministic-resume contract)
        ix = None

        def read():
            nonlocal ix
            get_injector().fail("data_read_fail", what=src.what)
            if ix is None:
                ix = self.rng.integers(0, src.n_positions, size=n)
            # tokens stay in the file's narrow dtype ON THE WIRE (uint16
            # legacy, uint32 for >65536 vocabs) — the jit'd step casts to
            # int32 on device (train/step.py), halving H2D bytes per
            # batch at uint16. Measured r5 on the tunneled bench chip:
            # ~230ms of per-window transfer serialization at int32, the
            # dominant loop-vs-step-harness gap; pods pay the same
            # halving on DCN-attached hosts.
            return src.gather(ix)

        x, y = call_with_retry(read, what=f"data read {src.what}")
        self._stats_fifo.append((split, None))
        return self._shape(x, y)

    def _sample_mixed(self, split, n):
        parts = self._mix_parts(split)
        drawn = None  # all rng consumption happens ONCE (retry contract)

        def read():
            nonlocal drawn
            get_injector().fail("data_read_fail", what=f"{split}[mix]")
            if drawn is None:
                # per-CROP corpus selection: thresholding fixed uniform
                # draws against the cumulative weights. Consumption is n
                # doubles however the weights are set, so a re-weighted
                # relaunch replays by COUNT alone.
                u = self._sel_rng.random(n)
                assign = np.minimum(
                    np.searchsorted(self._cuts, u, side="right"),
                    len(parts) - 1)
                per = []
                for c, (name, src, rng_c) in enumerate(parts):
                    slots = np.nonzero(assign == c)[0]
                    ixc = (rng_c.integers(0, src.n_positions,
                                          size=slots.size)
                           if slots.size else None)
                    per.append((slots, ixc))
                drawn = per
            # widest wire dtype across the mix: one dtype per batch
            wide = (np.dtype(np.uint32)
                    if any(src.dtype.itemsize > 2 for _, src, _ in parts)
                    else np.dtype(np.uint16))
            x = np.empty((n, self.block_size), dtype=wide)
            y = np.empty_like(x)
            counts = {}
            for (name, src, _), (slots, ixc) in zip(parts, drawn):
                counts[name] = int(slots.size)
                if slots.size:
                    xc, yc = src.gather(ixc)
                    x[slots] = xc
                    y[slots] = yc
            return x, y, counts

        x, y, counts = call_with_retry(read, what=f"data read {split} mix")
        self._stats_fifo.append((split, counts))
        return self._shape(x, y)

    def _shape(self, x, y):
        if self.flat:
            shape = (self.local_batch, self.block_size)
        else:
            shape = (self.grad_accum, self.local_batch, self.block_size)
        return x.reshape(shape), y.reshape(shape)

    # ---- deterministic resume --------------------------------------------

    def fast_forward(self, plan):
        """Advance the sampling rng as if the draws had already happened:
        `plan` is [(split, n_batches), ...] replayed in order. Resume
        support (ISSUE 5): a relaunched run fast-forwards its fresh
        loader past the batches the killed run consumed, making the
        post-resume batch stream bit-identical to an uninterrupted
        run's. The replay must use each split's REAL sampling bound —
        numpy's bounded-integer rejection sampling consumes a
        bound-dependent amount of the bit stream, so a dummy bound
        would desync it. (Consumption is per-DRAW, independent of how
        draws are grouped into calls, so the replay batches its calls.)
        Mixed loaders replay the selection stream and derive per-corpus
        counts under the CURRENT weights; a relaunch that changed the
        weights must use fast_forward_state with the checkpointed
        counts instead."""
        self._assert_fresh("fast_forward")
        n = self.grad_accum * self.local_batch
        for split, count in plan:
            count = int(count)
            if self._mix is not None:
                self._replay_mixed(split, count, n)
                continue
            hi = self._source(split).n_positions
            total = count * n
            for start in range(0, total, _REPLAY_CHUNK):
                self.rng.integers(0, hi,
                                  size=min(_REPLAY_CHUNK, total - start))
            b = self._consumed["batches"]
            b[split] = b.get(split, 0) + count

    def _replay_mixed(self, split, count, n):
        parts = self._mix_parts(split)
        crops = self._consumed["crops"].setdefault(split, {})
        batches_per_chunk = max(1, _REPLAY_CHUNK // max(n, 1))
        rem = count
        while rem:
            b = min(rem, batches_per_chunk)
            u = self._sel_rng.random(b * n)
            assign = np.minimum(
                np.searchsorted(self._cuts, u, side="right"),
                len(parts) - 1)
            for c, (name, src, rng_c) in enumerate(parts):
                kc = int((assign == c).sum())
                if kc:
                    rng_c.integers(0, src.n_positions, size=kc)
                crops[name] = crops.get(name, 0) + kc
            rem -= b
        self._consumed["sel_draws"] += count * n
        bt = self._consumed["batches"]
        bt[split] = bt.get(split, 0) + count

    def resume_state(self):
        """Checkpointable consumption record: batches popped per split
        and, for mixed loaders, selection draws + per-corpus crop counts
        — tracked at buffer-POP time, because prefetch stages rng draws
        AHEAD of consumption (a kill loses the staged-but-unconsumed
        draws, and resume must not replay them). This is what rides the
        checkpoint as `data_state`; `fast_forward_state` replays it on a
        fresh loader even if the mixture weights changed in between."""
        st = {"version": 1, "mixed": self._mix is not None,
              "batches": {k: int(v)
                          for k, v in self._consumed["batches"].items()}}
        if self._mix is not None:
            st["sel_draws"] = int(self._consumed["sel_draws"])
            st["crops"] = {s: {k: int(v) for k, v in d.items()}
                           for s, d in self._consumed["crops"].items()}
        return st

    def fast_forward_state(self, state):
        """Replay a `resume_state` record on a fresh loader. For mixed
        loaders the per-corpus counts come from the CHECKPOINT, not from
        re-deriving the selection — so the replay stays exact even when
        the relaunch changed the mixture weights (each corpus's own rng
        advances by exactly the draws that corpus consumed)."""
        self._assert_fresh("fast_forward_state")
        mixed = bool(state.get("mixed"))
        assert mixed == (self._mix is not None), (
            f"checkpoint data_state is {'mixed' if mixed else 'unmixed'} "
            f"but this loader is {'mixed' if self._mix else 'unmixed'} — "
            "resume with the corpus configuration the run was using"
        )
        if not mixed:
            batches = state.get("batches") or {}
            assert len(batches) <= 1, (
                "unmixed data_state covering multiple splits loses draw "
                "ORDER (one shared rng, split-dependent bounds) — resume "
                "this loader with an ordered fast_forward plan instead"
            )
            for split, count in batches.items():
                self.fast_forward([(split, int(count))])
            return
        rem = int(state.get("sel_draws", 0))
        while rem:
            take = min(rem, _REPLAY_CHUNK)
            self._sel_rng.random(take)
            rem -= take
        for split, d in (state.get("crops") or {}).items():
            for name, kc in d.items():
                assert name in self._mix_dirs, (
                    f"checkpoint data_state names corpus {name!r} which "
                    f"is not in this run's data_mix "
                    f"({sorted(self._mix_dirs)}) — a removed corpus "
                    "cannot have its consumed stream replayed"
                )
                src = self._source(split, name)
                rng_c = self._corpus_rng(name, split)
                kc = int(kc)
                for start in range(0, kc, _REPLAY_CHUNK):
                    rng_c.integers(0, src.n_positions,
                                   size=min(_REPLAY_CHUNK, kc - start))
        self._consumed = {
            "batches": {k: int(v)
                        for k, v in (state.get("batches") or {}).items()},
            "sel_draws": int(state.get("sel_draws", 0)),
            "crops": {s: {k: int(v) for k, v in d.items()}
                      for s, d in (state.get("crops") or {}).items()},
        }

    def _assert_fresh(self, who):
        assert (not self._buf and self._prefetch_thread is None
                and self._deep is None), (
            f"{who} must run on a fresh loader (before any draw or "
            "prefetch)"
        )

    # ---- telemetry & accounting ------------------------------------------

    def _count(self, x, t0):
        """Batch-staging telemetry: wall time spent sampling + assembling
        on this process, batches staged, input tokens moved."""
        self._reg.counter("data_stage_ms").add((time.perf_counter() - t0) * 1e3)
        self._reg.counter("data_batches").add(1)
        self._reg.counter("data_tokens").add(int(np.prod(x.shape)))

    def _account(self, split):
        """Pop-time consumption bookkeeping (resume_state's source of
        truth): one stats entry per REAL _sample_local batch rides a
        parallel FIFO, so staged-but-unconsumed draws never count.
        (Monkeypatched samplers in tests stage no stats — skip.)"""
        if not self._stats_fifo:
            return
        sp, counts = self._stats_fifo.popleft()
        b = self._consumed["batches"]
        b[sp] = b.get(sp, 0) + 1
        if counts is not None:
            self._consumed["sel_draws"] += self.grad_accum * self.local_batch
            d = self._consumed["crops"].setdefault(sp, {})
            for name, k in counts.items():
                d[name] = d.get(name, 0) + k

    def data_report(self):
        """Schema-free loader summary for the run_end record (per-corpus
        draw counts cannot be fixed METRIC_SCHEMA keys): consumed
        batches, per-corpus crops, and the loader config — what
        tools/obs_report.py's "data:" line reads."""
        rep = {"prefetch_depth": self.prefetch_depth,
               "batches": {k: int(v)
                           for k, v in self._consumed["batches"].items()}}
        if self._mix is not None:
            rep["mix"] = [[n, round(w, 6)] for n, w in self._mix]
            rep["crops"] = {s: {k: int(v) for k, v in d.items()}
                            for s, d in self._consumed["crops"].items()}
        srcs = {}
        for (corpus, split), src in self._sources.items():
            label = split if corpus is None else f"{corpus}/{split}"
            info = {"kind": src.kind, "dtype": src.dtype.name}
            if src.local_range is not None:
                info["local_shards"] = list(src.local_range)
            srcs[label] = info
        if srcs:
            rep["sources"] = srcs
        return rep

    # ---- prefetch ---------------------------------------------------------

    def _poison_check(self):
        """A stored prefetch failure raises at the NEXT get_batch — and
        keeps raising (sticky): the background thread already advanced
        the rng for its partial draws, so every later batch would be
        silently desynced."""
        from avenir_tpu.data.streaming import raise_prefetch_error

        err = self._prefetch_error
        if err is None and self._deep is not None:
            err = self._deep.error
        if err is not None:
            raise_prefetch_error(err)

    def _join_prefetch(self):
        """Wait out an in-flight background stage (counting the blocked
        time — a nonzero data_prefetch_wait_ms means the window finished
        before the host did). After the join only the calling thread
        touches the buffer/rng. A stage() failure re-raises HERE (and
        stays poisoned — see _poison_check)."""
        t = self._prefetch_thread
        if t is None:
            self._poison_check()
            return
        t0 = time.perf_counter()
        was_running = t.is_alive()
        t.join()
        self._prefetch_thread = None
        if was_running:
            self._reg.counter("data_prefetch_wait_ms").add(
                (time.perf_counter() - t0) * 1e3)
        self._poison_check()

    def _take(self, split, k, count_hit=True):
        """Pop `k` staged batches (topping up synchronously on a miss) in
        strict FIFO order. `split` must match what was staged — one
        DataLoader serves one split once prefetch is engaged (the loop's
        train/eval loaders are separate instances). `count_hit=False` for
        non-window callers: data_prefetch_hit counts whole WINDOWS served
        from the buffer (the METRIC_SCHEMA contract; data_windows is the
        denominator), not stray single-batch drains."""
        if self._deep is not None:
            assert self._deep_split == split, (
                f"prefetch buffer holds {self._deep_split!r} batches but "
                f"{split!r} was requested — a prefetching DataLoader "
                "serves a single split (use a second loader)"
            )
            out, hit, waited_ms = self._deep.pop(k)
            if waited_ms:
                self._reg.counter("data_prefetch_wait_ms").add(waited_ms)
            if count_hit:
                self._reg.counter("data_windows").add(1)
                if hit:
                    self._reg.counter("data_prefetch_hit").add(1)
            for _ in out:
                self._account(split)
            return out
        self._join_prefetch()
        if self._buf:
            assert self._buf_split == split, (
                f"prefetch buffer holds {self._buf_split!r} batches but "
                f"{split!r} was requested — a prefetching DataLoader "
                "serves a single split (use a second loader)"
            )
        if count_hit:
            self._reg.counter("data_windows").add(1)
            if len(self._buf) >= k:
                self._reg.counter("data_prefetch_hit").add(1)
        while len(self._buf) < k:
            self._buf.append(self._sample_local(split))
        out, self._buf = self._buf[:k], self._buf[k:]
        for _ in out:
            self._account(split)
        return out

    def _spawn_prefetch(self, split, k):
        """Stage the next `k` batches in the background (double buffer:
        at most one window in flight — the prefetch_depth=1 path). The
        thread's sampling time lands in data_stage_ms (thread-safe
        counter) so the memmap cost stays visible even though it no
        longer blocks the loop; its exceptions are re-raised by the next
        _join_prefetch."""

        def stage():
            t0 = time.perf_counter()
            try:
                for _ in range(k):
                    self._buf.append(self._sample_local(split))
            except BaseException as e:  # surfaced at the next join
                self._prefetch_error = e
            finally:
                self._reg.counter("data_stage_ms").add(
                    (time.perf_counter() - t0) * 1e3)

        self._buf_split = split
        self._prefetch_error = None
        self._prefetch_thread = threading.Thread(
            target=stage, name="avenir-data-prefetch", daemon=True)
        self._prefetch_thread.start()

    def _ensure_deep(self, split, k):
        """Engage (or retarget) the persistent deep pipeline. One
        Prefetcher per loader, bound to one split — its single worker
        owns the rng from here on, so the staged stream is exactly the
        sequence a synchronous loader would draw."""
        from avenir_tpu.data.streaming import Prefetcher

        if self._deep is None:
            assert not self._buf and self._prefetch_thread is None
            self._deep = Prefetcher(lambda: self._sample_local(split),
                                    self.prefetch_depth)
            self._deep_split = split
        assert self._deep_split == split, (
            f"prefetch buffer holds {self._deep_split!r} batches but "
            f"{split!r} was requested — a prefetching DataLoader serves "
            "a single split (use a second loader)"
        )
        self._deep.ensure(k)

    def close(self):
        """Stop background staging (bench/test hygiene; training relies
        on daemon threads dying with the process)."""
        if self._deep is not None:
            self._deep.stop()
        t = self._prefetch_thread
        if t is not None:
            t.join()
            self._prefetch_thread = None

    # ---- batch API --------------------------------------------------------

    def get_batch(self, split):
        self._poison_check()
        t0 = time.perf_counter()
        if (self._deep is not None or self._buf
                or self._prefetch_thread is not None):
            # a windowed caller left staged batches behind: consume them
            # in order so the stream stays bit-identical
            x, y = self._take(split, 1, count_hit=False)[0]
        else:
            x, y = self._sample_local(split)
            self._account(split)
        if self.sharding is None:
            out = jax.numpy.asarray(x), jax.numpy.asarray(y)
            self._count(x, t0)
            return out
        if self.flat:
            global_shape = (self.batch_size, self.block_size)
        else:
            global_shape = (self.grad_accum, self.batch_size, self.block_size)
        gx = jax.make_array_from_process_local_data(self.sharding, x, global_shape)
        gy = jax.make_array_from_process_local_data(self.sharding, y, global_shape)
        self._count(x, t0)
        return gx, gy

    def get_batch_window(self, split, k):
        """`k` consecutive batches stacked on a leading (unsharded) step
        axis — (k, grad_accum, B, T) — for the windowed multi-step
        dispatch (train/step.jit_windowed_train_step). Draws from the SAME
        per-process stream as get_batch, so k window calls and k·1 single
        calls yield the identical batch sequence."""
        assert not self.flat, "windowed batches are a train-path concept"
        self._poison_check()
        t0 = time.perf_counter()
        if self.prefetch_depth > 1:
            # deep pipeline: the persistent worker keeps depth*k batches
            # staged; this pop usually returns without touching a file
            self._ensure_deep(split, k)
        xs, ys = zip(*self._take(split, k))
        if self._deep is None:
            # double-buffer: stage the NEXT window on a background thread
            # while this one's device window runs
            self._spawn_prefetch(split, k)
        x, y = np.stack(xs), np.stack(ys)
        if self.sharding is None:
            out = jax.numpy.asarray(x), jax.numpy.asarray(y)
            self._count(x, t0)
            return out
        from jax.sharding import NamedSharding, PartitionSpec as P

        wsh = NamedSharding(self.sharding.mesh, P(None, *self.sharding.spec))
        gshape = (k, self.grad_accum, self.batch_size, self.block_size)
        gx = jax.make_array_from_process_local_data(wsh, x, gshape)
        gy = jax.make_array_from_process_local_data(wsh, y, gshape)
        self._count(x, t0)
        return gx, gy
