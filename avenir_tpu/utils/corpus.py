"""Deterministic synthetic corpus for tests and offline data prep.

The sandbox has zero network egress, so `data/*/prepare.py` cannot download
tinyshakespeare. This module generates a deterministic pseudo-English corpus
with enough statistical structure (Zipf word distribution, stable bigram
statistics, line structure) that a small LM's loss drops fast — good enough
to anchor golden-loss tests (SURVEY.md §4) and smoke training runs. A real
`input.txt` dropped next to a prepare.py always takes precedence.

Torch-free (importable on a TPU pod)."""

import os

import numpy as np

_WORDS = (
    "the and to of a in that is was he for it with as his on be at by i "
    "this had not are but from or have an they which one you were her all "
    "she there would their we him been has when who will more no if out so "
    "said what up its about into than them can only other new some could "
    "time these two may then do first any my now such like our over man me "
    "even most made after also did many before must through back years where "
    "much your way well down should because each just those people mr how "
    "too little state good very make world still own see men work long get "
    "here between both life being under never day same another know while "
    "last might us great old year off come since against go came right used "
    "take three"
).split()


def synthetic_corpus(n_chars: int = 500_000, seed: int = 1337) -> str:
    """Deterministic pseudo-text: Zipf-distributed words, ~12 words/line."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, len(_WORDS) + 1, dtype=np.float64)
    probs = 1.0 / ranks
    probs /= probs.sum()
    parts = []
    total = 0
    line_len = 0
    # draw in chunks for speed
    while total < n_chars:
        idxs = rng.choice(len(_WORDS), size=4096, p=probs)
        for i in idxs:
            w = _WORDS[i]
            parts.append(w)
            total += len(w) + 1
            line_len += 1
            if line_len >= 12:
                parts.append("\n")
                line_len = 0
            else:
                parts.append(" ")
            if total >= n_chars:
                break
    return "".join(parts)


def write_char_dataset(out_dir: str, text: str, train_frac: float = 0.9):
    """Char-level tokenize `text` into train.bin/val.bin uint16 memmaps plus
    a meta.pkl with the stoi/itos tables (nanoGPT-lineage on-disk layout, so
    both backends' get_batch can memmap it — SURVEY.md §2a R4)."""
    import pickle

    chars = sorted(set(text))
    stoi = {ch: i for i, ch in enumerate(chars)}
    itos = {i: ch for i, ch in enumerate(chars)}
    data = np.array([stoi[c] for c in text], dtype=np.uint16)
    n = int(train_frac * len(data))
    os.makedirs(out_dir, exist_ok=True)
    data[:n].tofile(os.path.join(out_dir, "train.bin"))
    data[n:].tofile(os.path.join(out_dir, "val.bin"))
    meta = {"vocab_size": len(chars), "stoi": stoi, "itos": itos}
    with open(os.path.join(out_dir, "meta.pkl"), "wb") as f:
        pickle.dump(meta, f)
    return meta
