"""Capped exponential backoff for flaky IO (ISSUE 5 tentpole, part 3).

Production filesystems (GCS fuse mounts, NFS exports, preempted-node
local disks) return transient EIO/ESTALE/ECONNRESET long before they
return clean data — a training run that dies on the first flaky read
wastes everything since the last checkpoint. `call_with_retry` wraps the
IO-shaped call sites (checkpoint body reads/writes, loader memmap reads)
with a small, fully deterministic-under-test policy:

  delay_n = min(cap, base * 2**n) * (1 + jitter * u),  u ~ U[0, 1)

Every retry increments the `io_retries` counter and writes a `retry`
record to the JSONL run log (obs.sink.get_run_sink), so flaky storage is
VISIBLE in tools/obs_report.py output instead of silently stretching
step time. Exhausted attempts re-raise the last error — retries mask
transience, never corruption (checksum failures are NOT retryable:
avenir_tpu/checkpoint/manifest.py raises CorruptCheckpoint, which no
policy here catches).

Testing: `clock` and `rng` are injectable, so the backoff sequence is
asserted without sleeping (tests/test_retry.py).
"""

import time


class RetryPolicy:
    """Immutable backoff description. `sleep`/`rng` injectable for tests;
    `attempts` counts TOTAL tries (1 = no retries)."""

    def __init__(self, attempts=4, base_s=0.05, cap_s=2.0, jitter=0.25,
                 sleep=time.sleep, rng=None):
        assert attempts >= 1 and base_s >= 0 and cap_s >= base_s
        self.attempts = int(attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.jitter = float(jitter)
        self.sleep = sleep
        import random

        self._rng = rng if rng is not None else random.Random()

    def delay_s(self, n_failures):
        """Backoff before the (n_failures+1)-th try (n_failures >= 1)."""
        d = min(self.cap_s, self.base_s * (2 ** (n_failures - 1)))
        return d * (1.0 + self.jitter * self._rng.random())


# module default, swappable in tests (e.g. a no-sleep policy for the
# whole suite) via set_default_policy
_default = [RetryPolicy()]


def set_default_policy(policy):
    prev, _default[0] = _default[0], policy
    return prev


def default_policy():
    return _default[0]


# errors worth retrying: the OS-level transient class. ValueError /
# pickle / zip errors are NOT here on purpose — garbage bytes must
# surface as corruption (fallback territory), not burn the retry budget.
TRANSIENT_ERRORS = (OSError,)


def call_with_retry(fn, *, what, policy=None, retry_on=TRANSIENT_ERRORS,
                    registry=None, sink=None, echo=print):
    """Run `fn()` with up to policy.attempts tries. Each retry is counted
    (`io_retries`), logged to the run sink as a `retry` record, and
    echoed — a retried save that eventually lands must leave a trace.
    The final failure re-raises the ORIGINAL exception."""
    policy = policy or _default[0]
    if registry is None:
        from avenir_tpu.obs.metrics import get_registry

        registry = get_registry()
    if sink is None:
        from avenir_tpu.obs.sink import get_run_sink

        sink = get_run_sink()
    failures = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            failures += 1
            if failures >= policy.attempts:
                raise
            delay = policy.delay_s(failures)
            registry.counter("io_retries").add(1)
            echo(f"[retry] {what}: attempt {failures}/{policy.attempts} "
                 f"failed ({type(e).__name__}: {e}); retrying in "
                 f"{delay * 1e3:.0f}ms")
            sink.write({
                "kind": "retry", "t": time.time(), "what": what,
                "attempt": failures, "max_attempts": policy.attempts,
                "error": f"{type(e).__name__}: {e}",
                "delay_ms": round(delay * 1e3, 3),
            })
            policy.sleep(delay)
