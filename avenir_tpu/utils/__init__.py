from avenir_tpu.utils.corpus import synthetic_corpus, write_char_dataset
