"""Deterministic fault injection for the IO paths (ISSUE 5 tentpole).

The crash-consistency story is only trustworthy if it is EXERCISED:
this module lets a test (or tools/chaos_train.py) make the checkpoint
writer fail transiently, the reader see corrupt bytes, or the data
loader hit flaky storage — without monkeypatching library internals.

Spec format (env var `AVENIR_FAULTS`, or FaultInjector(spec)):

    AVENIR_FAULTS="ckpt_write_fail:p=0.3,read_corrupt:p=0.05:n=1"

Comma-separated sites, colon-separated options per site:
    p=<float>   probability a consult fires (default 1.0)
    n=<int>     max total fires for the site (default unlimited)
    after=<int> skip the first N consults (default 0)

`AVENIR_FAULTS_SEED` seeds the injector's private rng, so a chaos run's
fault schedule is reproducible from its seed alone.

Sites consulted by the production IO paths:

    ckpt_write_fail      raise OSError before a checkpoint body/manifest
                         rename lands (checkpoint/io.py writers)
    ckpt_read_fail       raise OSError before a checkpoint body read
    read_corrupt         flip one byte in checkpoint body bytes as read
                         (detected by the manifest CRC, never retried)
    data_read_fail       raise OSError in DataLoader._sample_local
    serve_step_fail      raise inside a serve replica's engine step
                         (serve/replica.py) — the replica dies and the
                         router fails its in-flight work over
    replica_stall        wedge a serve replica: it keeps "running" but
                         stops working AND stops heartbeating, until
                         the router's stall detector declares it dead
    worker_kill          SIGKILL a serve WORKER PROCESS mid-step
                         (serve/worker.py) — a real kill, not an
                         injected exception: the parent ProcReplica
                         sees pipe EOF and fails the work over
    worker_hang          wedge a serve worker process: it stops
                         replying forever; only the parent's per-op
                         RPC timeout can tell (serve/proc.py)
    frame_corrupt        flip one byte of an outgoing frame payload
                         AFTER its CRC is computed (serve/frames.py
                         writer) — trips the reader's CRC check, which
                         is treated as replica death, never retried
    train_step_degrade   each fire adds a PERMANENT +2 ms/iter of host
                         latency to the train loop (train/loop.py) —
                         gradual rot, not a stall: windows keep
                         completing so the watchdog never fires, which
                         is exactly the gap the anomaly engine's
                         step-time drift detector closes
                         (obs/anomaly.py, tools/anomaly_bench.py)
    serve_step_degrade   each fire adds a PERMANENT +2 ms of host
                         latency to every busy step of ONE serve
                         replica (serve/replica.py / serve/proc.py,
                         parent-side) — the poisoned-canary pattern:
                         the replica keeps serving, only slower, so
                         nothing but the rollout canary's TTFT/TPOT
                         drift detectors can tell (serve/rollout.py,
                         ISSUE 20)

The default injector (no env var) is inert: `enabled()` is a dict
lookup returning False, so the hot paths pay nothing. Inject faults in
tests with `set_injector(FaultInjector("..."))`, restoring after.
"""

import os
import random


class FaultInjected(OSError):
    """The injected transient-IO error. An OSError subclass ON PURPOSE:
    the retry policy must treat injected write/read failures exactly
    like real EIO/ESTALE, or the harness would not be testing the
    production retry path."""


def _parse_spec(spec):
    sites = {}
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        parts = entry.split(":")
        opts = {"p": 1.0, "n": None, "after": 0}
        for opt in parts[1:]:
            k, _, v = opt.partition("=")
            assert k in opts, f"unknown fault option {k!r} in {entry!r}"
            opts[k] = float(v) if k == "p" else int(v)
        sites[parts[0]] = opts
    return sites


class FaultInjector:
    def __init__(self, spec="", seed=0):
        self.sites = _parse_spec(spec or "")
        self._rng = random.Random(seed)
        self.fired = {}     # site -> times a consult fired
        self.consults = {}  # site -> times a consult happened

    @classmethod
    def from_env(cls):
        return cls(os.environ.get("AVENIR_FAULTS", ""),
                   seed=int(os.environ.get("AVENIR_FAULTS_SEED", "0")))

    def enabled(self, site):
        return site in self.sites

    def should_fire(self, site):
        """Consult the schedule; True when the fault fires this time."""
        opts = self.sites.get(site)
        if opts is None:
            return False
        seen = self.consults.get(site, 0)
        self.consults[site] = seen + 1
        if seen < opts["after"]:
            return False
        if opts["n"] is not None and self.fired.get(site, 0) >= opts["n"]:
            return False
        if self._rng.random() >= opts["p"]:
            return False
        self.fired[site] = self.fired.get(site, 0) + 1
        return True

    def fail(self, site, what=""):
        """Raise FaultInjected when the site fires; no-op otherwise."""
        if self.should_fire(site):
            raise FaultInjected(f"injected fault {site!r}"
                                + (f" ({what})" if what else ""))

    def corrupt(self, site, data):
        """Flip one byte of `data` (bytes) when the site fires. The flip
        position is drawn from the injector rng, so it is reproducible
        and can land anywhere — header, body, or manifest bytes."""
        if not data or not self.should_fire(site):
            return data
        pos = self._rng.randrange(len(data))
        out = bytearray(data)
        out[pos] ^= 0xFF
        return bytes(out)

    def report(self):
        """{site: {consults, fired}} — chaos_train's JSON artifact."""
        return {s: {"consults": self.consults.get(s, 0),
                    "fired": self.fired.get(s, 0)}
                for s in self.sites}


_injector = [None]


def get_injector():
    if _injector[0] is None:
        _injector[0] = FaultInjector.from_env()
    return _injector[0]


def set_injector(inj):
    """Swap the process injector (tests); returns the previous one."""
    prev, _injector[0] = _injector[0], inj
    return prev
