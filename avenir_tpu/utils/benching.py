"""Pipelined round timing for the tunneled-TPU bench harnesses.

THE one implementation of the fence-hiding measurement discipline both
bench.py and tools/bench_ladder.py (and, in spirit, the trainer's
one-window-lag logging) rely on: on the axon-tunneled platform a D2H
loss fetch is the only reliable execution fence and costs ~100ms RTT, so
billing it inside a timed round understates throughput. Dispatch round
i+1 BEFORE fetching round i's loss: the fence and the next dispatch
overlap device compute, and the spacing between consecutive fetch
completions is the round's true device-steady-state time. The LAST round
has no successor and pays its fence exposed — use the (lower) median so
it is discarded.
"""

import time


def time_pipelined_rounds(dispatch, fetch, n_rounds=4):
    """Times `n_rounds` calls of `dispatch()` (async; returns a handle)
    with `fetch(handle)` forced one round behind. Returns the per-round
    wall times; take `median_low` of them as the round time."""
    assert n_rounds >= 2, "pipelining needs a successor round"
    rounds, pending = [], None
    t_prev = time.perf_counter()
    for _ in range(n_rounds):
        handle = dispatch()
        if pending is not None:
            fetch(pending)
            t1 = time.perf_counter()
            rounds.append(t1 - t_prev)
            t_prev = t1
        pending = handle
    fetch(pending)
    rounds.append(time.perf_counter() - t_prev)  # exposed fence
    return rounds


def median_low(xs):
    """Lower median — discards the exposed-fence last round at even n."""
    s = sorted(xs)
    return s[(len(s) - 1) // 2]


def peak_hbm_bytes():
    """Peak device-memory bytes of device 0 via PJRT memory_stats —
    None-tolerant (CPU/interpret backends return None or {}), so bench
    JSON always carries the field. NB this is a PROCESS-LIFETIME
    high-water mark: PJRT never resets it, so per-variant A/Bs must run
    each variant in its own process (tools/loss_tail_bench.py does)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak else None
