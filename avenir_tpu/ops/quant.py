"""int8 quantized-compute matmuls for training (ISSUE 15 tentpole).

Training has been pinned at 62% of bf16 peak for ten PRs; v5e int8 peak
is ~2x its bf16 peak (394.8 TOPS vs 197 TFLOPS), so the next plateau
lives behind the MXU's int8 mode. This module is the ONE home for the
quantized-matmul numerics, behind the `compute_dtype='int8'` config knob
(the attn_impl/loss_impl/kv_dtype knob pattern; kv side lives in
ops/kv_quant.py). Which tensors participate is NOT decided here: the
per-tensor `PrecisionPolicy` rides in the unified partition-rules table
(parallel/partition.py) — one source of truth per tensor class for BOTH
sharding and precision, resolved by the models at construction.

Scheme (AQT-style symmetric absmax):

  forward   y = (qx int8 . qw int8 -> int32) * sx * sw, where each
            operand is quantized PER CHANNEL along its contraction axis
            (x per row over C, w per output column over C) — scales
            factor out of the dot exactly, so the MXU consumes int8 and
            the fp32 rescale is a cheap epilogue.
  backward  straight-through estimator w.r.t. the quantization grid
            (round is piecewise constant — its true derivative is 0
            a.e.; STE passes the cotangent through, the standard and
            provably-stable choice for symmetric absmax), with BOTH
            backward matmuls (dx = dy . w^T, dw = x^T . dy) also int8.
            The residuals saved by the custom_vjp are the int8 data +
            scales from the forward — HBM holds int8 between the
            passes, which is the activation-memory half of the win.

Delayed scaling (the `PrecisionPolicy.scaling='delayed'` default): the
backward quantizes the incoming cotangent with ONE per-tensor scale
calibrated over the whole window of rows and channels (a single amax
reduction, reused by both backward matmuls), instead of re-deriving
per-channel scales per matmul. Gradients are heavy-tailed across
channels but the tail is what carries the signal — per-tensor absmax
never clips it — and the single reduction keeps the backward's
calibration cost O(1) instead of O(channels) reductions on the hot
path. `scaling='dynamic'` restores per-channel cotangent scales for
A/B. The x/w sides always reuse the FORWARD-calibrated int8 grid (the
residuals) — backward never re-quantizes from master weights.

Error budget (docs/PERFORMANCE.md "Past the bf16 plateau"): per-channel
absmax rounding error is <= scale/2 = amax/254 per element, relative
error ~0.4% of each channel's dynamic range; the parity contract is the
loss-TRAJECTORY tolerance pinned by tests/test_quant.py, not bit
equality — the same contract split as attn_impl='pallas' and
kv_dtype='int8'.
"""

import functools

import jax
import jax.numpy as jnp

# symmetric int8 range; absmax maps onto it exactly (ops/kv_quant.py
# uses the same constants for the KV cache — training side kept
# separate because the policies differ: per-channel here, per-head there)
Q_MAX = 127.0
# floor keeps an all-zero channel from a 0-divide; its dequantized zeros
# stay exact zeros. Channels that HIT the floor are dead weight-range
# (see audit_quantization / the quant_scale_clip counter).
SCALE_FLOOR = 1e-8

# One entry per TRACE of a quantized matmul (appends happen at trace
# time only) — the ledger idiom shared with ops/fused_ce and
# infer/decode. tests/test_quant.py pins that steady-state int8 steps
# never retrace and that the bf16 path never touches this ledger.
_trace_events = []


def trace_count():
    """Number of int8_matmul traces (== appearances in XLA compiles)."""
    return len(_trace_events)


def quantized_compute(compute_dtype) -> bool:
    """True when the config's compute_dtype selects the int8 matmul
    path. The base arithmetic dtype (norms, softmax, residual stream)
    for 'int8' is bf16 — models/common.resolve_dtype owns that mapping."""
    return compute_dtype == "int8"


def resolve_compute_dtype(compute_dtype) -> str:
    """The startup-line string for the resolved compute mode — mirrors
    resolve_attention_impl/resolve_loss_impl so a silent fallback to
    bf16 matmuls would be visible in the `[tpu]` startup log."""
    if quantized_compute(compute_dtype):
        return "int8"
    return {"bfloat16": "bf16", "float32": "fp32", "float16": "fp16"}.get(
        compute_dtype, str(compute_dtype))


def matmul_bits(compute_dtype) -> int:
    """Element width of the hot-matmul operands (the `matmul_bits`
    gauge): 8 under the int8 knob, else the compute dtype's width."""
    if quantized_compute(compute_dtype):
        return 8
    return {"bfloat16": 16, "float16": 16}.get(compute_dtype, 32)


def quantize_channelwise(x, axis):
    """Symmetric absmax int8 along `axis` (the contraction axis):
    returns (int8 data, fp32 scale with `axis` removed). Per remaining
    index ("channel"), scale = amax / 127 and data = round(x / scale);
    round-trip error per element is bounded by scale/2."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis)
    scale = jnp.maximum(amax, SCALE_FLOOR) / Q_MAX
    data = jnp.round(xf / jnp.expand_dims(scale, axis)).astype(jnp.int8)
    return data, scale


def quantize_tensorwise(x):
    """One per-tensor scale calibrated over the whole window of rows and
    channels — the delayed-scaling form the backward uses for the
    cotangent (one amax reduction, shared by both backward matmuls)."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf)), SCALE_FLOOR) / Q_MAX
    data = jnp.round(xf / scale).astype(jnp.int8)
    return data, scale


def dequantize(data, scale, axis, dtype=jnp.float32):
    """(int8 data, scale) -> dense values in `dtype`; `axis` is where the
    reduced channel axis sits in `data` (same convention as
    quantize_channelwise)."""
    return (data.astype(jnp.float32)
            * jnp.expand_dims(scale, axis)).astype(dtype)


def fake_quant(x, axis):
    """Straight-through fake quantization: forward lands exactly on the
    per-channel int8 grid, backward is identity. The blocked fused-CE
    tail uses this for its weight so plain autodiff reproduces the
    int8 kernels' STE semantics (the CPU-testable oracle)."""
    q, s = quantize_channelwise(x, axis)
    return x + jax.lax.stop_gradient(
        dequantize(q, s, axis, x.dtype) - x.astype(x.dtype))


def _int_dot(qa, qb, dims):
    """int8 x int8 -> int32 dot_general (the MXU's int8 mode on TPU;
    XLA's integer dot elsewhere — same accumulation either way)."""
    return jax.lax.dot_general(qa, qb, (dims, ((), ())),
                               preferred_element_type=jnp.int32)


def _quantize_cotangent(dy, axis, scaling):
    """Quantize the incoming cotangent for the backward matmuls:
    'delayed' -> one per-tensor window-calibrated scale (expanded to the
    per-channel shape so both modes share the matmul epilogue),
    'dynamic' -> per-channel over the contraction `axis`."""
    if scaling == "delayed":
        qdy, sdy = quantize_tensorwise(dy)
        return qdy, jnp.broadcast_to(
            sdy, tuple(d for i, d in enumerate(dy.shape) if i != axis
                       % dy.ndim))
    return quantize_channelwise(dy, axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _int8_matmul(x, w, w_layout, scaling, x_dtype, w_dtype):
    y, _ = _int8_matmul_fwd(x, w, w_layout, scaling, x_dtype, w_dtype)
    return y


def _int8_matmul_fwd(x, w, w_layout, scaling, x_dtype, w_dtype):
    # x: (..., K); w: (K, N) for 'io', (N, K) for 'oi' (the GPT tied
    # embedding's orientation — consumed via contraction dims, never
    # via a transposed copy, the fused_ce w_layout discipline)
    k_ax = 0 if w_layout == "io" else 1
    qx, sx = quantize_channelwise(x, -1)
    qw, sw = quantize_channelwise(w, k_ax)
    acc = _int_dot(qx, qw, (((x.ndim - 1,), (k_ax,))))
    y = (acc.astype(jnp.float32) * sx[..., None] * sw).astype(x_dtype)
    # residuals are the int8 grids + scales: what HBM holds between the
    # passes is int8, not the bf16 originals
    return y, (qx, sx, qw, sw)


def _int8_matmul_bwd(w_layout, scaling, x_dtype, w_dtype, res, dy):
    qx, sx, qw, sw = res
    k_ax = 0 if w_layout == "io" else 1
    n_ax = 1 - k_ax
    dyf = dy.astype(jnp.float32)
    # dx = dy . w^T (contraction over N): the weight grid from the
    # forward is re-quantized along N (its forward scales ride along K's
    # channel axis, which is now a free axis) — double rounding on an
    # already-int8 grid, error bounded by one further scale/2 step
    w_dq = dequantize(qw, sw, k_ax)
    qw2, sw2 = quantize_channelwise(w_dq, n_ax)
    qdy, sdy = _quantize_cotangent(dyf, -1, scaling)
    acc = _int_dot(qdy, qw2, (((dy.ndim - 1,), (n_ax,))))
    dx = (acc.astype(jnp.float32) * sdy[..., None] * sw2).astype(x_dtype)
    # dw = x^T . dy (contraction over the flattened row window)
    K = qx.shape[-1]
    N = dyf.shape[-1]
    x_dq = dequantize(qx, sx, -1).reshape(-1, K)
    qx2, sx2 = quantize_channelwise(x_dq, 0)          # (K,)
    dy2 = dyf.reshape(-1, N)
    qdy2, sdy2 = _quantize_cotangent(dy2, 0, scaling)  # (N,)
    acc_w = _int_dot(qx2, qdy2, (((0,), (0,))))        # (K, N)
    dw_io = acc_w.astype(jnp.float32) * sx2[:, None] * sdy2[None, :]
    dw = (dw_io if w_layout == "io" else dw_io.T).astype(w_dtype)
    return dx, dw


_int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


def int8_matmul(x, w, *, w_layout="io", scaling="delayed"):
    """Quantized matmul of x (..., K) with w — (K, N) under
    w_layout='io' (nnx.Linear kernels), (N, K) under 'oi' (the GPT tied
    wte embedding). Forward is a true int8 dot with per-channel absmax
    scales; backward is STE with int8 matmuls over the saved int8
    residuals (module docstring). `scaling` is the backward cotangent
    calibration: 'delayed' (per-tensor, window-calibrated — the rules-
    table default) or 'dynamic' (per-channel)."""
    assert w_layout in ("io", "oi"), f"unknown w_layout {w_layout!r}"
    assert scaling in ("delayed", "dynamic"), (
        f"unknown scaling {scaling!r}; one of ['delayed', 'dynamic']")
    _trace_events.append((x.shape, w.shape, w_layout, scaling))
    # dtypes ride as STATIC names (residuals must be jax types; the
    # cotangents must land back in the primal dtypes)
    return _int8_matmul(x, w, w_layout, scaling,
                        jnp.dtype(x.dtype).name, jnp.dtype(w.dtype).name)


def audit_quantization(named_arrays):
    """Host-side startup/bench audit: quantize each (name, array) pair
    per-channel along its LAST axis and count channels whose scale
    clamped to SCALE_FLOOR (an all-zero channel — harmless once, but a
    rising count across a sweep means dead channels are wasting int8
    range). Bumps the `quant_scale_clip` counter by the total and
    returns {name: clipped_channels}. Pure numpy — callable on
    checkpoint trees and on gathered params without entering jit."""
    import numpy as np

    from avenir_tpu.obs.metrics import get_registry

    out = {}
    total = 0
    for name, arr in named_arrays:
        a = np.asarray(arr, dtype=np.float32)
        if a.ndim < 2:
            continue  # scalar/vector params never quantize (rules table)
        amax = np.max(np.abs(a), axis=-1)
        n = int(np.sum(amax <= SCALE_FLOOR))
        out[name] = n
        total += n
    if total:
        get_registry().counter("quant_scale_clip").add(total)
    return out
