"""int8 KV cache: per-head symmetric quantization behind the kv_ops
injection point (ISSUE 11 tentpole, part 2).

Decode is bandwidth-bound — every decode step re-reads the whole KV
window, so KV bytes ARE the token latency. Storing K/V as int8 with a
per-(position, head) fp32 scale halves the bytes the attend streams
(int8 data + a D-times-smaller scale sidecar, ~6% overhead at D=64)
and, under paged KV, doubles how many tokens a fixed HBM budget holds —
compounding the paging capacity win (BENCH_paged_kv.json's mechanism).

Scheme: symmetric absmax. On every cache write the new K/V vectors are
quantized per head: scale = max|x| / 127 over the head dim, data =
round(x / scale) int8 — quantize-on-write means the cache NEVER holds a
bf16 copy, and re-quantization error never compounds (each position is
quantized exactly once, from the compute-dtype value the dense cache
would have stored). The attend dequantizes data * scale back to the
compute dtype; the reference path then reuses the dense
`_attend_cached` verbatim (CPU-testable — the attn_impl
parity-tolerance pattern: numerically close, not bitwise), and the TPU
kernels (`ops/pallas/flash_attention.decode_attention_int8`,
`ops/pallas/paged_attention.paged_attention_int8`) fuse the dequant
into the page/block DMA so HBM only ever moves int8.

Error budget (docs/PERFORMANCE.md): absmax-int8 rounding error per
element is <= scale/2 = amax/254, i.e. ~0.4% of the head's dynamic
range; softmax scores see the error pre-softmax where it perturbs
logits by O(||q|| * amax/254). The serve tests pin logits closeness
across GPT/Llama/Mixtral in both KV layouts rather than bit parity —
the same contract split as `attn_impl='pallas'`.

The cache pytree: a quantized cache half is a `QuantKV(data, scale)`
NamedTuple wherever the dense pools hold a bare array. Everything that
moves caches (`infer.decode._run_layers`, the engine's slot splices,
the paged COW copy) is tree-mapped, so ONE code path serves both
layouts — and donation/scan semantics are unchanged (NamedTuples are
pytrees).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from avenir_tpu.infer.decode import _attend_cached

# the symmetric int8 range; scale maps amax onto it exactly
Q_MAX = 127.0
# floor keeps an all-zero head (a fresh pool row) from a 0-divide;
# dequantizing its zeros still yields exact zeros
SCALE_FLOOR = 1e-8


class QuantKV(NamedTuple):
    """One quantized cache half. `data` int8, `scale` fp32 with the
    head dim reduced away — slab: data (L, B, T, H_kv, D) / scale
    (L, B, T, H_kv); paged: data (L, n_pages, ps, H_kv, D) / scale
    (L, n_pages, ps, H_kv)."""

    data: jax.Array
    scale: jax.Array


def quantize(x):
    """Per-head absmax int8: x (..., D) -> (int8 data, fp32 scale) with
    scale = max|x| / 127 over the last axis. Round-trip error per
    element is bounded by scale/2 (tests pin it)."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), SCALE_FLOOR) / Q_MAX
    data = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return data, scale


def dequantize(qkv, dtype):
    """QuantKV -> dense (..., D) in `dtype`."""
    return (qkv.data.astype(jnp.float32)
            * qkv.scale[..., None].astype(jnp.float32)).astype(dtype)


def init_quant_kv(shape):
    """Zeroed QuantKV for a dense-cache shape (..., H_kv, D)."""
    return QuantKV(jnp.zeros(shape, jnp.int8),
                   jnp.zeros(shape[:-1], jnp.float32))


def quant_slab_kv_ops(compute_dtype, attend_fn=None):
    """(write, attend) pair for `infer.decode._forward_cached` over a
    QUANTIZED slab layer cache — the int8 twin of the default
    `_write_cache`/`_attend_cached` pair, same position semantics
    (scalar prefill pos, (B,) per-row decode/verify pos, any T width).

    `attend_fn(q, kc, vc, q_pos)`, when given, replaces the
    dequant-gather for SINGLE-token queries (the Pallas int8 decode
    kernel); multi-token queries (prefill chunks, spec verify) always
    take the dequant + dense-attend reference path."""

    def write(kc, vc, k, v, pos):
        kd, ks = quantize(k)
        vd, vs = quantize(v)
        if getattr(pos, "ndim", 0) == 1:
            def row(kc_r, vc_r, kd_r, ks_r, vd_r, vs_r, p):
                upd = jax.lax.dynamic_update_slice
                return (QuantKV(upd(kc_r.data, kd_r, (p, 0, 0)),
                                upd(kc_r.scale, ks_r, (p, 0))),
                        QuantKV(upd(vc_r.data, vd_r, (p, 0, 0)),
                                upd(vc_r.scale, vs_r, (p, 0))))

            return jax.vmap(row)(kc, vc, kd, ks, vd, vs, pos)
        upd = jax.lax.dynamic_update_slice
        kc = QuantKV(upd(kc.data, kd, (0, pos, 0, 0)),
                     upd(kc.scale, ks, (0, pos, 0)))
        vc = QuantKV(upd(vc.data, vd, (0, pos, 0, 0)),
                     upd(vc.scale, vs, (0, pos, 0)))
        return kc, vc

    def attend(q, kc, vc, q_pos):
        if attend_fn is not None and q.shape[1] == 1:
            return attend_fn(q, kc, vc, q_pos)
        return _attend_cached(q, dequantize(kc, compute_dtype),
                              dequantize(vc, compute_dtype), q_pos)

    return write, attend
