"""Fused chunked lm-head + cross-entropy tail (ISSUE 3 tentpole).

The loss tail is the last big HBM sink in the train step: the reference
path materializes the full (B, T, V) logits — 3.3 GB of fp32 at the
GPT-2 bench config (16x1024x50257x4B) — writes it, reads it back for the
softmax, and saves it as the residual for the backward. But cross-entropy
only ever needs per-ROW statistics of the logits (the logsumexp and the
target logit), and the same online-softmax recurrence that powers the
Pallas flash attention applies verbatim to the vocabulary axis
(Liger-Kernel-style fused linear+CE, Hsu et al. 2024): stream the logits
in chunks, carry (running max m, running normalizer l) per row, and the
full logits array never exists in HBM in either pass.

Two interchangeable implementations behind ONE entry point
(`fused_cross_entropy`), selected by the models' `loss_impl` config knob
(plumbed exactly like `attn_impl`):

  - "blocked": pure XLA — `lax.scan` over T-chunks with `jax.checkpoint`
    around the chunk body, so the backward recomputes each chunk's
    logits instead of saving them (without the checkpoint the scan would
    stack per-chunk logits residuals and quietly rebuild the full
    (B, T, V) array). Works everywhere, composes with every mesh the
    same way the reference path does (plain jnp ops: vocab stays
    tensor-sharded inside each chunk and GSPMD inserts the psum over
    'tensor' for the row reductions — chunk over time, psum over
    tensor), and is the CPU-testable counterpart of the Pallas kernel.
  - "pallas": the TPU kernel (ops/pallas/fused_ce.py) — grid over
    (T-blocks, V-blocks), fp32 running max/normalizer in VMEM scratch,
    bf16 MXU matmuls, custom VJP emitting dx and the (tied) projection
    weight's gradient one block at a time.

  - "reference" resolves to the models' original
    full-logits + models/common.cross_entropy_loss path (the oracle).

Weight layout: `w_layout="cv"` takes the projection as (C, V) — the
Llama/Mixtral `lm_head.kernel` orientation; `w_layout="vc"` takes
(V, C) — the GPT tied `wte.embedding`. Both are consumed through
dot_general contraction dims, so neither family pays a transposed copy
of the (V, C)-sized weight, and the "vc" gradient lands directly in the
embedding's own layout (the tied-wte gradient contribution).
"""

import jax
import jax.numpy as jnp


# Default time-chunk: (B, t_chunk, V) fp32 is the largest live logits
# slab — 128 rows x 50304 vocab x 16 batch ~= 412 MB at the bench rung,
# an 8x cut vs the full tail, while each chunk's matmul still feeds the
# MXU (B*t_chunk) rows at a time.
_DEFAULT_T_CHUNK = 128

# One entry per TRACE of the fused tail (appends happen at trace time
# only, so len() counts retraces without touching jit internals) — the
# same ledger idiom as infer/decode. Tests pin that the chunked scan
# traces once per compiled train step, not once per step.
_trace_events = []


def trace_count():
    """Number of fused-loss-tail traces (== appearances in XLA compiles)."""
    return len(_trace_events)


def _tp_mesh_active():
    """True when the ambient mesh has a tensor axis > 1 — there the
    blocked tail keeps the vocab sharded while the pallas wrap would
    all-gather the full projection weight over 'tensor' every step
    (docs/PERFORMANCE.md "The loss tail")."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return False
    from avenir_tpu.parallel.partition import TP_AXIS

    return dict(mesh.shape).get(TP_AXIS, 1) > 1


def resolve_loss_impl(impl):
    """Resolve the config knob to the concrete impl that will run —
    mirrors ops.attention.resolve_attention_impl so the training loop's
    startup log can print the truth (a silent fallback must be visible).

    '' / None / 'reference' -> 'reference'; 'auto' -> 'pallas' on TPU
    when the kernel imports AND the mesh has no tensor axis > 1 (the
    pallas wrap replicates the weight over 'tensor' — on TP meshes
    'auto' picks 'blocked', which keeps the vocab sharded), else
    'blocked'. An explicit 'pallas' is honored anywhere (tests force it
    through interpret mode; a TP operator who accepts the all-gather
    can too)."""
    if impl in (None, "", "reference"):
        return "reference"
    if impl == "auto":
        from avenir_tpu.ops.attention import _on_tpu

        if _on_tpu() and not _tp_mesh_active():
            try:
                from avenir_tpu.ops.pallas import fused_ce  # noqa: F401

                return "pallas"
            except ImportError:
                return "blocked"
        return "blocked"
    assert impl in ("blocked", "pallas"), (
        f"unknown loss_impl {impl!r}; one of "
        "['reference', 'blocked', 'pallas', 'auto']"
    )
    return impl


def _logits_chunk(xc, w, w_layout):
    """(B, tc, C) @ w -> (B, tc, V) with fp32 MXU accumulation. The
    contraction dims consume either weight orientation in place — no
    transposed (V, C)-sized copy for either family."""
    eq = "btc,cv->btv" if w_layout == "cv" else "btc,vc->btv"
    return jnp.einsum(eq, xc, w, preferred_element_type=jnp.float32)


def _chunk_loss_terms(xc, w, yc, *, ignore_index, w_layout):
    """One chunk's (loss_sum, valid_count). Max-subtraction before the
    exp (shift-invariant, so stop_gradient keeps the VJP exact); invalid
    rows (ignore_index) contribute 0 to both terms."""
    z = _logits_chunk(xc, w, w_layout)
    m = jax.lax.stop_gradient(jnp.max(z, axis=-1, keepdims=True))
    z = z - m
    lse = jnp.log(jnp.sum(jnp.exp(z), axis=-1))
    valid = yc != ignore_index
    safe = jnp.where(valid, yc, 0)
    tgt = jnp.take_along_axis(z, safe[..., None], axis=-1)[..., 0]
    losses = jnp.where(valid, lse - tgt, 0.0)
    return losses.sum(), valid.sum()


def blocked_ce_terms(x, w, targets, *, ignore_index=-1, w_layout="cv",
                     t_chunk=0, w_dtype="compute"):
    """(loss_sum, valid_count) of the chunked tail — the un-normalized
    form the 1f1b pipeline runs per-MICRObatch at the last stage
    (parallel/pipeline.pipeline_1f1b_loss): callers own the division, so
    per-micro SUMS reduce to exactly the full-batch mean regardless of
    how the ignored positions fall across micros. Same chunking,
    jax.checkpoint and dtype discipline as the `blocked` impl of
    fused_cross_entropy (which is this divided through).

    `w_dtype='int8'` (the compute_dtype='int8' tail, ISSUE 15): the
    projection weight is straight-through fake-quantized ONCE, outside
    the chunk scan, with per-vocab-channel absmax scales over the
    contraction axis (ops/quant.py) — every chunk of the step's window
    consumes the same int8 grid (the delayed-scaling discipline), and
    plain autodiff through the STE reproduces exactly the gradient the
    pallas int8-stripe kernels hand-write. This blocked form is the
    CPU-testable oracle; the pallas twin is where HBM actually moves
    int8 stripes."""
    if w_dtype == "int8":
        from avenir_tpu.ops.quant import fake_quant

        w = fake_quant(w, 0 if w_layout == "cv" else 1)
    else:
        assert w_dtype == "compute", f"unknown w_dtype {w_dtype!r}"
    B, T, C = x.shape
    tc = min(t_chunk or _DEFAULT_T_CHUNK, T)
    nc = -(-T // tc)
    Tp = nc * tc
    if Tp != T:
        # non-divisible edge: pad with ignore_index rows (zero loss/grad)
        x = jnp.pad(x, ((0, 0), (0, Tp - T), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, Tp - T)),
                          constant_values=ignore_index)

    chunk = jax.checkpoint(
        lambda xc, yc: _chunk_loss_terms(
            xc, w, yc, ignore_index=ignore_index, w_layout=w_layout)
    )

    if nc == 1:
        # single-chunk tail: the scan would be a length-1 loop — call the
        # chunk directly (saves the scan wrapper; also what lets the
        # 1f1b per-micro tail run inside the legacy harness's
        # partial-auto regions, where scans trip the old partitioner)
        ls, nv = chunk(x, targets)
        return ls.astype(jnp.float32), nv.astype(jnp.int32)

    def body(carry, i):
        ls, nv = carry
        xc = jax.lax.dynamic_slice_in_dim(x, i * tc, tc, axis=1)
        yc = jax.lax.dynamic_slice_in_dim(targets, i * tc, tc, axis=1)
        l, v = chunk(xc, yc)
        return (ls + l, nv + v), None

    from avenir_tpu import compat

    manual = getattr(compat._manual_axes, "names", frozenset())
    if getattr(jax, "shard_map", None) is compat.shard_map and manual:
        # legacy harness, nested inside a manual region (the 1f1b tail):
        # when any NON-manual mesh axis is live the old SPMD partitioner
        # CHECK-aborts on scans in the partial-auto region (same gate as
        # pipeline._use_psum_hop, which unrolls its tick/layer scans for
        # exactly this reason) — unroll the chunk loop; nc is static and
        # the unrolled sum is the same sequential reduction bit-for-bit
        mesh = jax.sharding.get_abstract_mesh()
        auto = 1
        if mesh is not None and not mesh.empty:
            for name, sz in dict(mesh.shape).items():
                if name not in manual:
                    auto *= sz
        if auto > 1:
            carry = (jnp.float32(0.0), jnp.int32(0))
            for i in range(nc):
                carry, _ = body(carry, i)
            return carry

    (ls, nv), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), jnp.arange(nc)
    )
    return ls, nv


def _blocked_ce(x, w, targets, *, ignore_index, w_layout, t_chunk,
                w_dtype="compute"):
    """lax.scan over T-chunks; jax.checkpoint on the chunk body so the
    backward recomputes each chunk's logits (the scan would otherwise
    stack them into the full (B, T, V) as residuals). dx is scattered
    back chunk-by-chunk through the dynamic_slice transpose; dw
    accumulates across scan steps — neither pass holds more than one
    (B, t_chunk, V) slab."""
    ls, nv = blocked_ce_terms(x, w, targets, ignore_index=ignore_index,
                              w_layout=w_layout, t_chunk=t_chunk,
                              w_dtype=w_dtype)
    return ls / jnp.maximum(nv, 1).astype(jnp.float32)


def fused_cross_entropy(x, w, targets, *, ignore_index=-1, impl="blocked",
                        w_layout="cv", t_chunk=0, w_dtype="compute"):
    """Mean token cross-entropy of `x @ w` over non-ignored targets,
    without materializing the (B, T, V) logits.

      x: (B, T, C) final hidden states (compute dtype)
      w: lm-head projection — (C, V) for w_layout='cv' (Llama lm_head
         kernel), (V, C) for 'vc' (GPT tied wte embedding)
      targets: (B, T) int token ids; `ignore_index` rows are skipped

    Semantics match models/common.cross_entropy_loss(x @ w, targets)
    within fp32 tolerance (the fused paths accumulate logits in fp32
    where the reference round-trips them through the compute dtype).
    `impl` must already be resolved ('blocked' | 'pallas' | 'auto');
    'reference' is the callers' own full-logits branch, not ours.
    `w_dtype='int8'` (compute_dtype='int8'): weight-only quantization —
    blocked consumes the STE fake-quant grid (oracle), pallas streams
    real int8 stripes with fused dequant (ISSUE 15)."""
    impl = resolve_loss_impl(impl)
    assert impl in ("blocked", "pallas"), (
        "fused_cross_entropy handles the fused impls; the 'reference' "
        "path is the caller's full-logits branch"
    )
    assert w_layout in ("cv", "vc"), f"unknown w_layout {w_layout!r}"
    _trace_events.append((impl, x.shape, w.shape, w_dtype))
    if impl == "pallas":
        from avenir_tpu.ops.attention import _on_tpu
        from avenir_tpu.ops.pallas.fused_ce import fused_ce_pallas

        return fused_ce_pallas(
            x, w, targets, ignore_index=ignore_index, w_layout=w_layout,
            interpret=not _on_tpu(), w_dtype=w_dtype,
        )
    return _blocked_ce(x, w, targets, ignore_index=ignore_index,
                       w_layout=w_layout, t_chunk=t_chunk, w_dtype=w_dtype)
