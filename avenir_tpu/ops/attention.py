"""Causal self-attention op with Pallas/XLA dispatch (SURVEY.md §2b T6).

Public entry: `causal_attention(q, k, v, ...)` in (B, T, H, D) layout.

Implementations:
  - "xla": pure-jnp reference (fp32 softmax, fp32 matmul accumulation) —
    the semantic spec, matching torch `F.scaled_dot_product_attention`
    (model.py:91-97) at fp32. Runs anywhere; XLA fuses it decently.
  - "pallas": blockwise online-softmax flash attention compiled by Mosaic
    for TPU (avenir_tpu/ops/pallas/flash_attention.py).
  - "auto": pallas on TPU when shapes allow, else xla.

Dropout on attention probabilities is only supported on the xla path
(flash kernels and prob-dropout don't mix; the reference trains with
dropout=0.0 in every ladder config, BASELINE.json:7-11).
"""

import math

import jax
import jax.numpy as jnp

_NEG_INF = float("-inf")

# Mesh-axis conventions live in ONE place (parallel/partition.py): batch
# shards over the data-like axes, attention heads over the TP axis
# (c_attn is column-parallel, so heads land tensor-sharded).
from avenir_tpu.parallel.partition import (  # noqa: E402
    BATCH_AXES as _BATCH_AXES,
    TP_AXIS as _HEAD_AXIS,
)


def _on_tpu() -> bool:
    """True when jit traces will lower to TPU. Safe to call at trace time
    (reads the default backend, not the current trace)."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def causal_attention_reference(q, k, v, *, dropout_rate=0.0, deterministic=True,
                               dropout_rng=None, segment_ids=None):
    """Pure-jnp causal attention, (B, T, H, D) layout.

    Softmax and score accumulation in fp32 regardless of input dtype
    (bf16-safe); output cast back to q.dtype. `segment_ids` (B, T) optional:
    positions may only attend within their own segment (packed sequences).
    """
    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    if segment_ids is not None:
        seg = segment_ids[:, :, None] == segment_ids[:, None, :]  # (B, T, T)
        mask = mask[None, :, :] & seg
        mask = mask[:, None, :, :]  # (B, 1, T, T)
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    probs = probs.astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _causal_attention_reference_bhtd(q, k, v, **kw):
    """Head-major entry to the single reference implementation: q/k/v
    (B, H, T, D), output (B, H, T, D). The xla path is never the hot path
    (pallas is, and it is natively head-major), so transposing around the
    one reference body beats maintaining a twin of its numerically
    sensitive fp32 softmax/mask/dropout logic."""
    out = causal_attention_reference(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), **kw,
    )
    return out.transpose(0, 2, 1, 3)


def resolve_attention_impl(impl, *, use_dropout=False, segment_ids=None):
    """Resolve 'auto' to the concrete impl that will run ('pallas' or
    'xla'). Used by the dispatch below AND by the training loop's startup
    log, so a silent fallback to the slow path is always visible."""
    if impl != "auto":
        return impl
    if _on_tpu() and not use_dropout and segment_ids is None:
        try:  # fall back gracefully while/where the kernel is unavailable
            from avenir_tpu.ops.pallas import flash_attention  # noqa: F401

            return "pallas"
        except ImportError:
            return "xla"
    return "xla"


def _flash_shard_specs(layout, q_shape, h, h_kv):
    """(PartitionSpec, axis_names) for running the Pallas flash kernel
    under SPMD — the spec is shared by q/k/v/out (head entries name the
    same axis for H and H_kv dims) — or None when no wrap is needed.

    GSPMD has NO partitioning rule for the pallas_call custom call: on an
    8-device data:2,fsdp:2,tensor:2 mesh the jitted kernel compiles with
    33 all-gathers and returns a fully REPLICATED output (measured on the
    CPU harness, VERDICT r3 item 1) — every operand is dragged to every
    device. Flash attention is embarrassingly parallel over batch and
    heads, so the dispatcher wraps the kernel in jax.shard_map over
    whichever of those mesh axes exist and divide the dims.

    The wrap names ALL free (non-Manual) mesh axes — never the axes an
    enclosing shard_map (the GPipe 'pipe' region) is already manual
    over. Naming a Manual axis whose in_spec entry is absent claims the
    inputs are replicated over it, and the shard_map transpose then
    psums cotangents over that axis — stage activations are NOT
    replicated over 'pipe', so that psum silently corrupted every
    upstream gradient (measured 2.8e-3; the r4 release refused to nest
    at all and ran the kernel replicated inside pipeline meshes). See
    partition.free_axis_names for the rule; naming all FREE axes (not
    just the ones in the spec) also keeps GSPMD from re-entering the
    body and replicating the kernel over an unnamed free axis.
    check_vma=True would catch this class statically but cannot run the
    interpret-mode kernels (vma mismatch inside pallas's hlo_interpreter
    — upstream limitation, re-verified on jax 0.9)."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return None
    from avenir_tpu.parallel.partition import free_axis_names

    names = free_axis_names(mesh)
    sizes = dict(mesh.shape)
    free = {n: s for n, s in sizes.items() if n in names and s > 1}
    if not free:
        return None
    b = q_shape[0]
    batch_axes = [a for a in _BATCH_AXES if a in free]
    while batch_axes and b % math.prod(free[a] for a in batch_axes):
        batch_axes.pop()  # drop innermost-listed first (expert, then fsdp)
    t = free.get(_HEAD_AXIS, 1)
    # both H and H_kv must divide: shard i then holds q heads
    # [i·H/t, (i+1)·H/t) and kv heads [i·H_kv/t, (i+1)·H_kv/t), and the
    # kernels' local group map h // (H/H_kv) coincides with the global one
    head = _HEAD_AXIS if t > 1 and h % t == 0 and h_kv % t == 0 else None
    if not batch_axes and head is None:
        return None
    b_entry = tuple(batch_axes) if batch_axes else None
    from jax.sharding import PartitionSpec as P

    if layout == "bhtd":
        return P(b_entry, head, None, None), names
    return P(b_entry, None, head, None), names


def causal_attention(q, k, v, *, dropout_rate=0.0, deterministic=True,
                     dropout_rng=None, impl="auto", segment_ids=None,
                     layout="bthd"):
    """Causal multi-head attention. layout='bthd' (default): q is
    (B, T, H, D); k, v are (B, T, H_kv, D) with H_kv | H (GQA).
    layout='bhtd': head-major — q (B, H, T, D), k/v (B, H_kv, T, D),
    output (B, H, T, D). Head-major is the pallas kernels' native layout:
    models that project straight into it (einsum 'btc,chd->bhtd', the
    transpose riding the matmul epilogue) skip the standalone
    (B,T,H,D)<->(B,H,T,D) copies around the kernel (VERDICT r2 item 1).

    GQA head sharing is impl-specific: the pallas kernels index the shared
    kv head in their BlockSpec index maps, the ulysses path all-to-alls
    unrepeated KV to the local kernel, and the ring rotates H_kv-sized
    stripes with grouped-einsum block kernels (K/V never repeated on any
    of the three — no 4x HBM/VMEM/comm tax at Llama-3's 32:8); only the
    xla reference path repeats explicitly (XLA fuses the broadcast into
    the einsum)."""
    assert layout in ("bthd", "bhtd"), f"unknown layout {layout!r}"
    h_axis = 1 if layout == "bhtd" else 2
    assert q.shape[h_axis] % k.shape[h_axis] == 0, (
        f"GQA requires n_head % n_kv_head == 0, got "
        f"{q.shape[h_axis]} % {k.shape[h_axis]}"
    )

    use_dropout = dropout_rate > 0.0 and not deterministic
    impl = resolve_attention_impl(impl, use_dropout=use_dropout,
                                  segment_ids=segment_ids)
    if (impl not in ("pallas", "ulysses", "ring")
            and q.shape[h_axis] != k.shape[h_axis]):
        rep = q.shape[h_axis] // k.shape[h_axis]
        k = jnp.repeat(k, rep, axis=h_axis)
        v = jnp.repeat(v, rep, axis=h_axis)
    if impl in ("ring", "ulysses"):
        # context parallelism: sequence sharded over the 'context' mesh
        # axis — 'ring' rotates KV via ppermute (parallel/ring_attention.py),
        # 'ulysses' re-shards heads via all-to-all (parallel/ulysses.py);
        # tradeoffs in the ulysses module docstring
        assert not use_dropout, f"{impl} attention does not support attn dropout"
        assert segment_ids is None, f"{impl} attention does not take segment_ids"
        if impl == "ring":
            from avenir_tpu.parallel.ring_attention import (
                ring_causal_attention as cp_attention,
            )
        else:
            from avenir_tpu.parallel.ulysses import (
                ulysses_causal_attention as cp_attention,
            )

        if layout == "bhtd":
            out = cp_attention(q.transpose(0, 2, 1, 3),
                               k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3))
            return out.transpose(0, 2, 1, 3)
        return cp_attention(q, k, v)
    if impl == "pallas":
        assert not use_dropout, "pallas flash attention does not support attn dropout"
        assert segment_ids is None, "pallas flash attention does not take segment_ids"
        from avenir_tpu.ops.pallas.flash_attention import flash_attention

        # Mosaic only lowers on TPU; everywhere else (the 8-CPU test
        # harness, the driver's virtual-device dryrun) the kernel runs in
        # interpret mode — same trace, emulated execution.
        interpret = not _on_tpu()
        sn = _flash_shard_specs(layout, q.shape, q.shape[h_axis],
                                k.shape[h_axis])
        if sn is not None:
            spec, names = sn
            body = lambda ql, kl, vl: flash_attention(
                ql, kl, vl, causal=True, layout=layout, interpret=interpret
            )
            return jax.shard_map(
                body, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False, axis_names=names,
            )(q, k, v)
        return flash_attention(q, k, v, causal=True, layout=layout,
                               interpret=interpret)
    if impl == "jax_ref":
        # upstream jax.experimental TPU flash kernel — calibration yardstick
        # for ours (`python bench.py --attn=jax_ref`), not a product path
        assert not use_dropout, "jax_ref flash attention does not support attn dropout"
        assert segment_ids is None, "jax_ref path does not take segment_ids"
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash,
        )

        sc = 1.0 / math.sqrt(q.shape[-1])
        if layout == "bhtd":
            return jax_flash(q, k, v, causal=True, sm_scale=sc)
        out = jax_flash(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                        v.transpose(0, 2, 1, 3), causal=True, sm_scale=sc)
        return out.transpose(0, 2, 1, 3)
    assert impl == "xla", f"unknown attention impl {impl!r}"
    if layout == "bhtd":
        return _causal_attention_reference_bhtd(
            q, k, v, dropout_rate=dropout_rate, deterministic=deterministic,
            dropout_rng=dropout_rng, segment_ids=segment_ids,
        )
    return causal_attention_reference(
        q, k, v, dropout_rate=dropout_rate, deterministic=deterministic,
        dropout_rng=dropout_rng, segment_ids=segment_ids,
    )
