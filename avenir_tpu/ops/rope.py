"""Rotary position embeddings (SURVEY.md §2b T6, for Llama-3 —
BASELINE.json:10).

Llama-style "split halves" RoPE: the head dim is split into two halves that
form the (real, imag) parts of complex rotation. This matches the HF/Llama
reference convention (`rotate_half`), which the checkpoint bridge relies on.
"""

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_t: int, theta: float = 10000.0,
                     dtype=jnp.float32):
    """Precompute (cos, sin) tables of shape (max_t, head_dim // 2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_t, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (max_t, head_dim/2)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope_reference(x, cos, sin, positions=None, layout="bthd"):
    """x: (B, T, H, D) for layout='bthd', (B, H, T, D) for 'bhtd';
    cos/sin: (max_t, D/2). Rotates in fp32."""
    if layout == "bhtd":
        T = x.shape[2]
        if positions is None:
            c = cos[:T][None, None, :, :]  # (1, 1, T, D/2)
            s = sin[:T][None, None, :, :]
        else:
            c = cos[positions][:, None, :, :]  # positions: (B, T)
            s = sin[positions][:, None, :, :]
    else:
        T = x.shape[1]
        if positions is None:
            c = cos[:T][None, :, None, :]  # (1, T, 1, D/2)
            s = sin[:T][None, :, None, :]
        else:
            c = cos[positions][:, :, None, :]
            s = sin[positions][:, :, None, :]
    orig = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(orig)


def apply_rope(x, cos, sin, positions=None, layout="bthd"):
    """Apply rotary embeddings. Measured (tools/bench_act.py, BASELINE.md
    "silu / RoPE on the VPU" table): rope on q+k costs 1.1% of a 12-layer
    Llama-8B attention chain fwd+bwd on v5e (1.6ms/139ms) — XLA fuses the
    standalone form fine, and only fusing INTO the flash kernel's q/k load
    path could recover that ~0.4%-of-step tax, so there is deliberately no
    pallas variant here."""
    return apply_rope_reference(x, cos, sin, positions=positions,
                                layout=layout)
