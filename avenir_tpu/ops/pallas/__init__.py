"""avenir_tpu.ops.pallas — Mosaic/TPU kernels for the hot path
(SURVEY.md §2b T6; BASELINE.json:5 mandates Pallas for the fused
attention + AdamW hot path).

Every kernel has a pure-jnp oracle in avenir_tpu/ops/*.py; tests run the
kernels in interpret mode on CPU against those oracles (SURVEY.md §4).
"""

from avenir_tpu.ops.pallas.flash_attention import flash_attention
from avenir_tpu.ops.pallas.rmsnorm import rmsnorm_pallas
