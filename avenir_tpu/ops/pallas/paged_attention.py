"""Paged decode attention for TPU (ISSUE 9): one query token per
sequence attending a page-table-indirected KV cache.

The serve engine's paged pool (serve/pages.py) keeps KV in a fixed pool
of (page_size,) token blocks; a sequence's cache is whichever pages its
table row names. The reference implementation gathers the table's pages
into a contiguous (B, P*page_size, H_kv, D) view and runs the dense
masked attention — exact, CPU-testable, but the gather materializes the
whole padded window in HBM every decode step. This kernel is the
vLLM-PagedAttention shape of the same computation, built on scalar
prefetch:

  - the page table and per-row lengths ride as SCALAR-PREFETCH
    operands, so each grid step's BlockSpec index_map dereferences
    `tables[b, p]` and DMAs exactly that physical page HBM->VMEM —
    the indirection costs an SMEM read, not a gather;
  - grid (B, H_kv, P) with the page dim innermost ("arbitrary"):
    online-softmax statistics (m, l, acc) carry across a row's page
    steps in fp32 VMEM scratch, Mosaic double-buffers the page DMAs;
  - pages past a row's length skip ALL compute via pl.when (the DMA
    still lands — bandwidth on a dead page is cheaper than a pipeline
    bubble); the partial last page masks positions >= length;
  - GQA: the G = H // H_kv query heads sharing a kv head are one
    (G, D) block, so K/V are read once per kv head — never repeated.

Numerics: online softmax in fp32, like ops/pallas/flash_attention.py —
numerically equivalent to the reference, NOT bitwise (the engine's
bit-parity contract is pinned on the reference path; this kernel has
its own closeness tests, the same contract split as `attn_impl`).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref,
                         o_ref, acc_ref, m_ref, l_ref, *, page_size):
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)
    ps = page_size

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lengths_ref[b]

    @pl.when(p * ps < length)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)        # (G, D)
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # (ps, D)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        d = q.shape[-1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (d ** -0.5)                            # (G, ps)
        k_pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(pexp, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when(p == n_p - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_decode_kernel_int8(tables_ref, lengths_ref, q_ref, k_ref,
                              ks_ref, v_ref, vs_ref, o_ref, acc_ref,
                              m_ref, l_ref, *, page_size):
    """int8 twin of `_paged_decode_kernel` (ISSUE 11): K/V pages arrive
    as int8 with a per-(position, head) fp32 scale page riding beside
    them. The dequant (data * scale) happens HERE, in VMEM, after the
    DMA — so HBM only ever moves int8 pages, which is the entire point:
    decode is bandwidth-bound and the page stream just halved."""
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)
    ps = page_size

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lengths_ref[b]

    @pl.when(p * ps < length)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)        # (G, D)
        k = (k_ref[0, :, 0, :].astype(jnp.float32)
             * ks_ref[0, :, 0][:, None])           # (ps, D) dequant
        v = (v_ref[0, :, 0, :].astype(jnp.float32)
             * vs_ref[0, :, 0][:, None])
        d = q.shape[-1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (d ** -0.5)                            # (G, ps)
        k_pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(pexp, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when(p == n_p - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention_int8(q, k_data, k_scale, v_data, v_scale, tables,
                         lengths, *, interpret=False):
    """`paged_attention` over an int8 page pool: k_data/v_data
    (n_pages, page_size, H_kv, D) int8, k_scale/v_scale (n_pages,
    page_size, H_kv) fp32 (ops/kv_quant absmax layout). Same grid,
    masking and online-softmax as the bf16 kernel; same numerics
    contract (close to the dequant reference, not bitwise)."""
    B, H, D = q.shape
    n_pages, ps, h_kv, _ = k_data.shape
    P = tables.shape[1]
    assert tables.shape == (B, P) and lengths.shape == (B,)
    assert H % h_kv == 0, (H, h_kv)
    G = H // h_kv
    qg = q.reshape(B, h_kv, G, D)
    grid = (B, h_kv, P)

    def q_index(b, h, p, tables_ref, lengths_ref):
        return (b, h, 0, 0)

    def kv_index(b, h, p, tables_ref, lengths_ref):
        return (tables_ref[b, p], 0, h, 0)

    def scale_index(b, h, p, tables_ref, lengths_ref):
        return (tables_ref[b, p], 0, h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, G, D), q_index),
            pl.BlockSpec((1, ps, 1, D), kv_index),
            pl.BlockSpec((1, ps, 1), scale_index),
            pl.BlockSpec((1, ps, 1, D), kv_index),
            pl.BlockSpec((1, ps, 1), scale_index),
        ],
        out_specs=pl.BlockSpec((1, 1, G, D), q_index),
        scratch_shapes=[
            pltpu.VMEM((G, D), jnp.float32),    # acc
            pltpu.VMEM((G, 128), jnp.float32),  # m (col 0; lane-tiled)
            pltpu.VMEM((G, 128), jnp.float32),  # l
        ],
    )
    try:
        params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except (AttributeError, TypeError):
        params = pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel_int8, page_size=ps),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h_kv, G, D), q.dtype),
        compiler_params=params,
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qg, k_data, k_scale.astype(jnp.float32),
      v_data, v_scale.astype(jnp.float32))
    return out.reshape(B, H, D)


def paged_attention(q, k_pages, v_pages, tables, lengths, *,
                    interpret=False):
    """q: (B, H, D) single decode token per row; k_pages/v_pages:
    (n_pages, page_size, H_kv, D); tables: (B, P) int32 logical->
    physical page map; lengths: (B,) int32 attendable positions per row
    (the row's current pos + 1 — its own just-written token included).
    Returns (B, H, D) in q's dtype. Rows whose table entries past
    ceil(length/page_size) are garbage are safe: those pages are never
    attended (compute-skipped and masked)."""
    B, H, D = q.shape
    n_pages, ps, h_kv, _ = k_pages.shape
    P = tables.shape[1]
    assert tables.shape == (B, P) and lengths.shape == (B,)
    assert H % h_kv == 0, (H, h_kv)
    G = H // h_kv
    qg = q.reshape(B, h_kv, G, D)
    # physical page indices must stay in range for the BlockSpec DMA:
    # pad/garbage table entries are CLAMPED host-side by the caller's
    # contract (serve tables only hold real page ids; 0-padded)
    grid = (B, h_kv, P)

    def q_index(b, h, p, tables_ref, lengths_ref):
        return (b, h, 0, 0)

    def kv_index(b, h, p, tables_ref, lengths_ref):
        return (tables_ref[b, p], 0, h, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, G, D), q_index),
            pl.BlockSpec((1, ps, 1, D), kv_index),
            pl.BlockSpec((1, ps, 1, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, G, D), q_index),
        scratch_shapes=[
            pltpu.VMEM((G, D), jnp.float32),    # acc
            pltpu.VMEM((G, 128), jnp.float32),  # m (col 0; lane-tiled)
            pltpu.VMEM((G, 128), jnp.float32),  # l
        ],
    )
    try:
        params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except (AttributeError, TypeError):
        params = pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page_size=ps),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h_kv, G, D), q.dtype),
        compiler_params=params,
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(B, H, D)
