"""Blockwise causal flash attention for TPU (fwd + bwd), SURVEY.md §2b T6.

Design (FlashAttention-2 recurrence on the TPU memory hierarchy — the
largest in-repo kernel, SURVEY.md §7 "hard parts"):

  - public layout (B, T, H, D) — transposed to (B, H, T, D) so each block's
    trailing dims (T, D) map onto (sublane, lane) tiles
  - KV STREAMING VIA THE GRID: grid (B, H, nq, nk) with the kv index as the
    innermost ("arbitrary") dimension. Each kv block arrives as its own
    BlockSpec slice, so Mosaic double-buffers the HBM→VMEM DMAs and every
    in-kernel index is static. (The round-1 kernel held the whole KV
    sequence in one VMEM block and walked it with `pl.ds` inside a
    `fori_loop`; measured on v5e that serialized ~2x slower than this
    form and capped VMEM at long T. Measured in BASELINE.md.)
  - online softmax in fp32 carried in VMEM scratch across the kv grid steps
    (running max m, normalizer l, accumulator acc); MXU matmuls take bf16
    inputs with preferred_element_type=fp32
  - causal BLOCK SKIPPING: kv grid steps above the diagonal skip all
    compute via `pl.when` (the DMA still lands, bandwidth is cheap; the
    MXU/VPU work — the expensive part — is halved). The diagonal block
    applies a broadcasted-iota mask.
  - backward, fast path: ONE fused kernel gridded (B*H, nq) — each (q
    block × full KV) tile computes s/p/dp/ds once, emits dq per q block
    and accumulates dk/dv in fp32 VMEM scratch flushed on the last q step
    (no atomics; measured ~9ms/step FASTER than the split dq/dkv pair at
    GPT-2 shapes — BASELINE.md). Softmax stats (m, l) and delta are
    recomputed/derived in-kernel, so no (T, 1) side arrays ever hit HBM
    (they are tile-padded 128× there; A/B-measured +1.2%). Blocked path
    (long T): two kernels, dq gridded (B, H, nq, nk), dk/dv gridded
    (B, H, nk, nq), each recomputing p from the saved logsumexp (which
    the blocked fwd still emits)
  - padding: sequences are padded to the block size; padded kv columns are
    masked with -1e30 (finite, so fully-padded q rows stay NaN-free and
    are sliced away by the wrapper)

Semantics match ops.attention.causal_attention_reference (the oracle used
by tests/test_pallas_kernels.py).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# m/l scratch rows keep a full 128-wide lane tile (column 0 is the value);
# a (bq, 1) scratch would be padded to this anyway, the explicit shape keeps
# the loads/stores layout-friendly.
_LANES = 128
# Sequences up to this padded length take the single-KV-block fast path:
# softmax computed directly (no online-softmax scratch carry) and the
# backward fully fused. Measured on v5e the scratch carry costs ~2x on the
# fwd kernel (BASELINE.md attention table). Ceiling set by the fused
# backward's VMEM live set — ~3 concurrent (block_q, T) fp32 blocks
# (p/dp/ds) + (T, D) fp32 dk/dv scratch ≈ 26MB at 4096, verified compiling
# and running on chip under the 64MB scoped limit; 8192 would brush the
# limit and is unmeasured, so longer sequences stream KV through the
# blocked online-softmax path.
_FAST_PATH_MAX_T = 4096


def _branch(pred, then_fn, else_fn):
    """Exactly one of the two branches runs per grid step (the else branch
    is the negation by construction — non-exclusive pairs unrepresentable)."""
    pl.when(pred)(then_fn)
    pl.when(jnp.logical_not(pred))(else_fn)


# Causal staircase: the fast-path kernels see the whole (padded) KV as one
# block, but a causal q block at row offset (i+1)*block_q never looks past
# that row — so each q grid step statically slices KV to its own staircase
# length and skips the dead MXU/VPU work above the diagonal, generalizing
# round-2's two-way halving. MEASURED on v5e (Llama-8B rung, T=4096,
# nq=8): finer staircases LOSE despite the lower work factor — 2 branches
# 27.6k tok/s (53.6% MFU), 4 branches 27.1k, 8 branches 24.4k; the
# unrolled branch bodies defeat Mosaic's cross-grid-step pipelining. The
# default therefore stays at the measured winner, halving (2); the env
# knob exists for re-sweeping on other chips.
_ENV_STAIRCASE = os.environ.get("AVENIR_STAIRCASE_BRANCHES", "2")
assert _ENV_STAIRCASE.lstrip("-").isdigit(), (
    f"AVENIR_STAIRCASE_BRANCHES must be an integer branch count, got "
    f"{_ENV_STAIRCASE!r}"
)
# <1 would emit no pl.when branch at all -> uninitialized output
_STAIRCASE_MAX_BRANCHES = max(1, int(_ENV_STAIRCASE))


def _staircase(i, nq, block_q, tp, body):
    """Run `body(kv_len)` with the static staircase length for q block `i`.
    Every branch is guarded by pl.when on the *runtime* block index; lengths
    are compile-time constants so all KV slices are static."""
    if nq <= 1 or tp % block_q != 0:
        body(tp)
        return
    n_branch = min(nq, _STAIRCASE_MAX_BRANCHES)
    # partition the nq q-blocks into n_branch contiguous groups; a group's
    # kv_len is the staircase length of its LAST member (safe overestimate)
    bounds = [((g + 1) * nq + n_branch - 1) // n_branch for g in range(n_branch)]
    for g, last_blk in enumerate(bounds):
        lo = bounds[g - 1] if g > 0 else 0
        kv_len = last_blk * block_q
        pred = i < last_blk if g == 0 else jnp.logical_and(
            i >= lo, i < last_blk)
        pl.when(pred)(functools.partial(body, kv_len))


def _mask_scores(s, q_off, k_off, causal, seq_len):
    """Apply padded-kv and (optionally) causal masking to a score block.
    `s` is (BQ, BK) fp32; q_off/k_off are the block's global row/col bases."""
    bq, bk = s.shape
    k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = k_pos < seq_len
    if causal:
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        mask = mask & (q_pos >= k_pos)
    return jnp.where(mask, s, NEG_INF)



def _compiler_params(n_parallel, n_arbitrary=1):
    """dimension_semantics hint: the leading grid dims are parallel, the
    trailing (streamed/accumulated) ones arbitrary. The scoped-vmem limit
    is raised from the 16MB default: the fast path's fp32 score block plus
    the fused-bwd dk/dv scratch legitimately use more at long T (v5e has
    128MB of VMEM; 64MB leaves ample headroom for double buffering)."""
    sem = ("parallel",) * n_parallel + ("arbitrary",) * n_arbitrary
    kw = dict(dimension_semantics=sem, vmem_limit_bytes=64 * 1024 * 1024)
    try:
        return pltpu.CompilerParams(**kw)
    except (AttributeError, TypeError):  # older jax spelling
        return pltpu.TPUCompilerParams(**kw)


# ---------------------------------------------------------------------------
# Fast path: the whole (padded) KV sequence is a single block per grid step,
# so the softmax is computed directly — no scratch carry, no pl.when. Grid is
# (B*H, nq) over a (B*H, T, D) view. Wins ~2x over the online-softmax form on
# v5e at GPT-2 sequence lengths (BASELINE.md).
# ---------------------------------------------------------------------------


def _fwd_kernel_fast(q_ref, k_ref, v_ref, o_ref, *, block_q,
                     causal, sm_scale, seq_len):
    i = pl.program_id(1)
    nq = pl.num_programs(1)
    q = q_ref[0]  # (BQ, D)
    tp = k_ref.shape[1]

    def _attend(kv_len):
        # static upper bound on the kv columns this q block can see
        k = k_ref[0, :kv_len, :]
        v = v_ref[0, :kv_len, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, kv_len)
        s = _mask_scores(s, i * block_q, 0, causal, seq_len)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0] = (o / l).astype(o_ref.dtype)

    # causal staircase: q block i only sees KV up to its own diagonal —
    # static-slice pl.when branches per q step (see _staircase)
    if causal:
        _staircase(i, nq, block_q, tp, _attend)
    else:
        _attend(tp)


def _dqkv_kernel_fast(q_ref, k_ref, v_ref, o_ref, do_ref,
                      dq_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                      *, block_q, causal, sm_scale, seq_len):
    """Fused single-pass backward for the fast path: one (q block × full
    KV) tile computes s/p/dp/ds ONCE and emits dq (per q block) plus
    dk/dv (accumulated in fp32 VMEM scratch, flushed on the last step).
    The split dq/dkv pair recomputed s and dp in each kernel — fusing
    saves ~2 of 7 matmuls and one exp pass per tile, and halves the
    kernel dispatches and input DMA traffic.
    The softmax statistics (m, l) are RECOMPUTED from the in-VMEM score
    block and delta = rowsum(do·o) from the o block — neither lse nor
    delta ever touches HBM (a (T, 1) fp32 side array is tile-padded 128x
    there: real write/read bandwidth; A/B-measured +1.2% ≈ 1.5ms/step at
    GPT-2 shapes, BASELINE.md).

    Grid is (B*H_kv, G, nq), G = n_head // n_kv_head: the G q-heads
    sharing a kv head run consecutively, so dk/dv sum over the whole
    group in scratch before ONE flush — GQA needs no KV repetition and
    no post-kernel reduction (MHA is the G=1 special case)."""
    j, i = pl.program_id(1), pl.program_id(2)
    ng, nq = pl.num_programs(1), pl.num_programs(2)
    tp = k_ref.shape[1]

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[0]
    delta = jnp.sum(
        do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (BQ, 1)

    def _grad(kv_len):
        k = k_ref[0, :kv_len, :]
        v = v_ref[0, :kv_len, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, kv_len)
        s = _mask_scores(s, i * block_q, 0, causal, seq_len)
        # same math as the forward softmax: p == exp(s - lse)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        dob = do_ref[0].astype(v.dtype)
        dp = jax.lax.dot_general(
            dob, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dq_ref[0] = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)
        dv_acc[:kv_len] += jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[:kv_len] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        _staircase(i, nq, block_q, tp, _grad)
    else:
        _grad(tp)

    @pl.when(jnp.logical_and(i == nq - 1, j == ng - 1))
    def _flush():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _make_fwd_fast(seq_len, n_head, n_kv_head):
    """Fast-path forward. GQA (n_kv_head < n_head): K/V stay at their
    H_kv head count — each q-head grid step maps to its shared kv head in
    the BlockSpec index fn, so repeated KV never exists in HBM or VMEM
    (VERDICT r2 item 2: the old jnp.repeat cost 4x KV traffic at
    Llama-3's 32:8)."""
    group = n_head // n_kv_head

    def kv_index(g, i):
        # flat q index g = b*H + h  →  flat kv index b*H_kv + h//group
        return ((g // n_head) * n_kv_head + (g % n_head) // group, 0, 0)

    def fwd(q, k, v, causal, sm_scale, block_q, interpret):
        BH, Tp, D = q.shape
        nq = Tp // block_q
        o = pl.pallas_call(
            functools.partial(
                _fwd_kernel_fast, block_q=block_q, causal=causal,
                sm_scale=sm_scale, seq_len=seq_len,
            ),
            grid=(BH, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda g, i: (g, i, 0)),
                pl.BlockSpec((1, Tp, D), kv_index),
                pl.BlockSpec((1, Tp, D), kv_index),
            ],
            out_specs=pl.BlockSpec((1, block_q, D), lambda g, i: (g, i, 0)),
            out_shape=jax.ShapeDtypeStruct((BH, Tp, D), q.dtype),
            compiler_params=_compiler_params(1),
            interpret=interpret,
        )(q, k, v)
        return o

    return fwd


def _make_bwd_fast(seq_len, n_head, n_kv_head):
    """Fused fast-path backward, grid (B*H_kv, G, nq). For GQA the dk/dv
    of a kv head accumulate across its G query heads in VMEM scratch (the
    G dim is 'arbitrary', so the revisited output block stays resident)."""
    group = n_head // n_kv_head

    def q_index(g, j, i):
        # kv-flat g = b*H_kv + kvh → q-flat b*H + kvh*group + j
        b, kvh = g // n_kv_head, g % n_kv_head
        return (b * n_head + kvh * group + j, i, 0)

    def kv_index(g, j, i):
        return (g, 0, 0)

    def bwd(q, k, v, o, do, causal, sm_scale, block_q, block_k,
            interpret):
        BH, Tp, D = q.shape
        BHkv = k.shape[0]
        nq = Tp // block_q

        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _dqkv_kernel_fast, block_q=block_q, causal=causal,
                sm_scale=sm_scale, seq_len=seq_len,
            ),
            grid=(BHkv, group, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, D), q_index),
                pl.BlockSpec((1, Tp, D), kv_index),
                pl.BlockSpec((1, Tp, D), kv_index),
                pl.BlockSpec((1, block_q, D), q_index),
                pl.BlockSpec((1, block_q, D), q_index),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), q_index),
                pl.BlockSpec((1, Tp, D), kv_index),
                pl.BlockSpec((1, Tp, D), kv_index),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((BH, Tp, D), q.dtype),
                jax.ShapeDtypeStruct((BHkv, Tp, D), k.dtype),
                jax.ShapeDtypeStruct((BHkv, Tp, D), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((Tp, D), jnp.float32),
                pltpu.VMEM((Tp, D), jnp.float32),
            ],
            compiler_params=_compiler_params(1, 2),
            interpret=interpret,
        )(q, k, v, o, do)
        return dq, dk, dv

    return bwd


# ---------------------------------------------------------------------------
# Blocked path (long sequences): KV streamed via the grid with an
# online-softmax scratch carry.
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, block_q, block_k, causal, sm_scale, seq_len):
    i, j = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: kv blocks fully above the diagonal contribute nothing;
    # when not causal every step runs unconditionally (no pl.when region)
    def _step():
        q = q_ref[0, 0]  # (BQ, D) input dtype
        k = k_ref[0, 0]  # (BK, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, BK)
        s = _mask_scores(s, i * block_q, j * block_k, causal, seq_len)

        m_prev = m_ref[:, :1]  # (BQ, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        pl.when(j * block_k < (i + 1) * block_q)(_step)
    else:
        _step()

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, :1] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc_ref, *, block_q, block_k, causal, sm_scale, seq_len):
    i, j = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # (BQ, 1)
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        s = _mask_scores(s, i * block_q, j * block_k, causal, seq_len)
        p = jnp.exp(s - lse)  # (BQ, BK), masked entries ~0
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(j * block_k < (i + 1) * block_q)(_step)
    else:
        _step()

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, block_q, block_k,
                causal, sm_scale, seq_len):
    # grid (B, H_kv, nk, G, nq): kv block outer, then the G query heads
    # sharing this kv head, then q blocks — dk/dv accumulate over (G, nq)
    j, jj, i = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    ng, nq = pl.num_programs(3), pl.num_programs(4)

    @pl.when(jnp.logical_and(jj == 0, i == 0))
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: q blocks strictly above this kv block see none of it
    def _step():
        q = q_ref[0, 0]  # (BQ, D)
        k = k_ref[0, 0]  # (BK, D)
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # (BQ, 1)
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        s = _mask_scores(s, i * block_q, j * block_k, causal, seq_len)
        p = jnp.exp(s - lse)  # (BQ, BK)
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when((i + 1) * block_q > j * block_k)(_step)
    else:
        _step()

    @pl.when(jnp.logical_and(jj == ng - 1, i == nq - 1))
    def _finish():
        dk_ref[0, 0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _pad_to(x, t_target, axis=2):
    pad = t_target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _make_fwd(seq_len, n_head, n_kv_head):
    group = n_head // n_kv_head

    def fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
        B, H, Tp, D = q.shape
        nq, nk = Tp // block_q, Tp // block_k
        kernel = functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
            sm_scale=sm_scale, seq_len=seq_len,
        )
        o, lse = pl.pallas_call(
            kernel,
            grid=(B, H, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // group, j, 0)),
                pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // group, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, Tp, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
            ],
            compiler_params=_compiler_params(3),
            interpret=interpret,
        )(q, k, v)
        return o, lse

    return fwd


def _make_bwd(seq_len, n_head, n_kv_head):
    group = n_head // n_kv_head

    def bwd(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
            interpret):
        B, H, Tp, D = q.shape
        H_kv = k.shape[1]
        nq, nk = Tp // block_q, Tp // block_k
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
            keepdims=True,
        )  # (B, H, Tp, 1)

        dq = pl.pallas_call(
            functools.partial(
                _dq_kernel, block_q=block_q, block_k=block_k, causal=causal,
                sm_scale=sm_scale, seq_len=seq_len,
            ),
            grid=(B, H, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // group, j, 0)),
                pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, i, j: (b, h // group, j, 0)),
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            compiler_params=_compiler_params(3),
            interpret=interpret,
        )(q, k, v, do, lse, delta)

        # grid (B, H_kv, nk, G, nq): dk/dv of one kv block accumulate over
        # the G sharing query heads AND the q blocks before one flush
        qh = lambda b, g, j, jj, i: (b, g * group + jj, i, 0)
        kvh = lambda b, g, j, jj, i: (b, g, j, 0)
        dk, dv = pl.pallas_call(
            functools.partial(
                _dkv_kernel, block_q=block_q, block_k=block_k, causal=causal,
                sm_scale=sm_scale, seq_len=seq_len,
            ),
            grid=(B, H_kv, nk, group, nq),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D), qh),
                pl.BlockSpec((1, 1, block_k, D), kvh),
                pl.BlockSpec((1, 1, block_k, D), kvh),
                pl.BlockSpec((1, 1, block_q, D), qh),
                pl.BlockSpec((1, 1, block_q, 1), qh),
                pl.BlockSpec((1, 1, block_q, 1), qh),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_k, D), kvh),
                pl.BlockSpec((1, 1, block_k, D), kvh),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H_kv, Tp, D), k.dtype),
                jax.ShapeDtypeStruct((B, H_kv, Tp, D), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
            compiler_params=_compiler_params(2, 3),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    return bwd


@functools.lru_cache(maxsize=64)
def _build_flash_fast(seq_len, causal, sm_scale, block_q, block_k,
                      interpret, n_head=1, n_kv_head=1, block_q_bwd=None):
    """Fast-path custom_vjp: q on a (B*H, Tp, D) view, k/v on
    (B*H_kv, Tp, D) (GQA heads shared via index maps, never repeated).
    The fwd and fused-bwd kernels take independent q block sizes (the
    bwd's working set per q step is ~3x the fwd's, so its sweep optimum
    differs — BASELINE.md block table)."""
    fwd_impl = _make_fwd_fast(seq_len, n_head, n_kv_head)
    bwd_impl = _make_bwd_fast(seq_len, n_head, n_kv_head)
    if block_q_bwd is None:
        block_q_bwd = block_q

    @jax.custom_vjp
    def f(q, k, v):
        return fwd_impl(q, k, v, causal, sm_scale, block_q, interpret)

    def f_fwd(q, k, v):
        o = fwd_impl(q, k, v, causal, sm_scale, block_q, interpret)
        return o, (q, k, v, o)

    def f_bwd(res, do):
        q, k, v, o = res
        return bwd_impl(q, k, v, o, do, causal, sm_scale, block_q_bwd,
                        block_k, interpret)

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.lru_cache(maxsize=64)
def _build_flash(seq_len, causal, sm_scale, block_q, block_k, interpret,
                 n_head=1, n_kv_head=1):
    """One custom_vjp per static config (lru so jit retrace reuses it)."""
    fwd_impl = _make_fwd(seq_len, n_head, n_kv_head)
    bwd_impl = _make_bwd(seq_len, n_head, n_kv_head)

    @jax.custom_vjp
    def f(q, k, v):
        o, _ = fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                        interpret)
        return o

    def f_fwd(q, k, v):
        o, lse = fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
        return o, (q, k, v, o, lse)

    def f_bwd(res, do):
        q, k, v, o, lse = res
        return bwd_impl(q, k, v, o, lse, do, causal, sm_scale, block_q,
                        block_k, interpret)

    f.defvjp(f_fwd, f_bwd)
    return f


# Default (block_q, block_k, block_q_bwd); overridable via
# AVENIR_FLASH_BLOCKS="bq,bk,bqb" for sweeps (tools/bench_sweep.py).
# Values are the v5e real-train-step sweep winners at GPT shapes (D=64);
# when the env is NOT set, fast-path shapes with D >= 128 get q blocks of
# 256 instead — the Llama-rung sweep winner (D=128 tiles half as many q
# rows per VMEM byte; 256,1024,256 measured 29.1k tok/s vs 28.1k at the
# GPT defaults, BASELINE.md "Llama-shape block sweep").
_ENV_BLOCKS = os.environ.get("AVENIR_FLASH_BLOCKS") or None
_DEFAULT_BLOCKS = tuple(
    int(x) for x in (_ENV_BLOCKS or "512,1024,512").split(",")
)
assert len(_DEFAULT_BLOCKS) == 3, (
    f"AVENIR_FLASH_BLOCKS must be 'block_q,block_k,block_q_bwd', got "
    f"{_ENV_BLOCKS!r}"
)


def flash_attention(q, k, v, *, causal=True, sm_scale=None, block_q=None,
                    block_k=None, block_q_bwd=None, interpret=False,
                    layout="bthd"):
    """Flash attention. layout='bthd' (default): q (B, T, H, D), k/v
    (B, T, H_kv, D) — transposed to head-major around the kernels.
    layout='bhtd': q (B, H, T, D), k/v (B, H_kv, T, D), output head-major
    too — the kernels' native layout, no wrapper transposes (callers that
    project directly into it skip the layout copies; VERDICT r2 item 1).
    GQA is handled INSIDE the kernels: each q-head grid step maps to its
    shared kv head via the BlockSpec index fn (h // (H/H_kv)), and the
    fused backward sums a kv head's dk/dv over its query group in VMEM
    scratch — K/V are never repeated, so HBM traffic and VMEM footprint
    stay at the H_kv size (4x smaller at Llama-3's 32:8; VERDICT r2
    item 2).

    Sequences with padded length <= _FAST_PATH_MAX_T dispatch to the
    single-KV-block kernels; longer ones stream KV blocks through the grid
    with the online-softmax carry. Default block sizes are D-adaptive v5e
    sweep winners: 512/1024/512 at GPT shapes (D=64), 256-row q blocks
    (fwd + bwd) for fast-path shapes with D >= 128 (the Llama-rung
    winner, BASELINE.md "Llama-shape block sweep"); explicit args or
    AVENIR_FLASH_BLOCKS override. All clamp to the padded sequence.
    `block_q_bwd` sizes the fused backward's q blocks independently
    (fast path only; the blocked path shares block_q).
    """
    assert layout in ("bthd", "bhtd"), f"unknown layout {layout!r}"
    if layout == "bhtd":
        B, H, T, D = q.shape
        H_kv = k.shape[1]
    else:
        B, T, H, D = q.shape
        H_kv = k.shape[2]
    # D-adaptive q blocks on the fast path (see _DEFAULT_BLOCKS note); an
    # explicit arg or the env override always wins
    wide_fast = (_ENV_BLOCKS is None and D >= 128
                 and T <= _FAST_PATH_MAX_T)
    if block_q_bwd is None:
        # an explicit block_q governs the backward too (the old contract);
        # only the all-defaults call takes the swept bwd size
        if block_q is not None:
            block_q_bwd = block_q
        else:
            block_q_bwd = 256 if wide_fast else _DEFAULT_BLOCKS[2]
    if block_q is None:
        block_q = 256 if wide_fast else _DEFAULT_BLOCKS[0]
    if block_k is None:
        block_k = _DEFAULT_BLOCKS[1]
    assert H % H_kv == 0, f"n_head {H} not divisible by n_kv_head {H_kv}"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    # Clamp oversized blocks to the next power of two >= T (never to the raw
    # T: a non-power-of-two clamp breaks the mutual divisibility that the
    # grids assume — q rows would silently be dropped). Then pad T to a
    # multiple of both block sizes and fail loud if user-supplied blocks
    # can't tile it.
    t_pow2 = 1 << max(T - 1, 1).bit_length()
    block_q = min(block_q, t_pow2)
    block_k = min(block_k, t_pow2)
    block_q_bwd = min(block_q_bwd, t_pow2)
    step = math.lcm(block_q, block_k, block_q_bwd)
    Tp = -(-T // step) * step
    assert Tp % block_q == 0 and Tp % block_k == 0 and Tp % block_q_bwd == 0, (
        f"block_q={block_q}, block_k={block_k}, block_q_bwd={block_q_bwd} "
        f"cannot tile padded seq {Tp}"
    )

    if layout == "bhtd":
        qt, kt, vt = _pad_to(q, Tp), _pad_to(k, Tp), _pad_to(v, Tp)
    else:
        qt = _pad_to(q.transpose(0, 2, 1, 3), Tp)
        kt = _pad_to(k.transpose(0, 2, 1, 3), Tp)
        vt = _pad_to(v.transpose(0, 2, 1, 3), Tp)
    if Tp <= _FAST_PATH_MAX_T:
        f = _build_flash_fast(T, causal, float(sm_scale), block_q, block_k,
                              interpret, H, H_kv, block_q_bwd)
        o = f(qt.reshape(B * H, Tp, D), kt.reshape(B * H_kv, Tp, D),
              vt.reshape(B * H_kv, Tp, D))
        o = o.reshape(B, H, Tp, D)
    else:
        f = _build_flash(T, causal, float(sm_scale), block_q, block_k,
                         interpret, H, H_kv)
        o = f(qt, kt, vt)
    o = o[:, :, :T, :]
    return o if layout == "bhtd" else o.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# int8 single-token decode attention (ISSUE 11): the slab serve path
# ---------------------------------------------------------------------------


def _decode_int8_kernel(lengths_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                        o_ref, acc_ref, m_ref, l_ref, *, block_t):
    """One decode query (G grouped heads) against a row's int8 slab
    cache, streamed block_t tokens per grid step with the online-softmax
    carry. The dequant (data * per-(position, head) scale) happens in
    VMEM after the DMA, so the HBM read — the thing decode latency IS —
    moves int8: half the bytes of the bf16 slab per token."""
    b = pl.program_id(0)
    t = pl.program_id(2)
    n_t = pl.num_programs(2)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lengths_ref[b]

    @pl.when(t * block_t < length)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)            # (G, D)
        k = (k_ref[0, :, 0, :].astype(jnp.float32)
             * ks_ref[0, :, 0][:, None])               # (bt, D)
        v = (v_ref[0, :, 0, :].astype(jnp.float32)
             * vs_ref[0, :, 0][:, None])
        d = q.shape[-1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (d ** -0.5)                                # (G, bt)
        k_pos = t * block_t + jax.lax.broadcasted_iota(jnp.int32,
                                                       s.shape, 1)
        s = jnp.where(k_pos < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(pexp, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when(t == n_t - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def decode_attention_int8(q, k_data, k_scale, v_data, v_scale, lengths,
                          *, block_t=128, interpret=False):
    """Single-token decode attention over an int8 SLAB cache — the
    kv_dtype='int8' twin of the serve engine's `_attend_cached` decode
    read (the paged twin is paged_attention.paged_attention_int8).

    q: (B, H, D) one decode token per row; k_data/v_data: (B, T_max,
    H_kv, D) int8; k_scale/v_scale: (B, T_max, H_kv) fp32 (the
    ops/kv_quant absmax layout); lengths: (B,) attendable positions per
    row (pos + 1 — the just-written token included). Blocks past a
    row's length skip all compute; the partial block masks with
    NEG_INF. Numerics: fp32 online softmax, close to the dequant
    reference, not bitwise — the attn_impl contract split."""
    B, H, D = q.shape
    _, T, h_kv, _ = k_data.shape
    assert H % h_kv == 0, (H, h_kv)
    G = H // h_kv
    bt = min(block_t, 1 << max(T - 1, 1).bit_length())
    Tp = -(-T // bt) * bt
    if Tp != T:
        pad = [(0, 0), (0, Tp - T), (0, 0), (0, 0)]
        k_data = jnp.pad(k_data, pad)
        v_data = jnp.pad(v_data, pad)
        k_scale = jnp.pad(k_scale, pad[:-1])
        v_scale = jnp.pad(v_scale, pad[:-1])
    qg = q.reshape(B, h_kv, G, D)
    grid = (B, h_kv, Tp // bt)

    def q_index(b, h, t, lengths_ref):
        return (b, h, 0, 0)

    def kv_index(b, h, t, lengths_ref):
        return (b, t, h, 0)

    def scale_index(b, h, t, lengths_ref):
        return (b, t, h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, G, D), q_index),
            pl.BlockSpec((1, bt, 1, D), kv_index),
            pl.BlockSpec((1, bt, 1), scale_index),
            pl.BlockSpec((1, bt, 1, D), kv_index),
            pl.BlockSpec((1, bt, 1), scale_index),
        ],
        out_specs=pl.BlockSpec((1, 1, G, D), q_index),
        scratch_shapes=[
            pltpu.VMEM((G, D), jnp.float32),      # acc
            pltpu.VMEM((G, _LANES), jnp.float32),  # m (col 0)
            pltpu.VMEM((G, _LANES), jnp.float32),  # l
        ],
    )
    try:
        params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except (AttributeError, TypeError):
        params = pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_decode_int8_kernel, block_t=bt),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h_kv, G, D), q.dtype),
        compiler_params=params,
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, k_data,
      k_scale.astype(jnp.float32), v_data, v_scale.astype(jnp.float32))
    return out.reshape(B, H, D)
