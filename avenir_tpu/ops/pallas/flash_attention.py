"""Blockwise causal flash attention for TPU (fwd + bwd), SURVEY.md §2b T6.

Design (classic FlashAttention-2 shape, written for the TPU memory
hierarchy — this is the largest in-repo kernel, §7 "hard parts"):

  - public layout (B, T, H, D) — transposed to (B, H, T, D) so the block's
    trailing dims (T, D) map onto (sublane, lane) tiles
  - grid (B, H, T/block): each program owns one q (or kv) stripe in VMEM;
    the opposing sequence streams through `pl.ds` slices of a
    whole-sequence VMEM block
  - online softmax in fp32 carried through `lax.fori_loop` (running max m,
    normalizer l, accumulator acc); MXU matmuls take bf16 inputs with
    preferred_element_type=fp32
  - causal BLOCK SKIPPING: the kv loop stops at the diagonal, halving the
    work vs masked dense attention; within the diagonal block a
    broadcasted-iota mask applies
  - backward = two kernels (no atomics): dq gridded over q blocks, dk/dv
    gridded over kv blocks, both recomputing p from the saved logsumexp
  - padding: sequences are padded to the block size; padded kv columns are
    masked with -1e30 (finite, so fully-padded q rows stay NaN-free and
    are sliced away by the wrapper)

Semantics match ops.attention.causal_attention_reference (the oracle used
by tests/test_pallas_kernels.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_k,
                causal, sm_scale, seq_len):
    qi = pl.program_id(2)
    q = q_ref[0, 0]  # (BQ, D) input dtype
    kv_len = k_ref.shape[2]
    nk_total = kv_len // block_k
    if causal:
        # block skipping: only kv blocks touching the lower triangle
        nk = jnp.minimum(
            ((qi + 1) * block_q + block_k - 1) // block_k, nk_total
        )
    else:
        nk = nk_total

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]  # (BK, D)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, BK)
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)  # (BQ, 1)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_q, block_k, causal, sm_scale, seq_len):
    qi = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]  # (BQ, 1)
    delta = delta_ref[0, 0]
    kv_len = k_ref.shape[2]
    nk_total = kv_len // block_k
    nk = (
        jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k, nk_total)
        if causal else nk_total
    )
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # (BQ, BK), masked entries ~0
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dq = dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dq

    dq = jax.lax.fori_loop(
        0, nk, body, jnp.zeros((block_q, q.shape[1]), jnp.float32)
    )
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q, block_k, causal, sm_scale,
                seq_len):
    ki = pl.program_id(2)
    k = k_ref[0, 0]  # (BK, D)
    v = v_ref[0, 0]
    q_len = q_ref.shape[2]
    nq_total = q_len // block_q
    # causal: the first q block that can see this kv block
    i0 = (ki * block_k) // block_q if causal else 0
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), :]  # (BQ, 1)
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        mask = k_pos < seq_len
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # (BQ, BK)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    D = k.shape[1]
    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(i0, nq_total, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _pad_to(x, t_target, axis=2):
    pad = t_target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _make_fwd(seq_len):
    def fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
        B, H, Tp, D = q.shape
        nq = Tp // block_q
        kernel = functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
            sm_scale=sm_scale, seq_len=seq_len,
        )
        o, lse = pl.pallas_call(
            kernel,
            grid=(B, H, nq),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, Tp, D), lambda b, h, i: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, Tp, D), lambda b, h, i: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, Tp, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
        return o, lse

    return fwd


def _make_bwd(seq_len):
    def bwd(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
            interpret):
        B, H, Tp, D = q.shape
        nq, nk = Tp // block_q, Tp // block_k
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
            keepdims=True,
        )  # (B, H, Tp, 1)

        dq = pl.pallas_call(
            functools.partial(
                _dq_kernel, block_q=block_q, block_k=block_k, causal=causal,
                sm_scale=sm_scale, seq_len=seq_len,
            ),
            grid=(B, H, nq),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, Tp, D), lambda b, h, i: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, Tp, D), lambda b, h, i: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
            interpret=interpret,
        )(q, k, v, do, lse, delta)

        dk, dv = pl.pallas_call(
            functools.partial(
                _dkv_kernel, block_q=block_q, block_k=block_k, causal=causal,
                sm_scale=sm_scale, seq_len=seq_len,
            ),
            grid=(B, H, nk),
            in_specs=[
                pl.BlockSpec((1, 1, Tp, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
                pl.BlockSpec((1, 1, Tp, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, Tp, 1), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, Tp, 1), lambda b, h, j: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, Tp, D), k.dtype),
                jax.ShapeDtypeStruct((B, H, Tp, D), v.dtype),
            ],
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    return bwd


@functools.lru_cache(maxsize=64)
def _build_flash(seq_len, causal, sm_scale, block_q, block_k, interpret):
    """One custom_vjp per static config (lru so jit retrace reuses it)."""
    fwd_impl = _make_fwd(seq_len)
    bwd_impl = _make_bwd(seq_len)

    @jax.custom_vjp
    def f(q, k, v):
        o, _ = fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                        interpret)
        return o

    def f_fwd(q, k, v):
        o, lse = fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
        return o, (q, k, v, o, lse)

    def f_bwd(res, do):
        q, k, v, o, lse = res
        return bwd_impl(q, k, v, o, lse, do, causal, sm_scale, block_q,
                        block_k, interpret)

    f.defvjp(f_fwd, f_bwd)
    return f


def flash_attention(q, k, v, *, causal=True, sm_scale=None, block_q=128,
                    block_k=128, interpret=False):
    """Flash attention, public layout (B, T, H, D). K/V must already be
    repeated to Q's head count (ops.attention handles GQA)."""
    B, T, H, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, max(T, 1))
    block_k = min(block_k, max(T, 1))
    Tp = -(-T // max(block_q, block_k)) * max(block_q, block_k)

    qt = _pad_to(q.transpose(0, 2, 1, 3), Tp)
    kt = _pad_to(k.transpose(0, 2, 1, 3), Tp)
    vt = _pad_to(v.transpose(0, 2, 1, 3), Tp)
    f = _build_flash(T, causal, float(sm_scale), block_q, block_k, interpret)
    o = f(qt, kt, vt)
    return o[:, :, :T, :].transpose(0, 2, 1, 3)
