"""RMSNorm Pallas kernel (fwd + dx bwd), SURVEY.md §2b T6.

Rows stream through VMEM in (block_rows, D) tiles; normalization runs in
fp32. The backward splits work by bandwidth profile: dx (row-local) is a
kernel, dw (a cross-row reduction) is one jnp einsum XLA handles well.

Math (oracle: ops.rmsnorm.rmsnorm_reference):
  inv = rsqrt(mean(x^2) + eps);  y = x * inv * w
  dx  = inv * (w*dy) - x * inv^3 * mean(w*dy*x)
  dw  = sum_rows(dy * x * inv)
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(x_ref, w_ref, y_ref, inv_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)  # (R, D)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    w = w_ref[...].astype(jnp.float32)
    y_ref[...] = (x * inv * w).astype(y_ref.dtype)
    inv_ref[...] = inv  # (R, 1): 2-D so XLA/Mosaic agree on the tiling
    # (a 1-D (N,) side output trips a layout mismatch at N >= 4096)


def _dx_kernel(x_ref, w_ref, dy_ref, inv_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    inv = inv_ref[...]  # (R, 1)
    wdy = w * dy
    mean_term = jnp.mean(wdy * x, axis=-1, keepdims=True)
    dx = inv * wdy - x * (inv ** 3) * mean_term
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _choose_rows(n_rows, d):
    """Largest row block that divides n_rows AND keeps the kernel's ~6
    live (R, D) fp32 buffers within the 16MB scoped-VMEM budget (at
    d=4096, R=256 was 18MB — the long-T Llama ladder OOM)."""
    cap = max(8, (1 << 19) // max(d, 1))  # R*d*4B*6bufs <= ~12MB
    for r in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if r <= cap and n_rows % r == 0:
            return r
    return 1


def _fwd_call(x2, w, eps, interpret):
    N, D = x2.shape
    R = _choose_rows(N, D)
    y, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(N // R,),
        in_specs=[
            pl.BlockSpec((R, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((R, D), lambda i: (i, 0)),
            pl.BlockSpec((R, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x2.dtype),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w[None, :])
    return y, inv


@functools.lru_cache(maxsize=8)
def _build(eps, interpret):
    @jax.custom_vjp
    def f(x2, w):
        y, _ = _fwd_call(x2, w, eps, interpret)
        return y

    def f_fwd(x2, w):
        y, inv = _fwd_call(x2, w, eps, interpret)
        return y, (x2, w, inv)

    def f_bwd(res, dy):
        x2, w, inv = res
        N, D = x2.shape
        R = _choose_rows(N, D)
        dx = pl.pallas_call(
            _dx_kernel,
            grid=(N // R,),
            in_specs=[
                pl.BlockSpec((R, D), lambda i: (i, 0)),
                pl.BlockSpec((1, D), lambda i: (0, 0)),
                pl.BlockSpec((R, D), lambda i: (i, 0)),
                pl.BlockSpec((R, 1), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((R, D), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((N, D), x2.dtype),
            interpret=interpret,
        )(x2, w[None, :], dy, inv)
        # dw: cross-row reduction — one fused XLA contraction
        dw = jnp.einsum(
            "nd,nd,n->d",
            dy.astype(jnp.float32), x2.astype(jnp.float32), inv[:, 0],
        ).astype(w.dtype)
        return dx, dw

    f.defvjp(f_fwd, f_bwd)
    return f


def rmsnorm_pallas(x, weight, eps=1e-5, interpret=False):
    """x: (..., D); weight: (D,)."""
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    y = _build(float(eps), interpret)(x2, weight)
    return y.reshape(shape)
