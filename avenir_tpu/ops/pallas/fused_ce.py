"""Fused lm-head + cross-entropy Pallas kernels (ISSUE 3 tentpole).

The vocabulary-axis analogue of the flash-attention recurrence, and it
follows that file's design conventions:

  - grid (T-blocks, V-blocks) with the vocab index innermost
    ("arbitrary"), so each (C, block_v) weight stripe arrives as its own
    BlockSpec slice and Mosaic double-buffers the HBM->VMEM DMAs
  - online logsumexp carried in fp32 VMEM scratch across the vocab grid
    steps (running max m, normalizer l, plus the target-column logit t —
    each row's target lands in exactly one vocab block)
  - MXU matmuls take the input dtype (bf16 on TPU) with
    preferred_element_type=fp32
  - `ignore_index` rows are masked IN-KERNEL: they contribute zero loss
    and zero gradient, so padded rows ride the same mechanism
  - backward = two kernels, both recomputing the score block from
    (x, w, lse) like the blocked flash backward: dx gridded
    (T-blocks, V-blocks) accumulating ds @ w^T in a (block_t, C) fp32
    scratch, dw gridded (V-blocks, T-blocks) accumulating x^T @ ds in a
    (C, block_v) scratch — the (N, V) probability matrix never exists
    in HBM in either pass.

Weight layouts: 'cv' (C, V) — Llama/Mixtral lm_head.kernel; 'vc'
(V, C) — the GPT tied wte embedding. Both are consumed via dot_general
contraction dims (no transposed copy), and dw is emitted in the same
layout, so the tied-embedding gradient lands directly.

Under SPMD the public entry wraps the kernels in jax.shard_map over the
free batch-like mesh axes (rows sharded, weight replicated, dw psum'd
over the batch axes inside the HAND-WRITTEN backward — the custom_vjp
sits OUTSIDE the shard_maps, so jax never transposes them and the
replicated-cotangent hazard documented in partition.free_axis_names
cannot arise). The weight is all-gathered over 'tensor' inside the wrap;
on tensor-parallel meshes prefer loss_impl='blocked', which keeps the
vocab sharded (docs/PERFORMANCE.md "The loss tail").
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from avenir_tpu.ops.pallas.flash_attention import (
    _LANES,
    _compiler_params,
    NEG_INF,
)

# Default (block_t, block_v); AVENIR_CE_BLOCKS="bt,bv" overrides for
# sweeps (tools/loss_tail_bench.py). 256x512 keeps the dw scratch
# (C, block_v) fp32 at 1.5MB for GPT-2 and 8MB for Llama-3 C=4096 —
# comfortably under the 64MB scoped-VMEM limit with double buffering.
_ENV_CE_BLOCKS = os.environ.get("AVENIR_CE_BLOCKS") or None
_DEFAULT_CE_BLOCKS = tuple(
    int(s) for s in (_ENV_CE_BLOCKS or "256,512").split(",")
)
assert len(_DEFAULT_CE_BLOCKS) == 2, (
    f"AVENIR_CE_BLOCKS must be 'block_t,block_v', got {_ENV_CE_BLOCKS!r}"
)


def _dot(a, b, contract, preferred=jnp.float32):
    return jax.lax.dot_general(
        a, b, (contract, ((), ())), preferred_element_type=preferred
    )


def _scores(x, w, j, block_v, vocab, w_layout):
    """One (block_t, block_v) logits block in fp32, padded vocab columns
    masked to NEG_INF (finite, like the attention kernels' padding)."""
    if w_layout == "cv":
        s = _dot(x, w, (((1,), (0,))))  # (bt, C) @ (C, bv)
    else:
        s = _dot(x, w, (((1,), (1,))))  # (bt, C) @ (bv, C)^T
    bt, bv = s.shape
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    return jnp.where(col < vocab, s, NEG_INF), col


def _dequant_stripe(w_ref, ws_ref, dtype):
    """Fused dequant of one int8 weight stripe (ISSUE 15): the stripe
    arrives in VMEM as int8 — HBM moved 1/2 the bf16 bytes per grid
    step — and the per-vocab-channel scale rides a tiny sidecar block
    ((1, bv) for 'cv', (bv, 1) for 'vc' — shaped so the broadcast needs
    no in-kernel transpose)."""
    return (w_ref[...].astype(jnp.float32)
            * ws_ref[...].astype(jnp.float32)).astype(dtype)


def _fwd_kernel(x_ref, w_ref, *rest, block_v, vocab, ignore_index,
                w_layout, w_int8=False):
    if w_int8:
        ws_ref, y_ref, rows_ref, lse_ref, m_ref, l_ref, t_ref = rest
    else:
        y_ref, rows_ref, lse_ref, m_ref, l_ref, t_ref = rest
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        t_ref[...] = jnp.zeros_like(t_ref)

    x = x_ref[...]
    w = _dequant_stripe(w_ref, ws_ref, x.dtype) if w_int8 else w_ref[...]
    s, col = _scores(x, w, j, block_v, vocab, w_layout)
    y = y_ref[...]  # (bt, 1) int32
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_ref[:, :1] * alpha + jnp.sum(jnp.exp(s - m_new), axis=-1,
                                           keepdims=True)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
    # target-column logit: exactly one hit across the vocab sweep
    # (ignore_index rows never hit — col is always >= 0)
    tgt = jnp.sum(jnp.where(col == y, s, 0.0), axis=-1, keepdims=True)
    t_ref[...] = t_ref[...] + jnp.broadcast_to(tgt, t_ref.shape)

    @pl.when(j == nv - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        lse = m_ref[:, :1] + jnp.log(l)
        valid = y != ignore_index
        rows_ref[...] = jnp.where(valid, lse - t_ref[:, :1], 0.0)
        lse_ref[...] = lse


def _ds_block(x, w, y, lse, g, j, *, block_v, vocab, ignore_index, w_layout):
    """d loss_sum / d scores for one block: g * valid * (softmax - onehot),
    recomputed from (x, w, lse) exactly like the flash backward rebuilds
    p from its saved logsumexp. Masked vocab columns give p = 0."""
    s, col = _scores(x, w, j, block_v, vocab, w_layout)
    p = jnp.exp(s - lse)  # (bt, bv); lse (bt, 1)
    onehot = (col == y).astype(jnp.float32)
    valid = (y != ignore_index).astype(jnp.float32)  # (bt, 1)
    return (p - onehot) * (g * valid)


def _dx_kernel(x_ref, w_ref, *rest, block_v, vocab, ignore_index,
               w_layout, w_int8=False):
    if w_int8:
        ws_ref, y_ref, lse_ref, g_ref, dx_ref, dx_acc = rest
    else:
        y_ref, lse_ref, g_ref, dx_ref, dx_acc = rest
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        dx_acc[...] = jnp.zeros_like(dx_acc)

    w = (_dequant_stripe(w_ref, ws_ref, x_ref.dtype) if w_int8
         else w_ref[...])
    ds = _ds_block(x_ref[...], w, y_ref[...], lse_ref[...], g_ref[0, 0], j,
                   block_v=block_v, vocab=vocab, ignore_index=ignore_index,
                   w_layout=w_layout)
    if w_layout == "cv":  # (bt, bv) @ (C, bv)^T -> (bt, C)
        dx_acc[...] += _dot(ds.astype(w.dtype), w, (((1,), (1,))))
    else:  # (bt, bv) @ (bv, C) -> (bt, C)
        dx_acc[...] += _dot(ds.astype(w.dtype), w, (((1,), (0,))))

    @pl.when(j == nv - 1)
    def _flush():
        dx_ref[...] = dx_acc[...].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, *rest, block_v, vocab, ignore_index,
               w_layout, w_int8=False):
    if w_int8:
        ws_ref, y_ref, lse_ref, g_ref, dw_ref, dw_acc = rest
    else:
        y_ref, lse_ref, g_ref, dw_ref, dw_acc = rest
    # grid (nv, nt): the row index is innermost so one (C, block_v)
    # stripe of dw accumulates over every row block before ONE flush
    j, i = pl.program_id(0), pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        dw_acc[...] = jnp.zeros_like(dw_acc)

    x = x_ref[...]
    w = (_dequant_stripe(w_ref, ws_ref, x.dtype) if w_int8
         else w_ref[...])
    ds = _ds_block(x, w, y_ref[...], lse_ref[...], g_ref[0, 0], j,
                   block_v=block_v, vocab=vocab, ignore_index=ignore_index,
                   w_layout=w_layout)
    if w_layout == "cv":  # (bt, C)^T @ (bt, bv) -> (C, bv)
        dw_acc[...] += _dot(x, ds.astype(x.dtype), (((0,), (0,))))
    else:  # (bt, bv)^T @ (bt, C) -> (bv, C)
        dw_acc[...] += _dot(ds.astype(x.dtype), x, (((0,), (0,))))

    @pl.when(i == nt - 1)
    def _flush():
        dw_ref[...] = dw_acc[...].astype(dw_ref.dtype)


def _pow2_ceil(n):
    return 1 << max(n - 1, 1).bit_length()


def pick_ce_blocks(n_rows, vocab, block_t=None, block_v=None):
    """(block_t, block_v) for these shapes. block_v prefers a divisor of
    the vocab (50304 and 128256 both take 384) so the weight is consumed
    in place — a non-dividing block_v forces a padded COPY of the whole
    (V, C)-sized weight every step. Both clamp to the next power of two
    of their dim so tiny test shapes stay one block."""
    bt = block_t or _DEFAULT_CE_BLOCKS[0]
    bv = block_v or _DEFAULT_CE_BLOCKS[1]
    bt = min(bt, _pow2_ceil(n_rows))
    if vocab % bv:
        for cand in (448, 384, 320, 256, 192, 128, 64):
            if cand <= bv and vocab % cand == 0:
                bv = cand
                break
        else:
            bv = min(bv, _pow2_ceil(vocab))
    return bt, bv


def _pad_rows(a, n_target, fill=0):
    pad = n_target - a.shape[0]
    if pad == 0:
        return a
    widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, widths, constant_values=fill)


def _pad_vocab(w, v_target, w_layout):
    axis = 1 if w_layout == "cv" else 0
    pad = v_target - w.shape[axis]
    if pad == 0:
        return w
    widths = [(0, 0)] * w.ndim
    widths[axis] = (0, pad)
    return jnp.pad(w, widths)


def _ce_shard_axes(n_rows):
    """Free batch-like mesh axes that divide the row count, or None when
    no wrap is needed (no mesh / nothing to shard over). The rule set
    follows ops.attention._flash_shard_specs: GSPMD has no partitioning
    rule for a pallas_call, so left alone it replicates every operand."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return None
    from avenir_tpu.parallel.partition import BATCH_AXES, free_axis_names

    names = free_axis_names(mesh)
    sizes = dict(mesh.shape)
    free = {n: s for n, s in sizes.items() if n in names and s > 1}
    if not free:
        return None
    batch_axes = [a for a in BATCH_AXES if a in free]
    while batch_axes and n_rows % math.prod(free[a] for a in batch_axes):
        batch_axes.pop()
    if not batch_axes:
        return None
    return tuple(batch_axes), names


@functools.lru_cache(maxsize=64)
def _build_fused_ce(vocab, n_embd, w_layout, ignore_index, block_t, block_v,
                    interpret, w_int8=False):
    """custom_vjp over (x2, w, y2) -> scalar loss SUM (the mean's divide
    lives in the caller, so the upstream cotangent already carries the
    1/n_valid factor). One build per static config, lru-cached like
    flash_attention._build_flash.

    `w_int8` (ISSUE 15): the weight is quantized ONCE per call with
    per-vocab-channel absmax scales over the contraction axis
    (ops/quant.py) and every kernel — fwd, dx, dw — consumes int8
    stripes with the dequant fused after the DMA, so the (V, C)-sized
    HBM reads of all three grids move int8. dw is emitted against the
    dequantized grid (straight-through, matching the blocked oracle's
    fake-quant autodiff), in the compute dtype."""
    nv = -(-vocab // block_v)
    vp = nv * block_v
    kw = dict(block_v=block_v, vocab=vocab, ignore_index=ignore_index,
              w_layout=w_layout, w_int8=w_int8)
    if w_layout == "cv":
        w_block, w_index = (n_embd, block_v), lambda i, j: (0, j)
        w_block_jt, w_index_jt = (n_embd, block_v), lambda j, i: (0, j)
        ws_shape = (1, vp)
        ws_block, ws_index = (1, block_v), lambda i, j: (0, j)
        ws_block_jt, ws_index_jt = (1, block_v), lambda j, i: (0, j)
    else:
        w_block, w_index = (block_v, n_embd), lambda i, j: (j, 0)
        w_block_jt, w_index_jt = (block_v, n_embd), lambda j, i: (j, 0)
        ws_shape = (vp, 1)
        ws_block, ws_index = (block_v, 1), lambda i, j: (j, 0)
        ws_block_jt, ws_index_jt = (block_v, 1), lambda j, i: (j, 0)
    row_spec = pl.BlockSpec((block_t, 1), lambda i, j: (i, 0))
    g_spec = lambda ix: pl.BlockSpec((1, 1), ix, memory_space=pltpu.SMEM)

    def _prep_w(w):
        """Padded weight operands: (wp,) dense, (qw, ws) under w_int8 —
        quantized AFTER padding (padded channels quantize to exact
        zeros; their columns are NEG_INF-masked in _scores anyway).
        Deterministic, so the bwd's re-quantization reproduces the
        forward grid bit-for-bit."""
        wp = _pad_vocab(w, vp, w_layout)
        if not w_int8:
            return (wp,)
        from avenir_tpu.ops.quant import quantize_channelwise

        qw, sw = quantize_channelwise(wp, 0 if w_layout == "cv" else 1)
        return (qw, sw.reshape(ws_shape))

    def _kernel_fwd(x2, w, y2):
        """(rows (Np, 1), lse (Np, 1)) on padded rows."""
        np_, _ = x2.shape
        nt = np_ // block_t
        w_ops = _prep_w(w)
        w_specs = [pl.BlockSpec(w_block, w_index)] + (
            [pl.BlockSpec(ws_block, ws_index)] if w_int8 else [])
        return pl.pallas_call(
            functools.partial(_fwd_kernel, **kw),
            grid=(nt, nv),
            in_specs=[
                pl.BlockSpec((block_t, n_embd), lambda i, j: (i, 0)),
                *w_specs,
                row_spec,
            ],
            out_specs=[row_spec, row_spec],
            out_shape=[
                jax.ShapeDtypeStruct((np_, 1), jnp.float32),
                jax.ShapeDtypeStruct((np_, 1), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((block_t, _LANES), jnp.float32)] * 3,
            compiler_params=_compiler_params(1, 1),
            interpret=interpret,
        )(x2, *w_ops, y2)

    def _kernel_bwd(x2, w, y2, lse, g):
        np_, _ = x2.shape
        nt = np_ // block_t
        w_ops = _prep_w(w)
        g2 = jnp.reshape(g.astype(jnp.float32), (1, 1))
        w_specs = [pl.BlockSpec(w_block, w_index)] + (
            [pl.BlockSpec(ws_block, ws_index)] if w_int8 else [])
        dx = pl.pallas_call(
            functools.partial(_dx_kernel, **kw),
            grid=(nt, nv),
            in_specs=[
                pl.BlockSpec((block_t, n_embd), lambda i, j: (i, 0)),
                *w_specs,
                row_spec, row_spec,
                g_spec(lambda i, j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block_t, n_embd), lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((np_, n_embd), x2.dtype),
            scratch_shapes=[pltpu.VMEM((block_t, n_embd), jnp.float32)],
            compiler_params=_compiler_params(1, 1),
            interpret=interpret,
        )(x2, *w_ops, y2, lse, g2)
        w_specs_jt = [pl.BlockSpec(w_block_jt, w_index_jt)] + (
            [pl.BlockSpec(ws_block_jt, ws_index_jt)] if w_int8 else [])
        dwp = pl.pallas_call(
            functools.partial(_dw_kernel, **kw),
            grid=(nv, nt),
            in_specs=[
                pl.BlockSpec((block_t, n_embd), lambda j, i: (i, 0)),
                *w_specs_jt,
                pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
                pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
                g_spec(lambda j, i: (0, 0)),
            ],
            out_specs=pl.BlockSpec(w_block_jt, w_index_jt),
            out_shape=jax.ShapeDtypeStruct(
                (n_embd, vp) if w_layout == "cv" else (vp, n_embd),
                x2.dtype if w_int8 else w.dtype
            ),
            scratch_shapes=[pltpu.VMEM(w_block, jnp.float32)],
            compiler_params=_compiler_params(1, 1),
            interpret=interpret,
        )(x2, *w_ops, y2, lse, g2)
        if vp != vocab:
            dwp = (dwp[:, :vocab] if w_layout == "cv" else dwp[:vocab])
        return dx, dwp

    def _fwd_local(x2, w, y2):
        n = x2.shape[0]
        np_ = -(-n // block_t) * block_t
        rows, lse = _kernel_fwd(
            _pad_rows(x2, np_),
            w,
            _pad_rows(y2.reshape(n, 1), np_, fill=ignore_index),
        )
        # padded rows carry ignore_index -> zero loss rows; lse sliced
        # back to the real rows (pad lse is never consumed: ds == 0)
        return jnp.sum(rows), lse[:n]

    def _bwd_local(x2, w, y2, lse, g):
        n = x2.shape[0]
        np_ = -(-n // block_t) * block_t
        dx, dw = _kernel_bwd(
            _pad_rows(x2, np_),
            w,
            _pad_rows(y2.reshape(n, 1), np_, fill=ignore_index),
            _pad_rows(lse, np_),
            g,
        )
        return dx[:n], dw

    def _fwd_dispatch(x2, w, y2):
        sn = _ce_shard_axes(x2.shape[0])
        if sn is None:
            return _fwd_local(x2, w, y2)
        batch_axes, names = sn
        from jax.sharding import PartitionSpec as P

        def body(xl, wl, yl):
            part, lse = _fwd_local(xl, wl, yl)
            return jax.lax.psum(part, batch_axes), lse

        return jax.shard_map(
            body,
            in_specs=(P(batch_axes, None), P(None, None), P(batch_axes)),
            out_specs=(P(), P(batch_axes, None)),
            check_vma=False, axis_names=names,
        )(x2, w, y2)

    def _bwd_dispatch(x2, w, y2, lse, g):
        sn = _ce_shard_axes(x2.shape[0])
        if sn is None:
            return _bwd_local(x2, w, y2, lse, g)
        batch_axes, names = sn
        from jax.sharding import PartitionSpec as P

        def body(xl, wl, yl, lsel, gl):
            dxl, dwl = _bwd_local(xl, wl, yl, lsel, gl)
            # each shard's dw covers only its rows: sum over batch axes
            # HERE (hand-written backward — no shard_map transpose runs)
            return dxl, jax.lax.psum(dwl, batch_axes)

        return jax.shard_map(
            body,
            in_specs=(P(batch_axes, None), P(None, None), P(batch_axes),
                      P(batch_axes, None), P()),
            out_specs=(P(batch_axes, None), P(None, None)),
            check_vma=False, axis_names=names,
        )(x2, w, y2, lse, g)

    @jax.custom_vjp
    def f(x2, w, y2):
        loss_sum, _ = _fwd_dispatch(x2, w, y2)
        return loss_sum

    def f_fwd(x2, w, y2):
        loss_sum, lse = _fwd_dispatch(x2, w, y2)
        return loss_sum, (x2, w, y2, lse)

    def f_bwd(res, g):
        x2, w, y2, lse = res
        dx, dw = _bwd_dispatch(x2, w, y2, lse, g)
        return dx, dw, np.zeros(y2.shape, jax.dtypes.float0)

    f.defvjp(f_fwd, f_bwd)
    return f


def fused_ce_pallas(x, w, targets, *, ignore_index=-1, w_layout="cv",
                    block_t=None, block_v=None, interpret=False,
                    w_dtype="compute"):
    """Mean token cross-entropy of x @ w without materializing (B, T, V).
    Same contract as ops.fused_ce.fused_cross_entropy (which dispatches
    here for impl='pallas'). `w_dtype='int8'` streams the weight as int8
    stripes with fused dequant in every kernel — numerics pinned against
    the blocked fake-quant oracle by tests/test_quant.py."""
    assert w_layout in ("cv", "vc"), f"unknown w_layout {w_layout!r}"
    assert w_dtype in ("compute", "int8"), f"unknown w_dtype {w_dtype!r}"
    B, T, C = x.shape
    V = w.shape[1] if w_layout == "cv" else w.shape[0]
    bt, bv = pick_ce_blocks(B * T, V, block_t, block_v)
    f = _build_fused_ce(V, C, w_layout, int(ignore_index), bt, bv,
                        bool(interpret), w_dtype == "int8")
    loss_sum = f(x.reshape(B * T, C), w,
                 targets.reshape(B * T).astype(jnp.int32))
    n_valid = jnp.sum(targets != ignore_index)
    return loss_sum / jnp.maximum(n_valid, 1).astype(jnp.float32)
