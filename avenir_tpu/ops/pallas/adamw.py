"""Fused AdamW as a Pallas kernel wrapped in an optax transform
(SURVEY.md §2b T2; BASELINE.json:5 "fused attention + AdamW hot path as
Pallas kernels / optax").

One kernel pass per tensor reads (g, p, m, v) and writes (delta, m', v'),
with the bias-corrected update computed in-register — vs the chain of
elementwise HLOs optax emits. Semantics are exactly optax.adamw
(b1/b2/eps, decoupled weight decay, mask) — verified against it in
tests/test_pallas_kernels.py.

Tensors are flattened and padded to (rows, 128) lanes; the grid streams
row blocks through VMEM.
"""

import functools

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
BLOCK_ROWS = 512


def _adamw_kernel(g_ref, p_ref, m_ref, v_ref, sc_ref,
                  delta_ref, m_out_ref, v_out_ref):
    """sc_ref (SMEM): [lr, b1, b2, eps, wd, bc1, bc2] fp32 scalars."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    bc1 = sc_ref[5]  # 1 / (1 - b1^t)
    bc2 = sc_ref[6]  # 1 / (1 - b2^t)
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    m_hat = m_new * bc1
    v_hat = v_new * bc2
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    delta_ref[...] = (-lr * update).astype(delta_ref.dtype)
    m_out_ref[...] = m_new
    v_out_ref[...] = v_new


def _pad_rows(flat, rows):
    pad = rows * LANES - flat.shape[0]
    return jnp.pad(flat, (0, pad)) if pad else flat


def _fused_update_one(g, p, m, v, scalars, interpret):
    n = g.size
    rows = -(-n // LANES)
    block = min(BLOCK_ROWS, rows)
    rows_padded = -(-rows // block) * block

    def shape2(x):
        return _pad_rows(x.reshape(-1), rows_padded).reshape(rows_padded, LANES)

    g2, p2, m2, v2 = (shape2(x) for x in (g, p, m, v))
    delta, m_new, v_new = pl.pallas_call(
        _adamw_kernel,
        grid=(rows_padded // block,),
        in_specs=[
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),  # scalars, whole array
        ],
        out_specs=[
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_padded, LANES), p.dtype),
            jax.ShapeDtypeStruct((rows_padded, LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows_padded, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(g2, p2, m2, v2, scalars)

    def unshape(x2, dtype):
        return x2.reshape(-1)[:n].reshape(g.shape).astype(dtype)

    return (unshape(delta, p.dtype), unshape(m_new, jnp.float32),
            unshape(v_new, jnp.float32))


def fused_adamw(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, mask=None, interpret=False):
    """optax.GradientTransformation with the update math in one Pallas
    kernel per tensor. `learning_rate` may be a schedule or float;
    `mask` is a pytree of bools — True leaves get weight decay."""

    def init(params):
        zeros = lambda p: jnp.zeros(jnp.shape(p), jnp.float32)
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
        )

    def update(grads, state, params=None):
        assert params is not None, "fused_adamw needs params (weight decay)"
        count = optax.safe_int32_increment(state.count)
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate
        t = count.astype(jnp.float32)
        bc1 = 1.0 / (1.0 - jnp.power(b1, t))
        bc2 = 1.0 / (1.0 - jnp.power(b2, t))

        mask_tree = (
            mask if mask is not None
            else jax.tree.map(lambda _: True, params)
        )

        flat_g, treedef = jax.tree.flatten(grads)
        flat_p = treedef.flatten_up_to(params)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_mask = treedef.flatten_up_to(mask_tree)

        deltas, mus, nus = [], [], []
        for g, p, m, v, use_wd in zip(flat_g, flat_p, flat_m, flat_v,
                                      flat_mask):
            wd = weight_decay if use_wd else 0.0
            scalars = jnp.stack([
                jnp.asarray(lr, jnp.float32),
                jnp.float32(b1), jnp.float32(b2), jnp.float32(eps),
                jnp.float32(wd), bc1, bc2,
            ])
            d, mn, vn = _fused_update_one(g, p, m, v, scalars, interpret)
            deltas.append(d)
            mus.append(mn)
            nus.append(vn)

        new_state = optax.ScaleByAdamState(
            count=count,
            mu=jax.tree.unflatten(treedef, mus),
            nu=jax.tree.unflatten(treedef, nus),
        )
        return jax.tree.unflatten(treedef, deltas), new_state

    return optax.GradientTransformation(init, update)
