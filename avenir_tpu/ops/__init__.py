"""avenir_tpu.ops — the kernel layer (SURVEY.md §1 L1, §2b T6).

Every op exposes a single public function that dispatches between a
Pallas/Mosaic TPU kernel and a pure-jnp reference implementation. The jnp
path is the semantic spec (used on CPU, in tests, and as the Pallas
correctness oracle); the Pallas path is the TPU hot path mandated by
BASELINE.json:5 ("fused attention + AdamW hot path as Pallas kernels").
"""

from avenir_tpu.ops.attention import causal_attention
from avenir_tpu.ops.fused_ce import fused_cross_entropy, resolve_loss_impl
from avenir_tpu.ops.rmsnorm import rmsnorm
from avenir_tpu.ops.rope import apply_rope, rope_frequencies
from avenir_tpu.ops.swiglu import swiglu
