"""SwiGLU activation (SURVEY.md §2b T6, for Llama-3 — BASELINE.json:10).

swiglu(gate, up) = silu(gate) * up. Elementwise — XLA fuses it into the
adjacent matmuls on its own; the explicit op exists so the model code names
the semantic and the pallas fused-MLP variant can slot in behind it.
"""

import jax
import jax.numpy as jnp


def swiglu_reference(gate, up):
    return jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype) * up


def swiglu(gate, up):
    return swiglu_reference(gate, up)
