"""SwiGLU activation (SURVEY.md §2b T6, for Llama-3 — BASELINE.json:10).

swiglu(gate, up) = silu(gate) * up. Elementwise — measured on v5e
(tools/bench_act.py; BASELINE.md "silu / RoPE on the VPU" table): silu
costs the same as tanh-GELU (84.8% of peak at the Llama shape, 6% of
the SwiGLU MLP chain vs identity);
unlike erf-GELU it pipelines behind the MXU, so no pallas kernel is
warranted. The explicit op exists so the model code names the semantic.
"""

import jax
import jax.numpy as jnp


def swiglu_reference(gate, up):
    return jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype) * up


def swiglu(gate, up):
    return swiglu_reference(gate, up)
