"""RMSNorm op (SURVEY.md §2b T6, for Llama-3 — BASELINE.json:10).

Matches torch's `nn.RMSNorm` / Llama reference semantics: normalize in
fp32, scale by a learned weight, cast back to input dtype.
"""

import jax
import jax.numpy as jnp


def rmsnorm_reference(x, weight, eps=1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rmsnorm(x, weight, eps=1e-5, impl="auto"):
    """Root-mean-square layer norm over the last axis."""
    if impl == "auto":
        from avenir_tpu.ops.attention import _on_tpu

        if _on_tpu():
            try:
                from avenir_tpu.ops.pallas import rmsnorm as _  # noqa: F401

                impl = "pallas"
            except ImportError:
                impl = "xla"
        else:
            impl = "xla"
    if impl == "pallas":
        from avenir_tpu.ops.pallas.rmsnorm import rmsnorm_pallas

        return rmsnorm_pallas(x, weight, eps=eps)
    return rmsnorm_reference(x, weight, eps=eps)
