"""Platform selection shared by the CLI entrypoints.

The axon sandbox's sitecustomize imports jax and pins the tunneled TPU
platform BEFORE an entrypoint's environment is consulted, so setting
JAX_PLATFORMS=cpu in the env alone is not enough — the live jax config
must be updated too. Every entrypoint that may run on the tunneled host
(train.py, sample.py, bench.py) calls this before its first jax op."""

import os


def honor_jax_platforms_env():
    """If the environment explicitly requests CPU, pin it through the live
    jax config as well. No-op otherwise (the real chip stays default).
    Also installs the ambient-mesh API compat shims (avenir_tpu/compat.py)
    so entrypoints written against modern jax run on legacy runtimes."""
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from avenir_tpu.compat import install_jax_compat

    install_jax_compat()
