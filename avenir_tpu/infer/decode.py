"""Jitted fixed-shape KV-cache decoding (SURVEY.md §3.5 "TPU: jit with a
fixed-size KV cache"; the fast path models/gpt.py:generate documents).

Design (TPU-first: everything static-shaped, one compile per
(prompt_len, max_len) pair, single dispatch per generated token):

  - KVCache: (L, B, T_max, H_kv, D) stacked over layers, donated through
    the jitted step so the update is in-place in HBM. GQA models cache
    only the KV heads (memory / bandwidth win vs repeating to Q heads).
  - prefill: ONE full forward over the prompt that also writes the cache
    (causal masking via per-query positions), returning the last logits.
  - step: single-token forward attending against the cache — the
    (B, 1, H, D) query attends to T_max cached keys with positions > pos
    masked; `lax.dynamic_update_slice` writes the new KV at pos.
  - sampling math (temperature / top-k / categorical and the rng fold
    sequence) mirrors GPT.generate exactly, so `generate_cached` is
    token-for-token identical to the recompute-full-prefix path
    (tests/test_decode.py asserts this).

Works for GPT (learned pos emb, MHA), Llama (RoPE, GQA) and Mixtral (MoE
layers), in both layer layouts (python-loop modules and scan-stacked
`*_scan` modules).
"""

import functools
import math
import weakref
from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import nnx

# jitted prefill/step closures cached per live model object: repeated
# generate_cached calls (sample.py's num_samples loop) must reuse ONE
# compile per (B, prompt_len, max_t) instead of retracing fresh closures
_DECODE_CACHE = weakref.WeakKeyDictionary()


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, T_max, H_kv, D)
    v: jax.Array


def init_cache(*, n_layer, batch, max_t, n_kv_head, head_dim, dtype):
    shape = (n_layer, batch, max_t, n_kv_head, head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def _attend_cached(q, kc, vc, q_pos):
    """q: (B, T, H, D) at absolute positions q_pos (T,); kc/vc the full
    (B, T_max, H_kv, D) cache. Each query attends to cached positions
    <= its own. fp32 softmax, mirrors ops.causal_attention_reference.

    GQA: the cache is read at H_kv heads — grouped einsums contract q
    head h against cache head h // (H/H_kv) directly, so attend-time
    bandwidth stays at the cache's true size (the old jnp.repeat read
    G× the bytes — 4× at Llama-3's 32:8, on the latency path the repo
    quotes numbers for; VERDICT r3 weak #6)."""
    B, Tm, Hkv, D = kc.shape
    T, H = q.shape[1], q.shape[2]
    G = H // Hkv
    qg = q.reshape(B, T, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kc,
                   preferred_element_type=jnp.float32)
    s = s.reshape(B, H, T, Tm) * (1.0 / math.sqrt(D))
    k_idx = jnp.arange(Tm)
    mask = k_idx[None, :] <= q_pos[:, None]  # (T, T_max)
    s = jnp.where(mask[None, None], s, float("-inf"))
    p = jax.nn.softmax(s, axis=-1).astype(vc.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p.reshape(B, Hkv, G, T, Tm), vc,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, T, H, D).astype(q.dtype)


def _write_cache(kc, vc, k, v, pos):
    """Write (B, T, H_kv, D) new keys/values at absolute position pos."""
    kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, pos, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, pos, 0, 0))
    return kc, vc


# ---- per-layer steps (reach into the module's own submodules so the
# weights/semantics are the model's; parity is pinned by tests) ----


def _gpt_block_step(blk, x, kc, vc, pos, q_pos):
    B, T, C = x.shape
    h = blk.ln_1(x).astype(x.dtype)
    qkv = blk.attn.c_attn(h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    H = blk.attn.n_head
    q = q.reshape(B, T, H, C // H)
    k = k.reshape(B, T, H, C // H)
    v = v.reshape(B, T, H, C // H)
    kc, vc = _write_cache(kc, vc, k, v, pos)
    y = _attend_cached(q, kc, vc, q_pos).reshape(B, T, C)
    x = x + blk.attn.c_proj(y)
    x = x + blk.mlp(blk.ln_2(x).astype(x.dtype))
    return x, kc, vc


def _llama_layer_step(lyr, x, kc, vc, pos, q_pos, cos, sin):
    from avenir_tpu.ops import apply_rope

    B, T, C = x.shape
    attn = lyr.self_attn
    h = lyr.input_layernorm(x).astype(x.dtype)
    q = attn.q_proj(h).reshape(B, T, attn.n_head, attn.head_dim)
    k = attn.k_proj(h).reshape(B, T, attn.n_kv_head, attn.head_dim)
    v = attn.v_proj(h).reshape(B, T, attn.n_kv_head, attn.head_dim)
    positions = jnp.broadcast_to(q_pos[None], (B, T))
    q = apply_rope(q, cos, sin, positions=positions)
    k = apply_rope(k, cos, sin, positions=positions)
    kc, vc = _write_cache(kc, vc, k, v, pos)
    y = _attend_cached(q, kc, vc, q_pos)
    x = x + attn.o_proj(y.reshape(B, T, attn.n_head * attn.head_dim))
    h2 = lyr.post_attention_layernorm(x).astype(x.dtype)
    if hasattr(lyr, "block_sparse_moe"):
        moe_out, _ = lyr.block_sparse_moe(h2)
        x = x + moe_out
    else:
        x = x + lyr.mlp(h2)
    return x, kc, vc


def _run_layers(model, x, cache, pos, q_pos, layer_step):
    """Apply layer_step across the model's layers, handling both the
    python-loop and the scan-stacked layouts. Returns (x, new_cache)."""
    # explicit `is None` checks: nnx.Module truthiness is not a reliable
    # presence test (a falsy module would silently fall into the loop path)
    scanned = getattr(model, "h_scan", None)
    if scanned is None:
        scanned = getattr(model, "layers_scan", None)
    if scanned is not None:
        @nnx.scan(in_axes=(nnx.Carry, 0, 0, 0), out_axes=(nnx.Carry, 0, 0))
        def body(h, layer, kc, vc):
            h, kc, vc = layer_step(layer, h, kc, vc, pos, q_pos)
            return h, kc, vc

        x, k_new, v_new = body(x, scanned, cache.k, cache.v)
        return x, KVCache(k_new, v_new)
    layers = getattr(model, "h", None)
    if layers is None:
        layers = model.layers
    ks, vs = [], []
    for l, layer in enumerate(layers):
        x, kc, vc = layer_step(layer, x, cache.k[l], cache.v[l], pos, q_pos)
        ks.append(kc)
        vs.append(vc)
    return x, KVCache(jnp.stack(ks), jnp.stack(vs))


def _forward_cached(model, idx, cache, pos):
    """Forward `idx` (B, T) at absolute start position `pos`, reading and
    writing the cache. Returns (last-position fp32 logits, new cache)."""
    B, T = idx.shape
    q_pos = pos + jnp.arange(T)
    if hasattr(model, "wte"):  # GPT
        x = model.wte(idx) + model.wpe(q_pos)[None]
        x, cache = _run_layers(model, x, cache, pos, q_pos, _gpt_block_step)
        x = model.ln_f(x[:, -1:]).astype(x.dtype)
        logits = model.wte.attend(x)
    else:  # Llama / Mixtral
        from avenir_tpu.ops import rope_frequencies

        cfg = model.config
        cos, sin = rope_frequencies(
            cfg.n_embd // cfg.n_head, cfg.block_size, cfg.rope_theta
        )
        x = model.embed_tokens(idx)
        x, cache = _run_layers(
            model, x, cache, pos, q_pos,
            lambda lyr, h, kc, vc, p, qp: _llama_layer_step(
                lyr, h, kc, vc, p, qp, cos, sin),
        )
        x = model.norm(x[:, -1:]).astype(x.dtype)
        logits = model.lm_head(x)
    return logits[:, -1].astype(jnp.float32), cache


def _sample(rng, logits, temperature, top_k):
    """GPT.generate's sampling math, verbatim (models/gpt.py)."""
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -min(top_k, logits.shape[-1])]
        logits = jnp.where(logits < kth[:, None], -jnp.inf, logits)
    rng, sub = jax.random.split(rng)
    return rng, jax.random.categorical(sub, logits, axis=-1)


def generate_cached(model, rng, idx, max_new_tokens, temperature=1.0,
                    top_k=None):
    """Drop-in replacement for model.generate: same outputs, one jitted
    single-token dispatch per new token instead of a full-prefix recompute.
    Total length must fit the model's position table (block_size)."""
    cfg = model.config
    B, T0 = idx.shape
    max_t = T0 + max_new_tokens
    assert max_t <= cfg.block_size, (
        f"cache decoding needs prompt+new <= block_size "
        f"({max_t} > {cfg.block_size})"
    )
    n_kv = getattr(cfg, "n_kv_head", cfg.n_head)
    from avenir_tpu.models.common import resolve_dtype

    cache = init_cache(
        n_layer=cfg.n_layer, batch=B, max_t=max_t, n_kv_head=n_kv,
        head_dim=cfg.n_embd // cfg.n_head,
        dtype=resolve_dtype(cfg.compute_dtype),
    )
    try:
        per_model = _DECODE_CACHE.setdefault(model, {})
    except TypeError:  # model not weakref-able: still works, just retraces
        per_model = {}
    # two-level cache: prefill depends only on shapes; the scanned loop
    # additionally bakes in max_new_tokens and the sampling params — a
    # temperature sweep must not recompile the (expensive) prefill
    pre_key = ("prefill", B, T0, max_t)
    key = (B, T0, max_t, max_new_tokens, float(temperature), top_k)
    if pre_key not in per_model:
        graphdef, state = nnx.split(model)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def prefill(state, idx, cache):
            m = nnx.merge(graphdef, state)
            return _forward_cached(m, idx, cache, 0)

        per_model[pre_key] = prefill
    if key not in per_model:
        graphdef, state = nnx.split(model)

        # The whole decode loop is ONE dispatch: a lax.scan whose body
        # samples from the carried logits then runs the cached single-token
        # forward. A host-side loop costs a tunnel/dispatch round-trip per
        # token (measured 102 ms/token for GPT-2-124M on the axon chip —
        # the eager _sample ops and the per-token jnp.int32(pos) H2D each
        # round-trip); the scan form makes decode latency pure device time.
        # The rng fold sequence and sampling math are unchanged, so outputs
        # stay token-for-token identical to GPT.generate (tests/
        # test_decode.py). The final iteration's forward is wasted work
        # (its logits are never sampled) but keeps the body uniform; its
        # cache write at pos = T0+max_new_tokens-1 is in bounds.
        @functools.partial(jax.jit, donate_argnums=(3,))
        def decode_loop(state, rng, logits, cache, pos0):
            m = nnx.merge(graphdef, state)

            # nnx.scan (module broadcast via in_axes=None), not raw
            # lax.scan: the module's Variables belong to the jit trace and
            # the nnx trace-level guard rejects re-splitting them inside a
            # plain lax.scan body; nnx.scan lifts the module state through
            # the scan properly (same mechanism as scan_layer_stack).
            def body(carry, mm):
                rng, logits, cache, pos = carry
                rng, nxt = _sample(rng, logits, temperature, top_k)
                logits2, cache = _forward_cached(mm, nxt[:, None], cache, pos)
                return (rng, logits2, cache, pos + 1), nxt

            _, toks = nnx.scan(
                body, in_axes=(nnx.Carry, None), out_axes=(nnx.Carry, 0),
                length=max_new_tokens,
            )((rng, logits, cache, pos0), m)
            return toks  # (max_new_tokens, B)

        per_model[key] = decode_loop
    prefill, decode_loop = per_model[pre_key], per_model[key]
    # state re-split per call (cheap): picks up in-place weight mutations
    state = nnx.split(model)[1]

    logits, cache = prefill(state, idx, cache)
    toks = decode_loop(state, rng, logits, cache, jnp.int32(T0))
    return jnp.concatenate([idx, toks.T], axis=1)
