"""Jitted fixed-shape KV-cache decoding (SURVEY.md §3.5 "TPU: jit with a
fixed-size KV cache"; the fast path models/gpt.py:generate documents).

Design (TPU-first: everything static-shaped, one compile per
(prompt_len, max_len) pair, single dispatch per generated token):

  - KVCache: (L, B, T_max, H_kv, D) stacked over layers, donated through
    the jitted step so the update is in-place in HBM. GQA models cache
    only the KV heads (memory / bandwidth win vs repeating to Q heads).
  - prefill: ONE full forward over the prompt that also writes the cache
    (causal masking via per-query positions), returning the last logits.
  - step: single-token forward attending against the cache — the
    (B, 1, H, D) query attends to T_max cached keys with positions > pos
    masked; `lax.dynamic_update_slice` writes the new KV at pos.
  - sampling math (temperature / top-k / categorical and the rng fold
    sequence) mirrors GPT.generate exactly, so `generate_cached` is
    token-for-token identical to the recompute-full-prefix path
    (tests/test_decode.py asserts this).

ISSUE 2 additions:
  - prompt-length bucketing: prompts right-pad to power-of-2 buckets
    and the cache width rounds up to a 64 quantum (`width_bucket` —
    coarse enough to bound decode compiles, fine enough that the
    per-step attention overshoot is capped at 63 positions), so nearby
    lengths share ONE prefill + ONE decode compile (padding is masked
    out of attention and overwritten before it can be attended).
  - stop tokens: `stop_tokens=` decodes through a while_loop with a
    done-mask that exits the moment every row stops; emitted prefixes
    are unchanged vs no-stop decoding (`first_stop_index` is the shared
    truncation rule with the serve engine).
  - per-row rng: pass a (B,) key vector and each row samples from its
    own key with bits identical to a B=1 run — sample.py's batched
    samples and the serve engine's parity contract.
  - batched positions: `_forward_cached`/`_attend_cached`/`_write_cache`
    accept a (B,) per-row position vector — the serve slot pool, where
    every slot sits at its own depth (avenir_tpu/serve/).

Works for GPT (learned pos emb, MHA), Llama (RoPE, GQA) and Mixtral (MoE
layers), in both layer layouts (python-loop modules and scan-stacked
`*_scan` modules).
"""

import functools
import math
import weakref
from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import nnx

# jitted prefill/step closures cached per live model object: repeated
# generate_cached calls (sample.py's batched call) must reuse ONE
# compile per (B, prompt_bucket, width_bucket) instead of retracing
# fresh closures
_DECODE_CACHE = weakref.WeakKeyDictionary()

# One entry per TRACE of a decode-path jit (tracing happens exactly once
# per compiled specialization, so len() counts compiles without touching
# private jit internals). Tests pin compile budgets against this; the
# serve engine keeps its own per-engine ledger the same way.
_trace_events = []


def trace_count():
    """Number of decode-path traces (== XLA compiles) so far."""
    return len(_trace_events)


def prompt_bucket(n, cap, floor=8):
    """Pad target for a length-n prompt: the smallest power of two >=
    max(n, floor), clamped to cap. Bucketing bounds the number of
    prefill compiles at O(log cap) instead of one per prompt length
    (tests pin the count); prompts are right-padded to the bucket and
    the real last-token logits are read at a *traced* index, so padding
    never retraces."""
    assert n <= cap, f"prompt length {n} > cap {cap}"
    b = floor
    while b < n:
        b *= 2
    return min(b, cap)


def bucket_ladder(cap, floor=8):
    """Every value prompt_bucket(-, cap) can return, ascending. The
    serve scheduler asserts its prefill compiles stay within this
    ladder (the 'number of prefill compiles is bounded' contract)."""
    out = []
    b = floor
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return tuple(out)


def width_bucket(n, cap, quantum=64):
    """KV-cache width for a total length n: n rounded up to a multiple
    of `quantum`, clamped to cap. Coarser than exact (bounds decode
    compiles at cap/quantum variants instead of one per length) but much
    finer than power-of-2 (a too-wide cache is pure waste EVERY decode
    step — attention reads the full width — so the overshoot is capped
    at quantum-1 positions, not ~n)."""
    assert n <= cap
    return min(cap, -(-n // quantum) * quantum)


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, T_max, H_kv, D)
    v: jax.Array


def init_cache(*, n_layer, batch, max_t, n_kv_head, head_dim, dtype):
    shape = (n_layer, batch, max_t, n_kv_head, head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def _attend_cached(q, kc, vc, q_pos):
    """q: (B, T, H, D) at absolute positions q_pos — (T,) shared across
    the batch (one-shot decode) or (B, T) per-row (the serve engine's
    slot pool, where every slot sits at its own depth); kc/vc the full
    (B, T_max, H_kv, D) cache. Each query attends to cached positions
    <= its own. fp32 softmax, mirrors ops.causal_attention_reference.

    GQA: the cache is read at H_kv heads — grouped einsums contract q
    head h against cache head h // (H/H_kv) directly, so attend-time
    bandwidth stays at the cache's true size (the old jnp.repeat read
    G× the bytes — 4× at Llama-3's 32:8, on the latency path the repo
    quotes numbers for; VERDICT r3 weak #6)."""
    B, Tm, Hkv, D = kc.shape
    T, H = q.shape[1], q.shape[2]
    G = H // Hkv
    qg = q.reshape(B, T, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kc,
                   preferred_element_type=jnp.float32)
    s = s.reshape(B, H, T, Tm) * (1.0 / math.sqrt(D))
    k_idx = jnp.arange(Tm)
    if q_pos.ndim == 2:
        mask = k_idx[None, None, :] <= q_pos[:, :, None]  # (B, T, T_max)
        s = jnp.where(mask[:, None], s, float("-inf"))
    else:
        mask = k_idx[None, :] <= q_pos[:, None]  # (T, T_max)
        s = jnp.where(mask[None, None], s, float("-inf"))
    p = jax.nn.softmax(s, axis=-1).astype(vc.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p.reshape(B, Hkv, G, T, Tm), vc,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, T, H, D).astype(q.dtype)


def _write_cache(kc, vc, k, v, pos):
    """Write (B, T, H_kv, D) new keys/values at absolute position pos —
    a scalar shared by the batch, or a (B,) vector of per-row positions
    (vmapped per-row writes, the slot-pool case)."""
    if getattr(pos, "ndim", 0) == 1:
        def row(kc_r, vc_r, k_r, v_r, p):
            kc_r = jax.lax.dynamic_update_slice(
                kc_r, k_r.astype(kc_r.dtype), (p, 0, 0))
            vc_r = jax.lax.dynamic_update_slice(
                vc_r, v_r.astype(vc_r.dtype), (p, 0, 0))
            return kc_r, vc_r

        return jax.vmap(row)(kc, vc, k, v, pos)
    kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, pos, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, pos, 0, 0))
    return kc, vc


# ---- per-layer steps (reach into the module's own submodules so the
# weights/semantics are the model's; parity is pinned by tests) ----


def _gpt_block_step(blk, x, kc, vc, pos, q_pos, kv_ops=None):
    write, attend = kv_ops if kv_ops is not None else (_write_cache,
                                                      _attend_cached)
    B, T, C = x.shape
    h = blk.ln_1(x).astype(x.dtype)
    qkv = blk.attn.c_attn(h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    H = blk.attn.n_head
    q = q.reshape(B, T, H, C // H)
    k = k.reshape(B, T, H, C // H)
    v = v.reshape(B, T, H, C // H)
    kc, vc = write(kc, vc, k, v, pos)
    y = attend(q, kc, vc, q_pos).reshape(B, T, C)
    x = x + blk.attn.c_proj(y)
    x = x + blk.mlp(blk.ln_2(x).astype(x.dtype))
    return x, kc, vc


def _llama_layer_step(lyr, x, kc, vc, pos, q_pos, cos, sin, kv_ops=None):
    from avenir_tpu.ops import apply_rope

    write, attend = kv_ops if kv_ops is not None else (_write_cache,
                                                      _attend_cached)
    B, T, C = x.shape
    attn = lyr.self_attn
    h = lyr.input_layernorm(x).astype(x.dtype)
    q = attn.q_proj(h).reshape(B, T, attn.n_head, attn.head_dim)
    k = attn.k_proj(h).reshape(B, T, attn.n_kv_head, attn.head_dim)
    v = attn.v_proj(h).reshape(B, T, attn.n_kv_head, attn.head_dim)
    positions = q_pos if q_pos.ndim == 2 else jnp.broadcast_to(q_pos[None], (B, T))
    q = apply_rope(q, cos, sin, positions=positions)
    k = apply_rope(k, cos, sin, positions=positions)
    kc, vc = write(kc, vc, k, v, pos)
    y = attend(q, kc, vc, q_pos)
    x = x + attn.o_proj(y.reshape(B, T, attn.n_head * attn.head_dim))
    h2 = lyr.post_attention_layernorm(x).astype(x.dtype)
    if hasattr(lyr, "block_sparse_moe"):
        moe_out, _ = lyr.block_sparse_moe(h2)
        x = x + moe_out
    else:
        x = x + lyr.mlp(h2)
    return x, kc, vc


def _run_layers(model, x, cache, pos, q_pos, layer_step):
    """Apply layer_step across the model's layers, handling both the
    python-loop and the scan-stacked layouts. Returns (x, new_cache).

    The per-layer cache halves (cache.k / cache.v) are treated as
    PYTREES, not bare arrays: the int8 KV pools (ops/kv_quant.py) carry
    (data, scale) pairs per half, and tree-mapped indexing/stacking lets
    one loop serve both the dense and the quantized layouts."""
    # explicit `is None` checks: nnx.Module truthiness is not a reliable
    # presence test (a falsy module would silently fall into the loop path)
    scanned = getattr(model, "h_scan", None)
    if scanned is None:
        scanned = getattr(model, "layers_scan", None)
    if scanned is not None:
        @nnx.scan(in_axes=(nnx.Carry, 0, 0, 0), out_axes=(nnx.Carry, 0, 0))
        def body(h, layer, kc, vc):
            h, kc, vc = layer_step(layer, h, kc, vc, pos, q_pos)
            return h, kc, vc

        x, k_new, v_new = body(x, scanned, cache.k, cache.v)
        return x, KVCache(k_new, v_new)
    layers = getattr(model, "h", None)
    if layers is None:
        layers = model.layers
    ks, vs = [], []
    for l, layer in enumerate(layers):
        kc = jax.tree.map(lambda a: a[l], cache.k)
        vc = jax.tree.map(lambda a: a[l], cache.v)
        x, kc, vc = layer_step(layer, x, kc, vc, pos, q_pos)
        ks.append(kc)
        vs.append(vc)
    stack = lambda cs: jax.tree.map(lambda *xs: jnp.stack(xs), *cs)
    return x, KVCache(stack(ks), stack(vs))


def _take_last(x, last_index):
    """(B, T, C) -> (B, 1, C) at `last_index` (traced; None = T-1). A
    traced index is what lets right-padded prompts (bucketing) read the
    real last-token logits without a retrace per prompt length."""
    if last_index is None:
        return x[:, -1:]
    return jax.lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)


def _forward_cached(model, idx, cache, pos, last_index=None, kv_ops=None,
                    return_all=False):
    """Forward `idx` (B, T) at absolute start position `pos` — a scalar
    shared by the batch, or a (B,) vector of per-row positions (serve
    slot pool) — reading and writing the cache. Returns (fp32 logits at
    `last_index` (default: the last position), new cache).

    `kv_ops`: optional (write, attend) pair replacing the dense
    `_write_cache`/`_attend_cached` — the paged-KV serve pool
    (serve/pages.py) routes cache reads/writes through a page table
    this way, so one forward serves both cache layouts.

    `return_all` (ISSUE 11): return fp32 logits at EVERY position,
    (B, T, V) — the speculative-decoding k-token verify forward, where
    position i's logits are the target distribution conditioned on the
    draft prefix idx[:, :i+1]. The cache write is unchanged: draft
    tokens' KV lands at pos..pos+T-1 and stays masked (unattendable)
    past the accepted point until real tokens overwrite it — the slot-
    hygiene invariant covers rejected tokens exactly like recycling."""
    B, T = idx.shape
    if getattr(pos, "ndim", 0) == 1:
        q_pos = pos[:, None] + jnp.arange(T)[None]  # (B, T)
    else:
        q_pos = pos + jnp.arange(T)  # (T,)
    if hasattr(model, "wte"):  # GPT
        wpe = model.wpe(q_pos)
        x = model.wte(idx) + (wpe if q_pos.ndim == 2 else wpe[None])
        x, cache = _run_layers(
            model, x, cache, pos, q_pos,
            lambda blk, h, kc, vc, p, qp: _gpt_block_step(
                blk, h, kc, vc, p, qp, kv_ops=kv_ops),
        )
        if not return_all:
            x = _take_last(x, last_index)
        x = model.ln_f(x).astype(x.dtype)
        logits = model.wte.attend(x)
    else:  # Llama / Mixtral
        from avenir_tpu.ops import rope_frequencies

        cfg = model.config
        cos, sin = rope_frequencies(
            cfg.n_embd // cfg.n_head, cfg.block_size, cfg.rope_theta
        )
        x = model.embed_tokens(idx)
        x, cache = _run_layers(
            model, x, cache, pos, q_pos,
            lambda lyr, h, kc, vc, p, qp: _llama_layer_step(
                lyr, h, kc, vc, p, qp, cos, sin, kv_ops=kv_ops),
        )
        if not return_all:
            x = _take_last(x, last_index)
        x = model.norm(x).astype(x.dtype)
        logits = model.lm_head(x)
    if return_all:
        return logits.astype(jnp.float32), cache
    return logits[:, -1].astype(jnp.float32), cache


def _sample(rng, logits, temperature, top_k):
    """GPT.generate's sampling math, verbatim (models/gpt.py)."""
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -min(top_k, logits.shape[-1])]
        logits = jnp.where(logits < kth[:, None], -jnp.inf, logits)
    rng, sub = jax.random.split(rng)
    return rng, jax.random.categorical(sub, logits, axis=-1)


def _sample_rows(keys, logits, temperature, top_k=None):
    """Per-row sampling: row r consumes ONLY its own key (keys: (B,)
    typed key array), with the same op sequence as `_sample` on a
    (1, V) batch — so each row's token stream is bit-identical to
    decoding that row alone at B=1 regardless of what shares the batch.
    (vmap of jax's counter-mode PRNG reproduces the unbatched bits;
    the serve engine's parity contract and sample.py's batched samples
    both rest on this.) temperature/top_k are per-row arrays; top_k == V
    means "no top-k" and its mask is an exact no-op — a STATIC None
    skips the per-token full-vocab sort at trace time, and a traced
    all->=V batch skips it at RUNTIME through a batch-level lax.cond
    (same bits either way: an all-V mask never changes a logit)."""
    V = logits.shape[-1]

    def one(key, row, temp, k):
        l = (row / temp)[None]  # (1, V): same aval as a B=1 _sample
        if k is not None:
            kth = jnp.sort(l, axis=-1)[0, V - k]
            l = jnp.where(l < kth, -jnp.inf, l)
        key, sub = jax.random.split(key)
        return key, jax.random.categorical(sub, l, axis=-1)[0]

    if top_k is None:
        return jax.vmap(lambda ky, r, t: one(ky, r, t, None))(
            keys, logits, temperature)
    # Traced per-row k: a row with k >= V has an exactly-no-op mask (an
    # all-V mask never changes a logit) but would still pay the per-row
    # full-vocab SORT every decode step — and in the serve engine that is
    # every EMPTY/padding slot (pool top_k defaults to V) plus every
    # no-top-k request. One batch-level lax.cond keeps the single
    # compiled step (the engine's compile-budget contract) while skipping
    # the sort branch at RUNTIME whenever no row in the batch needs it;
    # bits are identical by the no-op-mask argument above. Mixed batches
    # (any real top-k row) take the full path — per-row skipping under
    # vmap would lower to select and run both branches anyway.
    def with_sort(args):
        ky, lg, tp, k = args
        return jax.vmap(one)(ky, lg, tp, k)

    def no_sort(args):
        ky, lg, tp, _ = args
        return jax.vmap(lambda kk, r, t: one(kk, r, t, None))(ky, lg, tp)

    return jax.lax.cond(jnp.all(top_k >= V), no_sort, with_sort,
                        (keys, logits, temperature, top_k))


def _sample_any(rng, logits, temperature, top_k):
    """Dispatch on the rng form: one shared key -> the classic batched
    categorical; a (B,) key vector -> per-row sampling (each row
    bit-identical to its own B=1 run)."""
    if getattr(rng, "ndim", 0) == 1:
        B, V = logits.shape
        ks = None
        if top_k is not None:
            k_eff = max(1, min(int(top_k), V))
            ks = jnp.full((B,), k_eff, jnp.int32)
        return _sample_rows(
            rng, logits, jnp.full((B,), temperature, jnp.float32), ks)
    return _sample(rng, logits, temperature, top_k)


def _normalize_stop(stop_tokens):
    """None | int | iterable -> None or a sorted tuple of ints (part of
    the decode compile key, so a set and a list of the same ids share
    one compile)."""
    if stop_tokens is None:
        return None
    import numbers

    if isinstance(stop_tokens, numbers.Integral):  # incl. numpy scalars
        return (int(stop_tokens),)
    stop = tuple(sorted(int(t) for t in stop_tokens))
    return stop or None


def first_stop_index(tokens, stop_tokens):
    """Index just past the first stop token in a 1-D token sequence, or
    len(tokens) if none occurs — the shared truncation rule between the
    one-shot done-mask output and the serve engine's per-request
    retirement."""
    stop = set(_normalize_stop(stop_tokens) or ())
    for i, t in enumerate(tokens):
        if int(t) in stop:
            return i + 1
    return len(tokens)


def generate_cached(model, rng, idx, max_new_tokens, temperature=1.0,
                    top_k=None, stop_tokens=None, pad_id=None):
    """Drop-in replacement for model.generate: same outputs, one jitted
    single-token dispatch per new token instead of a full-prefix recompute.
    Total length must fit the model's position table (block_size).

    rng: one key (classic batched sampling), or a (B,) key vector —
    per-row sampling where row r's stream is bit-identical to decoding
    it alone at B=1 (sample.py's batched samples; the serve engine's
    parity reference).

    stop_tokens: optional id or iterable of ids. Once a row emits one,
    its remaining positions are `pad_id` (default: the first stop id)
    and the decode while-loop exits as soon as EVERY row is done — the
    cheap early exit; the emitted prefix is unchanged vs no-stop
    decoding (tests pin this). `first_stop_index` gives the shared
    truncation rule.

    Prompt-length bucketing: the prompt is right-padded to a power-of-2
    bucket and the KV width rounds up to a 64 quantum, so nearby
    (prompt, budget) pairs reuse ONE prefill + ONE decode compile
    (padding is masked out of attention and overwritten before it ever
    becomes attendable; the real last-prompt logits are read at a
    traced index)."""
    cfg = model.config
    B, T0 = idx.shape
    max_t = T0 + max_new_tokens
    assert max_t <= cfg.block_size, (
        f"cache decoding needs prompt+new <= block_size "
        f"({max_t} > {cfg.block_size})"
    )
    t_pad = prompt_bucket(T0, cfg.block_size)
    # width must cover the padded prompt (prefill writes t_pad rows)
    width = max(width_bucket(max_t, cfg.block_size), t_pad)
    stop = _normalize_stop(stop_tokens)
    pad = int(pad_id) if pad_id is not None else (stop[0] if stop else 0)
    rng_rows = getattr(rng, "ndim", 0) == 1
    if rng_rows:
        assert rng.shape[0] == B, (
            f"per-row rng wants one key per row ({rng.shape[0]} keys, "
            f"batch {B})"
        )
    n_kv = getattr(cfg, "n_kv_head", cfg.n_head)
    from avenir_tpu.models.common import resolve_dtype

    cache = init_cache(
        n_layer=cfg.n_layer, batch=B, max_t=width, n_kv_head=n_kv,
        head_dim=cfg.n_embd // cfg.n_head,
        dtype=resolve_dtype(cfg.compute_dtype),
    )
    try:
        per_model = _DECODE_CACHE.setdefault(model, {})
    except TypeError:  # model not weakref-able: still works, just retraces
        per_model = {}
    # two-level cache: prefill depends only on (bucketed) shapes; the
    # scanned loop additionally bakes in max_new_tokens and the sampling
    # params — a temperature sweep must not recompile the (expensive)
    # prefill
    pre_key = ("prefill", B, t_pad, width)
    key = (B, width, max_new_tokens, float(temperature), top_k, stop, pad,
           rng_rows)
    if pre_key not in per_model:
        graphdef, state = nnx.split(model)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def prefill(state, idx, cache, last_index):
            _trace_events.append(pre_key)
            m = nnx.merge(graphdef, state)
            return _forward_cached(m, idx, cache, 0, last_index=last_index)

        per_model[pre_key] = prefill
    if key not in per_model:
        graphdef, state = nnx.split(model)

        # The whole decode loop is ONE dispatch: a scan/while whose body
        # samples from the carried logits then runs the cached single-token
        # forward. A host-side loop costs a tunnel/dispatch round-trip per
        # token (measured 102 ms/token for GPT-2-124M on the axon chip —
        # the eager _sample ops and the per-token jnp.int32(pos) H2D each
        # round-trip); the fused form makes decode latency pure device
        # time. The rng fold sequence and sampling math are unchanged, so
        # outputs stay token-for-token identical to GPT.generate (tests/
        # test_decode.py).
        if stop is None:
            # The final iteration's forward is wasted work (its logits are
            # never sampled) but keeps the body uniform; its cache write at
            # pos = T0+max_new_tokens-1 is in bounds.
            @functools.partial(jax.jit, donate_argnums=(3,))
            def decode_loop(state, rng, logits, cache, pos0):
                _trace_events.append(key)
                m = nnx.merge(graphdef, state)

                # nnx.scan (module broadcast via in_axes=None), not raw
                # lax.scan: the module's Variables belong to the jit trace
                # and the nnx trace-level guard rejects re-splitting them
                # inside a plain lax.scan body; nnx.scan lifts the module
                # state through the scan properly (same mechanism as
                # scan_layer_stack).
                def body(carry, mm):
                    rng, logits, cache, pos = carry
                    rng, nxt = _sample_any(rng, logits, temperature, top_k)
                    logits2, cache = _forward_cached(
                        mm, nxt[:, None], cache, pos)
                    return (rng, logits2, cache, pos + 1), nxt

                _, toks = nnx.scan(
                    body, in_axes=(nnx.Carry, None), out_axes=(nnx.Carry, 0),
                    length=max_new_tokens,
                )((rng, logits, cache, pos0), m)
                return toks  # (max_new_tokens, B)

        else:
            # Stop-token path: a lax.while_loop that exits the moment
            # every row is done (the cheap early exit — no dispatch or
            # device work for the unused tail). The body merges the
            # module from the closed-over state pytree each iteration
            # (trace-time only), which is what lets a plain while_loop
            # host nnx modules. Done rows keep consuming rng and emit
            # `pad`, so live rows' streams are bit-identical to the
            # no-stop scan.
            @functools.partial(jax.jit, donate_argnums=(3,))
            def decode_loop(state, rng, logits, cache, pos0):
                _trace_events.append(key)
                stop_arr = jnp.asarray(stop, jnp.int32)

                def cond(carry):
                    i, done = carry[0], carry[5]
                    return jnp.logical_and(i < max_new_tokens,
                                           ~jnp.all(done))

                def body(carry):
                    i, rng, logits, cache, pos, done, toks = carry
                    rng, nxt = _sample_any(rng, logits, temperature, top_k)
                    nxt = jnp.where(done, jnp.int32(pad), nxt)
                    done = jnp.logical_or(done, jnp.isin(nxt, stop_arr))
                    toks = jax.lax.dynamic_update_slice(
                        toks, nxt[None].astype(jnp.int32), (i, 0))
                    m = nnx.merge(graphdef, state)
                    logits2, cache = _forward_cached(
                        m, nxt[:, None], cache, pos)
                    return (i + 1, rng, logits2, cache, pos + 1, done, toks)

                carry = (
                    jnp.int32(0), rng, logits, cache, pos0,
                    jnp.zeros((B,), bool),
                    jnp.full((max_new_tokens, B), pad, jnp.int32),
                )
                return jax.lax.while_loop(cond, body, carry)[6]

        per_model[key] = decode_loop
    prefill, decode_loop = per_model[pre_key], per_model[key]
    # state re-split per call (cheap): picks up in-place weight mutations
    state = nnx.split(model)[1]

    idx_in = idx if T0 == t_pad else jnp.pad(idx, ((0, 0), (0, t_pad - T0)))
    logits, cache = prefill(state, idx_in, cache, jnp.int32(T0 - 1))
    toks = decode_loop(state, rng, logits, cache, jnp.int32(T0))
    return jnp.concatenate([idx, toks.T.astype(idx.dtype)], axis=1)
