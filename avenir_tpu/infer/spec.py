"""Speculative decoding: the accept/reject math for batched one-step
verification (ISSUE 11 tentpole, part 1).

A small DRAFT model proposes k tokens autoregressively; the TARGET
verifies all k in ONE batched forward over the cached path
(`infer.decode._forward_cached(..., return_all=True)` — the k-token
verify forward). This module owns the sampling theory between those two
forwards: modified rejection sampling (Leviathan et al. / Chen et al.)
that keeps every emitted token EXACTLY target-distributed:

    for i = 1..k:   accept d_i  iff  u_i < p_{i-1}(d_i) / q_i(d_i)
    on the first rejection at j: emit one token from the residual
        normalize(max(p_{j-1} - q_j, 0))
    if all k accepted: emit a BONUS token from p_k

so every tick emits between 1 and k+1 tokens. Two properties the serve
suite pins:

  - **distribution exactness**: each emitted token is distributed
    exactly as target-only sampling at that position (the classic
    rejection-sampling identity; tests/test_spec_decode.py checks
    seeded frequencies against the analytic target distribution).
  - **greedy bit-parity**: with top_k=1 the target distribution is
    one-hot at argmax, so accept/reject outcomes are DETERMINISTIC
    (p(d)/q(d) is 1/q >= 1 or exactly 0) and both the residual and the
    bonus distributions collapse to that one-hot — the emitted stream
    is the argmax chain bit-identical to sequential `generate_cached`
    decoding, for ANY draft model and ANY rng. A bad draft can only
    cost speed, never correctness.

`p`/`q` are computed from raw logits with the SAME per-row
temperature/top-k masking `_sample_rows` applies (sort-threshold mask
then softmax), so "the target distribution" here is literally the
distribution the sequential sampler draws from.

Everything is fixed-shape: drafts ride as (B, k), emissions as a
(B, k+1) token block plus a (B,) accepted-count vector — the variable
1..k+1 harvest is host bookkeeping over traced outputs, never a
retrace (the page-table traced-arg discipline).
"""

import jax
import jax.numpy as jnp

# the draft model's proposal rng is derived from the request key with
# this fold constant — a fixed, documented split so the draft stream
# can never collide with the target stream (which sequential decoding
# owns) while staying a pure function of the request's rng
DRAFT_RNG_FOLD = 0x5bec


def draft_key(rng):
    """The draft-proposal key for a request key. Deterministic: a
    failed-over request re-drafts identically, so spec-decode output is
    a pure function of (prompt, rng) — the router's bit-identical
    failover contract survives spec decoding."""
    return jax.random.fold_in(rng, DRAFT_RNG_FOLD)


def masked_probs(logits, temperature, top_k):
    """(B, T, V) logits -> (B, T, V) probabilities under the per-row
    temperature/top-k the sequential sampler uses: divide by temp, mask
    strictly below the row's k-th largest logit to -inf, softmax.
    `top_k` is (B,) int32 with V meaning "no top-k" (the slot-pool
    convention); like `_sample_rows`, an all->=V batch skips the
    full-vocab sort at RUNTIME through one lax.cond inside the same
    compiled step."""
    V = logits.shape[-1]
    l = logits / temperature[:, None, None]

    def with_mask(lx):
        srt = jnp.sort(lx, axis=-1)  # ascending
        k = jnp.clip(top_k, 1, V)
        kth = jnp.take_along_axis(
            srt, jnp.broadcast_to((V - k)[:, None, None],
                                  (lx.shape[0], lx.shape[1], 1)), axis=-1)
        return jnp.where(lx < kth, -jnp.inf, lx)

    l = jax.lax.cond(jnp.all(top_k >= V), lambda lx: lx, with_mask, l)
    return jax.nn.softmax(l, axis=-1)


def spec_accept(keys, p_logits, q_logits, drafts, temperature, top_k,
                k_eff=None):
    """One verification round. All shapes fixed; k = drafts.shape[1].

    keys:      (B,) typed target keys (each row consumes only its own —
               the per-row-stream discipline of `_sample_rows`)
    p_logits:  (B, k+1, V) target logits from the verify forward over
               [tail, d_1..d_k]; index i is p(.|prefix, d_1..d_i)
               (index 0 conditions on the tail alone)
    q_logits:  (B, k, V) draft logits d_i was sampled from
    drafts:    (B, k) int32 proposed tokens
    temperature/top_k: (B,) per-row sampling params (top_k = V none)
    k_eff:     (B,) int32 per-row EFFECTIVE k (adaptive spec_k, ISSUE
               18), or None = k everywhere. Draft positions >= the
               row's k_eff are force-rejected BEFORE the uniforms are
               compared, so a row emits at most k_eff+1 tokens and its
               final token is the bonus p(.|d_1..d_{k_eff}) when every
               considered draft survived — exactly the distribution a
               width-k_eff verify would have produced. The rng budget
               stays k+2 splits per row whatever k_eff is, so adapting
               k mid-request never skews a fixed-k row's stream.

    Returns (new_keys, toks, counts): `toks` (B, k+1) int32 holds the
    emitted tokens left-aligned — positions 0..counts-2 are accepted
    drafts, position counts-1 is the residual correction (on a
    rejection) or the bonus token (all accepted); entries past counts
    are dead. `counts` (B,) in 1..k+1.
    """
    B, K1, V = p_logits.shape
    K = K1 - 1
    assert drafts.shape == (B, K) and q_logits.shape == (B, K, V)
    if k_eff is None:
        k_eff = jnp.full((B,), K, jnp.int32)
    p = masked_probs(p_logits, temperature, top_k)        # (B, K+1, V)
    q = masked_probs(q_logits, temperature, top_k)        # (B, K, V)

    # fixed rng budget per tick: 1 carry + 1 final draw + K accept
    # uniforms per row, consumed whatever the accept pattern — counts
    # can never skew the stream (no data-dependent key use)
    ks = jax.vmap(lambda kk: jax.random.split(kk, K + 2))(keys)
    new_keys = ks[:, 0]
    u = jax.vmap(lambda row: jax.vmap(
        lambda kk: jax.random.uniform(kk))(row))(ks[:, 2:])   # (B, K)

    p_d = jnp.take_along_axis(p[:, :K], drafts[..., None], -1)[..., 0]
    q_d = jnp.take_along_axis(q, drafts[..., None], -1)[..., 0]
    # u < p/q, written divide-free (q_d > 0: d was sampled from q);
    # positions past the row's effective k are dead by fiat
    accept = (u * q_d < p_d) & (jnp.arange(K)[None, :] < k_eff[:, None])
    acc = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = acc.sum(axis=1)                                # (B,) 0..k_eff

    # the final token's distribution: residual at the first rejection,
    # the bonus p_{k_eff} when everything considered was accepted
    p_sel = jnp.take_along_axis(p, n_acc[:, None, None], axis=1)[:, 0]
    q_sel = jnp.take_along_axis(
        q, jnp.minimum(n_acc, K - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(p_sel - q_sel, 0.0)
    rs = resid.sum(-1, keepdims=True)
    # rs == 0 cannot follow a genuine rejection (a rejection implies
    # q > p somewhere, hence p > q somewhere else); the where() guards
    # float underflow only — fall back to the target distribution,
    # which is still exactly correct sampling, just not residual-shaped
    resid = jnp.where(rs > 0, resid / jnp.maximum(rs, 1e-38), p_sel)
    final_dist = jnp.where((n_acc < k_eff)[:, None], resid, p_sel)
    final_tok = jax.vmap(
        lambda kk, pr: jax.random.categorical(kk, jnp.log(pr)))(
            ks[:, 1], final_dist).astype(jnp.int32)

    counts = n_acc + 1                                     # 1..K+1
    toks = jnp.concatenate(
        [drafts.astype(jnp.int32),
         jnp.zeros((B, 1), jnp.int32)], axis=1)            # (B, K+1)
    toks = toks.at[jnp.arange(B), n_acc].set(final_tok)
    return new_keys, toks, counts


# ---------------------------------------------------------------------------
# Draft-free n-gram self-draft (ISSUE 18): prompt-lookup proposals
# ---------------------------------------------------------------------------

# longest suffix n-gram the host matcher tries before giving up (3, 2,
# then 1) — the prompt-lookup-decoding default; longer n-grams buy
# nothing on the workloads this serves (a 3-gram repeat is already a
# near-certain continuation match) and cost host scan time per tick
NGRAM_MAX_N = 3


def ngram_propose(ctx, k, max_n=NGRAM_MAX_N):
    """Prompt-lookup self-draft (`draft_model='ngram'`): propose the k
    tokens that literally FOLLOW the most recent earlier occurrence of
    the context's longest matching suffix n-gram. `ctx` is the request's
    full token context (prompt + everything emitted) — matching over
    emitted tokens too is what makes extraction/summarization/RAG
    workloads (and any self-repeating generation) near-free to draft.

    Returns (proposal list of k ints, hit bool). On a miss — no suffix
    of any tried length recurs — the proposal is the last token repeated
    (cheap, and on a run-loop workload frequently right anyway); `hit`
    feeds the `ngram_hits` counter so the obs surface can tell lookup
    coverage from accept luck. Pure host arithmetic, deterministic in
    `ctx`: a failed-over request re-proposes identically, so the
    pure-function-of-(prompt, rng) replay contract survives the draft-
    free draft too."""
    L = len(ctx)
    assert L >= 1 and k >= 1
    for n in range(min(max_n, L - 1), 0, -1):
        suffix = ctx[L - n:]
        # most recent earlier occurrence whose continuation exists
        for i in range(L - n - 1, -1, -1):
            if ctx[i:i + n] == suffix:
                cont = list(ctx[i + n:i + n + k])
                cont += [ctx[-1]] * (k - len(cont))
                return cont, True
    return [ctx[-1]] * k, False


def ngram_q_logits(drafts, vocab_size):
    """Point-mass draft logits for ngram proposals: 0 at the proposed
    token, -inf elsewhere, so `masked_probs` yields EXACTLY a one-hot q
    at any temperature/top-k (temperature rescales -inf to -inf; the
    top-k threshold can never mask the only finite entry). Feeding this
    q through `spec_accept` reduces rejection sampling to: accept d with
    probability p(d), resample a rejection from p excluding d — the
    classic prompt-lookup acceptance rule, with the SAME exactness
    guarantees (each emitted token is distributed exactly as target-only
    sampling; greedy is bit-deterministic) because q is a legitimate
    proposal distribution that happens to be deterministic."""
    one_hot = jax.nn.one_hot(drafts, vocab_size, dtype=jnp.float32)
    return jnp.where(one_hot > 0, 0.0, -jnp.inf)


def expected_tokens_per_tick(accept_rate, k):
    """E[emitted/tick] under an i.i.d. per-draft accept rate `a`:
    1 + a + a^2 + ... + a^k = (1 - a^(k+1)) / (1 - a). The accept-rate
    math docs/PERFORMANCE.md quotes; benches report the measured
    counterpart (tokens_out / verify ticks)."""
    a = float(accept_rate)
    if a >= 1.0:
        return float(k + 1)
    return (1.0 - a ** (k + 1)) / (1.0 - a)
