"""avenir_tpu.obs — structured run telemetry (ISSUE 1).

Dependency-free (stdlib; jax only for trace annotations, optional):

- metrics.py: schema-checked counters/gauges/histograms in one
  process-local registry (METRIC_SCHEMA is the JSONL contract)
- sink.py:    JSONL run log (out_dir/metrics.jsonl), coordinator-owned
- spans.py:   phase spans feeding both XProf and the registry
- watchdog.py: stall watchdog for silently hung pod collectives
- trace.py:   per-request trace events + ring-buffer flight recorder +
              Perfetto (Chrome trace JSON) export (ISSUE 10)
- series.py:  mergeable streaming percentile sketches + windowed
              time-series + the shared stall-threshold and percentile
              rules (ISSUE 14)
- anomaly.py: schema-pinned detector table over the series — drift /
              trend / collapse / heartbeat-creep, each firing before
              the watchdog/SLO tiers, wired to the flight recorder
              (ISSUE 14)
- report.py:  metrics.jsonl -> goodput/timing summary (tools/obs_report.py)
"""

from avenir_tpu.obs.metrics import (
    METRIC_SCHEMA,
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from avenir_tpu.obs.sink import RECORD_KINDS, JsonlSink, NullSink
from avenir_tpu.obs.spans import span
from avenir_tpu.obs.trace import (
    TRACE_EVENTS,
    TraceBuffer,
    Tracer,
    chrome_trace,
    get_tracer,
    install_crash_hooks,
    disarm_crash_hooks,
    request_segments,
    set_tracer,
    ttft_attribution,
)
from avenir_tpu.obs.anomaly import DETECTOR_SCHEMA, AnomalyEngine
from avenir_tpu.obs.series import (
    QuantileSketch,
    Series,
    SeriesStore,
    stall_threshold_secs,
)
from avenir_tpu.obs.watchdog import StallWatchdog

__all__ = [
    "METRIC_SCHEMA", "MetricsRegistry", "get_registry", "reset_registry",
    "RECORD_KINDS", "JsonlSink", "NullSink", "span", "StallWatchdog",
    "TRACE_EVENTS", "TraceBuffer", "Tracer", "chrome_trace",
    "get_tracer", "set_tracer", "request_segments", "ttft_attribution",
    "install_crash_hooks", "disarm_crash_hooks",
    "DETECTOR_SCHEMA", "AnomalyEngine", "QuantileSketch", "Series",
    "SeriesStore", "stall_threshold_secs",
]
