"""avenir_tpu.obs — structured run telemetry (ISSUE 1).

Dependency-free (stdlib; jax only for trace annotations, optional):

- metrics.py: schema-checked counters/gauges/histograms in one
  process-local registry (METRIC_SCHEMA is the JSONL contract)
- sink.py:    JSONL run log (out_dir/metrics.jsonl), coordinator-owned
- spans.py:   phase spans feeding both XProf and the registry
- watchdog.py: stall watchdog for silently hung pod collectives
- report.py:  metrics.jsonl -> goodput/timing summary (tools/obs_report.py)
"""

from avenir_tpu.obs.metrics import (
    METRIC_SCHEMA,
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from avenir_tpu.obs.sink import RECORD_KINDS, JsonlSink, NullSink
from avenir_tpu.obs.spans import span
from avenir_tpu.obs.watchdog import StallWatchdog

__all__ = [
    "METRIC_SCHEMA", "MetricsRegistry", "get_registry", "reset_registry",
    "RECORD_KINDS", "JsonlSink", "NullSink", "span", "StallWatchdog",
]
