"""JSONL run-log sink (ISSUE 1 tentpole).

One line per record, flushed on every write so a killed run still leaves
a parseable log up to its last event. Records are plain dicts; the loop
stamps each with a `kind` (see RECORD_KINDS) and wall time `t`. The
coordinator owns the file; other processes (and library code that may
run without a sink) use NullSink so call sites stay branch-free.

Thread-safe: the stall watchdog and async checkpoint callbacks write
from their own threads.
"""

import json
import threading

# every record's "kind" value; docs/OBSERVABILITY.md documents each and
# tests/test_metrics_schema.py pins the mirror
RECORD_KINDS = {
    "run_meta",   # one per run, at loop start: static run facts
    "iter",       # per logged iter: loss/dt/mfu/tok_per_sec + counters
    "eval",       # per estimate_loss: split losses + duration
    "ckpt",       # per checkpoint save decision: duration, async or not
    "compile",    # per first-dispatch of a window length: compile wall
    "stall",      # watchdog warning: seconds since last progress
    "request",    # per finished serve-engine request: ttft/tpot/tokens
    "trace",      # one per-request trace event (obs/trace.py, --trace)
    "retry",      # per transient-IO retry (utils/retry.py): site + delay
    "anomaly",    # per detector fire (obs/anomaly.py): detector, key,
                  # value, threshold + the robust-statistic evidence
                  # (the early-warning tier's durable record)
    "restore",    # per resume source decision: dir, kind, fallback count
    "run_end",    # one per run, at exit: final counter snapshot
}


class JsonlSink:
    def __init__(self, path, append=False):
        """`append=True` (resumed runs) keeps the earlier segments'
        records — a preempted-and-relaunched run must not destroy the
        telemetry of the segment before the preemption. Each segment
        starts with its own run_meta record; report.summarize() analyzes
        the last segment."""
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a" if append else "w")

    def write(self, record):
        assert record.get("kind") in RECORD_KINDS, (
            f"unknown record kind {record.get('kind')!r} — add it to "
            "sink.RECORD_KINDS and the docs/OBSERVABILITY.md table"
        )
        line = json.dumps(record)  # raises on non-serializable: fail loud
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class NullSink:
    """No-op sink for non-coordinator processes / metrics_log=False."""

    def write(self, record):
        pass

    def close(self):
        pass


# process-wide "current run log" handle, for library layers that have no
# sink plumbed through their call chain (the retry wrapper fires from
# loader prefetch threads and checkpoint writer threads). The training
# loop installs its JsonlSink for the duration of the run; outside a run
# the default is a NullSink, so call sites stay branch-free.
_run_sink = [None]


def get_run_sink():
    return _run_sink[0] if _run_sink[0] is not None else NullSink()


def set_run_sink(sink):
    """Install `sink` as the process run log; returns the previous one
    (restore it when the run ends — a closed sink must not linger)."""
    prev, _run_sink[0] = _run_sink[0], sink
    return prev
