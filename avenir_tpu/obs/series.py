"""Streaming metric series: mergeable percentile sketches, windowed
time-series, and the ONE quantile/stall-threshold rule (ISSUE 14
tentpole, part 1).

The registry (obs/metrics.py) answers "how much, in aggregate"; the
anomaly engine (obs/anomaly.py) needs "how is this signal MOVING" —
which takes a bounded history per signal, not a cumulative total. Three
pieces, all stdlib, all fixed-memory:

- **QuantileSketch** — a DDSketch-style log-bucketed quantile sketch:
  relative-error-bounded quantiles (|q_est - q_true| <= alpha * q_true
  for any quantile of positive values), O(max_bins) memory however long
  the stream, and MERGEABLE — bucket counts add, so process-worker
  sketches ship in step replies as bucket DELTAS and merge parent-side
  exactly like the counter deltas serve/proc.py already mirrors
  (`take_delta()`/`merge_dict()` are that wire form). This replaces the
  ad-hoc per-tool percentile code paths: serve_bench and obs_report now
  read p50/p99 from one sketch instead of re-deriving them from raw
  lists, and the `run_end` record carries sketch snapshots so a report
  never needs the per-request records at all.

- **Series** — a windowed time-series over one signal: a ring of
  per-window aggregates (count/sum/min/max/mean over `window_s`-second
  windows, `n_windows` deep) plus a QuantileSketch over the whole
  stream. The per-window means are what the anomaly detectors consume
  (drift and trend live at window granularity, not per-event), and the
  ring bounds memory the same way the flight recorder's ring does.

- **Shared rules** — `percentile()` (the exact nearest-rank rule every
  report uses; moved here from obs/report.py, which re-exports it) and
  `stall_threshold_secs()`: `max(floor, factor x median)` — previously
  duplicated between obs/watchdog.py and serve/replica.py, now ONE
  function both import (the ISSUE 14 consolidation satellite, same move
  as SLOEngine's shared `request_met_slo`).
"""

import math
import time
from collections import deque

# ---------------------------------------------------------------------------
# The one stall-threshold rule (watchdog + replica health share it)
# ---------------------------------------------------------------------------


def stall_threshold_secs(floor_secs, median_secs, factor=10.0):
    """THE stall-threshold rule: `max(floor, factor x median completed
    step/window time)` — scale-free from ms CPU smokes to tens-of-
    seconds pod windows. obs/watchdog.py (training windows) and
    serve/replica.py (replica heartbeats) both delegate here; the
    anomaly engine's heartbeat-creep detector fires at a SMALLER factor
    of the same median, which is what makes "strictly before the stall
    tier" a property of the rule rather than of tuning luck."""
    return max(float(floor_secs), float(factor) * float(median_secs))


# ---------------------------------------------------------------------------
# The one exact small-n quantile rule (reports, benches)
# ---------------------------------------------------------------------------


def percentile(xs, q):
    """Exact nearest-rank percentile (index ceil(q*n)-1) of a small
    list. Returns None on empty input. `percentile(xs, 0.5)` equals
    `median_low` by construction (both return the lower-middle
    ELEMENT), so benches that switched here from statistics.median_low
    report bit-identical headlines."""
    if not xs:
        return None
    s = sorted(xs)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


# ---------------------------------------------------------------------------
# Mergeable streaming quantile sketch
# ---------------------------------------------------------------------------


class QuantileSketch:
    """DDSketch-style log-bucketed quantile sketch.

    Positive values land in bucket `ceil(log_gamma(v))` with
    gamma = (1 + alpha) / (1 - alpha); the bucket's representative value
    `2 * gamma^k / (gamma + 1)` is within relative error `alpha` of
    every value the bucket holds, so any quantile estimate is within
    `alpha` relative error of an exact rank statistic — the bound the
    sketch-vs-numpy agreement tests assert. Zero/negative values (a
    0.0 ms wait is real) count in a dedicated zero bucket.

    Fixed memory: beyond `max_bins` distinct buckets the LOWEST buckets
    collapse into one (tail quantiles — the p99s operators alert on —
    keep their error bound; the collapsed low end degrades first, by
    design). Mergeable: bucket counts add (`merge`), and
    `take_delta()` returns the counts since the last take — the wire
    form a worker ships in its step replies so the parent-side sketch
    equals one built from the raw stream (tests pin merge-of-deltas ==
    direct)."""

    def __init__(self, alpha=0.01, max_bins=512):
        assert 0.0 < alpha < 1.0
        self.alpha = float(alpha)
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._lg = math.log(self.gamma)
        self.max_bins = int(max_bins)
        self.bins = {}        # bucket key -> count
        self.zero = 0         # values <= 0 (latencies: exactly-0 waits)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._shipped = None  # last take_delta() snapshot

    # -- write --

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= 0.0:
            self.zero += 1
            return
        k = math.ceil(math.log(v) / self._lg)
        self.bins[k] = self.bins.get(k, 0) + 1
        if len(self.bins) > self.max_bins:
            self._collapse()

    def _collapse(self):
        """Fold the two lowest buckets together until under max_bins —
        the low tail loses resolution, the operator-facing high tail
        never does."""
        keys = sorted(self.bins)
        while len(self.bins) > self.max_bins:
            k0, k1 = keys[0], keys[1]
            self.bins[k1] += self.bins.pop(k0)
            keys = keys[1:]

    # -- read --

    def _bucket_value(self, k):
        return 2.0 * (self.gamma ** k) / (self.gamma + 1.0)

    def quantile(self, q):
        """Value at quantile q in [0, 1]; None when empty. Exact-rank
        semantics over buckets: the bucket holding the ceil(q*n)-th
        smallest observation answers, via its representative value."""
        if self.count == 0:
            return None
        rank = min(self.count, max(1, math.ceil(q * self.count)))
        if rank <= self.zero:
            return 0.0
        acc = self.zero
        for k in sorted(self.bins):
            acc += self.bins[k]
            if acc >= rank:
                return self._bucket_value(k)
        return self.max  # numeric-slop fallback; unreachable in theory

    def summary(self, qs=(0.50, 0.95, 0.99)):
        out = {"count": self.count, "sum": self.sum,
               "min": self.min, "max": self.max}
        for q in qs:
            out[f"p{int(q * 100)}"] = self.quantile(q)
        return out

    # -- merge / wire form --

    def merge(self, other):
        """Fold another sketch (same alpha) into this one in place."""
        assert abs(other.gamma - self.gamma) < 1e-12, (
            "merging sketches with different alpha would silently "
            "mis-bucket — build both ends with the same resolution")
        for k, c in other.bins.items():
            self.bins[k] = self.bins.get(k, 0) + c
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        for v in (other.min, other.max):
            if v is not None:
                self.min = v if self.min is None else min(self.min, v)
                self.max = v if self.max is None else max(self.max, v)
        if len(self.bins) > self.max_bins:
            self._collapse()
        return self

    def to_dict(self):
        """JSON-serializable snapshot (the run_end form)."""
        return {"alpha": self.alpha, "zero": self.zero,
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "bins": {str(k): c for k, c in self.bins.items()}}

    @classmethod
    def from_dict(cls, d, max_bins=512):
        sk = cls(alpha=float(d.get("alpha", 0.01)), max_bins=max_bins)
        sk.zero = int(d.get("zero", 0))
        sk.count = int(d.get("count", 0))
        sk.sum = float(d.get("sum", 0.0))
        sk.min = d.get("min")
        sk.max = d.get("max")
        sk.bins = {int(k): int(c) for k, c in (d.get("bins")
                                               or {}).items()}
        return sk

    def take_delta(self):
        """Bucket counts since the last take — the per-step-reply wire
        form (serve/worker.py ships it, serve/proc.py merge_dict()s it
        parent-side, exactly like the engine counter deltas). Returns
        None when nothing new landed."""
        cur = self.to_dict()
        prev = self._shipped
        self._shipped = cur
        if prev is None:
            return cur if cur["count"] else None
        if cur["count"] == prev["count"]:
            return None
        d = {"alpha": self.alpha,
             "zero": cur["zero"] - prev["zero"],
             "count": cur["count"] - prev["count"],
             "sum": cur["sum"] - prev["sum"],
             # min/max of the delta window are unknowable from
             # snapshots; ship the lifetime ones (merge keeps min/max
             # correct because they are monotone under observation)
             "min": cur["min"], "max": cur["max"],
             "bins": {}}
        prev_bins = prev["bins"]
        for k, c in cur["bins"].items():
            dc = c - prev_bins.get(k, 0)
            if dc:
                d["bins"][k] = dc
        return d

    def merge_dict(self, d):
        """Fold a to_dict()/take_delta() payload into this sketch (the
        parent side of the heartbeat shipping)."""
        if not d:
            return self
        return self.merge(QuantileSketch.from_dict(d,
                                                   max_bins=self.max_bins))


# ---------------------------------------------------------------------------
# Windowed time-series
# ---------------------------------------------------------------------------


class Series:
    """One signal's bounded history: per-window aggregates (ring) + a
    lifetime QuantileSketch.

    `observe(v, t=None)` files the value into the current `window_s`
    window; when t crosses a window boundary the finished window's
    aggregate enters the ring (oldest evicted past `n_windows`). The
    detectors read `window_means()` — drift/trend live at window
    granularity — and the sketch answers p50/p99 for the per-series
    gauges and the run_end snapshot."""

    __slots__ = ("key", "window_s", "n_windows", "clock", "sketch",
                 "_win", "_ring")

    def __init__(self, key, *, window_s=1.0, n_windows=64, clock=None,
                 alpha=0.01):
        self.key = key
        self.window_s = float(window_s)
        self.n_windows = int(n_windows)
        self.clock = clock if clock is not None else time.perf_counter
        self.sketch = QuantileSketch(alpha=alpha)
        self._win = None           # [start, count, sum, min, max]
        self._ring = deque(maxlen=self.n_windows)

    def observe(self, v, t=None):
        v = float(v)
        t = self.clock() if t is None else float(t)
        self.sketch.observe(v)
        w = self._win
        if w is None:
            self._win = [t, 1, v, v, v]
            return
        if t - w[0] >= self.window_s:
            self._roll(t)
            self._win = [self._win[0], 1, v, v, v]
            return
        w[1] += 1
        w[2] += v
        w[3] = min(w[3], v)
        w[4] = max(w[4], v)

    def _roll(self, t):
        """Close the current window into the ring and open the one
        containing `t` (empty windows — between-gap ones AND a
        just-flushed still-empty current — are dropped, never ringed:
        a count-0 window's inf/-inf min/max would poison the snapshot
        JSON, and a gap in the signal should read as a time gap, not
        phantom zeros)."""
        w = self._win
        if w[1] > 0:
            self._ring.append((w[0], w[1], w[2], w[3], w[4]))
        n_ahead = math.floor((t - w[0]) / self.window_s)
        self._win = [w[0] + n_ahead * self.window_s, 0, 0.0,
                     math.inf, -math.inf]

    def flush(self, now=None):
        """Force the open window into the ring (detectors run at check
        cadence, which need not align with window boundaries)."""
        now = self.clock() if now is None else now
        if self._win is not None and self._win[1] > 0 \
                and now - self._win[0] >= self.window_s:
            self._roll(now)

    # -- read --

    @property
    def count(self):
        return self.sketch.count

    def last(self):
        if self._win is not None and self._win[1] > 0:
            return self._win[2] / self._win[1]
        if self._ring:
            _, n, s, _, _ = self._ring[-1]
            return s / n if n else None
        return None

    def window_means(self, include_open=True):
        """Per-window mean values, oldest first — the detector input."""
        out = [(t0, s / n) for t0, n, s, _, _ in self._ring if n]
        if include_open and self._win is not None and self._win[1] > 0:
            out.append((self._win[0], self._win[2] / self._win[1]))
        return out

    def last_window_sum(self):
        """SUM of the newest complete window (falling back to the open
        one, then None). Rate detectors divide this by window_s — the
        per-window mean would shrink with the caller's check frequency
        and silently under-read a real event rate."""
        if self._ring:
            return self._ring[-1][2]
        if self._win is not None and self._win[1] > 0:
            return self._win[2]
        return None

    def quantile(self, q):
        return self.sketch.quantile(q)

    def snapshot(self):
        return {"key": self.key, "window_s": self.window_s,
                "sketch": self.sketch.to_dict(),
                "windows": [[round(t0, 6), n, s, lo, hi]
                            for t0, n, s, lo, hi in self._ring]}


class SeriesStore:
    """Keyed Series collection — the per-process home the anomaly
    engine and the engines observe into. `schema` (default
    METRIC_SCHEMA) gates keys the same way the registry does: a series
    over an undeclared signal fails in tests, not in production."""

    def __init__(self, *, schema=None, clock=None, window_s=1.0,
                 n_windows=64, alpha=0.01):
        if schema is None:
            from avenir_tpu.obs.metrics import METRIC_SCHEMA

            schema = METRIC_SCHEMA
        self._schema = schema
        self.clock = clock if clock is not None else time.perf_counter
        self.window_s = float(window_s)
        self.n_windows = int(n_windows)
        self.alpha = float(alpha)
        self._series = {}

    def series(self, key, *, window_s=None):
        s = self._series.get(key)
        if s is None:
            assert key in self._schema, (
                f"series key {key!r} is not declared in METRIC_SCHEMA — "
                "a series is a view over a declared metric signal (add "
                "the key there AND to docs/OBSERVABILITY.md)")
            s = self._series[key] = Series(
                key, window_s=window_s or self.window_s,
                n_windows=self.n_windows, clock=self.clock,
                alpha=self.alpha)
        return s

    def observe(self, key, v, t=None):
        self.series(key).observe(v, t=t)

    def get(self, key):
        return self._series.get(key)

    def keys(self):
        return list(self._series)

    def snapshot(self):
        """{key: series snapshot} — JSON-serializable (run_end)."""
        return {k: s.snapshot() for k, s in self._series.items()}


__all__ = [
    "QuantileSketch", "Series", "SeriesStore", "percentile",
    "stall_threshold_secs",
]
