"""Stall watchdog: a daemon thread that notices a frozen training loop.

A hung pod collective (one process wedged in a psum the others already
entered, a dead DCN link, a deadlocked host callback) freezes the loop
SILENTLY — no exception, no log line, just no more windows. The
watchdog turns that silence into a warning: the loop calls
`notify(window_secs)` every time a window completes, and the thread
fires when no progress lands within max(floor_secs, factor x median
window time). On the first warning of a stall episode it optionally
dumps all Python thread stacks via faulthandler (the fastest way to see
WHERE the main thread is wedged), increments the `watchdog_stalls`
counter, and writes a `stall` record to the run log. Repeat warnings
are spaced one threshold apart so a long stall logs O(log) lines, not
one per poll tick.

The median-based threshold keeps one knob (`--watchdog_secs`, the
floor) meaningful across model scales: tiny CPU smokes complete windows
in milliseconds, an 8B pod run in tens of seconds — 10x the median is a
stall for both.
"""

import statistics
import sys
import threading
import time
from contextlib import contextmanager


class StallWatchdog:
    def __init__(self, *, floor_secs, factor=10.0, poll_secs=1.0,
                 registry=None, sink=None, dump_stacks=True,
                 echo=print, fatal_count=0, exit_fn=None):
        """`floor_secs`: minimum stall threshold (the --watchdog_secs
        flag; also the only threshold until the first window lands).
        `factor`: multiple of the median completed-window time that
        counts as a stall once windows have completed.
        `fatal_count` (the --watchdog_fatal_count flag, default 0=off):
        after that many CONSECUTIVE warnings with no progress between
        them, dump stacks one last time and exit the process non-zero —
        a hung collective holds every process of a pod hostage forever
        otherwise, and a supervisor can only restart a job that DIES.
        `exit_fn` is injectable for tests; the default is os._exit
        (sys.exit from a daemon thread cannot kill the process)."""
        assert floor_secs > 0 and factor > 0
        self.floor_secs = float(floor_secs)
        self.factor = float(factor)
        self.poll_secs = float(poll_secs)
        self.fatal_count = int(fatal_count or 0)
        self._exit_fn = exit_fn if exit_fn is not None else self._os_exit
        self._registry = registry
        self._sink = sink
        self._dump_stacks = dump_stacks
        self._echo = echo
        self._lock = threading.Lock()
        self._last_progress = time.monotonic()
        self._durations = []  # recent window wall times, secs (cap 128)
        self._iter = 0
        self._paused = 0  # >0: inside a declared host boundary, don't fire
        self._warned_at = None  # monotonic time of last warning, or None
        self._consecutive = 0  # warnings since the last progress/pause
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="avenir-stall-watchdog", daemon=True)
        self._thread.start()

    # non-zero and distinctive: a supervisor (or a human reading pod
    # logs) can tell a watchdog kill from an OOM or a python traceback
    FATAL_EXIT_CODE = 70  # EX_SOFTWARE

    @staticmethod
    def _os_exit(code):  # pragma: no cover — tests inject exit_fn
        import os

        os._exit(code)

    def notify(self, window_secs=None, iter_num=None):
        """Record loop progress (call on every completed window)."""
        with self._lock:
            self._last_progress = time.monotonic()
            self._warned_at = None
            self._consecutive = 0
            if iter_num is not None:
                self._iter = int(iter_num)
            if window_secs is not None:
                self._durations.append(float(window_secs))
                if len(self._durations) > 128:
                    del self._durations[:64]

    @contextmanager
    def pause(self):
        """Declare a legitimate long host boundary (eval, sync save, an
        expected first-window compile): the watchdog holds its fire for
        the duration and restarts its clock when the boundary ends. A
        hang INSIDE a paused region is by definition indistinguishable
        from the boundary running long, so it is not flagged — the
        watchdog's contract is steady-state window progress."""
        with self._lock:
            self._paused += 1
        try:
            yield
        finally:
            with self._lock:
                self._paused -= 1
                self._last_progress = time.monotonic()
                self._warned_at = None
                self._consecutive = 0

    def threshold_secs(self):
        # the ONE stall-threshold rule (obs/series.py, ISSUE 14): shared
        # with serve/replica.py's heartbeat health check so the two
        # stall tiers can never drift apart
        from avenir_tpu.obs.series import stall_threshold_secs

        with self._lock:
            if not self._durations:
                return self.floor_secs
            return stall_threshold_secs(
                self.floor_secs, statistics.median_low(self._durations),
                factor=self.factor)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    # ---- thread body ----

    def _run(self):
        while not self._stop.wait(self.poll_secs):
            now = time.monotonic()
            thr = self.threshold_secs()
            with self._lock:
                if self._paused:
                    continue
                since = now - self._last_progress
                warned_at = self._warned_at
            if since <= thr:
                continue
            # re-warn one threshold after the previous warning, not per tick
            if warned_at is not None and now - warned_at < thr:
                continue
            with self._lock:
                self._warned_at = now
            self._fire(since, thr)

    def _fire(self, since, thr):
        with self._lock:
            self._consecutive += 1
            consecutive = self._consecutive
        fatal = bool(self.fatal_count) and consecutive >= self.fatal_count
        self._echo(
            f"[watchdog] no training window completed in {since:.1f}s "
            f"(stall threshold {thr:.1f}s = max(floor {self.floor_secs:.1f}s, "
            f"{self.factor:.0f}x median window)); last progress at iter "
            f"{self._iter} — a hung collective or wedged host thread?"
            + (f" [warning {consecutive}/{self.fatal_count} before fatal "
               "exit]" if self.fatal_count else "")
        )
        if self._registry is not None:
            self._registry.counter("watchdog_stalls").add(1)
        if self._sink is not None:
            self._sink.write({
                "kind": "stall", "t": time.time(), "iter": self._iter,
                "secs_since_progress": round(since, 3),
                "threshold_s": round(thr, 3), "fatal": fatal,
            })
        if self._dump_stacks or fatal:
            import faulthandler

            self._echo("[watchdog] python stacks of all threads:")
            try:
                faulthandler.dump_traceback(file=sys.stderr)
            except Exception:
                pass  # never let diagnostics kill the watchdog
        # a stall is exactly what the flight recorder exists for: dump
        # the last-N trace events when a tracer is armed (ISSUE 10;
        # flight_dump never raises and no-ops without an out_dir)
        from avenir_tpu.obs.trace import get_tracer

        tr = get_tracer()
        if tr is not None:
            path = tr.flight_dump("watchdog")
            if path:
                self._echo(f"[watchdog] flight recorder dumped: {path}")
        if fatal:
            # escalation (ISSUE 5 satellite): the loop is not coming
            # back — exit non-zero so a pod supervisor restarts the job
            # (which resumes from the last committed checkpoint). The
            # JSONL sink flushes per write, so the stall record above is
            # already durable.
            self._echo(
                f"[watchdog] FATAL: {consecutive} consecutive stall "
                f"warnings with no progress — exiting "
                f"{self.FATAL_EXIT_CODE} for the supervisor to restart"
            )
            self._exit_fn(self.FATAL_EXIT_CODE)
