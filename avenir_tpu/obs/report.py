"""Turn a metrics.jsonl run log into a goodput/timing summary.

The report answers the question the raw iter lines can't: where did the
wall time actually go? Components (docs/OBSERVABILITY.md "Goodput"):

  device      steady-state window time minus host batch staging —
              time the devices were doing optimizer steps
  host_batch  host-side batch staging (loop spans; overlapped with
              device compute in the windowed loop, charged here so the
              components partition the total)
  eval        estimate_loss (host-blocking by design)
  checkpoint  loop-blocking save time (async writer time is separate —
              it overlaps training and is reported as a footnote)
  compile     trace+compile of each new window length
  untracked   total minus all of the above (loop bookkeeping, signal
              exchanges; should be small — a big number here is a bug)

CLI wrapper: tools/obs_report.py. Library entry: summarize(records).
"""

import json
import statistics

# the ONE quantile rule (ISSUE 14): exact nearest-rank for small lists
# lives in obs/series.py beside the streaming sketch; re-exported here
# because every report/bench historically imported it from this module
from avenir_tpu.obs.series import QuantileSketch, percentile  # noqa: F401


def load_records_with_skips(path):
    """Parse a metrics.jsonl; returns (records, skipped_line_numbers).

    A killed run (SIGKILL, ENOSPC) can truncate the final line
    MID-RECORD — including mid-multibyte-character, which used to raise
    UnicodeDecodeError out of text-mode iteration and crash the report
    on exactly the logs a crashed run leaves behind. Read bytes, decode
    and parse per line, and SKIP what doesn't parse; the skip is
    surfaced in the report output (and on stderr), never silent."""
    import sys

    records, skipped = [], []
    with open(path, "rb") as f:
        # iterate BYTES lines (streaming — a multi-day log never sits in
        # memory whole; binary iteration also never decodes, so the torn
        # multibyte tail surfaces at json-parse time, per line)
        for lineno, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                records.append(json.loads(raw.decode("utf-8")))
            except (json.JSONDecodeError, UnicodeDecodeError):
                skipped.append(lineno)
                print(f"[obs_report] skipping unparseable line {lineno} "
                      f"of {path} (torn write from a killed run?)",
                      file=sys.stderr)
    return records, skipped


def load_records(path):
    """Parse a metrics.jsonl, torn lines skipped (see
    load_records_with_skips for the skip accounting)."""
    return load_records_with_skips(path)[0]


def _by_kind(records, kind):
    return [r for r in records if r.get("kind") == kind]


def summarize(records, *, skipped_lines=()):
    """Compute the goodput breakdown + run facts from parsed records.
    Returns a plain dict (format_report renders it). A resumed run's log
    holds one SEGMENT per launch (each starting with run_meta, appended
    by the sink); the summary covers the last segment — earlier segments
    stay on disk and can be sliced out by their run_meta records.
    `skipped_lines`: line numbers load_records_with_skips dropped (torn
    writes) — noted in the report so a truncated log reads as one."""
    assert records, "empty metrics log"
    metas = [i for i, r in enumerate(records) if r.get("kind") == "run_meta"]
    n_segments = len(metas)
    if metas:
        records = records[metas[-1]:]
    meta = (_by_kind(records, "run_meta") or [{}])[0]
    end = (_by_kind(records, "run_end") or [{}])[-1]
    iters = _by_kind(records, "iter")
    evals = _by_kind(records, "eval")
    stalls = _by_kind(records, "stall")

    counters = dict(end.get("counters") or
                    (iters[-1].get("counters") if iters else {}) or {})
    t0 = meta.get("t", records[0].get("t"))
    t1 = end.get("t", records[-1].get("t"))
    total_ms = max(0.0, (t1 - t0) * 1e3) if (t0 and t1) else 0.0

    step_window = counters.get("step_window_ms", 0.0)
    host_batch = counters.get("host_batch_ms", 0.0)
    components = {
        "device": max(0.0, step_window - host_batch),
        "host_batch": host_batch,
        "eval": counters.get("eval_ms", 0.0),
        "checkpoint": counters.get("checkpoint_ms", 0.0),
        "compile": counters.get("compile_ms", 0.0),
    }
    tracked_ms = sum(components.values())
    untracked_ms = total_ms - tracked_ms

    losses = [(r["iter"], r["loss"]) for r in iters]
    dts = [r["dt_ms"] for r in iters if "dt_ms" in r]
    toks = [r["tok_per_sec"] for r in iters if "tok_per_sec" in r]
    retries = _by_kind(records, "retry")
    restores = _by_kind(records, "restore")
    requests = _by_kind(records, "request")
    anomalies = _by_kind(records, "anomaly")

    # ISSUE 14: run_end carries the health engine's series sketches —
    # percentiles come from THE sketch, not re-derived from raw records
    # (the one quantile rule); raw per-request records stay the fallback
    def sketch_q(key, q):
        d = ((end.get("series") or {}).get(key) or {}).get("sketch")
        if not d:
            return None
        sk = QuantileSketch.from_dict(d)
        return sk.quantile(q) if sk.count else None

    serve = None
    if requests:
        ttfts = [r["ttft_ms"] for r in requests if "ttft_ms" in r]
        tpots = [r["tpot_ms"] for r in requests if "tpot_ms" in r]
        sk_ttft50 = sketch_q("ttft_ms", 0.50)
        sk_ttft99 = sketch_q("ttft_ms", 0.99)
        sk_tpot50 = sketch_q("tpot_ms", 0.50)
        sk_tpot99 = sketch_q("tpot_ms", 0.99)
        # run_end counters when the run exited cleanly; a torn log (the
        # exact case load_records tolerates) still has per-request n_out
        tokens_out = (counters.get("tokens_out")
                      or float(sum(r.get("n_out", 0) for r in requests)))
        serve = {
            "n_requests": len(requests),
            "n_timeouts": sum(1 for r in requests
                              if r.get("finish_reason") == "timeout"),
            "n_shed": sum(1 for r in requests
                          if r.get("finish_reason") == "shed"),
            "n_rejected": sum(1 for r in requests
                              if r.get("finish_reason") == "rejected"),
            "failovers": counters.get("serve_failovers", 0.0),
            "respawns": counters.get("replica_respawns", 0.0),
            "rpc_timeouts": counters.get("rpc_timeouts", 0.0),
            "frame_crc_errors": counters.get("frame_crc_errors", 0.0),
            # elastic control plane (ISSUE 12): decision counts + the
            # integrated replica-second bill the autoscaler optimizes
            "scale_up": counters.get("scale_up", 0.0),
            "scale_down": counters.get("scale_down", 0.0),
            "replica_seconds": counters.get("fleet_replica_seconds", 0.0),
            "prewarm_ticks": counters.get("prewarm_ticks", 0.0),
            "tokens_out": tokens_out,
            "goodput_tok_per_sec": (tokens_out / (total_ms / 1e3)
                                    if total_ms else None),
            "ttft_p50_ms": (sk_ttft50 if sk_ttft50 is not None
                            else percentile(ttfts, 0.50)),
            "ttft_p99_ms": (sk_ttft99 if sk_ttft99 is not None
                            else percentile(ttfts, 0.99)),
            "tpot_p50_ms": (sk_tpot50 if sk_tpot50 is not None
                            else percentile(tpots, 0.50)),
            "tpot_p99_ms": (sk_tpot99 if sk_tpot99 is not None
                            else percentile(tpots, 0.99)),
            "latency_source": ("sketch" if sk_ttft50 is not None
                               else "records"),
            # paged KV (ISSUE 9): chunk counter from counters, pool
            # pressure from the run_end record's gauge snapshot (when
            # the bench wrote one — gauges are points, not totals)
            "prefill_chunks": counters.get("prefill_chunks", 0.0),
            "kv_page_util": (end.get("gauges") or {}).get("kv_page_util"),
            "kv_pages_free": (end.get("gauges") or {}).get("kv_pages_free"),
            "prefix_hit_rate": (end.get("gauges")
                                or {}).get("prefix_hit_rate"),
            # speculative decoding (ISSUE 11): counters carry totals;
            # the gauge snapshot names the KV width the run served at
            "spec_proposed": counters.get("spec_proposed", 0.0),
            "spec_accepted": counters.get("spec_accepted", 0.0),
            # spec composition (ISSUE 18): the n-gram self-draft's
            # lookup hit count and the adaptive-k controller's
            # effective depth at the end of the run
            "ngram_hits": counters.get("ngram_hits", 0.0),
            # the counter is registered (at 0) iff the engine ran the
            # n-gram self-draft, so presence names the draft source
            "spec_draft_source": ("ngram" if "ngram_hits" in counters
                                  else "model"),
            "spec_k_effective": (end.get("gauges")
                                 or {}).get("spec_k_effective"),
            "kv_dtype_bits": (end.get("gauges") or {}).get("kv_dtype"),
            # fleet cache telescope (ISSUE 16): the reuse audit's token
            # partition; est saved ms derives from the run's own
            # measured per-token prefill cost over the tokens prefill
            # actually computed (missed + cold)
            "prefix_tokens_reused": counters.get(
                "prefix_tokens_reused", 0.0),
            "prefix_tokens_missed": counters.get(
                "prefix_tokens_missed", 0.0),
            "prefix_tokens_cold": counters.get("prefix_tokens_cold", 0.0),
            "est_prefill_ms_saved": (
                counters.get("prefix_tokens_missed", 0.0)
                * counters.get("serve_prefill_ms", 0.0)
                / (counters.get("prefix_tokens_missed", 0.0)
                   + counters.get("prefix_tokens_cold", 0.0))
                if (counters.get("prefix_tokens_missed", 0.0)
                    + counters.get("prefix_tokens_cold", 0.0)) > 0
                else 0.0),
            # live weight lifecycle (ISSUE 20): campaign counts + the
            # version the fleet last CONVERGED on (gauge snapshot —
            # mid-rollout it still names the previous converged value)
            "rollouts": counters.get("rollouts", 0.0),
            "rollbacks": counters.get("rollbacks", 0.0),
            "canary_anomalies": counters.get("canary_anomalies", 0.0),
            "weight_version": (end.get("gauges")
                               or {}).get("weight_version"),
            # fleet KV CDN (ISSUE 17): affinity placements + the peer
            # pull ledger (pages/bytes shipped, fallbacks taken)
            "affinity_hits": counters.get("affinity_hits", 0.0),
            "prefix_pull_pages": counters.get("prefix_pull_pages", 0.0),
            "prefix_pull_bytes": counters.get("prefix_pull_bytes", 0.0),
            "prefix_pull_fallbacks": counters.get(
                "prefix_pull_fallbacks", 0.0),
        }
    by_detector = {}
    for r in anomalies:
        d = r.get("detector", "?")
        by_detector[d] = by_detector.get(d, 0) + 1
    # input pipeline (ISSUE 19): counter totals + the run_end record's
    # schema-free loader report (per-corpus draw counts keyed by corpus
    # NAME can't be fixed METRIC_SCHEMA keys, so they ride the record)
    data_end = end.get("data") or {}
    data = {
        "windows": counters.get("data_windows", 0.0),
        "prefetch_hit": counters.get("data_prefetch_hit", 0.0),
        "prefetch_wait_ms": counters.get("data_prefetch_wait_ms", 0.0),
        "stage_ms": counters.get("data_stage_ms", 0.0),
        "tokens": counters.get("data_tokens", 0.0),
        "prefetch_depth": data_end.get("prefetch_depth"),
        "mix": data_end.get("mix"),
        "crops": data_end.get("crops"),
    }
    return {
        "serve": serve,
        "data": data,
        "meta": meta,
        # fleet health engine (ISSUE 14): the early-warning tier's
        # activity — counter totals when the run ended cleanly, the
        # per-event records cover killed runs too (the io_retries rule)
        "anomalies": {
            "n": max(int(counters.get("anomaly", 0.0)), len(anomalies)),
            "suppressed": counters.get("anomalies_suppressed", 0.0),
            "by_detector": by_detector,
            "first_t": min((r["t"] for r in anomalies), default=None),
            "last_t": max((r["t"] for r in anomalies), default=None),
            "t0": t0,
        },
        "skipped_lines": list(skipped_lines),
        "n_segments": n_segments,
        "total_ms": total_ms,
        "components": components,
        "tracked_ms": tracked_ms,
        "untracked_ms": untracked_ms,
        "coverage": (tracked_ms / total_ms) if total_ms else None,
        "counters": counters,
        "n_iter_records": len(iters),
        "first_loss": losses[0] if losses else None,
        "last_loss": losses[-1] if losses else None,
        "median_dt_ms": statistics.median_low(dts) if dts else None,
        "median_tok_per_sec": statistics.median_low(toks) if toks else None,
        "n_evals": len(evals),
        "n_stalls": len(stalls),
        "ckpt_async_writer_ms": counters.get("ckpt_save_ms", 0.0),
        "ckpt_bytes_written": counters.get("ckpt_bytes_written", 0.0),
        "restore_ms": counters.get("ckpt_restore_ms", 0.0),
        "restore_bytes": counters.get("ckpt_restore_bytes", 0.0),
        "pipe_ticks_real": counters.get("pipe_ticks_real", 0.0),
        "pipe_ticks_bubble": counters.get("pipe_ticks_bubble", 0.0),
        # fault tolerance (ISSUE 5): counters carry totals when the run
        # ended cleanly; the per-event records cover killed runs too
        "io_retries": max(counters.get("io_retries", 0.0), len(retries)),
        "ckpt_fallback": counters.get("ckpt_fallback", 0.0),
        "ckpt_corrupt_detected": counters.get("ckpt_corrupt_detected", 0.0),
        "ckpt_save_errors": counters.get("ckpt_save_errors", 0.0),
        "n_restores": len(restores),
        "restore_fallbacks": sum(r.get("skipped_bad", 0) for r in restores),
    }


def _fmt_ms(ms):
    return f"{ms / 1e3:10.3f}s"


def format_report(s):
    meta = s["meta"]
    lines = []
    lines.append("== avenir run report ==")
    if s.get("skipped_lines"):
        sk = s["skipped_lines"]
        lines.append(f"(skipped {len(sk)} unparseable log line(s) "
                     f"[{', '.join(str(n) for n in sk[:8])}"
                     f"{', ...' if len(sk) > 8 else ''}] — torn write "
                     "from a killed run; totals may undercount the "
                     "final instants)")
    if s.get("n_segments", 1) > 1:
        lines.append(f"(resumed run: {s['n_segments']} segments in the log; "
                     "summarizing the last)")
    if meta:
        fields = [f"{k}={meta[k]}" for k in
                  ("model_type", "n_chips", "tokens_per_iter", "block_size")
                  if k in meta]
        if fields:
            lines.append("run:      " + "  ".join(fields))
    if s["first_loss"] is not None:
        (i0, l0), (i1, l1) = s["first_loss"], s["last_loss"]
        lines.append(f"loss:     {l0:.4f} (iter {i0}) -> {l1:.4f} (iter {i1})"
                     f"  over {s['n_iter_records']} logged iters")
    if s["median_dt_ms"] is not None:
        tps = s["median_tok_per_sec"]
        lines.append(f"speed:    median {s['median_dt_ms']:.2f} ms/iter"
                     + (f", {tps:,.0f} tok/s global" if tps else ""))
    d = s.get("data") or {}
    if d.get("windows") or d.get("crops"):
        bits = []
        if d.get("windows"):
            bits.append(f"prefetch hit {d['prefetch_hit'] / d['windows']:.0%}"
                        f" of {d['windows']:.0f} windows")
        bits.append(f"wait {d['prefetch_wait_ms']:.0f} ms")
        if d.get("prefetch_depth"):
            bits.append(f"depth {d['prefetch_depth']}")
        # per-corpus draw counts (mixed runs): the train split's totals
        crops = (d.get("crops") or {}).get("train") or {}
        if crops:
            bits.append("mix " + " ".join(f"{k}:{v:,.0f}"
                                          for k, v in sorted(crops.items())))
        lines.append("data:     " + "   ".join(bits))
    lines.append("")
    lines.append("-- goodput (share of loop wall time) --")
    total = s["total_ms"]
    for name in ("device", "host_batch", "eval", "checkpoint", "compile"):
        ms = s["components"][name]
        pct = (100.0 * ms / total) if total else 0.0
        lines.append(f"  {name:<11}{_fmt_ms(ms)}  {pct:5.1f}%")
    pct_un = (100.0 * s["untracked_ms"] / total) if total else 0.0
    lines.append(f"  {'untracked':<11}{_fmt_ms(s['untracked_ms'])}  {pct_un:5.1f}%")
    lines.append(f"  {'total':<11}{_fmt_ms(total)}  100.0%")
    if s["coverage"] is not None:
        lines.append(f"  tracked coverage: {100.0 * s['coverage']:.1f}% "
                     "(device+host_batch+eval+checkpoint+compile)")
    extras = []
    if s["ckpt_async_writer_ms"]:
        extras.append(f"checkpoint writer {s['ckpt_async_writer_ms'] / 1e3:.3f}s "
                      f"/ {s['ckpt_bytes_written'] / 1e6:.1f} MB "
                      "(overlaps training when async)")
    if s["restore_ms"]:
        extras.append(f"restore {s['restore_ms'] / 1e3:.3f}s "
                      f"/ {s['restore_bytes'] / 1e6:.1f} MB read")
    pp_total = s["pipe_ticks_real"] + s["pipe_ticks_bubble"]
    if pp_total:
        extras.append(
            f"pipeline: {s['pipe_ticks_bubble'] / pp_total:.0%} bubble "
            f"({s['pipe_ticks_real']:.0f} real / "
            f"{s['pipe_ticks_bubble']:.0f} bubble tick-slots, summed "
            "over region traces)")
    if s["io_retries"]:
        extras.append(f"flaky IO: {s['io_retries']:.0f} transient-read/"
                      "write retries (see `retry` records)")
    if s["ckpt_save_errors"]:
        extras.append(f"CHECKPOINT SAVE ERRORS: {s['ckpt_save_errors']:.0f}")
    if s["ckpt_corrupt_detected"] or s["ckpt_fallback"]:
        extras.append(
            f"CHECKPOINT CORRUPTION: {s['ckpt_corrupt_detected']:.0f} "
            f"artifact(s) refused, {s['ckpt_fallback']:.0f} restore "
            "fallback(s) to an older generation — check the storage")
    if s["n_stalls"]:
        extras.append(f"WATCHDOG STALL WARNINGS: {s['n_stalls']}")
    an = s.get("anomalies") or {}
    if an.get("n"):
        bits = [f"{k}={v}" for k, v in sorted(an["by_detector"].items())]
        line = (f"ANOMALIES: {an['n']:.0f}"
                + (f" ({', '.join(bits)})" if bits else ""))
        if an.get("first_t") is not None and an.get("t0"):
            line += (f"  first +{an['first_t'] - an['t0']:.1f}s"
                     f"  last +{an['last_t'] - an['t0']:.1f}s")
        if an.get("suppressed"):
            line += f"  [{an['suppressed']:.0f} suppressed by cooldown]"
        extras.append(line)
    if extras:
        lines.append("")
        lines += ["  " + e for e in extras]
    sv = s.get("serve")
    if sv:
        lines.append("")
        lines.append("-- serving --")
        lines.append(f"  requests: {sv['n_requests']}   "
                     f"tokens out: {sv['tokens_out']:,.0f}"
                     + (f"   goodput {sv['goodput_tok_per_sec']:,.1f} tok/s"
                        if sv["goodput_tok_per_sec"] is not None else "")
                     + (f"   TIMEOUTS: {sv['n_timeouts']}"
                        if sv.get("n_timeouts") else ""))
        fleet_bits = [
            f"failovers {sv['failovers']:.0f}" if sv.get("failovers") else "",
            f"respawns {sv['respawns']:.0f}" if sv.get("respawns") else "",
            (f"RPC TIMEOUTS: {sv['rpc_timeouts']:.0f}"
             if sv.get("rpc_timeouts") else ""),
            (f"FRAME CRC ERRORS: {sv['frame_crc_errors']:.0f}"
             if sv.get("frame_crc_errors") else ""),
            f"SHED: {sv['n_shed']}" if sv.get("n_shed") else "",
            f"rejected {sv['n_rejected']}" if sv.get("n_rejected") else "",
            (f"scale +{sv['scale_up']:.0f}/-{sv['scale_down']:.0f}"
             if sv.get("scale_up") or sv.get("scale_down") else ""),
            (f"replica-seconds {sv['replica_seconds']:.1f}"
             if sv.get("replica_seconds") else ""),
            (f"prewarm ticks {sv['prewarm_ticks']:.0f}"
             if sv.get("prewarm_ticks") else ""),
            (f"version: {sv['weight_version']:.0f}"
             + (f" (rollouts {sv['rollouts']:.0f}"
                + (f", ROLLBACKS {sv['rollbacks']:.0f}"
                   if sv.get("rollbacks") else "") + ")"
                if sv.get("rollouts") else "")
             if sv.get("weight_version") is not None else ""),
            (f"affinity hits {sv['affinity_hits']:.0f}"
             if sv.get("affinity_hits") else ""),
            (f"pulls {sv['prefix_pull_pages']:.0f} pages/"
             f"{sv['prefix_pull_bytes'] / 1024:.0f} KiB"
             + (f" ({sv['prefix_pull_fallbacks']:.0f} fallbacks)"
                if sv.get("prefix_pull_fallbacks") else "")
             if sv.get("prefix_pull_pages")
             or sv.get("prefix_pull_fallbacks") else ""),
        ]
        fleet_bits = [b for b in fleet_bits if b]
        if fleet_bits:
            lines.append("  fleet: " + "   ".join(fleet_bits))
        src = (" (run_end sketch)" if sv.get("latency_source") == "sketch"
               else "")
        if sv["ttft_p50_ms"] is not None:
            lines.append(f"  ttft: p50 {sv['ttft_p50_ms']:.1f} ms  "
                         f"p99 {sv['ttft_p99_ms']:.1f} ms{src}")
        if sv["tpot_p50_ms"] is not None:
            lines.append(f"  tpot: p50 {sv['tpot_p50_ms']:.2f} ms  "
                         f"p99 {sv['tpot_p99_ms']:.2f} ms{src}")
        if sv.get("prefill_chunks") or sv.get("kv_page_util") is not None:
            bits = [f"chunks {sv['prefill_chunks']:.0f}"]
            if sv.get("kv_page_util") is not None:
                bits.append(f"page util {sv['kv_page_util']:.0%}")
            if sv.get("kv_pages_free") is not None:
                bits.append(f"pages free {sv['kv_pages_free']:.0f}")
            if sv.get("prefix_hit_rate") is not None:
                bits.append(f"prefix hit {sv['prefix_hit_rate']:.0%}")
            if (sv.get("prefix_tokens_reused") or sv.get(
                    "prefix_tokens_missed") or sv.get(
                    "prefix_tokens_cold")):
                # reuse audit (ISSUE 16): the dispatch token partition
                # plus the prefill ms a cache-affine placement would
                # have saved
                bits.append(
                    f"reused {sv['prefix_tokens_reused']:.0f}"
                    f"/missed {sv['prefix_tokens_missed']:.0f}"
                    f"/cold {sv['prefix_tokens_cold']:.0f} tok")
                if sv.get("est_prefill_ms_saved"):
                    bits.append("est saved "
                                f"{sv['est_prefill_ms_saved']:.1f} ms")
            lines.append("  paging: " + "   ".join(bits))
        if sv.get("spec_proposed"):
            rate = sv["spec_accepted"] / sv["spec_proposed"]
            bits = [f"{rate:.0%} of {sv['spec_proposed']:.0f} proposed "
                    "draft tokens"]
            if sv.get("spec_draft_source") == "ngram":
                bits.append(f"ngram draft ({sv['ngram_hits']:.0f} "
                            "lookup hits)")
            else:
                bits.append("model draft")
            if sv.get("spec_k_effective") is not None:
                bits.append(f"k_eff {sv['spec_k_effective']:.1f}")
            if sv.get("kv_dtype_bits") is not None:
                bits.append("kv " + ("int8" if sv["kv_dtype_bits"] == 8
                                     else "bf16"))
            lines.append("  accept: " + "   ".join(bits))
    return "\n".join(lines)


def main(argv):
    assert len(argv) == 1, "usage: python tools/obs_report.py <metrics.jsonl>"
    records, skipped = load_records_with_skips(argv[0])
    print(format_report(summarize(records, skipped_lines=skipped)))
