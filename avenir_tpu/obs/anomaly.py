"""Fleet health anomaly engine (ISSUE 14 tentpole, part 2).

The obs stack could *observe* (PR 1 metrics, PR 9 tracing + flight
recorder) and *act* (PR 11 SLO engine + autoscaler) — but nothing
detected GRADUAL degradation: the watchdog fires only on a total stall,
the SLO engine only after user-visible misses. This module is the tier
between them: a schema-pinned table of robust detectors over the
obs/series.py windowed series, each firing BEFORE the watchdog/SLO
tiers react. An anomaly is simultaneously:

- an `anomaly` counter bump (`anomalies_suppressed` for cooldown-
  swallowed re-fires — an ongoing incident alerts once per cooldown,
  never silently zero times and never once per check),
- an `anomaly` JSONL record carrying the evidence (detector, key,
  value, threshold, robust z / slope / baseline),
- an `anomaly` trace event with the same attrs (the PR 11 audit
  pattern — so Perfetto and fleet_report can line anomalies up against
  scale decisions), and
- a **flight-recorder dump** (`flight-anomaly-<detector>-NNN.jsonl`):
  the PR 9 recorder stops being a post-mortem tool and becomes an
  early-warning capture of the minutes BEFORE a death.

Detector statistics are ROBUST by construction — median + MAD z-scores
(a single outlier window cannot drag the baseline the way a mean/stddev
would), least-squares trend with a relative-growth floor, and
fraction-of-baseline collapse — and every detector carries an absolute
floor below which it never fires, which is what makes the no-flapping
pin (a steady in-SLO run produces ZERO anomalies) a property of the
table, not of tuning luck.

Disabled by default everywhere: the train loop and Router hold
`ae = self._anomaly; if ae is not None` — the exact `tr is not None`
shape PR 9 micro-pinned (<1 us/op disabled; tests/test_anomaly.py).
"""

import math
import time

from avenir_tpu.obs.metrics import get_registry
from avenir_tpu.obs.series import SeriesStore, stall_threshold_secs

# ---------------------------------------------------------------------------
# The detector table — the METRIC_SCHEMA pattern applied to detection:
# a detector not declared here cannot be built (fail loud), and the
# docs/OBSERVABILITY.md detector table mirrors this dict (pinned by
# tests/test_metrics_schema.py::test_doc_detector_table_matches_schema).
# name -> (series key, method, what it means / which knob to reach for)
# ---------------------------------------------------------------------------

DETECTOR_SCHEMA = {
    "step_time_drift": (
        "step_time_ms", "drift",
        "train-window / replica-step wall time drifting up (robust "
        "z over window means vs the median baseline) — a silent "
        "throughput regression forming; check data-loader backpressure, "
        "a thermally throttled or straggling host, or a recent config "
        "change (docs/OPERATIONS.md)"),
    "ttft_drift": (
        "ttft_ms", "drift",
        "TTFT drifting up before the SLO tier misses — queue or "
        "prefill pressure building; check prefill-class capacity / "
        "autoscaler max_replicas"),
    "tpot_drift": (
        "tpot_ms", "drift",
        "TPOT drifting up — decode bandwidth pressure; check decode-"
        "class capacity, co-tenant long prompts (disagg split), or "
        "kv_dtype"),
    "queue_wait_trend": (
        "queue_wait_ms", "trend",
        "oldest-queued-request age growing with a sustained positive "
        "slope — a backlog forming; check autoscaler max_replicas / "
        "admission limits"),
    "accept_rate_collapse": (
        "spec_accept_rate", "collapse",
        "speculative-decode accept rate collapsing below a fraction of "
        "its baseline — the draft stopped predicting the target; check "
        "the draft/target pair (a drifted fine-tune, wrong draft "
        "shipped)"),
    "heartbeat_creep": (
        "heartbeat_age_s", "level",
        "oldest replica heartbeat age creeping past a SMALL multiple "
        "of the median step — a stall forming, caught strictly before "
        "the stall tier's max(floor, 10x median) declares death; check "
        "the flight dump for the wedged replica's last events"),
    "io_retry_rate": (
        "io_retries", "level",
        "transient-IO retries arriving faster than the floor rate — "
        "storage degrading before it fails; check the retry records' "
        "sites and the storage backend"),
}

# per-series gauge refresh: series key -> the schema gauge that carries
# its live sketch p99 (literal keys so the schema source-scan lint sees
# only declared names)
_P99_GAUGE = {
    "step_time_ms": "step_time_p99_ms",
    "ttft_ms": "ttft_p99_ms",
    "tpot_ms": "tpot_p99_ms",
    "queue_wait_ms": "queue_wait_p99_ms",
}


def robust_z(baseline, value):
    """Median/MAD z-score of `value` against `baseline` values: MAD is
    scaled by 1.4826 (consistent with sigma under normality), floored
    at 5% of the median so a perfectly flat baseline (injected test
    clocks, paced ticks) cannot make an epsilon wiggle read as a 100-
    sigma event. Returns 0.0 with an empty baseline."""
    if not baseline:
        return 0.0
    s = sorted(baseline)
    med = s[len(s) // 2]
    mad = sorted(abs(x - med) for x in s)[len(s) // 2]
    scale = max(1.4826 * mad, 0.05 * abs(med), 1e-9)
    return (value - med) / scale


def ls_slope(points):
    """Least-squares slope of (t, v) points (value units per second);
    0.0 below 2 points."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    var = sum((t - mt) ** 2 for t, _ in points)
    if var <= 0.0:
        return 0.0
    return sum((t - mt) * (v - mv) for t, v in points) / var


class Detector:
    """One detector-table row bound to its knobs. Methods:

      drift     mean of the newest `recent` window means vs a robust
                (median/MAD) z against the OLDEST-half baseline
                windows (a gradual ramp cannot chase its own baseline
                that way) — fires at z >= z_thresh AND a >= min_rel
                relative rise (noise around a tiny mean must not
                alert), sustained `sustain` consecutive checks
      trend     least-squares slope over the window means — fires when
                the projected growth over `horizon_s` exceeds
                min_rel x the current level AND the level exceeds
                `floor`, sustained
      collapse  newest mean below collapse_frac x the baseline median —
                fires only when the baseline itself is >= floor (a
                signal that never established a baseline cannot
                collapse)
      level     value above an absolute/derived threshold (the
                heartbeat-creep and io-retry detectors; heartbeat's
                threshold is max(floor, factor x median step) with a
                factor STRICTLY below the stall tier's)
    """

    def __init__(self, name, *, key=None, method=None, z_thresh=4.0,
                 min_rel=0.25, sustain=2, min_windows=8, recent=2,
                 collapse_frac=0.5, floor=0.0, horizon_s=30.0,
                 factor=3.0, cooldown_s=30.0):
        assert name in DETECTOR_SCHEMA, (
            f"unknown detector {name!r} — add it to anomaly."
            "DETECTOR_SCHEMA and the docs/OBSERVABILITY.md detector "
            "table (the mirror test pins the two)")
        skey, smethod, _ = DETECTOR_SCHEMA[name]
        self.name = name
        self.key = key or skey
        self.method = method or smethod
        self.z_thresh = float(z_thresh)
        self.min_rel = float(min_rel)
        self.sustain = int(sustain)
        self.min_windows = int(min_windows)
        self.recent = int(recent)
        self.collapse_frac = float(collapse_frac)
        self.floor = float(floor)
        self.horizon_s = float(horizon_s)
        self.factor = float(factor)
        self.cooldown_s = float(cooldown_s)
        self._hits = 0          # consecutive checks the condition held

    def evaluate(self, series, *, context=None):
        """One check against the bound series. Returns None (quiet) or
        the evidence dict of a CONDITION HIT; the engine applies the
        sustain count and cooldown on top."""
        means = series.window_means()
        if self.method == "level":
            return self._eval_level(series, context or {})
        if len(means) < self.min_windows:
            return None
        values = [v for _, v in means]
        if self.method == "drift":
            # baseline = the OLDEST half of the ring: a gradual ramp
            # must not chase its own baseline (median over the full
            # history follows the drift — the classic slow-drift
            # evasion). The ring still turns over, so a PERMANENT new
            # plateau re-baselines in n_windows — an anomaly is a
            # change, not a level. Noise is estimated from the
            # baseline's FIRST DIFFERENCES (MAD/sqrt(2)): a drift that
            # began inside the baseline would inflate a plain value-MAD
            # and read its own trend as noise, suppressing the very z
            # it should raise (found live: rel_rise 1.26 at z 2.9).
            base = values[:max(1, (len(values) - self.recent) // 2)]
            recent = values[-self.recent:]
            cur = sum(recent) / len(recent)
            med = sorted(base)[len(base) // 2]
            if len(base) >= 3:
                diffs = sorted(abs(b - a)
                               for a, b in zip(base, base[1:]))
                noise = 1.4826 * diffs[len(diffs) // 2] / math.sqrt(2.0)
            else:
                noise = 0.0
            scale = max(noise, 0.05 * abs(med), 1e-9)
            z = (cur - med) / scale
            rel = (cur - med) / med if med > 0 else 0.0
            if z >= self.z_thresh and rel >= self.min_rel \
                    and cur >= self.floor:
                return {"value": cur, "baseline": med, "z": round(z, 2),
                        "rel_rise": round(rel, 4),
                        "threshold": round(self.z_thresh, 2)}
            return None
        if self.method == "trend":
            slope = ls_slope(means)
            cur = values[-1]
            if cur < self.floor:
                return None
            growth = slope * self.horizon_s
            if slope > 0 and growth >= self.min_rel * max(cur, 1e-9):
                return {"value": cur, "slope_per_s": round(slope, 4),
                        "projected_rise": round(growth, 2),
                        "threshold": round(self.min_rel * cur, 2)}
            return None
        if self.method == "collapse":
            base, recent = values[:-self.recent], values[-self.recent:]
            if not base:
                return None
            med = sorted(base)[len(base) // 2]
            cur = sum(recent) / len(recent)
            if med >= self.floor and med > 0 \
                    and cur <= self.collapse_frac * med:
                return {"value": cur, "baseline": med,
                        "threshold": round(self.collapse_frac * med, 4),
                        "collapse_frac": self.collapse_frac}
            return None
        raise AssertionError(f"unknown method {self.method!r}")

    def _eval_level(self, series, context):
        cur = series.last()
        if cur is None:
            return None
        if self.name == "heartbeat_creep":
            # the shared stall-threshold RULE at a strictly smaller
            # factor: the stall tier declares death at
            # max(stall_floor, 10 x median step); this detector warns at
            # max(floor, 3 x median step) over the SAME median — earlier
            # by construction, whatever the model scale. The median
            # comes from the step_time series when one is fed (the
            # router feeds both), else from context.
            med_ms = context.get("median_step_ms")
            if med_ms is None:
                st = context.get("step_series")
                med_ms = st.quantile(0.5) if st is not None \
                    and st.count else None
            if med_ms is None:
                return None
            thr = stall_threshold_secs(self.floor, med_ms / 1e3,
                                       factor=self.factor)
            if cur > thr:
                return {"value": round(cur, 4),
                        "threshold": round(thr, 4),
                        "median_step_ms": round(med_ms, 3),
                        "factor": self.factor}
            return None
        # generic level: windowed RATE above floor (io_retry_rate:
        # retries/sec). The window SUM over window_s — the per-window
        # MEAN of per-check deltas would divide the true rate by the
        # caller's check frequency and never fire under a fast loop
        s_sum = series.last_window_sum()
        if s_sum is None:
            return None
        rate = s_sum / max(series.window_s, 1e-9)
        if self.floor > 0 and rate >= self.floor:
            return {"value": round(rate, 4), "threshold": self.floor,
                    "unit": "per_s"}
        return None


def default_detectors(**overrides):
    """One Detector per DETECTOR_SCHEMA row, with per-detector knob
    overrides ({name: {knob: value}}). The defaults encode the shipped
    policy documented in docs/OBSERVABILITY.md."""
    base = {
        # drift floors are RELATIVE rises over a robust baseline: a
        # z-score alone would fire on tight baselines where a few
        # percent of jitter is many MADs — min_rel is the no-flapping
        # floor (the steady-run zero-anomaly pin leans on it)
        "step_time_drift": dict(z_thresh=4.0, min_rel=0.35, sustain=2),
        "ttft_drift": dict(z_thresh=4.0, min_rel=0.75, sustain=3),
        "tpot_drift": dict(z_thresh=4.0, min_rel=0.75, sustain=3),
        # trend: only a backlog BOTH above the absolute floor (ms) and
        # projected to double within horizon_s alerts — transient
        # sawtooth waits under a healthy fleet never do
        "queue_wait_trend": dict(min_rel=1.0, sustain=3, floor=100.0,
                                 horizon_s=10.0, min_windows=6),
        "accept_rate_collapse": dict(collapse_frac=0.5, floor=0.1,
                                     min_windows=8, sustain=2),
        "heartbeat_creep": dict(floor=0.25, factor=3.0, sustain=2),
        "io_retry_rate": dict(floor=1.0, sustain=2),
    }
    for name, kw in (overrides or {}).items():
        base.setdefault(name, {}).update(kw)
    return [Detector(name, **kw) for name, kw in base.items()]


class AnomalyEngine:
    """The detector table over a SeriesStore, with the four-way audit
    emission per fire (counter + record + trace event + flight dump).

    Drive it by observing signals (`observe(key, value)`, or the
    `observe_finished` helper for serve terminal records) and calling
    `check()` at loop cadence — checks are internally paced to
    `check_interval_s` so a hot loop pays one clock read per call
    between checks. Everything is injectable (clock, registry, sink,
    tracer) so the detection-latency pins run on driven time."""

    def __init__(self, *, registry=None, sink=None, tracer=None,
                 clock=None, detectors=None, window_s=1.0, n_windows=64,
                 check_interval_s=None, max_dumps=16, params=None):
        self.clock = clock if clock is not None else time.perf_counter
        self._reg = registry if registry is not None else get_registry()
        self._sink = sink
        self.tracer = tracer
        self.store = SeriesStore(clock=self.clock, window_s=window_s,
                                 n_windows=n_windows)
        if hasattr(self._reg, "attach_series_store"):
            # run_end snapshots carry these series' sketches, so a
            # report reads p50/p99 from the artifact, not re-derived
            self._reg.attach_series_store(self.store)
        self.detectors = (detectors if detectors is not None
                          else default_detectors(**(params or {})))
        self.check_interval_s = (float(check_interval_s)
                                 if check_interval_s is not None
                                 else float(window_s))
        self.max_dumps = int(max_dumps)
        self._n_dumps = 0
        self._last_check = None
        self._last_fire = {}     # detector name -> t of last emission
        self._counters_seen = {}  # counter key -> last total (rates)
        self.fired = []          # every emitted anomaly dict (host log)

    # -- feeding --

    def observe(self, key, value, t=None):
        self.store.observe(key, value, t=t)

    def observe_finished(self, finished, t=None):
        """Feed serve terminal records: TTFT/TPOT series (the drift
        detectors' inputs). Refusals carry no latency and are the SLO
        tier's business, not a latency drift's."""
        for f in finished:
            if getattr(f, "ttft_ms", None) is not None:
                self.store.observe("ttft_ms", f.ttft_ms, t=t)
            if getattr(f, "n_out", 0) > 1:
                self.store.observe("tpot_ms", f.tpot_ms, t=t)

    def observe_counter_rate(self, key, t=None):
        """Feed a counter's per-check DELTA into its series (io_retries
        and friends: rates drift, totals only grow)."""
        total = self._reg.counter(key).total
        seen = self._counters_seen.get(key, total)
        self._counters_seen[key] = total
        if total > seen:
            self.store.observe(key, total - seen, t=t)
            return total - seen
        # an explicit zero sample keeps the window honest (a quiet
        # stretch must pull the rate down, not freeze it)
        self.store.observe(key, 0.0, t=t)
        return 0.0

    # -- checking --

    def check(self, now=None, context=None):
        """Evaluate every detector whose series has data; returns the
        list of anomalies EMITTED this check (cooldown-suppressed hits
        are counted, not returned). Paced: calls inside
        check_interval_s of the last check return [] after one clock
        read."""
        now = self.clock() if now is None else now
        if self._last_check is not None \
                and now - self._last_check < self.check_interval_s:
            return []
        self._last_check = now
        ctx = dict(context or {})
        ctx.setdefault("step_series", self.store.get("step_time_ms"))
        out = []
        for det in self.detectors:
            s = self.store.get(det.key)
            if s is None or s.count == 0:
                det._hits = 0
                continue
            s.flush(now)
            hit = det.evaluate(s, context=ctx)
            if hit is None:
                det._hits = 0
                continue
            det._hits += 1
            if det._hits < det.sustain:
                continue
            last = self._last_fire.get(det.name)
            if last is not None and now - last < det.cooldown_s:
                self._reg.counter("anomalies_suppressed").add(1)
                continue
            self._last_fire[det.name] = now
            out.append(self._emit(det, hit, now))
        self._refresh_gauges()
        return out

    def _refresh_gauges(self):
        for key, gkey in _P99_GAUGE.items():
            s = self.store.get(key)
            if s is not None and s.count:
                self._reg.gauge(gkey).set(s.quantile(0.99))

    def _emit(self, det, hit, now):
        """The four-way audit trail, atomically per anomaly: counter +
        JSONL record + trace event (-> Perfetto/fleet_report) + flight
        dump. Mirrors the autoscaler's _decide discipline."""
        self._reg.counter("anomaly").add(1)
        rec = {"detector": det.name, "key": det.key,
               "method": det.method, **hit}
        if self._sink is not None:
            self._sink.write({"kind": "anomaly", "t": time.time(),
                              "ts": now, **rec})
        tr = self.tracer
        dump = None
        if tr is not None:
            tr.emit(None, "anomaly", t=now, **rec)
            if self._n_dumps < self.max_dumps:
                # flight-anomaly-<detector>-NNN.jsonl: the last-N
                # events BEFORE the degradation became a death — the
                # early-warning capture (never raises; None without an
                # out_dir, same policy as the watchdog's dump)
                dump = tr.flight_dump(f"anomaly-{det.name}")
                if dump is not None:
                    self._n_dumps += 1
        anomaly = {"t": now, **rec, "flight_dump": dump}
        self.fired.append(anomaly)
        return anomaly


__all__ = [
    "DETECTOR_SCHEMA", "Detector", "AnomalyEngine", "default_detectors",
    "robust_z", "ls_slope",
]
