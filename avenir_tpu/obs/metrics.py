"""Metrics registry: counters, gauges, histograms (ISSUE 1 tentpole).

Dependency-free (stdlib only) so every layer of the training path —
loader, checkpoint writer threads, the loop, the watchdog — can record
into one process-local registry without import cycles or optional deps.
The registry is the single source of truth for the JSONL schema: a
metric key that is not declared in METRIC_SCHEMA cannot be created
(fail loud, same policy as the partition-rule miss), which is what lets
tests/test_metrics_schema.py pin the docs/OBSERVABILITY.md table against
the code and keep the metrics.jsonl contract from drifting silently.

Thread-safety: one lock per registry guards every mutation and the
snapshot — async checkpoint writers and the stall watchdog record from
their own threads.
"""

import threading

# key -> (kind, unit, description). The ONE schema; docs/OBSERVABILITY.md
# mirrors this table and tests/test_metrics_schema.py asserts the mirror.
METRIC_SCHEMA = {
    # -- train-loop time accounting (goodput components) --
    "step_window_ms": (
        "counter", "ms",
        "wall time inside flushed train windows: host batch staging + "
        "dispatch + D2H fence; compile time excluded (see compile_ms)"),
    "host_batch_ms": (
        "counter", "ms",
        "loop-side batch staging (the host_batch spans; overlaps device "
        "compute in the windowed loop, so it is a subset of "
        "step_window_ms, not additive to it)"),
    "eval_ms": (
        "counter", "ms", "estimate_loss wall time (the eval spans)"),
    "checkpoint_ms": (
        "counter", "ms",
        "loop-blocking checkpoint time (snapshot + enqueue for async "
        "saves, the full write for sync saves)"),
    "compile_ms": (
        "counter", "ms",
        "trace+compile wall time of the first dispatch of each window "
        "length (the seen-window-length timer exclusions, made explicit)"),
    "d2h_fence_ms": (
        "counter", "ms",
        "loss-stack device-to-host fetch in the window flush (the only "
        "reliable execution fence on tunneled hosts)"),
    "train_dispatch_ms": (
        "counter", "ms",
        "wall time of train-step dispatch calls (includes trace+compile "
        "on the first call of each input shape)"),
    "train_dispatches": (
        "counter", "1", "train-step XLA dispatches issued"),
    # -- data loader --
    "data_stage_ms": (
        "counter", "ms",
        "loader-side sampling + global-array assembly, incl. the "
        "background prefetch thread's sampling (recorded from its "
        "thread — with prefetch engaged this counter can exceed the "
        "loop-blocking host_batch_ms)"),
    "data_batches": (
        "counter", "1", "batch stacks staged by the loader"),
    "data_tokens": (
        "counter", "tok", "input tokens staged by the loader (x only)"),
    "data_prefetch_hit": (
        "counter", "1",
        "batch windows served entirely from the loader's background-"
        "staged buffer (the double-buffered prefetch path)"),
    "data_prefetch_wait_ms": (
        "counter", "ms",
        "time the loop blocked joining an in-flight loader prefetch "
        "thread (nonzero means device windows outpace host staging)"),
    "data_windows": (
        "counter", "1",
        "batch windows requested from the loader (the denominator for "
        "the data_prefetch_hit rate)"),
    # -- checkpoint io --
    "ckpt_saves": ("counter", "1", "checkpoint saves started"),
    "ckpt_save_ms": (
        "counter", "ms",
        "checkpoint writer wall time (runs on the writer thread for "
        "async saves — not loop-blocking; see checkpoint_ms)"),
    "ckpt_bytes_written": (
        "counter", "bytes", "checkpoint bytes written by this process"),
    "ckpt_join_wait_ms": (
        "counter", "ms",
        "time the loop blocked joining an in-flight async writer "
        "(async-writer lag made visible)"),
    "ckpt_restore_ms": (
        "counter", "ms", "checkpoint read/assembly wall time on restore"),
    "ckpt_restore_bytes": (
        "counter", "bytes",
        "checkpoint bytes read on restore (sharded sets: only the shard "
        "files whose header index ranges intersect this process's "
        "addressable shards — ~1/N of the set per process; "
        "docs/OPERATIONS.md)"),
    # -- crash consistency / fault tolerance (ISSUE 5) --
    "io_retries": (
        "counter", "1",
        "transient-IO retries taken by utils/retry.call_with_retry "
        "(checkpoint body reads/writes, loader file reads); each also "
        "writes a `retry` record to the run log"),
    "ckpt_corrupt_detected": (
        "counter", "1",
        "checkpoint artifacts that failed manifest/checksum verification "
        "at restore (uncommitted sets, truncation, bit rot)"),
    "ckpt_fallback": (
        "counter", "1",
        "restores that fell back past a bad newest checkpoint to an "
        "older committed generation (checkpoint/io."
        "select_checkpoint_source)"),
    "ckpt_save_errors": (
        "counter", "1",
        "checkpoint save attempts that raised (async writer-thread "
        "failures surface at the next join/loop boundary; sync failures "
        "raise in place)"),
    # -- watchdog --
    "watchdog_stalls": (
        "counter", "1", "stall-watchdog warnings fired"),
    # -- int8 quantized training (ops/quant.py, ISSUE 15) --
    "matmul_bits": (
        "gauge", "bits",
        "element width of the training hot-matmul operands: 8 under "
        "compute_dtype='int8', 16 for bf16/fp16, 32 for fp32 — set at "
        "loop startup (the kv_dtype idiom); an int8 run that silently "
        "fell back to bf16 would halve throughput with no other "
        "visible cause"),
    "quant_scale_clip": (
        "counter", "1",
        "weight channels whose per-channel quantization scale clamped "
        "to the SCALE_FLOOR in an int8 audit (ops/quant."
        "audit_quantization: loop startup, tools/quant_bench.py) — an "
        "all-zero channel wastes int8 range; a rising count across a "
        "sweep means dead channels"),
    # -- fleet health engine (obs/series.py + obs/anomaly.py, ISSUE 14) --
    "anomaly": (
        "counter", "1",
        "anomalies fired by the detector table (obs/anomaly.py): each "
        "is simultaneously this counter, an `anomaly` JSONL record, an "
        "`anomaly` trace event with its evidence attrs, and a flight-"
        "recorder dump (flight-anomaly-*.jsonl) — the early-warning "
        "tier BEFORE the watchdog/SLO tiers react"),
    "anomalies_suppressed": (
        "counter", "1",
        "detector firings swallowed by the per-detector cooldown (an "
        "ongoing incident re-fires once per cooldown_s, not per check "
        "— O(log) alert volume, never silent: the suppression is "
        "counted here)"),
    "step_time_ms": (
        "hist", "ms",
        "per-step wall time observed by the fleet health series layer "
        "(train window dt; serve replica step walls) — the step-time "
        "drift detector's input signal"),
    "queue_wait_ms": (
        "hist", "ms",
        "age of the OLDEST router-queued request, sampled per fleet "
        "step when the health engine is armed — the queue-wait trend "
        "detector's input (a rising series is a backlog forming before "
        "any SLO miss lands)"),
    "step_time_p99_ms": (
        "gauge", "ms",
        "p99 of the step_time_ms series sketch (obs/series."
        "QuantileSketch; refreshed at anomaly-check cadence)"),
    "ttft_p99_ms": (
        "gauge", "ms",
        "p99 TTFT from the health engine's streaming sketch — the "
        "same number obs_report derives, refreshed live at check "
        "cadence instead of post-hoc"),
    "tpot_p99_ms": (
        "gauge", "ms",
        "p99 TPOT from the health engine's streaming sketch (see "
        "ttft_p99_ms)"),
    "queue_wait_p99_ms": (
        "gauge", "ms",
        "p99 of the queue_wait_ms series sketch (see queue_wait_ms)"),
    # -- request tracing / flight recorder (obs/trace.py, ISSUE 10) --
    "trace_events_dropped": (
        "counter", "1",
        "trace events dropped by a bounded ring or buffer (oldest "
        "first) — the flight recorder never grows unbounded, and never "
        "drops silently either"),
    "flight_dumps": (
        "counter", "1",
        "flight-recorder dumps written (out_dir/flight-*.jsonl): "
        "watchdog fire, worker death, drain failure, or unhandled "
        "crash via the obs/trace.py crash hooks"),
    # -- pipeline parallelism (parallel/pipeline.py) --
    "pp_bubble_frac": (
        "gauge", "1",
        "bubble fraction of the last-traced pipeline schedule (bubble "
        "tick-slots / total tick-slots, counted from _staircase over "
        "every (tick, stage) slot; 1f1b TRAINING ticks carry an F- and "
        "a B-slot, its eval trace counts the forward-only staircase)"),
    "pipe_ticks_real": (
        "counter", "1",
        "per-stage pipeline tick-slots that process a real microbatch, "
        "recorded once per REGION TRACE (schedule utilization is "
        "shape-static, so per-step counting would only repeat it)"),
    "pipe_ticks_bubble": (
        "counter", "1",
        "per-stage pipeline tick-slots spent in warmup/drain bubbles, "
        "recorded once per region trace (see pipe_ticks_real)"),
    # -- serving engine + fleet router (avenir_tpu/serve) --
    "serve_requests": (
        "counter", "1",
        "requests completed by the serving stack — engine or router — "
        "incl. timeouts"),
    "serve_rejected": (
        "counter", "1",
        "requests refused at submit for an impossible shape (prompt + "
        "budget exceeds max_seq_len); finish_reason='rejected', no slot "
        "or prefill ever spent, the engine does NOT crash"),
    "serve_shed": (
        "counter", "1",
        "requests refused at router admission (per-priority queue depth "
        "limit, or projected queue wait already exceeding deadline_ms); "
        "finish_reason='shed' — load shedding instead of unbounded "
        "queue growth (serve/router.py)"),
    "serve_failovers": (
        "counter", "1",
        "in-flight or engine-queued requests requeued off a dead or "
        "stalled replica for a from-scratch re-prefill on a healthy one "
        "(serve/router.py; completed outputs stay bit-identical to "
        "one-shot generation)"),
    "serve_timeouts": (
        "counter", "1",
        "requests that exceeded their deadline_ms (evicted from their "
        "slot mid-decode, or expired while queued) and finished with "
        "finish_reason='timeout'"),
    "tokens_out": (
        "counter", "tok",
        "tokens emitted by the serve engine (one per live slot per "
        "decode iteration)"),
    "serve_prefill_ms": (
        "counter", "ms",
        "admission prefill-into-slot dispatch wall time (the "
        "serve_prefill spans; includes compile on the first prompt of "
        "each bucket)"),
    "serve_decode_ms": (
        "counter", "ms",
        "batched decode dispatch wall time incl. the per-iteration D2H "
        "token fetch (the serve_decode spans)"),
    "queue_depth": (
        "gauge", "1",
        "requests waiting for a slot after the last engine event"),
    "router_queue_depth": (
        "gauge", "1",
        "requests waiting in the router's priority queues after the "
        "last router step (fleet-level; per-engine backlogs are "
        "queue_depth)"),
    "replica_healthy": (
        "gauge", "1",
        "healthy replicas in the serve fleet after the last router step "
        "(draining and dead excluded)"),
    "replica_respawns": (
        "counter", "1",
        "dead process-backend replicas respawned by the fleet "
        "supervisor (serve/proc.py RespawnSupervisor; capped "
        "exponential backoff via utils/retry.RetryPolicy) — the worker "
        "rejoins EMPTY, its former work having already failed over"),
    "rpc_timeouts": (
        "counter", "1",
        "worker RPCs that exceeded their per-op timeout "
        "(serve/proc.py) — the silent-wedge detection path: the replica "
        "is marked dead, its corpse SIGKILLed, its work failed over"),
    "frame_crc_errors": (
        "counter", "1",
        "worker frames refused for a CRC mismatch (serve/frames.py) — "
        "pipe corruption; treated as replica death and NEVER retried "
        "(the stream offset is no longer trustworthy)"),
    "heartbeat_age_s": (
        "gauge", "s",
        "oldest heartbeat age across non-dead replicas after the last "
        "router step — a rising value is a stall forming, visible "
        "before the threshold declares it"),
    # -- elastic control plane (serve/autoscale.py, ISSUE 12) --
    "scale_up": (
        "counter", "1",
        "autoscaler decisions that grew the fleet (incl. burst wakes "
        "and dead-replica replacement); every bump has a matching "
        "`scale` trace event carrying the evidence, and a row in "
        "tools/fleet_report.py"),
    "scale_down": (
        "counter", "1",
        "autoscaler decisions that retired a replica (surplus or "
        "scale-to-zero idle); the retiree drains before removal — "
        "in-flight work is never dropped by a scale decision"),
    "prewarm_ticks": (
        "counter", "1",
        "synthetic prefill+decode ticks run by Engine.prewarm at "
        "replica spawn (one per bucket) so a fresh worker never serves "
        "its first compile to a user; the synthetic requests touch no "
        "other metric"),
    "slo_attainment_interactive": (
        "gauge", "1",
        "windowed fraction of interactive-class requests meeting the "
        "TTFT/TPOT SLO (serve/autoscale.py SLOEngine; shed and "
        "timeouts count as misses, door rejections are excluded)"),
    "slo_attainment_batch": (
        "gauge", "1",
        "windowed fraction of batch-class requests meeting the SLO "
        "(see slo_attainment_interactive)"),
    "slo_burn_rate": (
        "gauge", "1",
        "worst-class error-budget burn: (1 - attainment) / "
        "(1 - target_attainment) over the SLO window — 1.0 spends the "
        "budget exactly at its sustainable rate; the autoscaler's "
        "primary scale-up signal"),
    "fleet_size": (
        "gauge", "1",
        "serving replicas (non-dead, not retiring) after the last "
        "autoscaler poll"),
    "fleet_replica_seconds": (
        "counter", "s",
        "integrated replica-seconds: each autoscaler poll adds "
        "dt x non-dead replicas (draining retirees still bill — they "
        "hold their chip until reaped). THE cost denominator of the "
        "autoscale bench: SLO attainment per replica-second"),
    "slot_occupancy": (
        "gauge", "1",
        "fraction of KV slots live (decoding or mid-chunked-prefill) "
        "after the last engine step"),
    # -- live weight lifecycle (serve/rollout.py, ISSUE 20) --
    "rollouts": (
        "counter", "1",
        "rolling weight-swap campaigns started by Router.rollout "
        "(serve/rollout.py); every stage transition has a matching "
        "`rollout` trace event carrying the evidence, and a row in "
        "tools/fleet_report.py"),
    "rollbacks": (
        "counter", "1",
        "rollout campaigns reverted to the previous weight version — "
        "canary detector fire, mid-rollout anomaly, or mixing-window "
        "overrun; the `rollout` trace event names the trigger"),
    "canary_anomalies": (
        "counter", "1",
        "drift-detector fires against the canary replica during a "
        "rollout's canary stage (the RolloutManager's private "
        "obs/anomaly.py oldest-half detector panel); each fire also "
        "triggers the automatic rollback"),
    "weight_version": (
        "gauge", "1",
        "numeric weight version the fleet last converged on (trailing "
        "integer of the version label, e.g. iter-00000120 -> 120; "
        "ordinal otherwise). Mid-rollout the fleet is version-MIXED "
        "and this gauge holds the previous converged value until the "
        "campaign lands"),
    # -- paged KV (serve/pages.py, kv_impl='paged') --
    "kv_pages_free": (
        "gauge", "1",
        "allocatable KV pages after the last paged-engine step: the "
        "free list plus cached (ref-0 but prefix-registered, evictable "
        "LRU) pages"),
    "kv_page_util": (
        "gauge", "1",
        "fraction of the KV page pool referenced by live requests "
        "after the last paged-engine step (cached prefix pages count "
        "as free — they are reclaimable)"),
    "prefix_hit_rate": (
        "gauge", "1",
        "cumulative fraction of admitted prompt tokens served from "
        "shared prefix pages instead of being recomputed (paged KV "
        "prefix sharing; 0 with prefix_sharing off)"),
    "prefill_chunks": (
        "counter", "1",
        "chunked-prefill dispatches by the paged engine (each computes "
        "at most prefill_chunk prompt tokens, so long prompts never "
        "stall a decode tick)"),
    # -- fleet cache telescope (ISSUE 16): the counterfactual reuse
    #    audit partitions every dispatched prompt's tokens into exactly
    #    these three (reused + missed + cold == prompt tokens, per
    #    dispatch decision; Router(cache_telescope=...) arms it) --
    "prefix_tokens_reused": (
        "counter", "tok",
        "prompt tokens the CHOSEN replica already held as a shared "
        "prefix chain at dispatch (cache-map content view; may "
        "overstate the actual attach by up to one page)"),
    "prefix_tokens_missed": (
        "counter", "tok",
        "prompt tokens some OTHER replica held but the chosen one did "
        "not — the fleet recomputing prefixes it already has; the "
        "missed-reuse headline an affinity router (PR 17) would "
        "reclaim"),
    "prefix_tokens_cold": (
        "counter", "tok",
        "prompt tokens no tracked replica held at dispatch — "
        "genuinely new prefill work no placement could have avoided"),
    # -- fleet KV CDN (ISSUE 17): prefix-affinity routing + peer
    #    prefix pull (Router(affinity=...) arms it, telescope required) --
    "affinity_hits": (
        "counter", "1",
        "dispatches the affinity router placed on a replica already "
        "advertising a shared prefix chain of the prompt — the "
        "placements the telescope's audit counts as reused"),
    "prefix_pull_pages": (
        "counter", "1",
        "KV pages WRITTEN into the chosen replica by brokered peer "
        "prefix pulls (chain nodes it already held dedupe and are not "
        "counted)"),
    "prefix_pull_bytes": (
        "counter", "bytes",
        "tensor bytes shipped over PT_KVPAGES frames by peer prefix "
        "pulls (page K/V data + int8 scale sidecars)"),
    "prefix_pull_fallbacks": (
        "counter", "1",
        "brokered pulls that fell back to local re-prefill — source "
        "died/evicted the chain, frame CRC trip, RPC timeout, or the "
        "destination died under the import; pulls are an optimization, "
        "never a correctness dependency"),
    # -- disaggregated prefill/decode (ISSUE 13) --
    "kv_pages_exported": (
        "counter", "1",
        "finished KV pages exported by prefill-class engines (each a "
        "page_size-token block fully covered by prompt tokens, "
        "streamed the moment its chunk completes)"),
    "kv_pages_imported": (
        "counter", "1",
        "transferred KV pages WRITTEN into a decode-class engine's "
        "pool (chain nodes already present dedupe and are not "
        "counted — their bytes were never sent twice either)"),
    "kv_transfers": (
        "counter", "1",
        "completed prefill->decode handoffs (router kv_transfer "
        "events with handoff=true; the decode replica's admission "
        "prefix-attaches the imported chain and computes only the "
        "sub-page tail)"),
    "kv_transfer_bytes": (
        "counter", "bytes",
        "tensor bytes shipped over PT_KVPAGES frames between replica "
        "classes (page K/V data + per-head int8 scale sidecars when "
        "kv_dtype='int8')"),
    # -- decode raw speed (ISSUE 11: spec decoding + int8 KV) --
    "spec_proposed": (
        "counter", "tok",
        "draft tokens proposed for verification (spec_k per live slot "
        "per speculative tick; serve/engine.py spec_decode='draft')"),
    "spec_accepted": (
        "counter", "tok",
        "draft tokens the target's rejection-sampling verify accepted "
        "(the correction/bonus token is target-sampled and not counted "
        "here)"),
    "spec_accept_rate": (
        "gauge", "1",
        "cumulative spec_accepted / spec_proposed — drives the "
        "effective tokens-per-model-pass: (1 - a^(k+1)) / (1 - a) "
        "(docs/PERFORMANCE.md accept-rate math)"),
    "ngram_hits": (
        "counter", "1",
        "per-slot-tick prompt-lookup matches under draft_model='ngram' "
        "(a suffix n-gram of the context recurred and its continuation "
        "was proposed; misses fall back to last-token repeats) — "
        "registered at engine construction in ngram mode, so presence "
        "marks the draft source even before the first hit"),
    "spec_k_effective": (
        "gauge", "1",
        "mean per-live-slot effective k at the last speculative tick — "
        "equals spec_k when fixed; under spec_k='auto' each slot walks "
        "the k bucket ladder on its accept-rate EWMA (floor k=1), so "
        "this gauge falling toward 1 is the adaptive-k response the "
        "accept_rate_collapse runbook row points at"),
    "kv_dtype": (
        "gauge", "bits",
        "KV-cache element width of the serving engine: 16 (bf16, the "
        "compute dtype) or 8 (int8 with per-head scales, "
        "ops/kv_quant.py) — set once at engine construction"),
    "ttft_ms": (
        "hist", "ms", "submit -> first token, per finished request"),
    "tpot_ms": (
        "hist", "ms",
        "mean inter-token time after the first token, per finished "
        "request"),
    # -- per-record gauges (latest value at log cadence) --
    "loss": ("gauge", "nats", "train loss at the last logged iter"),
    "lr": ("gauge", "1", "learning rate at the last logged iter"),
    "mfu": ("gauge", "1", "running MFU EMA (fraction of peak)"),
    "tokens_per_sec": (
        "gauge", "tok/s", "global tokens/sec over the last window"),
    "iter_dt_ms": (
        "gauge", "ms", "per-iter wall time, window-amortized"),
    "setup_ms": (
        "gauge", "ms",
        "run_training entry to loop start (mesh + init + restore)"),
    "grad_norm": ("gauge", "1", "global grad norm at the last logged iter"),
    # -- histograms --
    "window_dt_ms": (
        "hist", "ms", "per-iter wall time of each flushed window"),
    "host_batch_dt_ms": (
        "hist", "ms", "wall time of each host_batch staging span"),
}


class Counter:
    """Monotone cumulative sum. `add` accepts int or float."""

    def __init__(self, lock):
        self._lock = lock
        self.total = 0.0
        self.events = 0

    def add(self, v=1.0):
        with self._lock:
            self.total += float(v)
            self.events += 1


class Gauge:
    """Latest-value metric."""

    def __init__(self, lock):
        self._lock = lock
        self.value = None

    def set(self, v):
        with self._lock:
            self.value = float(v)


class Histogram:
    """count/sum/min/max plus p50/p95 from a bounded ring of the most
    recent observations (exact percentiles on short runs, recent-window
    percentiles on long ones — good enough for a stall threshold and a
    report, with O(1) memory)."""

    RING = 512

    def __init__(self, lock):
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._ring = []
        self._ring_pos = 0

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._ring) < self.RING:
                self._ring.append(v)
            else:
                self._ring[self._ring_pos] = v
                self._ring_pos = (self._ring_pos + 1) % self.RING

    def _percentile(self, q):
        # caller holds the lock
        if not self._ring:
            return None
        s = sorted(self._ring)
        return s[min(len(s) - 1, int(q * len(s)))]

    def summary(self):
        with self._lock:
            return {
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "p50": self._percentile(0.50), "p95": self._percentile(0.95),
            }


class MetricsRegistry:
    """get-or-create metric accessors, schema-checked at creation.

    `counter(key)` / `gauge(key)` / `hist(key)` raise on a key absent
    from METRIC_SCHEMA or declared under a different kind — emitting an
    undocumented metric must fail in tests, not drift in production
    JSONL (tests/test_metrics_schema.py)."""

    def __init__(self, schema=METRIC_SCHEMA):
        self._schema = schema
        self._lock = threading.Lock()
        self._metrics = {}
        self._series_store = None  # lazy (obs/series.SeriesStore)
        self._extra_series = []    # attached stores (anomaly engine)

    def _get(self, key, kind, cls):
        assert key in self._schema, (
            f"metric key {key!r} is not declared in METRIC_SCHEMA — add it "
            "there AND to the docs/OBSERVABILITY.md table (the schema lint "
            "test pins the two against each other)"
        )
        assert self._schema[key][0] == kind, (
            f"metric {key!r} is declared as a {self._schema[key][0]}, "
            f"not a {kind}"
        )
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(self._lock)
            assert isinstance(m, cls)
            return m

    def counter(self, key):
        return self._get(key, "counter", Counter)

    def gauge(self, key):
        return self._get(key, "gauge", Gauge)

    def hist(self, key):
        return self._get(key, "hist", Histogram)

    def series(self, key, **kw):
        """Opt a declared metric into a windowed time-series (ISSUE 14:
        ring-buffered per-window aggregates + a mergeable streaming
        percentile sketch, obs/series.py). Any schema key qualifies
        whatever its kind — a series is a VIEW over the signal, not a
        second metric — but an undeclared key fails loud exactly like
        counter()/gauge()/hist(). Lazily built: a run that never calls
        this pays nothing."""
        with self._lock:
            if self._series_store is None:
                from avenir_tpu.obs.series import SeriesStore

                self._series_store = SeriesStore(schema=self._schema)
        assert key in self._schema, (
            f"series key {key!r} is not declared in METRIC_SCHEMA — add "
            "it there AND to the docs/OBSERVABILITY.md table")
        return self._series_store.series(key, **kw)

    def attach_series_store(self, store):
        """Adopt an externally built obs/series.SeriesStore (the
        anomaly engine's, which needs its own clock/window config) so
        series_snapshot() — and therefore run_end records — sees its
        series alongside any opted in via series()."""
        with self._lock:
            self._extra_series.append(store)

    def series_snapshot(self):
        """{key: series snapshot} for every opted-in series (empty when
        none) — rides run_end records so reports read percentiles from
        the sketch instead of re-deriving them."""
        out = {}
        stores = ([self._series_store] if self._series_store is not None
                  else [])
        with self._lock:
            stores = stores + list(self._extra_series)
        for st in stores:
            out.update(st.snapshot())
        return out

    def counters(self):
        """Counters-only view ({key: total}) — the per-iter record's
        cheap path (no histogram ring sorting, unlike snapshot())."""
        with self._lock:
            return {k: m.total for k, m in self._metrics.items()
                    if isinstance(m, Counter)}

    def snapshot(self):
        """{"counters": {key: total}, "gauges": {key: value},
        "hists": {key: summary}} — JSON-serializable, for sink records."""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "hists": {}}
        for key, m in items:
            if isinstance(m, Counter):
                out["counters"][key] = m.total
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            elif isinstance(m, Histogram):
                out["hists"][key] = m.summary()
        return out


_global = [None]


def get_registry():
    """The process-global registry every instrumented layer records into.
    Created on first use; `reset_registry()` swaps in a fresh one (tests,
    or back-to-back runs in one process)."""
    if _global[0] is None:
        _global[0] = MetricsRegistry()
    return _global[0]


def reset_registry():
    _global[0] = MetricsRegistry()
    return _global[0]
