"""Host-side spans that feed both XProf and the metrics registry.

The loop's phase annotations (`host_batch`/`train`/`eval`/`checkpoint`)
already group device activity in XProf traces via
jax.profiler.TraceAnnotation; `span()` keeps that and ALSO accumulates
the host-side wall time of each phase into a registry counter — the raw
material for per-run goodput accounting (docs/OBSERVABILITY.md). The
annotation name is the XProf trace name, so a span in a trace viewer
and its `*_ms` counter in metrics.jsonl are the same phase by
construction.
"""

import time
from contextlib import contextmanager, nullcontext

from avenir_tpu.obs.metrics import get_registry
from avenir_tpu.obs.trace import get_tracer

try:
    from jax.profiler import StepTraceAnnotation, TraceAnnotation
except Exception:  # pragma: no cover — jax-less tooling contexts
    StepTraceAnnotation = TraceAnnotation = None


@contextmanager
def span(name, *, counter=None, hist=None, step_num=None, registry=None):
    """Context manager: XProf TraceAnnotation (StepTraceAnnotation when
    `step_num` is given) + wall-time accumulation into the counter
    `{name}_ms` (override with `counter=`; must be a METRIC_SCHEMA key).
    `hist` optionally also observes the duration into a histogram."""
    reg = registry if registry is not None else get_registry()
    c = reg.counter(counter or f"{name}_ms")
    h = reg.hist(hist) if hist else None
    if TraceAnnotation is None:
        ann = nullcontext()
    elif step_num is not None:
        ann = StepTraceAnnotation(name, step_num=step_num)
    else:
        ann = TraceAnnotation(name)
    t0 = time.perf_counter()
    try:
        with ann:
            yield
    finally:
        dt_ms = (time.perf_counter() - t0) * 1e3
        c.add(dt_ms)
        if h is not None:
            h.observe(dt_ms)
        tr = get_tracer()
        if tr is not None:
            # phase spans ride the trace too (ISSUE 10): the SAME name
            # in XProf, metrics.jsonl, and the Perfetto export. The
            # start is left to the tracer's own clock (now - duration)
            # so spans share the request events' time base even under
            # an injected test clock
            tr.span(name, dur_ms=dt_ms)
