"""Per-request causal tracing + flight recorder (ISSUE 10 tentpole).

The metrics registry answers "how much, in aggregate"; this module
answers "where did request X's 640 ms TTFT go" — queue, admission,
prefill chunks, a failover, a respawn backoff — across a fleet whose
replicas live in other PROCESSES. Three pieces:

- **TraceBuffer** — the per-engine event collector. Emission is one
  attribute check away from free: every instrumented site holds
  `tr = self._tr` and branches on `tr is not None`, so the hot decode
  tick pays ONE predictable-not-taken branch when tracing is off (the
  tier-1 micro-assert pins this). Buffers are bounded (oldest dropped,
  drops counted) and drained every engine step — by the in-process
  Replica directly, or by the worker into its step-reply frame.

- **Tracer** — the fleet-level recorder. A bounded ring of the most
  recent events (the FLIGHT RECORDER: dropped events are counted in
  `trace_events_dropped`, never silently, and memory never grows with
  run length), absorbed from replica buffers with engine-rid -> fleet-
  rid translation and CLOCK RESTAMPING: worker events cross the pipe as
  clock-free age deltas (`age_s` = worker-now - event-time at reply
  build) and are restamped `parent_now - age_s` on arrival — the same
  TTFT-restamp pattern serve/proc.py established, because a worker's
  clock is unrelated to the fleet's. Restamped times are clamped
  per-request monotone (pipe-latency jitter must never make a trace
  tree run backwards; pinned by tests/test_trace.py).

- **Exports** — `flight_dump()` writes the ring to
  `out_dir/flight-<reason>-NNN.jsonl` on incidents (watchdog fire,
  worker death, drain failure, unhandled crash via
  `install_crash_hooks`); `chrome_trace()` renders events as Chrome
  trace-event JSON that opens directly in Perfetto — request waterfalls
  as per-rid tracks (queue / prefill / failover / decode slices derived
  by `request_segments`) next to the training/serving phase spans
  obs/spans.py already times.

Event vocabulary (TRACE_EVENTS): one `finish` terminal event per
request — exactly one, whatever the finish_reason path (pinned) — plus
the lifecycle and incident events around it. Events are plain dicts
{"rid", "ev", "t", ...attrs}; `t` is clock seconds (the fleet clock,
injectable in tests), serialized as `ts` so JSONL records keep `t` for
wall time like every other sink record.
"""

import json
import os
import sys
import threading
import time
from collections import deque

from avenir_tpu.obs.metrics import get_registry

# the event vocabulary; docs/OBSERVABILITY.md "Tracing & flight
# recorder" documents each. Emitting an unknown event fails loud (the
# METRIC_SCHEMA policy applied to traces).
TRACE_EVENTS = {
    "submit",        # request entered the router front door
    "admit",         # passed door admission (queued for dispatch)
    "dispatch",      # handed to a replica engine
    "engine_admit",  # engine granted a slot (prefill begins)
    "prefill_chunk", # one prefill dispatch (slab: the whole prompt)
    "prefix_hit",    # paged admission attached shared prefix pages
    "cow",           # copy-on-write page copy for this request
    "first_token",   # first sampled token landed
    "decode_tick",   # sampled batched decode iteration (rid=None)
    "spec_verify",   # sampled speculative verify tick: proposed/
                     # accepted draft counts ride as attrs (rid=None)
    "evict",         # deadline eviction from a held slot
    "kv_transfer",   # KV pages shipped between disagg replica classes
                     # (ISSUE 13): attrs pages/bytes/src/dst; the
                     # handoff=True marker opens the `transfer` TTFT
                     # segment (streamed mid-prefill ships are instants
                     # — their latency hid behind prefill compute)
    "failover",      # the replica holding this request died
    "requeue",       # re-queued (front of class) for a fresh dispatch
    "finish",        # THE terminal event: reason in attrs, one per rid
    "span",          # a host phase span (obs/spans.py; rid=None)
    "scale",         # one autoscale decision (rid=None): action/reason,
                     # before/after fleet size, and the evidence window
                     # that triggered it (burn rate, attainment, queue
                     # wait) — the auditable control-plane trail
                     # (serve/autoscale.py, ISSUE 12)
    "missed_reuse",  # the reuse auditor found a BETTER placement than
                     # the dispatch took (ISSUE 16): attrs replica/
                     # best_replica/reused/missed/cold/est_ms_saved —
                     # the per-request counterfactual behind the
                     # prefix_tokens_missed counter (router-emitted,
                     # only when missed > 0)
    "prefix_pull",   # the affinity router brokered a peer prefix pull
                     # (ISSUE 17): attrs src/dst replica, pages written,
                     # depth (shared tokens at the source), outcome —
                     # 'ok', or the fallback taken ('src_dead',
                     # 'src_evicted', 'src_gone', 'dst_dead'); every
                     # non-ok outcome also bumps prefix_pull_fallbacks
    "rollout",       # one weight-lifecycle decision (rid=None): action
                     # (begin/canary_start/canary_pass/swap_begin/
                     # swap_done/rollback_begin/rollback_done/done),
                     # reason, from/to version, replica, and the
                     # evidence that drove it (detector z/rel, burn
                     # rate, mixing-window age) — the auditable rollout
                     # trail, `scale`-shaped (serve/rollout.py,
                     # ISSUE 20)
    "anomaly",       # one health-engine detector fire (rid=None):
                     # detector/key/value/threshold + robust-statistic
                     # evidence (obs/anomaly.py, ISSUE 14) — also a
                     # flight dump trigger, so the recorder captures
                     # the minutes BEFORE a degradation becomes a death
}

TERMINAL = "finish"


class TraceBuffer:
    """Per-engine bounded event collector (host-side, single-threaded —
    the engine's own thread is the only writer). Drained every step by
    whoever owns the engine; `dropped` rides along so bounded buffering
    is never silent loss."""

    __slots__ = ("clock", "cap", "events", "dropped", "decode_sample")

    def __init__(self, clock=None, cap=4096, decode_sample=8):
        self.clock = clock if clock is not None else time.perf_counter
        self.cap = int(cap)
        self.events = []
        self.dropped = 0
        self.decode_sample = max(1, int(decode_sample))

    def emit(self, rid, ev, t=None, **attrs):
        assert ev in TRACE_EVENTS, (
            f"unknown trace event {ev!r} — add it to trace.TRACE_EVENTS "
            "and the docs/OBSERVABILITY.md event table")
        if len(self.events) >= self.cap:
            del self.events[0]
            self.dropped += 1
        e = {"rid": rid, "ev": ev,
             "t": self.clock() if t is None else float(t)}
        if attrs:
            e.update(attrs)
        self.events.append(e)

    def drain(self):
        """Return and clear the buffered events (+ the drop count since
        the last drain, folded into the first event's owner)."""
        out, self.events = self.events, []
        return out

    def drain_aged(self, now=None):
        """Drain with each event's `t` replaced by `age_s` = now - t:
        the clock-free form that crosses a process boundary (pipes do
        not share clocks; serve/worker.py ships this in step replies)."""
        now = self.clock() if now is None else now
        out = []
        for e in self.drain():
            e["age_s"] = max(0.0, now - e.pop("t"))
            out.append(e)
        return out


class Tracer:
    """Fleet-level flight recorder: bounded ring, restamp+translate
    absorption, incident dumps, Chrome trace export.

    Thread-safe on the append/read surface — the stall watchdog dumps
    the ring from its own thread while the fleet loop appends."""

    def __init__(self, *, capacity=8192, registry=None, clock=None,
                 out_dir=None, decode_sample=8, max_dumps=64):
        """`capacity`: ring size (oldest dropped + counted beyond it).
        `out_dir`: where flight dumps land (None = dumps disabled).
        `decode_sample`: engines emit one `decode_tick` event per this
        many batched decode iterations — the hot tick must not write an
        event per token even when tracing is ON."""
        self._ring = deque()
        self.capacity = int(capacity)
        self._reg = registry if registry is not None else get_registry()
        self.clock = clock if clock is not None else time.perf_counter
        self.out_dir = out_dir
        self.decode_sample = max(1, int(decode_sample))
        self.max_dumps = int(max_dumps)
        self.dropped = 0
        self._lock = threading.Lock()
        self._last_t = {}   # rid -> last appended t (monotone clamp)
        self._n_dumps = 0

    # -- emission --

    def emit(self, rid, ev, t=None, **attrs):
        assert ev in TRACE_EVENTS, (
            f"unknown trace event {ev!r} — add it to trace.TRACE_EVENTS "
            "and the docs/OBSERVABILITY.md event table")
        e = {"rid": rid, "ev": ev,
             "t": self.clock() if t is None else float(t)}
        if attrs:
            e.update(attrs)
        self._append(e)

    def span(self, name, t0=None, dur_ms=0.0):
        """One host phase span (obs/spans.py feeds this when a process
        tracer is installed): rendered as a Perfetto slice. With
        `t0=None` the start is derived from THIS tracer's clock
        (now - duration), so span and request events share one time
        base even under an injected test clock."""
        if t0 is None:
            t0 = self.clock() - float(dur_ms) / 1e3
        self._append({"rid": None, "ev": "span", "t": float(t0),
                      "name": name, "dur_ms": float(dur_ms)})

    def absorb(self, events, *, rid_map=None, replica=None, now=None,
               dropped=0):
        """Fold a drained replica buffer into the ring. Events carrying
        `age_s` (a worker's clock-free form) are restamped `now - age_s`
        on THIS tracer's clock; `rid_map` translates engine-local rids
        to fleet rids (an unmapped rid keeps its engine id under
        `eng_rid` with rid=None — never silently lost, but never
        miscredited to another fleet request either)."""
        now = self.clock() if now is None else now
        for e in events:
            e = dict(e)
            if "age_s" in e:
                e["t"] = now - float(e.pop("age_s"))
            if replica is not None:
                e["replica"] = replica
            if rid_map is not None and e.get("rid") is not None:
                fleet = rid_map.get(e["rid"])
                if fleet is None:
                    e["eng_rid"], e["rid"] = e["rid"], None
                else:
                    e["rid"] = fleet
            self._append(e)
        if dropped:
            with self._lock:
                self.dropped += int(dropped)
            self._reg.counter("trace_events_dropped").add(int(dropped))

    def _append(self, e):
        rid = e.get("rid")
        with self._lock:
            if rid is not None:
                # per-request monotone clamp: restamped cross-process
                # events carry pipe-latency jitter; a trace tree must
                # never run backwards (tests pin this)
                last = self._last_t.get(rid)
                if last is not None and e["t"] < last:
                    e["t"] = last
                if e["ev"] == TERMINAL:
                    self._last_t.pop(rid, None)  # bound the clamp map
                else:
                    self._last_t[rid] = e["t"]
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self.dropped += 1
                self._reg.counter("trace_events_dropped").add(1)
            self._ring.append(e)

    # -- read surface --

    def events(self):
        with self._lock:
            return list(self._ring)

    def events_for(self, rid):
        with self._lock:
            return [e for e in self._ring if e.get("rid") == rid]

    def __len__(self):
        return len(self._ring)

    # -- exports --

    def flight_dump(self, reason, out_dir=None):
        """Dump the ring (the last `capacity` events) to
        `<dir>/flight-<reason>-NNN.jsonl` — the black box an operator
        reads after an incident (docs/OPERATIONS.md). Returns the path,
        or None when no dump directory is configured or the dump-count
        cap is hit. Never raises: a diagnostics failure must not worsen
        the incident it is recording (the watchdog's policy)."""
        d = out_dir if out_dir is not None else self.out_dir
        if d is None:
            return None
        try:
            with self._lock:
                # check-and-increment under the lock: a watchdog-thread
                # dump racing a fleet-loop one must not reuse a filename
                # (one incident overwriting another) or overshoot the cap
                if self._n_dumps >= self.max_dumps:
                    return None
                self._n_dumps += 1
                seq = self._n_dumps
                events = list(self._ring)
                dropped = self.dropped
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in str(reason))
            path = os.path.join(d, f"flight-{safe}-{seq:03d}.jsonl")
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps({
                    "kind": "flight_meta", "t": time.time(),
                    "reason": str(reason), "n_events": len(events),
                    "dropped_before_ring": dropped,
                }) + "\n")
                for e in events:
                    f.write(json.dumps(event_record(e)) + "\n")
            self._reg.counter("flight_dumps").add(1)
            return path
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            return None

    def write_events_jsonl(self, path):
        """Every ring event as one `trace` record per line — the
        tools/trace_report.py input (also what serve_bench forwards to
        the metrics JSONL under --trace)."""
        with open(path, "w") as f:
            for e in self.events():
                f.write(json.dumps(event_record(e)) + "\n")
        return path

    def chrome(self, **kw):
        return chrome_trace(self.events(), **kw)


def event_record(e):
    """Serialize an internal event for a JSONL sink: the clock time
    moves to `ts` (monotone/injectable clock seconds) so `t` stays wall
    time like every other record kind."""
    rec = {"kind": "trace", "ts": e["t"]}
    rec.update({k: v for k, v in e.items() if k != "t"})
    return rec


def record_event(rec):
    """Inverse of event_record (reading a trace JSONL back)."""
    e = {k: v for k, v in rec.items() if k not in ("kind", "ts", "t")}
    e["t"] = float(rec["ts"]) if "ts" in rec else float(rec.get("t", 0.0))
    return e


# ---------------------------------------------------------------------------
# Waterfall segmentation (shared by the exporter and trace_report)
# ---------------------------------------------------------------------------


def request_segments(events):
    """Partition one request's timeline into labeled segments:

        queue     submitted/requeued, waiting for a dispatch
        prefill   dispatched, working toward its first token
        transfer  the non-overlapped tail of a disagg page handoff
                  (kv_transfer handoff=True -> the decode dispatch);
                  pages streamed mid-prefill hid behind prefill compute
                  and never open this segment (ISSUE 13)
        failover  time sunk into an attempt whose replica died (the
                  work was discarded — re-prefill starts from scratch)
        decode    first token -> finish

    The segments PARTITION [submit, finish] by construction (each event
    closes the previous segment at its own timestamp), which is what
    lets trace_report attribute a TTFT exactly: queue + prefill +
    transfer + failover sums to first_token - submit with no residue.
    A failover retroactively relabels its whole attempt (dispatch
    onward — prefill, transfer AND any decoded tokens) as failover
    loss: the work was discarded, whatever it was called while it ran.
    A handoff dispatch (one that closes a `transfer` segment) CONTINUES
    the attempt rather than starting a new one — the prefill happened
    on another replica, but it is the same work product, and a death
    after handoff discards all of it."""
    evs = sorted((e for e in events if e.get("ev") != "span"),
                 key=lambda e: e["t"])  # stable: ties keep append order
    segs = []
    state, t0 = None, None
    attempt_at = 0  # first segment index of the current attempt

    def close(kind, t1):
        nonlocal t0
        if t0 is not None and t1 > t0:  # zero-length segments (e.g. a
            segs.append((kind, t0, t1))  # failover+requeue at the same
        t0 = t1                          # instant) contribute nothing

    for e in evs:
        ev, t = e["ev"], e["t"]
        if ev == "submit":
            state, t0 = "queue", t
        elif ev == "dispatch":
            handoff = state == "transfer"
            if state is not None:
                close(state, t)
            state = "prefill"
            if not handoff:
                attempt_at = len(segs)
        elif ev == "kv_transfer" and e.get("handoff"):
            if state is not None:
                close(state, t)
            state = "transfer"
        elif ev in ("failover", "requeue"):
            if state is not None:
                close(state, t)
                # the dead attempt's time — prefill underway, pages
                # transferred, tokens already decoded — died with the
                # replica: relabel it failover loss. Queue wait is
                # untouched (nothing was lost there; the wait grew).
                # EXCEPT a handoff-retry requeue (no healthy decode
                # target at handoff time, ISSUE 13): no replica died
                # and the work product is RETAINED — the retry
                # prefix-hits the warm chain — so relabeling it
                # failover would put failover_s in a report whose
                # failover count is 0.
                if not (ev == "requeue" and e.get("handoff_retry")):
                    for i in range(attempt_at, len(segs)):
                        k, a, b = segs[i]
                        if k in ("prefill", "transfer", "decode"):
                            segs[i] = ("failover", a, b)
            state = "queue"
        elif ev == "first_token":
            if state is not None:
                close(state or "prefill", t)
            state = "decode"
        elif ev == TERMINAL:
            if state is not None:
                close(state, t)
            state, t0 = None, None
    return segs


def ttft_attribution(events):
    """{"ttft_s", "queue_s", "prefill_s", "transfer_s", "failover_s"}
    for one request's events, or None when it never produced a token.
    The four components sum to ttft_s exactly (request_segments
    partitions) — `transfer` is the disagg handoff's non-overlapped
    remainder (ISSUE 13)."""
    firsts = [e["t"] for e in events if e.get("ev") == "first_token"]
    submits = [e["t"] for e in events if e.get("ev") == "submit"]
    if not firsts or not submits:
        return None
    t_first = max(firsts)  # the attempt that survived (failover
    #                        discards earlier attempts' tokens)
    out = {"ttft_s": t_first - submits[0], "queue_s": 0.0,
           "prefill_s": 0.0, "transfer_s": 0.0, "failover_s": 0.0}
    for kind, a, b in request_segments(events):
        if b <= t_first and kind in ("queue", "prefill", "transfer",
                                     "failover"):
            out[kind + "_s"] += b - a
    return out


# ---------------------------------------------------------------------------
# Chrome trace-event JSON (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

_SEG_PID = 1      # request waterfalls
_SPAN_PID = 2     # host phase spans (obs/spans.py)
_ENGINE_PID = 3   # rid-less engine events (sampled decode ticks)
_SCALE_PID = 4    # autoscale decisions + fleet-size counter (ISSUE 12)


def chrome_trace(events, *, origin=None):
    """Render events as a Chrome trace-event JSON object (the
    `{"traceEvents": [...]}` form Perfetto and chrome://tracing load
    directly). Each request is one track (pid 1, tid = rid) carrying
    its queue/prefill/failover/decode slices plus an instant marker per
    raw event; host phase spans get per-name tracks on pid 2."""
    events = [e for e in events]
    if origin is None:
        origin = min((e["t"] for e in events), default=0.0)

    def us(t):
        return round((t - origin) * 1e6, 3)

    out = [
        {"ph": "M", "name": "process_name", "pid": _SEG_PID,
         "args": {"name": "serve requests"}},
        {"ph": "M", "name": "process_name", "pid": _SPAN_PID,
         "args": {"name": "host phases"}},
        {"ph": "M", "name": "process_name", "pid": _ENGINE_PID,
         "args": {"name": "engine"}},
        {"ph": "M", "name": "process_name", "pid": _SCALE_PID,
         "args": {"name": "autoscaler"}},
    ]
    by_rid = {}
    span_tids = {}
    for e in events:
        rid = e.get("rid")
        if e["ev"] == "span":
            tid = span_tids.setdefault(e.get("name", "span"),
                                       len(span_tids))
            out.append({"ph": "X", "name": e.get("name", "span"),
                        "cat": "phase", "pid": _SPAN_PID, "tid": tid,
                        "ts": us(e["t"]),
                        "dur": round(e.get("dur_ms", 0.0) * 1e3, 3)})
            continue
        if e["ev"] == "scale":
            # scale decisions get their OWN track (ISSUE 12): a global
            # instant per decision — args carry the full evidence — and
            # a counter series so the fleet size renders as a stepped
            # timeline next to the request waterfalls it explains
            out.append({"ph": "i", "s": "g",
                        "name": f"scale {e.get('action', '?')}",
                        "cat": "autoscale", "pid": _SCALE_PID, "tid": 0,
                        "ts": us(e["t"]),
                        "args": {k: v for k, v in e.items()
                                 if k not in ("rid", "ev", "t")}})
            if e.get("to_size") is not None:
                out.append({"ph": "C", "name": "fleet_size",
                            "pid": _SCALE_PID, "tid": 0,
                            "ts": us(e["t"]),
                            "args": {"replicas": e["to_size"]}})
            continue
        if rid is None:
            out.append({"ph": "i", "s": "g", "name": e["ev"],
                        "cat": "engine", "pid": _ENGINE_PID, "tid": 0,
                        "ts": us(e["t"]),
                        "args": {k: v for k, v in e.items()
                                 if k not in ("rid", "ev", "t")}})
            continue
        by_rid.setdefault(rid, []).append(e)
    for rid, evs in sorted(by_rid.items()):
        sub = next((e for e in evs if e["ev"] == "submit"), None)
        label = f"req {rid}"
        if sub is not None and sub.get("priority"):
            label += f" ({sub['priority']})"
        fin = next((e for e in evs if e["ev"] == TERMINAL), None)
        if fin is not None and fin.get("reason"):
            label += f" [{fin['reason']}]"
        out.append({"ph": "M", "name": "thread_name", "pid": _SEG_PID,
                    "tid": rid, "args": {"name": label}})
        for kind, a, b in request_segments(evs):
            out.append({"ph": "X", "name": kind, "cat": "request",
                        "pid": _SEG_PID, "tid": rid, "ts": us(a),
                        "dur": max(round((b - a) * 1e6, 3), 0.001)})
        for e in evs:
            out.append({"ph": "i", "s": "t", "name": e["ev"],
                        "cat": "request", "pid": _SEG_PID, "tid": rid,
                        "ts": us(e["t"]),
                        "args": {k: v for k, v in e.items()
                                 if k not in ("rid", "ev", "t")}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Process-global tracer handle (the set_run_sink pattern: library layers
# with no tracer plumbed through — obs/spans.py, the watchdog — consult
# this; outside an armed run it stays None and every consult is one
# `is None` check)
# ---------------------------------------------------------------------------

_tracer = [None]


def get_tracer():
    return _tracer[0]


def set_tracer(tracer):
    """Install `tracer` as the process tracer; returns the previous one
    (restore it when the run ends)."""
    prev, _tracer[0] = _tracer[0], tracer
    return prev


# ---------------------------------------------------------------------------
# Crash hooks (ISSUE 10 satellite): a run that dies on an unhandled
# exception — or exits without reaching its normal shutdown path — must
# still leave a final run_end counter snapshot and a flight dump behind.
# ---------------------------------------------------------------------------

_hooks = {"armed": False, "sink": None, "registry": None, "tracer": None,
          "installed": False, "prev_excepthook": None}


def install_crash_hooks(*, sink, registry=None, tracer=None):
    """Arm a sys.excepthook + atexit pair that writes one final
    `run_end` record (crashed=True, full counter snapshot) and a flight
    dump if a tracer is active, BEFORE the interpreter dies. Idempotent
    and re-armable; `disarm_crash_hooks()` after the normal run_end is
    written so a clean exit emits nothing extra. The hooks fire at most
    once per arming (the excepthook path disarms, so atexit becomes a
    no-op)."""
    _hooks.update(sink=sink, registry=registry, tracer=tracer, armed=True)
    if not _hooks["installed"]:
        _hooks["installed"] = True
        _hooks["prev_excepthook"] = sys.excepthook
        sys.excepthook = _crash_excepthook
        import atexit

        atexit.register(_final_flush)


def disarm_crash_hooks():
    _hooks["armed"] = False


def _crash_excepthook(tp, val, tb):
    _final_flush(error=f"{tp.__name__}: {val}")
    prev = _hooks["prev_excepthook"] or sys.__excepthook__
    prev(tp, val, tb)


def _final_flush(error=None):
    """The one-shot crash emitter (excepthook, or atexit on an exit
    path that never disarmed). Best-effort by policy: the process is
    already dying — diagnostics must not mask the original failure."""
    if not _hooks["armed"]:
        return
    _hooks["armed"] = False
    tracer = _hooks["tracer"] if _hooks["tracer"] is not None \
        else get_tracer()
    if tracer is not None:
        tracer.flight_dump("crash")
    sink = _hooks["sink"]
    if sink is None:
        return
    try:
        reg = _hooks["registry"] if _hooks["registry"] is not None \
            else get_registry()
        rec = {"kind": "run_end", "t": time.time(), "crashed": True,
               **reg.snapshot()}
        if error is not None:
            rec["error"] = str(error)
        sink.write(rec)
    except Exception:  # noqa: BLE001 — never mask the original crash
        pass
