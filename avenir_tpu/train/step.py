"""The jit'd train/eval step (SURVEY.md §2b T5, call stack §3.2).

One XLA dispatch per optimizer step: grad accumulation runs as a
`lax.scan` over the leading micro-batch axis INSIDE the jit, gradients
live in fp32, params/opt-state are donated so the update is in-place in
HBM. Parallelism never appears here — it is carried entirely by the
shardings of the inputs (params pytree, batch) and XLA SPMD inserts the
psum / reduce-scatter / all-gather the layout implies (SURVEY.md §1).
"""

import time

import jax
import jax.numpy as jnp
import optax
from flax import nnx


def _count_dispatches(fn):
    """Wrap a jitted step dispatcher so every call lands in the metrics
    registry (train_dispatches / train_dispatch_ms) — the obs layer's
    view of dispatch pressure, shared by the trainer loop AND the bench
    harness's direct-call forms. The dispatch wall time includes
    trace+compile on the first call of each input shape (the loop
    separates that out as compile_ms via its seen-window-length
    accounting). ~µs of overhead per call against ~ms dispatches."""
    from avenir_tpu.obs.metrics import get_registry

    def wrapped(*args, **kwargs):
        reg = get_registry()
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            reg.counter("train_dispatches").add(1)
            reg.counter("train_dispatch_ms").add(
                (time.perf_counter() - t0) * 1e3)

    return wrapped


def make_step_fns(graphdef, *, dropout: float):
    """Build (train_step, eval_step) closures over the model graphdef.

    train_step(params, opt_state, tx, rng, x, y) -> (params, opt_state, metrics)
      x, y: (grad_accum, B, T) int32. `tx` is the optax transform (static).
    """

    def _i32(t):
        # batches arrive in the loader's narrow wire dtype (uint16
        # legacy, uint32 for >65536-vocab v2 files — data/loader.py) —
        # widen on device, fused into the gather
        return t.astype(jnp.int32) if t.dtype != jnp.int32 else t

    def micro_loss(params, x, y, step_rng):
        # the model computes its own loss tail (the config's `loss_impl`
        # knob: reference full-logits CE or the fused chunked tail,
        # ops/fused_ce.py) — the step only consumes the scalar, so the
        # same micro_loss/eval_step serve every tail impl, and with a
        # fused impl no (B, T, V) logits array exists anywhere in this
        # jaxpr (pinned by tests/test_fused_ce.py's shape scan)
        model = nnx.merge(graphdef, params)
        rngs = nnx.Rngs(dropout=step_rng) if dropout > 0.0 else None
        _, loss = model(_i32(x), _i32(y), deterministic=dropout == 0.0,
                        rngs=rngs)
        return loss

    def train_step(params, opt_state, tx, rng, x, y):
        grad_accum = x.shape[0]

        def body(carry, micro):
            g_acc, loss_acc = carry
            xb, yb, r = micro
            loss, g = jax.value_and_grad(micro_loss)(params, xb, yb, r)
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            return (g_acc, loss_acc + loss), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
        )
        rngs = jax.random.split(rng, grad_accum)
        (g_sum, loss_sum), _ = jax.lax.scan(
            body, (zeros, jnp.float32(0.0)), (x, y, rngs)
        )
        inv = 1.0 / grad_accum
        grads = jax.tree.map(lambda g: g * inv, g_sum)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {
            "loss": loss_sum * inv,
            "grad_norm": optax.global_norm(grads),
        }
        return params, opt_state, metrics

    def eval_step(params, x, y):
        model = nnx.merge(graphdef, params)
        _, loss = model(_i32(x), _i32(y), deterministic=True)
        return loss

    return train_step, eval_step


def jit_train_step(train_step, tx):
    """jit the step with donation of params+opt_state so the update happens
    in place in HBM (no transient second copy of the model). Output
    shardings follow the (already sharded) inputs; SPMD does the rest."""

    def wrapped(params, opt_state, rng, x, y):
        return train_step(params, opt_state, tx, rng, x, y)

    return _count_dispatches(jax.jit(wrapped, donate_argnums=(0, 1)))


def _scan_steps(train_step, tx, step_rngs, params, opt_state, xs, ys):
    """The ONE scan-over-steps body shared by both multi-step dispatchers
    (bench's split-rng form and the trainer's fold_in form) — the carry
    shape and metrics stacking must never diverge between them."""

    def body(carry, inp):
        p, o = carry
        x, y, r = inp
        p, o, m = train_step(p, o, tx, r, x, y)
        return (p, o), m

    (params, opt_state), metrics = jax.lax.scan(
        body, (params, opt_state), (xs, ys, step_rngs)
    )
    return params, opt_state, metrics


def jit_multi_train_step(train_step, tx):
    """K optimizer steps per XLA dispatch: `lax.scan` over the leading
    step axis of the batch stack. Semantically identical to K calls of the
    single step (same per-step rng split, same donated in-place update) —
    pinned by tests/test_train_tpu.py — but the host dispatches once per K
    steps instead of once per step. On hosts where per-dispatch latency is
    material (it is ~9ms/step on the tunneled bench chip: 115ms of device
    time measured by xprof vs 124ms wall) this recovers the gap; on a quiet
    host it is simply fewer dispatches.

    multi_step(params, opt_state, rng, xs, ys) -> (params, opt_state, metrics)
      xs, ys: (K, grad_accum, B, T) int32; metrics arrays are stacked (K,).
    """

    def wrapped(params, opt_state, rng, xs, ys):
        step_rngs = jax.random.split(rng, xs.shape[0])
        return _scan_steps(train_step, tx, step_rngs, params, opt_state,
                           xs, ys)

    return _count_dispatches(jax.jit(wrapped, donate_argnums=(0, 1)))


def jit_windowed_train_step(train_step, tx):
    """K optimizer steps per dispatch for the TRAINING LOOP (VERDICT r3
    item 2: the loop must deliver the throughput the bench harness
    measures). Same scan-over-steps body as `jit_multi_train_step`, but
    the per-step rngs are `fold_in(base_rng, global_iter)` — bit-identical
    to the single-step loop's rng stream, so `--dispatch_steps` can never
    change a training trajectory. `start_iter` is a traced scalar: the
    window's position in the run never forces a retrace (only a new window
    LENGTH does).

    windowed(params, opt_state, base_rng, start_iter, xs, ys)
      -> (params, opt_state, metrics)
      xs, ys: (K, grad_accum, B, T) int32; metrics arrays stacked (K,).
    """

    def wrapped(params, opt_state, base_rng, start_iter, xs, ys):
        iters = start_iter + jnp.arange(xs.shape[0])
        step_rngs = jax.vmap(
            lambda i: jax.random.fold_in(base_rng, i)
        )(iters)
        return _scan_steps(train_step, tx, step_rngs, params, opt_state,
                           xs, ys)

    return _count_dispatches(jax.jit(wrapped, donate_argnums=(0, 1)))
