"""The TPU training loop (SURVEY.md §2b T5/T11, call stack §3.2).

Driven by train.py --backend=tpu with the same config namespace as the
torch path. The shape of the loop mirrors train.py:251-316 exactly (eval
cadence, checkpoint policy, logging keys, MFU) so curves overlay; the body
is one jit dispatch per optimizer step with donated state.

tokens/iteration parity: the torch side divides gradient_accumulation_steps
across DDP ranks of micro-batch `batch_size` (train.py:117-118,126). Here
the batch-sharding axes ('data'×'fsdp'×'context'-free) play the rank role:
global micro-batch = batch_size × n_dp, accum = grad_accum_steps / n_dp —
same tokens/iter for the same config on any mesh.
"""

import os
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.checkpoint.io import (
    load_checkpoint,
    restore_opt_state,
    restore_params,
    save_checkpoint,
)
from avenir_tpu.data.loader import DataLoader
from avenir_tpu.models.common import (
    transformer_flops_per_token,
    tpu_peak_flops,
)
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import (
    JsonlSink,
    NullSink,
    StallWatchdog,
    get_registry,
    span,
)
from avenir_tpu.parallel.mesh import initialize_distributed, is_coordinator, make_mesh
from avenir_tpu.parallel.partition import (
    batch_pspec,
    match_partition_rules,
    rules_for_model,
    sanitize_specs,
)
from avenir_tpu.train.optimizer import make_optimizer
from avenir_tpu.train.step import jit_train_step, make_step_fns


def build_model_factory(cfg, model_args, mesh=None):
    """Return (model_type, config_obj, ctor) for the configured family.
    A 'context' mesh axis > 1 switches attention to a sequence-parallel
    impl: cfg['context_parallel_impl'] picks 'ring' (default;
    parallel/ring_attention.py) or 'ulysses' (all-to-all;
    parallel/ulysses.py — tradeoffs in its docstring)."""
    import dataclasses

    mt = cfg["model_type"]
    if mesh is not None and mesh.shape.get("pipe", 1) > 1:
        # pipeline parallelism shards the stacked layer axis
        # (parallel/pipeline.py) — there is nothing to shard without it
        assert cfg.get("scan_layers", False), (
            f"a pipe:{mesh.shape['pipe']} mesh requires scan_layers=True "
            "(pipeline stages own slices of the stacked layer params)"
        )
        # pipe×context composes since r5: ring/ulysses (and the pallas
        # wrap) name only the FREE mesh axes, so they nest correctly
        # inside the pipeline's partial-manual region — see
        # partition.free_axis_names for the transpose hazard that used
        # to make this combination silently wrong (r4 fail-louded it).
    cp = None
    if mesh is not None and mesh.shape.get("context", 1) > 1:
        cp = cfg.get("context_parallel_impl", "ring")
        assert cp in ("ring", "ulysses"), (
            f"context_parallel_impl must be 'ring' or 'ulysses', got {cp!r}"
        )
        assert model_args["dropout"] == 0.0, (
            f"{cp} attention requires dropout=0"
        )
        # the attn_impl hard override promises "never falls back silently"
        # (train.py): a context>1 mesh replacing it with ring/ulysses would
        # break that promise — make the conflict loud instead
        assert not cfg.get("attn_impl") or cfg["attn_impl"] == cp, (
            f"attn_impl={cfg['attn_impl']!r} conflicts with a context:"
            f"{mesh.shape['context']} mesh (sequence-parallel attention "
            f"{cp!r} is required there); drop --attn_impl or set it to {cp!r}"
        )
    if mt == "gpt":
        gcfg = GPTConfig(
            block_size=model_args["block_size"],
            vocab_size=model_args["vocab_size"],
            n_layer=model_args["n_layer"], n_head=model_args["n_head"],
            n_embd=model_args["n_embd"], dropout=model_args["dropout"],
            bias=model_args["bias"],
            # the compute_dtype knob ('int8' = quantized hot matmuls over
            # a bf16 base, ops/quant.py) overrides the dtype-derived base
            compute_dtype=(cfg.get("compute_dtype")
                           or ("float32" if cfg["dtype"] == "float16"
                               else cfg["dtype"])),
            attn_impl=(cp or cfg.get("attn_impl")
                       or ("auto" if cfg["use_pallas"] else "xla")),
            remat=cfg["remat"],
            remat_policy=cfg.get("remat_policy", "nothing"),
            scan_layers=cfg.get("scan_layers", False),
            pipeline_microbatches=cfg.get("pipeline_microbatches", 0),
            pipeline_schedule=cfg.get("pipeline_schedule", "gpipe"),
            loss_impl=cfg.get("loss_impl", "") or "reference",
            loss_chunk=cfg.get("loss_chunk", 0),
        )
        return mt, gcfg, (lambda seed: GPT(gcfg, rngs=nnx.Rngs(seed)))
    if mt == "llama":
        from avenir_tpu.models.llama import Llama, LlamaConfig

        lcfg = LlamaConfig.from_train_config(cfg, model_args)
        if cp or cfg.get("attn_impl"):
            lcfg = dataclasses.replace(lcfg, attn_impl=cp or cfg["attn_impl"])
        return mt, lcfg, (lambda seed: Llama(lcfg, rngs=nnx.Rngs(seed)))
    if mt == "mixtral":
        from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

        mcfg = MixtralConfig.from_train_config(cfg, model_args)
        if cp or cfg.get("attn_impl"):
            mcfg = dataclasses.replace(mcfg, attn_impl=cp or cfg["attn_impl"])
        return mt, mcfg, (lambda seed: Mixtral(mcfg, rngs=nnx.Rngs(seed)))
    raise ValueError(f"unknown model_type {mt!r}")


def setup_state(cfg, mesh, model_args, *, verbose=True):
    """Shared bring-up for training and sampling: sharded param init (or
    abstract shapes only), partition specs, graphdef."""
    from avenir_tpu.compat import set_mesh

    mt, gcfg, ctor = build_model_factory(cfg, model_args, mesh=mesh)
    set_mesh(mesh)  # context mesh: makes in-model PartitionSpec constraints live
    model_abs = nnx.eval_shape(lambda: ctor(cfg["seed"]))
    graphdef, abs_state = nnx.split(model_abs, nnx.Param)
    paths = [p for p, _ in abs_state.flat_state()]
    specs = match_partition_rules(rules_for_model(mt), paths)
    shapes = {p: tuple(v.get_value().shape) for p, v in abs_state.flat_state()}
    # fail loud on non-divisible shardings unless the config explicitly
    # accepts replication (tiny char-vocab runs); drops print coordinator-only
    specs = sanitize_specs(
        specs, shapes, mesh,
        strict=not cfg.get("allow_unsharded_fallback", False),
        log=(print if is_coordinator() else (lambda _msg: None)),
    )
    shardings = {p: NamedSharding(mesh, specs[p]) for p in paths}
    shard_tree = nnx.State.from_flat_path(
        {p: v.replace(shardings[p]) for p, v in abs_state.flat_state()}
    )
    if verbose and is_coordinator():
        n_params = sum(
            int(np.prod(v.get_value().shape)) for _, v in abs_state.flat_state()
        )
        print(f"[tpu] model={mt} params={n_params / 1e6:.2f}M "
              f"mesh={dict(mesh.shape)}")
    return {
        "model_type": mt, "model_config": gcfg, "ctor": ctor,
        "graphdef": graphdef, "abs_state": abs_state,
        "shardings": shardings, "shard_tree": shard_tree,
    }


def init_sharded_opt_state(tx, params, shard_tree):
    """tx.init with Adam mu/nu pinned to the PARAM shardings. ZeRO's whole
    point: moments shard exactly like their params — over 'fsdp' for dense
    weights and over 'expert'×'fsdp'×'tensor' for stacked expert weights
    (the Mixtral "optimizer wall": AdamW is O(params) VPU work, so
    sharding the expert moments over E devices shrinks the wall E× —
    demonstrated by tests/test_mixtral.py::test_expert_opt_state_sharded)."""

    def init_opt(p):
        state = tx.init(p)

        def constrain(node):
            if hasattr(node, "mu") and hasattr(node, "nu") and hasattr(node, "count"):
                con = lambda a, path_shard: jax.lax.with_sharding_constraint(a, path_shard)
                mu = jax.tree.map(con, node.mu, shard_tree)
                nu = jax.tree.map(con, node.nu, shard_tree)
                return node._replace(mu=mu, nu=nu)
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                return type(node)(*(constrain(c) for c in node))
            if isinstance(node, tuple):
                return tuple(constrain(c) for c in node)
            return node

        return constrain(state)

    return jax.jit(init_opt)(params)


def run_training(cfg):
    _t_entry = time.time()  # setup_ms gauge: entry -> loop start
    # fresh counters per run: a second in-process run_training (sweeps,
    # bench, tests) must not inherit the previous run's cumulative totals
    # — restore counters recorded later in THIS run are preserved
    from avenir_tpu.obs import reset_registry

    reset_registry()
    initialize_distributed()
    master = is_coordinator()
    if cfg.get("debug_nans"):
        # re-runs the offending dispatch op-by-op and raises at the first
        # NaN-producing primitive (SURVEY.md §5 "Race/NaN detection")
        jax.config.update("jax_debug_nans", True)
    mesh = make_mesh(cfg["mesh_shape"], dcn_spec=cfg.get("dcn_mesh_shape", ""))
    # every batch-sharding axis counts as data parallelism (see batch_pspec)
    n_dp = mesh.shape["data"] * mesh.shape["fsdp"] * mesh.shape["expert"]

    grad_accum_total = cfg["gradient_accumulation_steps"]
    assert grad_accum_total % n_dp == 0, (
        f"gradient_accumulation_steps={grad_accum_total} must divide across "
        f"{n_dp} data-parallel shards"
    )
    grad_accum = grad_accum_total // n_dp
    global_micro_batch = cfg["batch_size"] * n_dp
    block_size = cfg["block_size"]
    tokens_per_iter = grad_accum * global_micro_batch * block_size
    if master:
        print(f"tokens per iteration: {tokens_per_iter:,}")
        os.makedirs(cfg["out_dir"], exist_ok=True)

    # dataset may be a name under data/ or an absolute path (tests, pods)
    data_dir = (
        cfg["dataset"] if os.path.isabs(cfg["dataset"])
        else os.path.join("data", cfg["dataset"])
    )
    meta_path = os.path.join(data_dir, "meta.pkl")
    meta_vocab_size = None
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta_vocab_size = pickle.load(f)["vocab_size"]
        if master:
            print(f"found vocab_size = {meta_vocab_size} (from {meta_path})")

    model_args = dict(
        n_layer=cfg["n_layer"], n_head=cfg["n_head"], n_embd=cfg["n_embd"],
        block_size=block_size, bias=cfg["bias"], vocab_size=None,
        dropout=cfg["dropout"],
    )

    iter_num = 0
    best_val_loss = 1e9
    ckpt = None
    ckpt_sharded = None
    sh_meta = None
    hf_init = None
    resume_src = None
    resume_data_state = None
    if cfg["init_from"] == "scratch":
        model_args["vocab_size"] = meta_vocab_size if meta_vocab_size else 50304
    elif cfg["init_from"] == "resume":
        # crash-consistent source selection (ISSUE 5): pick the newest
        # artifact — live ckpt.pt, live sharded set, or a ring
        # generation — that passes manifest/checksum verification,
        # falling back past corrupt or uncommitted candidates (counted
        # as ckpt_corrupt_detected / ckpt_fallback). Every process walks
        # the same shared-storage state, so the decision agrees.
        from avenir_tpu.checkpoint.io import select_checkpoint_source

        resume_src = select_checkpoint_source(cfg["out_dir"])
        if resume_src["kind"] == "full":
            # lazy: tensors stream from the zip one at a time during restore
            ckpt = resume_src["meta"]
        else:
            sh_meta = resume_src["meta"]
        # NB the sharded BODIES are read only after setup_state below:
        # the locality-aware loader needs the mesh shardings to read just
        # the shard files whose index ranges intersect this process's
        # addressable shards (advisor r5 — kills the O(N×ckpt) read
        # amplification docs/OPERATIONS.md used to document as a cost)
        src = ckpt if ckpt is not None else sh_meta
        for k in ("n_layer", "n_head", "n_embd", "block_size", "bias", "vocab_size"):
            model_args[k] = src["model_args"][k]
        # coerce NOW: lazy/tensor scalars must not outlive the ckpt file
        # (the next save overwrites it, invalidating lazy readers)
        iter_num = int(src["iter_num"])
        best_val_loss = float(src["best_val_loss"])
        # per-corpus draw counts for the streaming loader (ISSUE 19);
        # absent in pre-streaming checkpoints (.get — resume falls back
        # to the derived fast_forward plan below)
        resume_data_state = src.get("data_state")
        if master:
            form = "sharded set" if ckpt is None else "ckpt.pt"
            print(f"resuming from {resume_src['dir']} ({form}) at iter "
                  f"{iter_num}")
    elif cfg["init_from"].startswith("gpt2"):
        # finetune from HF GPT-2 (train.py:167-176 torch equivalent)
        from avenir_tpu.tools.hf_import import HF_CONFIGS, hf_sd_to_torch_layout, _load_hf_numpy_sd

        assert cfg["model_type"] == "gpt", "gpt2* init requires model_type=gpt"
        hf_init = hf_sd_to_torch_layout(_load_hf_numpy_sd(cfg["init_from"]))
        model_args.update(HF_CONFIGS[cfg["init_from"]])
        model_args.update(vocab_size=50257, block_size=1024, bias=True)
        if cfg["block_size"] < 1024:
            # crop the position table like the torch path's
            # crop_block_size (train.py:203-205 / model.py:199-207)
            hf_init["transformer.wpe.weight"] = (
                hf_init["transformer.wpe.weight"][:cfg["block_size"]]
            )
            model_args["block_size"] = cfg["block_size"]
        if master:
            print(f"initializing from HF weights: {cfg['init_from']}")
    else:
        raise ValueError(f"init_from={cfg['init_from']!r}")

    st = setup_state(cfg, mesh, model_args)
    graphdef, shardings = st["graphdef"], st["shardings"]
    if cfg["init_from"] == "resume" and sh_meta is not None:
        # body read, now that the shardings say which index ranges this
        # process actually hosts — only intersecting files are opened
        from avenir_tpu.checkpoint.io import (
            load_sharded_checkpoint,
            local_shard_ranges,
        )

        ckpt_sharded = load_sharded_checkpoint(
            resume_src["dir"],
            local_ranges=local_shard_ranges(st["abs_state"], shardings),
        )
        assert ckpt_sharded is not None, (
            f"sharded set in {resume_src['dir']} disappeared or tore "
            "between the header check and the body read"
        )
    # matmul element width as a gauge (the kv_dtype idiom): an int8 run
    # that silently fell back to bf16 matmuls would halve throughput
    # with zero visible cause — the gauge plus the startup line below
    # make the resolved width a recorded fact on every process
    from avenir_tpu.ops.quant import matmul_bits, resolve_compute_dtype

    _compute_resolved = resolve_compute_dtype(
        getattr(st["model_config"], "compute_dtype", cfg["dtype"]))
    get_registry().gauge("matmul_bits").set(
        matmul_bits(getattr(st["model_config"], "compute_dtype",
                            cfg["dtype"])))
    if master:
        # print the RESOLVED hot-path impls — a silent fallback to the slow
        # path on a misconfigured pod must be visible at startup
        from avenir_tpu.ops.attention import resolve_attention_impl
        from avenir_tpu.ops.fused_ce import resolve_loss_impl

        attn_resolved = resolve_attention_impl(
            getattr(st["model_config"], "attn_impl", "auto"),
            use_dropout=model_args["dropout"] > 0,
        )
        loss_resolved = resolve_loss_impl(
            getattr(st["model_config"], "loss_impl", "reference"))
        if mesh.shape.get("pipe", 1) > 1:
            # on a pipe mesh the SCHEDULE decides the train-loss path:
            # 1f1b runs the blocked tail inside the pipeline region
            # regardless of loss_impl — say so, same no-silent-fallback
            # policy as the attn/loss lines
            sched = cfg.get("pipeline_schedule", "gpipe")
            if sched == "1f1b":
                loss_resolved = "blocked (inside 1f1b pipeline region)"
            print(f"[tpu] pipeline_schedule={sched} "
                  f"microbatches={cfg.get('pipeline_microbatches', 0) or 'auto'}")
        print(f"[tpu] attention={attn_resolved} loss={loss_resolved} "
              f"compute={_compute_resolved} "
              f"optimizer=optax_adamw "
              f"scan_layers={cfg.get('scan_layers', False)} "
              f"remat={cfg.get('remat', False)}")

    # ---- params: sharded init, HF weights, or checkpoint restore ----
    if ckpt_sharded is not None:
        from avenir_tpu.checkpoint.io import restore_params_sharded

        params = restore_params_sharded(ckpt_sharded["params"],
                                        st["abs_state"], shardings)
    elif ckpt is None and hf_init is None:
        def init_fn():
            m = st["ctor"](cfg["seed"])
            return nnx.split(m, nnx.Param)[1]

        params = jax.jit(init_fn, out_shardings=st["shard_tree"])()
    elif hf_init is not None:
        params = restore_params({"model": hf_init}, st["abs_state"],
                                shardings, model_family=st["model_type"])
    else:
        params = restore_params(ckpt, st["abs_state"], shardings,
                                model_family=st["model_type"])

    # int8 startup audit (ISSUE 15 obs satellite): count weight channels
    # whose quantization scale clamps to the floor (dead channels waste
    # int8 range — harmless at init, a symptom worth a counter when
    # restoring a long-trained checkpoint). Scoped to the tensors the
    # rules table actually quantizes — a dead wpe row or router column
    # never enters the int8 path and must not pollute the counter. One
    # host gather, single-process only (a pod-wide gather at startup is
    # not worth a counter).
    if _compute_resolved == "int8" and jax.process_count() == 1:
        from avenir_tpu.ops.quant import audit_quantization
        from avenir_tpu.parallel.partition import match_precision_rules

        flat = params.flat_state()
        pols = match_precision_rules(
            rules_for_model(st["model_type"]), [p for p, _ in flat],
            {p: tuple(v.get_value().shape) for p, v in flat})
        clipped = audit_quantization(
            (("/".join(str(s) for s in p), np.asarray(v.get_value()))
             for p, v in flat if pols[p].quantize))
        n_clip = sum(clipped.values())
        if master and n_clip:
            print(f"[tpu] quant audit: {n_clip} weight channel(s) at the "
                  "scale floor (quant_scale_clip)")

    # ---- optimizer ----
    tx, lr_schedule = make_optimizer(
        params,
        learning_rate=cfg["learning_rate"], weight_decay=cfg["weight_decay"],
        beta1=cfg["beta1"], beta2=cfg["beta2"], grad_clip=cfg["grad_clip"],
        warmup_iters=cfg["warmup_iters"], lr_decay_iters=cfg["lr_decay_iters"],
        min_lr=cfg["min_lr"], decay_lr=cfg["decay_lr"],
    )

    opt_state = init_sharded_opt_state(tx, params, st["shard_tree"])
    if ckpt is not None:
        opt_state = restore_opt_state(ckpt, opt_state, params, shardings,
                                      model_family=st["model_type"])
        ckpt = None  # free host copies
    elif ckpt_sharded is not None:
        from avenir_tpu.checkpoint.io import restore_opt_state_sharded

        opt_state = restore_opt_state_sharded(ckpt_sharded, opt_state,
                                              params, shardings)
        ckpt_sharded = None  # free host copies

    # ---- data ----
    batch_sharding = NamedSharding(mesh, batch_pspec())
    eval_sharding = NamedSharding(mesh, batch_pspec(with_accum=False))
    data_mix = cfg.get("data_mix", "") or None
    prefetch_depth = int(cfg.get("prefetch_depth", 1) or 1)
    train_loader = DataLoader(
        data_dir, block_size, global_micro_batch,
        sharding=batch_sharding, grad_accum=grad_accum, seed=cfg["seed"],
        vocab_size=model_args["vocab_size"],
        mix=data_mix, prefetch_depth=prefetch_depth,
    )
    eval_loader = DataLoader(
        data_dir, block_size, global_micro_batch,
        sharding=eval_sharding, grad_accum=1, seed=cfg["seed"] + 1, flat=True,
        vocab_size=model_args["vocab_size"], mix=data_mix,
    )
    if cfg["init_from"] == "resume" and iter_num > 0:
        # deterministic resume (ISSUE 5): a fresh loader's rng starts at
        # draw 0, but the run being resumed consumed one train draw per
        # iteration — replay the rng stream to where the kill left it,
        # so the post-resume batch sequence is BIT-IDENTICAL to the
        # uninterrupted run's (tools/chaos_train.py asserts the final
        # loss matches exactly). The eval loader likewise skips the
        # draws of every eval that ran at iters < iter_num (the eval AT
        # iter_num re-runs on resume, so it is not skipped — which is
        # also why ONLY the train loader's state rides the checkpoint:
        # the eval loader's checkpointed counts would include that
        # re-run eval's draws).
        if resume_data_state is not None:
            # checkpointed per-corpus counts (ISSUE 19): exact replay
            # even if the relaunch changed the data_mix weights
            train_loader.fast_forward_state(resume_data_state)
        else:
            train_loader.fast_forward([("train", iter_num)])
        n_past_evals = (iter_num - 1) // cfg["eval_interval"] + 1
        eval_loader.fast_forward(
            [("train", cfg["eval_iters"]), ("val", cfg["eval_iters"])]
            * n_past_evals)

    # ---- step fns ----
    train_step_fn, eval_step_fn = make_step_fns(
        graphdef, dropout=model_args["dropout"]
    )
    train_step = jit_train_step(train_step_fn, tx)
    eval_step = jax.jit(eval_step_fn)

    # dispatch granularity (VERDICT r3 item 2): 0 = auto (windows of up to
    # 32 steps between host boundaries — the loop then delivers the same
    # tok/s the bench harness measures; per-dispatch latency is ~9ms on a
    # tunneled host, train/step.py), 1 = one dispatch per step (legacy),
    # N>1 = explicit window cap. The rng stream, batch stream, logging
    # cadence and loss values are IDENTICAL across all settings (pinned by
    # tests/test_train_tpu.py::test_windowed_loop_matches_single_dispatch).
    dispatch_cap = int(cfg.get("dispatch_steps", 0)) or 32
    use_windowed = dispatch_cap != 1
    if use_windowed:
        from avenir_tpu.train.step import jit_windowed_train_step

        window_step = jit_windowed_train_step(train_step_fn, tx)

    def estimate_loss(params):
        """Mean eval loss per split. ALL dispatches for BOTH splits are
        enqueued before any host readback, and ONE stacked D2H fences the
        lot (r5, VERDICT r4 weak #6: the per-split float() of the r4 form
        still paid two fences per eval — the stacked-fetch discipline
        applied everywhere else stopped one line short here)."""
        means = {
            split: jnp.mean(jnp.stack([
                eval_step(params, *eval_loader.get_batch(split))
                for _ in range(cfg["eval_iters"])
            ]))
            for split in ("train", "val")
        }
        both = np.asarray(jnp.stack([means["train"], means["val"]]))
        return {"train": float(both[0]), "val": float(both[1])}

    if cfg["wandb_log"] and master:
        import wandb

        wandb.init(project=cfg["wandb_project"], name=cfg["wandb_run_name"],
                   config=cfg)

    base_rng = jax.random.key(cfg["seed"])
    flat_abs = dict(st["abs_state"].flat_state())
    n_params = sum(int(np.prod(v.get_value().shape)) for v in flat_abs.values())
    if ("wpe", "embedding") in flat_abs:  # gpt: exclude pos-emb, model.py:167-171
        n_params -= int(np.prod(flat_abs[("wpe", "embedding")].get_value().shape))
    flops_per_token = transformer_flops_per_token(
        n_params, model_args["n_layer"], model_args["n_head"],
        model_args["n_embd"] // model_args["n_head"], block_size,
    )
    peak = tpu_peak_flops()

    if not use_windowed:
        x, y = train_loader.get_batch("train")
    running_mfu = -1.0
    metrics = {"loss": jnp.float32(0.0)}
    profile_started = False
    loss_history = []  # (iter, loss) at log cadence; returned for tests/tools

    # async checkpointing is topology-complete since r5: single-process
    # backgrounds the full torch-compatible ckpt.pt; multi-process
    # backgrounds a per-host SHARDED set (zero collectives in the writer
    # thread — checkpoint/io.py section comment). Sync saves (final,
    # SIGTERM) always write the full collective ckpt.pt.
    use_async_ckpt = bool(cfg.get("async_checkpoint", False))
    pending_ckpt = [None]

    def do_save(lr_now, it, sync=False):
        from avenir_tpu.checkpoint.io import (
            save_checkpoint_async,
            save_checkpoint_sharded_async,
        )

        kw = dict(
            params=params, opt_state=opt_state,
            hyper={"lr": lr_now, "betas": (cfg["beta1"], cfg["beta2"]),
                   "eps": 1e-8, "weight_decay": cfg["weight_decay"]},
            model_args=model_args, iter_num=it,
            best_val_loss=best_val_loss, config=cfg,
            model_family=st["model_type"],
            keep_checkpoints=int(cfg.get("keep_checkpoints", 2)),
            # consumed-draw counts for the streaming loader: what
            # fast_forward_state replays on resume (per-corpus exact,
            # robust to a data_mix re-weight across the relaunch)
            data_state=train_loader.resume_state(),
        )
        # the span counts only LOOP-BLOCKING time: snapshot + enqueue for
        # async saves, the whole write for sync ones (the async writer's
        # own time lands in ckpt_save_ms from its thread)
        with span("checkpoint"), wd_pause():
            if pending_ckpt[0] is not None:
                # one save in flight at a time — and a sync save must never
                # race a background writer's rename of the same file
                pending_ckpt[0].join()
                pending_ckpt[0] = None
            t_s0 = time.time()
            is_async = use_async_ckpt and not sync
            if is_async:
                if jax.process_count() == 1:
                    pending_ckpt[0] = save_checkpoint_async(cfg["out_dir"], **kw)
                else:
                    pending_ckpt[0] = save_checkpoint_sharded_async(
                        cfg["out_dir"], **kw)
            else:
                save_checkpoint(cfg["out_dir"], **kw)
        sink.write({
            "kind": "ckpt", "t": time.time(), "iter": it,
            "dur_ms": round((time.time() - t_s0) * 1e3, 3),
            "async": is_async,
        })

    # graceful preemption (SURVEY §5 failure/recovery): SIGTERM sets a
    # flag; the loop finishes the in-flight iteration, saves, and exits
    # cleanly so a relaunch resumes from the latest state. Registered on
    # the main thread only; pods get the same behavior per-process (the
    # save itself is collective and runs on the main thread).
    import signal

    preempted = [False]
    _prev_handler = None

    def _on_sigterm(signum, frame):
        preempted[0] = True

    try:
        _prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not on the main thread (embedded use): skip
        _prev_handler = None

    # ---- observability (avenir_tpu/obs, ISSUE 1): metrics registry +
    # JSONL run log + stall watchdog. The registry is process-local and
    # always on (counter adds are ~ns); the sink file is coordinator-only
    # and gated on --metrics_log. run_meta/t below is the goodput "total"
    # anchor: everything after it is loop time (setup_ms covers before).
    reg = get_registry()
    sink = (JsonlSink(os.path.join(cfg["out_dir"], "metrics.jsonl"),
                      append=(cfg["init_from"] == "resume"))
            if (cfg.get("metrics_log", True) and master) else NullSink())
    # the process run-log handle: library layers without a plumbed sink
    # (the retry wrapper, writer threads) log retries through this
    from avenir_tpu.obs.sink import set_run_sink
    from avenir_tpu.obs.trace import disarm_crash_hooks, \
        install_crash_hooks

    _prev_sink = set_run_sink(sink)
    # crash hooks (ISSUE 10 satellite): the finally below writes the
    # normal run_end, but a crash that never reaches it — an exception
    # in a path outside this try, an exit from a non-main thread — must
    # still leave a final counter snapshot (and a flight dump when a
    # tracer is armed) in the log; disarmed before the normal run_end
    install_crash_hooks(sink=sink, registry=reg)
    if resume_src is not None:
        sink.write({
            "kind": "restore", "t": time.time(), "iter": iter_num,
            "source_kind": resume_src["kind"],
            "source_dir": resume_src["dir"],
            "skipped_bad": resume_src["skipped_bad"],
            "counters": reg.counters(),
        })
    wd = None
    if float(cfg.get("watchdog_secs", 0) or 0) > 0:
        wd = StallWatchdog(
            floor_secs=float(cfg["watchdog_secs"]), registry=reg, sink=sink,
            fatal_count=int(cfg.get("watchdog_fatal_count", 0) or 0),
            echo=(print if master else
                  (lambda m: print(f"[p{jax.process_index()}] {m}"))),
        )
    # fleet health engine (ISSUE 14): gradual-degradation detection the
    # watchdog's total-stall tier cannot see — step-time drift and io
    # retry rate over windowed series. Coordinator-only (the signals are
    # global), disabled by default; when armed, a Tracer is installed so
    # anomaly fires leave flight-anomaly-*.jsonl dumps in out_dir.
    anomaly = [None]
    _ae_tracer_installed = False
    if cfg.get("anomaly_detect") and master:
        from avenir_tpu.obs.anomaly import AnomalyEngine
        from avenir_tpu.obs.trace import Tracer, get_tracer, set_tracer

        _ae_tr = get_tracer()
        if _ae_tr is None:
            _ae_tr = Tracer(registry=reg, out_dir=cfg["out_dir"])
            set_tracer(_ae_tr)  # spans feed it; restored in the finally
            _ae_tracer_installed = True
        anomaly[0] = AnomalyEngine(
            registry=reg, sink=sink, tracer=_ae_tr,
            window_s=float(cfg.get("anomaly_window_s", 1.0) or 1.0))
    # gradual-degradation fault site (utils/faults.py,
    # `train_step_degrade`): each fire adds a permanent +2 ms/iter of
    # host latency — the slow rot the anomaly engine exists to catch
    # and the watchdog, by design, never fires on (windows keep
    # completing). Inert without AVENIR_FAULTS (enabled() is a dict
    # lookup returning False).
    from avenir_tpu.utils.faults import get_injector

    _degrade = [0]
    from contextlib import nullcontext

    # declared host boundaries (eval, saves, expected compiles) hold the
    # watchdog's fire — they are not missing-window stalls
    wd_pause = wd.pause if wd is not None else nullcontext
    if cfg["decay_lr"]:
        # warm the schedule's jnp kernels NOW: the one-time eager-op
        # compile of the first lr evaluation (~0.5s on a cold CPU host)
        # belongs to setup_ms, not smeared untracked into the loop
        float(lr_schedule(iter_num))
    reg.gauge("setup_ms").set((time.time() - _t_entry) * 1e3)

    # pipelined window logging: the windowed path fetches/logs a window's
    # metrics only AFTER the next window is enqueued, so the D2H fence and
    # the next window's host staging overlap device compute. `pending`
    # holds (start_iter, K, metrics) of the last dispatched window; it is
    # flushed before any host boundary (eval, save, profile stop, exit).
    pending = [None]
    _t0 = [time.time()]
    sink.write({
        "kind": "run_meta", "t": _t0[0], "schema": 1, "iter": iter_num,
        "model_type": st["model_type"], "n_chips": jax.device_count(),
        "n_processes": jax.process_count(), "mesh": dict(mesh.shape),
        "tokens_per_iter": tokens_per_iter, "block_size": block_size,
        "global_micro_batch": global_micro_batch, "grad_accum": grad_accum,
        "setup_ms": round((time.time() - _t_entry) * 1e3, 3),
    })
    window_times = []  # (start_iter, K, dt_per_iter) per flushed window —
    # returned for bench.py's --form=loop arm (the shipped trainer IS the
    # headline measurement, VERDICT r4 item 4)
    seen_window_lengths = set()

    def flush_pending():
        if pending[0] is None:
            return
        start, Kp, m = pending[0]
        pending[0] = None
        _log_window(start, Kp, m)

    def _log_window(start, Kp, m):
        nonlocal running_mfu
        _tf0 = time.time()
        # ONE stacked D2H for loss AND grad_norm (the estimate_loss
        # discipline: a second sequential fetch would bill another full
        # tunnel RTT to every window's dt)
        both = np.asarray(jnp.stack([jnp.ravel(m["loss"]),
                                     jnp.ravel(m["grad_norm"])]))
        losses_np, grad_norms_np = both[0], both[1]
        t1 = time.time()
        reg.counter("d2h_fence_ms").add((t1 - _tf0) * 1e3)
        dt = (t1 - _t0[0]) / Kp  # per-iter wall time, window-amortized
        _t0[0] = t1
        window_times.append((start, Kp, dt))
        # goodput accounting: the window's wall time (staging + dispatch +
        # fence, compile already excluded) and the per-iter dt histogram
        reg.counter("step_window_ms").add(dt * Kp * 1e3)
        reg.hist("window_dt_ms").observe(dt * 1e3)
        if wd is not None:
            wd.notify(window_secs=dt * Kp, iter_num=start + Kp)
        ae = anomaly[0]
        if ae is not None:  # the single-branch disabled guard
            ae.observe("step_time_ms", dt * 1e3)
            ae.observe_counter_rate("io_retries")
            ae.check()
        # every process checks (loss is a global value, identical on all
        # of them): a master-only raise would leave the other processes
        # blocked in the next collective on a pod
        if not np.all(np.isfinite(losses_np)):
            bad = start + int(np.argmax(~np.isfinite(losses_np)))
            raise FloatingPointError(
                f"non-finite loss at iter {bad} (windowed dispatch checks "
                "one window late: up to ~2 windows of further optimizer "
                "steps ran on the bad params before this abort; the "
                "checkpoint cadence is unaffected); rerun "
                "with --debug_nans=True to locate the producing op"
            )
        if not master:
            return
        tok_per_sec = tokens_per_iter / dt
        for j in range(Kp):
            if (start + j) % cfg["log_interval"] != 0:
                continue
            lossf = float(losses_np[j])
            loss_history.append((start + j, lossf))
            if (start - iter_start) + j >= 5:
                seqs_per_iter = cfg["batch_size"] * grad_accum_total
                flops_per_iter = flops_per_token * block_size * seqs_per_iter
                mfu = (flops_per_iter / dt) / (peak * jax.device_count())
                running_mfu = mfu if running_mfu == -1.0 else 0.9 * running_mfu + 0.1 * mfu
            print(f"iter {start + j}: loss {lossf:.4f}, "
                  f"time {dt * 1000:.2f}ms, mfu {running_mfu * 100:.2f}%")
            gnf = float(grad_norms_np[j])
            # the lr iter start+j actually ran under — the loop-level `lr`
            # is already the NEXT window's rate by flush time (one-window
            # lag). Scalar schedule call: the shape was warmed at setup,
            # so this is eager-dispatch cheap, and only at log cadence.
            lr_j = (float(lr_schedule(start + j)) if cfg["decay_lr"]
                    else cfg["learning_rate"])
            reg.gauge("loss").set(lossf)
            reg.gauge("grad_norm").set(gnf)
            reg.gauge("iter_dt_ms").set(dt * 1e3)
            reg.gauge("tokens_per_sec").set(tok_per_sec)
            reg.gauge("mfu").set(running_mfu)
            reg.gauge("lr").set(lr_j)
            sink.write({
                "kind": "iter", "t": t1, "iter": start + j, "loss": lossf,
                "grad_norm": gnf, "dt_ms": round(dt * 1e3, 4),
                "mfu": round(running_mfu, 6),
                "tok_per_sec": round(tok_per_sec, 2), "lr": lr_j,
                "counters": reg.counters(),
            })

    iter_start = iter_num  # first iter of this process's run (mfu warmup)

    try:
        while True:
            lr = float(lr_schedule(iter_num)) if cfg["decay_lr"] else cfg["learning_rate"]

            # eval + checkpointing run on EVERY process: the global-batch
            # construction and the save-time gathers are SPMD collectives, so
            # gating them on the coordinator would deadlock a pod. Only the
            # printing/logging is coordinator-only. All processes compute the
            # same losses (same global arrays), so the save decision agrees.
            if iter_num % cfg["eval_interval"] == 0:
                flush_pending()  # iter lines print before the eval line
                _te0 = time.time()
                with span("eval"), wd_pause():
                    losses = estimate_loss(params)
                sink.write({
                    "kind": "eval", "t": time.time(), "iter": iter_num,
                    "train_loss": losses["train"], "val_loss": losses["val"],
                    "dur_ms": round((time.time() - _te0) * 1e3, 3),
                })
                if master:
                    print(f"step {iter_num}: train loss {losses['train']:.4f}, "
                          f"val loss {losses['val']:.4f}")
                if cfg["wandb_log"] and master:
                    import wandb

                    wandb.log({
                        "iter": iter_num, "train/loss": losses["train"],
                        "val/loss": losses["val"], "lr": lr,
                        "mfu": running_mfu * 100,
                    })
                if losses["val"] < best_val_loss or cfg["always_save_checkpoint"]:
                    best_val_loss = min(best_val_loss, losses["val"])
                    if iter_num > 0:
                        if master:
                            print(f"saving checkpoint to {cfg['out_dir']}"
                                  + (" (async)" if use_async_ckpt else ""))
                        do_save(lr, iter_num)  # spans itself ("checkpoint")
                # eval + save are host boundaries, not step throughput:
                # restart the window timer so their cost doesn't smear
                # into the next window's K per-iter dt lines
                _t0[0] = time.time()
            if iter_num == 0 and cfg["eval_only"]:
                break

            # profile window: iters [10, 20) traced on the coordinator only
            # (start and stop both keyed on `profile_started`, which only the
            # coordinator ever sets — the gating is symmetric by construction)
            if cfg["profile"] and iter_num == 10 and master and not profile_started:
                jax.profiler.start_trace(os.path.join(cfg["out_dir"], "profile"))
                profile_started = True

            if use_windowed:
                # the [10,20) profile window is fully dispatched once
                # iter_num reaches 20: fence it (the flush's D2H) and stop
                # BEFORE enqueueing the next window
                if cfg["profile"] and profile_started and iter_num >= 20:
                    flush_pending()
                    jax.profiler.stop_trace()
                    profile_started = False
                # window length: steps to the next host boundary — the
                # upcoming eval (fires at the next eval_interval multiple),
                # the final step (max_iters inclusive), the profile
                # start/stop iters, capped at dispatch_cap (bounds SIGTERM
                # latency, host batch staging, and the number of distinct
                # compiled window lengths)
                K = cfg["eval_interval"] - (iter_num % cfg["eval_interval"])
                K = min(K, cfg["max_iters"] - iter_num + 1, dispatch_cap)
                if cfg["profile"]:
                    for b in (10, 20):
                        if iter_num < b:
                            K = min(K, b - iter_num)
                K = max(K, 1)
                # degradation fault site: fires accumulate a permanent
                # per-iter host latency (gradual rot, not a stall —
                # the anomaly engine's quarry, tools/anomaly_bench.py)
                _inj = get_injector()
                if _inj.enabled("train_step_degrade"):
                    if _inj.should_fire("train_step_degrade"):
                        _degrade[0] += 1
                    if _degrade[0]:
                        time.sleep(min(0.25, 0.002 * _degrade[0]) * K)
                # stage THIS window while the previous one still runs on
                # device (its metrics are only fetched below, after this
                # dispatch is enqueued) — the upload and the memmap crops
                # hide behind device compute
                with span("host_batch", hist="host_batch_dt_ms"):
                    xs, ys = train_loader.get_batch_window("train", K)
                # a new window LENGTH is about to trace+compile (can run
                # minutes on big models) — that is a declared boundary,
                # not a stall; steady-state dispatches stay watched
                _compile_expected = (
                    wd_pause() if K not in seen_window_lengths
                    else nullcontext())
                with jax.profiler.StepTraceAnnotation("train", step_num=iter_num), \
                        _compile_expected:
                    _td0 = time.time()
                    params, opt_state, metrics = window_step(
                        params, opt_state, base_rng, iter_num, xs, ys
                    )
                    _td = time.time() - _td0
                if K not in seen_window_lengths:
                    # first dispatch of this window LENGTH: the jit cache
                    # is keyed on the xs/ys shapes, which K determines, so
                    # exactly this call traced+compiled. That one-off host
                    # time is not device throughput — exclude it from the
                    # pending window's dt, or one compile smears ~1s/iter
                    # across K log lines and poisons the running-MFU EMA.
                    # Ground truth, not a threshold: the old `_td > 0.5`
                    # heuristic also excised real device backpressure
                    # (silently inflating MFU) and missed sub-0.5s
                    # compiles on tiny models (VERDICT r4 weak #4).
                    seen_window_lengths.add(K)
                    _t0[0] += _td
                    reg.counter("compile_ms").add(_td * 1e3)
                    sink.write({
                        "kind": "compile", "t": time.time(),
                        "iter": iter_num, "window_len": K,
                        "dur_ms": round(_td * 1e3, 3),
                    })
                flush_pending()  # logs the PREVIOUS window (one-window lag)
                pending[0] = (iter_num, K, metrics)
            else:
                K = 1
                step_rng = jax.random.fold_in(base_rng, iter_num)
                # first step of this run traces+compiles — a declared
                # boundary for the watchdog, like the windowed path's
                # first-window-length dispatch
                _compile_expected = (wd_pause() if iter_num == iter_start
                                     else nullcontext())
                # StepTraceAnnotation groups device activity per train step
                # in XProf/TensorBoard (SURVEY.md §5 "annotate phases")
                with jax.profiler.StepTraceAnnotation("train", step_num=iter_num), \
                        _compile_expected:
                    params, opt_state, metrics = train_step(params, opt_state,
                                                            step_rng, x, y)
                with span("host_batch", hist="host_batch_dt_ms"):
                    x, y = train_loader.get_batch("train")  # overlap host sampling w/ device step
                if cfg["profile"] and iter_num >= 20 and profile_started:
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    profile_started = False
                pending[0] = (iter_num, 1, metrics)
                if iter_num % cfg["log_interval"] == 0:
                    flush_pending()  # sync point at log cadence (old contract)
                else:
                    pending[0] = None  # un-logged iter: no fetch at all
                    _now = time.time()
                    # un-fetched iters still spent loop wall time (staging
                    # + dispatch, no fence) — account it, or the goodput
                    # report under-counts device time by ~(log_interval-1)/
                    # log_interval in single-dispatch mode; they are also
                    # watchdog progress, or a healthy loop with a long
                    # log_interval would read as a stall
                    reg.counter("step_window_ms").add((_now - _t0[0]) * 1e3)
                    if wd is not None:
                        wd.notify(window_secs=_now - _t0[0],
                                  iter_num=iter_num + 1)
                    _t0[0] = _now  # keep per-iter timing (old t0 contract)
            iter_num += K
            # surface async-writer failures at the NEXT loop boundary
            # (ISSUE 5 satellite): a writer thread that died must not
            # stay silent until the next save decision happens to join
            # it — a finished handle joins here for free (no blocking;
            # join() re-raises the writer's exception)
            if pending_ckpt[0] is not None and pending_ckpt[0].done():
                pending_ckpt[0].join()
                pending_ckpt[0] = None
            # coordinated preemption (r5, VERDICT r4 missing #3): SIGTERM
            # lands at different iterations on different processes, so no
            # process may save unilaterally (a lone collective save
            # deadlocks against the others' step collectives). Every
            # window boundary, all processes exchange their local flag —
            # one tiny allgather per ≤32 steps, host-side, ~sub-ms on
            # ICI — so the save decision below is unanimous and the
            # collective save runs at the SAME boundary iteration
            # everywhere. Single-process skips the exchange.
            if jax.process_count() > 1:
                # the exchange points must be DETERMINISTIC across
                # processes (a flag-dependent skip would desync the
                # collective): every window boundary, or every 32nd iter
                # in single-dispatch mode — same ≤32-step signal latency
                # either way
                if use_windowed or iter_num % 32 == 0:
                    from jax.experimental import multihost_utils

                    stop_now = bool(np.any(multihost_utils.process_allgather(
                        np.asarray([preempted[0]], np.uint8)
                    )))
                else:
                    stop_now = False
            else:
                stop_now = preempted[0]
            if stop_now:
                flush_pending()  # the dispatched window's iters get logged
                if master:
                    print(f"SIGTERM: saving checkpoint at iter "
                          f"{iter_num} and exiting cleanly")
                do_save(lr, iter_num, sync=True)
                break
            if iter_num > cfg["max_iters"]:
                flush_pending()
                if use_async_ckpt and jax.process_count() > 1:
                    # eval-cadence saves on pods were resume-only shard
                    # sets; leave behind the portable full ckpt.pt as the
                    # run's final artifact (export/sample/torch read it)
                    if master:
                        print(f"final checkpoint (full) at iter {iter_num}")
                    do_save(lr, iter_num, sync=True)
                break
    finally:
        try:
            # a trace started at iter 10 must not dangle if the loop exits
            # before the iter-20 stop (short runs, exceptions, eval_only)
            if profile_started:
                jax.block_until_ready(metrics["loss"])
                jax.profiler.stop_trace()
                profile_started = False
            # restore the handler FIRST: if the join re-raises a writer
            # error, the process must not keep the no-op SIGTERM handler
            if _prev_handler is not None:
                signal.signal(signal.SIGTERM, _prev_handler)
            if pending_ckpt[0] is not None:
                pending_ckpt[0].join()  # never exit with a half-written file
        finally:
            # the run log must close cleanly even when the joins above
            # re-raise; run_end carries the final counter snapshot (incl.
            # any async-writer time the join just accounted)
            if wd is not None:
                wd.stop()
            disarm_crash_hooks()  # the normal run_end below supersedes
            if _ae_tracer_installed:
                from avenir_tpu.obs.trace import set_tracer

                set_tracer(None)  # the run's tracer must not leak
            snap = reg.snapshot()
            series = reg.series_snapshot()  # sketches ride run_end so
            # reports read percentiles without re-deriving (ISSUE 14)
            sink.write({
                "kind": "run_end", "t": time.time(), "iter": iter_num,
                "best_val_loss": float(best_val_loss), **snap,
                # loader config + per-corpus draw counts (record fields
                # are schema-free; corpus names can't be METRIC_SCHEMA
                # keys) — obs_report's "data:" line reads this
                "data": train_loader.data_report(),
                **({"series": series} if series else {}),
            })
            set_run_sink(_prev_sink)  # before close: no writes to a
            sink.close()              # closed sink from stray threads

    return {
        "iter_num": iter_num, "best_val_loss": float(best_val_loss),
        "loss_history": loss_history,
        # steady-state throughput ingredients (bench.py --form=loop):
        # per-window amortized wall times plus the tokens each iter moved
        "window_times": window_times,
        "tokens_per_iter": cfg["batch_size"] * grad_accum_total * block_size,
    }
