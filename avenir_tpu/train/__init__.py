"""avenir_tpu.train — jit'd training loop (SURVEY.md §1 L4, §2b T2/T5)."""

from avenir_tpu.train.optimizer import make_lr_schedule, make_optimizer
