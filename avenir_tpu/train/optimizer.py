"""AdamW via optax, matching the torch reference semantics (SURVEY.md §2b
T2; BASELINE.json:5 "AdamW hot path as Pallas kernels / optax").

Parity notes vs model.py:255-271 + train.py:233-240:
  - decay mask: weight decay only on params with ndim >= 2 (matmul kernels
    and embeddings) — same predicate as configure_optimizers
  - decoupled weight decay, eps=1e-8 — optax.adamw matches torch.AdamW
  - grad clip by global norm BEFORE the Adam update (train.py:294-296)
  - schedule: linear warmup (it+1)/(warmup+1) → cosine to min_lr — exact
    get_lr translation; optax's `count` is the completed-update count,
    which equals the torch loop's iter_num at set-lr time
"""

import math

import jax
import jax.numpy as jnp
import optax


def make_lr_schedule(learning_rate, warmup_iters, lr_decay_iters, min_lr,
                     decay_lr=True):
    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        if not decay_lr:
            return jnp.full_like(count, learning_rate)
        warm = learning_rate * (count + 1.0) / (warmup_iters + 1.0)
        ratio = jnp.clip(
            (count - warmup_iters) / jnp.maximum(lr_decay_iters - warmup_iters, 1),
            0.0, 1.0,
        )
        coeff = 0.5 * (1.0 + jnp.cos(math.pi * ratio))
        cos = min_lr + coeff * (learning_rate - min_lr)
        return jnp.where(count < warmup_iters, warm, cos)

    return schedule


def decay_mask(params):
    """True (decay) for >=2-D params — model.py:258-260's predicate."""
    return jax.tree.map(lambda p: jnp.ndim(p) >= 2, params)


def make_optimizer(params, *, learning_rate, weight_decay, beta1, beta2,
                   grad_clip, warmup_iters, lr_decay_iters, min_lr,
                   decay_lr=True, use_pallas=False):
    """Build the optax chain. `params` is only used to shape the decay mask.

    There is deliberately NO Pallas AdamW kernel: XLA fuses this optax
    chain into the jit'd step with zero launch boundaries, and two rounds
    of kernel variants measured slower on v5e (BASELINE.md "fused AdamW"
    section: per-tensor launches + the extra apply-updates pass cost
    ~9-29ms/step at 124M). `use_pallas` is accepted and ignored for config
    compatibility. BASELINE.json:5's "AdamW hot path as Pallas kernels /
    optax" is satisfied by the optax arm."""
    del use_pallas
    schedule = make_lr_schedule(
        learning_rate, warmup_iters, lr_decay_iters, min_lr, decay_lr
    )
    mask = decay_mask(params)
    adamw = optax.adamw(
        learning_rate=schedule, b1=beta1, b2=beta2, eps=1e-8,
        weight_decay=weight_decay, mask=mask,
    )
    chain = []
    if grad_clip and grad_clip > 0.0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    chain.append(adamw)
    return optax.chain(*chain), schedule
