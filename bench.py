"""Throughput bench harness (SURVEY.md §2a R6 / §2b T12).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Two measurement forms (VERDICT r4 item 4):
  --form=loop (DEFAULT on TPU) — drives the SHIPPED training loop
    (train/loop.run_training: windowed multi-step dispatch, one-window-lag
    logging, real data loader on a synthetic token memmap) and reports
    the trainer's own steady-state tokens/sec/chip. The product path IS
    the headline number; r4 recorded the step-harness figure while the
    trainer measured ~3% faster.
  --form=step — the isolated jit'd train-step harness (fwd+bwd+AdamW,
    pipelined multi-step rounds), kept for component A/B (block sweeps,
    --attn=jax_ref calibration, --dispatch=single).

Both measure GPT-2-124M (bf16 compute, fp32 master params) and report
tokens/sec/chip. `vs_baseline` is relative to the public nanoGPT A100
number the north star targets (BASELINE.json:5 "≥1.0× A100
tokens/sec/chip"): ~1.06M tokens/sec on 8×A100-40GB ≈ 132,500
tokens/sec/GPU for the same model/optimizer in PyTorch.

Usage:
  python bench.py [--form=loop|step] [--steps=N] [--batch=N] [--block=N]
                  [--scan=1] [--attn=pallas|xla|jax_ref] [--no_pallas]
                  [--timing=median|min]
--timing (loop form) picks the headline window statistic: median (default,
ADVICE r5) or min — the best-case window, documented tunnel-only (see
_loop_form). --no_pallas forces XLA attention; --attn overrides it explicitly. The
optimizer is always XLA-fused optax (the measured winner — BASELINE.md
"fused AdamW" section). (No pytest conftest here: this must see the REAL
chip, not the 8-CPU test harness.)
"""

import json
import sys
import time

A100_BASELINE_TOKENS_PER_SEC_PER_CHIP = 132_500.0


def _peak_hbm_bytes():
    """ONE home: avenir_tpu.utils.benching.peak_hbm_bytes (None-tolerant
    off-TPU) — recorded in `extra` so the BENCH_* trajectory can track
    the loss-tail memory wins (ISSUE 3)."""
    from avenir_tpu.utils.benching import peak_hbm_bytes

    return peak_hbm_bytes()


def _resolved_compute(compute_dtype, base_dtype):
    """ONE home for the bench's resolved-precision string (mirrors the
    trainer startup line): 'int8' under the knob, else the base."""
    from avenir_tpu.ops.quant import resolve_compute_dtype

    return resolve_compute_dtype(compute_dtype or base_dtype)


def _gpt_mfu(value, *, n_layer, n_head, n_embd, block):
    """tokens/sec/chip → MFU for a GPT at these dims. ONE home for the
    param-count/flops accounting so the loop and step forms can never
    drift (the wpe subtraction included)."""
    import numpy as np
    from flax import nnx

    from avenir_tpu.models.common import (
        tpu_peak_flops,
        transformer_flops_per_token,
    )
    from avenir_tpu.models.gpt import GPT, GPTConfig

    gcfg = GPTConfig(block_size=block, vocab_size=50304, n_layer=n_layer,
                     n_head=n_head, n_embd=n_embd, dropout=0.0, bias=True)
    abs_state = nnx.split(
        nnx.eval_shape(lambda: GPT(gcfg, rngs=nnx.Rngs(0))), nnx.Param
    )[1]
    shapes = {p: tuple(v.get_value().shape)
              for p, v in abs_state.flat_state()}
    n_params = sum(int(np.prod(s)) for s in shapes.values())
    n_params -= int(np.prod(shapes[("wpe", "embedding")]))
    fpt = transformer_flops_per_token(n_params, n_layer, n_head,
                                      n_embd // n_head, block)
    return value * fpt / tpu_peak_flops()


def _loop_form(args, *, attn_impl, on_tpu, block, batch, scan=False,
               remat=False, loss_impl="auto", compute_dtype=""):
    """Measure through the shipped training loop. Builds a synthetic
    uint16 token memmap (the loader's real path; content is irrelevant to
    throughput), runs run_training for 5 full 32-step dispatch windows,
    and reports the median per-iter wall time the trainer itself logged
    (compile excluded by the loop's seen-window-length accounting)."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from avenir_tpu.obs import get_registry
    # the ONE quantile rule (ISSUE 14): nearest-rank percentile(0.5)
    # returns the lower-middle ELEMENT, bit-identical to the
    # median_low this form always reported — plus the streaming sketch
    # for the window-spread extras the perf-gate ledger's noise band
    # is derived from
    from avenir_tpu.obs.series import QuantileSketch, percentile
    from avenir_tpu.train.loop import run_training

    n_chips = jax.device_count()
    iters = int(args.get("steps", 159 if on_tpu else 4))
    tmp = tempfile.mkdtemp(prefix="avenir-bench-")
    try:
        rng = np.random.default_rng(0)
        rng.integers(0, 50304, 2_000_000, dtype=np.uint16).tofile(
            f"{tmp}/train.bin")
        rng.integers(0, 50304, 200_000, dtype=np.uint16).tofile(
            f"{tmp}/val.bin")
        cfg = dict(
            out_dir=f"{tmp}/out", eval_interval=100_000, log_interval=32,
            eval_iters=1, eval_only=False, always_save_checkpoint=False,
            init_from="scratch", wandb_log=False, wandb_project="bench",
            wandb_run_name="bench", dataset=tmp,
            gradient_accumulation_steps=1,
            batch_size=batch * n_chips, block_size=block,
            model_type="gpt", n_layer=12, n_head=12, n_embd=768,
            dropout=0.0, bias=True, n_kv_head=0, ffn_hidden=0,
            rope_theta=10000.0, n_experts=8, n_experts_per_tok=2,
            capacity_factor=1.25, learning_rate=6e-4, max_iters=iters,
            weight_decay=0.1, beta1=0.9, beta2=0.95, grad_clip=1.0,
            decay_lr=True, warmup_iters=10, lr_decay_iters=1000,
            min_lr=6e-5, backend="tpu", device="cpu",
            dtype="bfloat16" if on_tpu else "float32", compile=False,
            seed=1337, mesh_shape="", remat=remat, scan_layers=scan,
            use_pallas=attn_impl == "pallas", attn_impl=attn_impl,
            loss_impl=loss_impl, loss_chunk=0,
            compute_dtype=compute_dtype,
            fused_adamw=False, profile=False,
            allow_unsharded_fallback=False,
            # streaming loader config (ISSUE 19): overridable so the
            # loop bench can measure mixing/deep-prefetch variants
            data_mix=str(args.get("data_mix", "")),
            prefetch_depth=int(args.get("prefetch_depth", 1)),
        )
        if not on_tpu:  # CPU smoke: shrink to harness scale
            cfg.update(n_layer=2, n_head=2, n_embd=64,
                       batch_size=2 * n_chips, block_size=min(block, 256))
        res = run_training(cfg)
        # full-length windows only (the tail/eval-shortened ones amortize
        # their fence over fewer iters); their dt already excludes compile
        full = [dt for _, k, dt in res["window_times"]
                if k == max(k2 for _, k2, _ in res["window_times"])]
        # The HEADLINE is the MEDIAN window (ADVICE r5): what the trainer
        # sustains on THIS host, variance included. --timing=min instead
        # reports the best-case window — meaningful ONLY on the
        # axon-tunneled bench chip, where every window except the run's
        # last pays ~200-240ms of fixed per-window transfer serialization
        # (the runtime serializes batch H2D + loss D2H between queued
        # window programs; size-independent) and the final window — which
        # stages no successor inside its interval — lands within <1% of
        # min in every run (112.9-113.9ms at B=16,T=1024 across 6 runs,
        # matching the step harness's 113.1ms device time). There min IS
        # the artifact-free device steady state a locally-attached TPU
        # sustains every window; on any other host min is just the
        # luckiest sample, so it ships as an `extra`, not the `value`.
        dt_min = min(full)
        dt_med = percentile(full, 0.5)
        wsk = QuantileSketch()
        for w in full:
            wsk.observe(w * 1e3)
        timing = args.get("timing", "median")  # validated up front in main()
        dt = dt_min if timing == "min" else dt_med
        value = res["tokens_per_iter"] / dt / n_chips
        mfu = _gpt_mfu(value, n_layer=cfg["n_layer"], n_head=cfg["n_head"],
                       n_embd=cfg["n_embd"], block=cfg["block_size"])
        # goodput counters from the run's registry (avenir_tpu/obs): where
        # the bench run's wall time actually went, in the result JSON —
        # read AFTER run_training (it resets the registry at entry)
        c = get_registry().snapshot()["counters"]
        goodput_ms = {
            k: round(c.get(k + "_ms", 0.0), 1)
            for k in ("step_window", "host_batch", "eval", "compile",
                      "train_dispatch")
        }
        from avenir_tpu.ops.fused_ce import resolve_loss_impl

        return value, mfu, {
            "batch_per_chip": cfg["batch_size"] // n_chips,
            "block_size": cfg["block_size"], "n_chips": n_chips,
            "windows": len(full), "dispatch": "windowed",
            "timing": f"trainer-loop-{timing}",
            "min_window_ms": round(dt_min * 1000, 2),
            "median_window_ms": round(dt_med * 1000, 2),
            # window spread from the shared sketch: the run-variance
            # record tools/perf_gate.py's ledger noise bands cite
            "window_p90_ms": round(wsk.quantile(0.90), 2),
            "window_spread_frac": round(
                (max(full) - dt_min) / dt_med, 4) if dt_med else None,
            "goodput_ms": goodput_ms,
            # record what actually ran (auto resolves per platform) plus
            # the run's peak HBM — the loss-tail memory win's ledger
            "loss_impl": resolve_loss_impl(cfg["loss_impl"]),
            # the resolved matmul precision (ISSUE 15): BENCH artifacts
            # must say which compute path their headline measured
            "compute_dtype": _resolved_compute(cfg.get("compute_dtype"),
                                               cfg["dtype"]),
            "peak_hbm_bytes": _peak_hbm_bytes(),
            # loader config the run fed from (ISSUE 19): BENCH artifacts
            # must say which input pipeline their headline measured
            "loader": {
                "layout": "file",  # this form writes single-file splits
                "data_mix": cfg["data_mix"] or None,
                "prefetch_depth": cfg["prefetch_depth"],
                "prefetch_hit": c.get("data_prefetch_hit", 0.0),
                "windows_requested": c.get("data_windows", 0.0),
                "prefetch_wait_ms": round(
                    c.get("data_prefetch_wait_ms", 0.0), 1),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    from avenir_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    import jax
    import numpy as np
    from flax import nnx
    from jax.sharding import NamedSharding, PartitionSpec as P

    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    # 40 steps per timed round: the round's ONE host D2H fence costs a full
    # tunnel round-trip (~100ms measured — it showed up as a phantom
    # ~10ms/step at steps=10, 132k tok/s vs 141k at steps>=30). Real
    # training never fences per-10-steps, so the larger round is the
    # representative steady-state measurement (BASELINE.md round 3).
    steps = int(args.get("steps", 40))
    block = int(args.get("block", 1024))
    use_pallas = "no_pallas" not in args
    attn_impl_flag = args.get("attn", "")   # '', 'pallas', 'xla', 'jax_ref' (calibration)
    on_tpu = jax.default_backend() == "tpu"

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.parallel.partition import (
        match_partition_rules, rules_for_model, sanitize_specs,
    )
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import (
        jit_multi_train_step, jit_train_step, make_step_fns,
    )

    if on_tpu:
        batch_candidates = [int(args["batch"])] if "batch" in args else [16, 8, 4]
    else:  # CPU smoke: tiny so the harness itself can be tested anywhere
        batch_candidates = [int(args.get("batch", 2))]
        block = min(block, 256)
        steps = min(steps, 3)

    # resolve the attention impl HERE (not 'auto') so the result JSON
    # records what actually ran — a silent xla fallback must be visible
    attn_impl = attn_impl_flag
    if not attn_impl:
        attn_impl = "xla"
        if use_pallas and on_tpu:
            try:
                from avenir_tpu.ops.pallas import flash_attention  # noqa: F401

                attn_impl = "pallas"
            except ImportError:
                pass
    form = args.get("form", "loop")
    assert form in ("loop", "step"), f"--form must be loop|step, got {form!r}"
    # validate BEFORE the run: a typo'd flag must not burn minutes of chip
    # time and then die reporting nothing
    timing = args.get("timing", "median")
    assert timing in ("median", "min"), (
        f"--timing must be median|min, got {timing!r} (min is the "
        "tunnel-only best-case window; see _loop_form)"
    )
    scan = args.get("scan", "") in ("1", "True", "true")
    remat = args.get("remat", "") in ("1", "True", "true")
    # the bench model defaults to the FUSED loss tail (ISSUE 3: pallas on
    # TPU, blocked elsewhere); --loss_impl=reference restores the full-
    # logits tail for A/B
    loss_impl = args.get("loss_impl", "auto")
    from avenir_tpu.ops.fused_ce import resolve_loss_impl

    resolve_loss_impl(loss_impl)  # validate before burning chip time
    # --compute_dtype=int8 arms the quantized-matmul path (ops/quant.py);
    # '' follows the base dtype — validated up front like --timing
    compute_dtype = args.get("compute_dtype", "")
    assert compute_dtype in ("", "int8", "bfloat16", "float32"), (
        f"--compute_dtype must be ''|int8|bfloat16|float32, got "
        f"{compute_dtype!r}")
    if form == "loop":
        # --dispatch selects the step harness's dispatcher; the loop form
        # always uses the trainer's windowed dispatch — reject rather than
        # silently measure something else
        assert "dispatch" not in args, (
            "--dispatch is a --form=step knob (the loop form always uses "
            "the trainer's windowed dispatch); add --form=step"
        )
        value, mfu, extra = _loop_form(
            args, attn_impl=attn_impl, on_tpu=on_tpu, block=block,
            batch=batch_candidates[0], scan=scan, remat=remat,
            loss_impl=loss_impl, compute_dtype=compute_dtype,
        )
        result = {
            "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
            "value": round(value, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": round(
                value / A100_BASELINE_TOKENS_PER_SEC_PER_CHIP, 4),
            "extra": {
                "device": str(jax.devices()[0].device_kind),
                "mfu": round(float(mfu), 4), "attn": attn_impl,
                "opt": "optax_xla_fused", "form": "loop",
                "remat": remat, "scan_layers": scan, **extra,
            },
        }
        print(json.dumps(result))
        return

    cfg = GPTConfig(
        block_size=block, vocab_size=50304, n_layer=12, n_head=12,
        n_embd=768, dropout=0.0, bias=True,
        compute_dtype=(compute_dtype
                       or ("bfloat16" if on_tpu else "float32")),
        attn_impl=attn_impl,
        remat=remat,
        scan_layers=scan,
        loss_impl=loss_impl,
    )
    mesh = make_mesh("")  # all chips on 'data'
    n_chips = int(np.prod(list(mesh.shape.values())))

    model_abs = nnx.eval_shape(lambda: GPT(cfg, rngs=nnx.Rngs(0)))
    graphdef, abs_state = nnx.split(model_abs, nnx.Param)
    paths = [p for p, _ in abs_state.flat_state()]
    specs = match_partition_rules(rules_for_model("gpt"), paths)
    shapes = {p: tuple(v.get_value().shape) for p, v in abs_state.flat_state()}
    specs = sanitize_specs(specs, shapes, mesh)
    shard_tree = nnx.State.from_flat_path({
        p: v.replace(NamedSharding(mesh, specs[p]))
        for p, v in abs_state.flat_state()
    })

    def init_fn():
        return nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)[1]

    params = jax.jit(init_fn, out_shardings=shard_tree)()
    tx, _ = make_optimizer(
        params, learning_rate=6e-4, weight_decay=0.1, beta1=0.9, beta2=0.95,
        grad_clip=1.0, warmup_iters=10, lr_decay_iters=1000, min_lr=6e-5,
    )
    opt_state = jax.jit(tx.init)(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    # ONE dispatch for all `steps` optimizer steps (lax.scan over the step
    # axis, train/step.py jit_multi_train_step; equivalence to K single
    # steps is pinned by tests/test_train_tpu.py). xprof measured ~9ms/step
    # of exposed dispatch latency on the tunneled bench chip.
    # --dispatch=single restores the one-call-per-step form for comparison.
    multi = args.get("dispatch", "multi") != "single"
    step = (jit_multi_train_step if multi else jit_train_step)(step_fn, tx)
    bsh_multi = NamedSharding(mesh, P(None, None, ("data", "fsdp"), None))
    bsh = NamedSharding(mesh, P(None, ("data", "fsdp"), None))

    rng = np.random.default_rng(0)
    value = None
    for batch in batch_candidates:
        gb = batch * n_chips
        if multi:
            x = jax.device_put(rng.integers(
                0, 50304, (steps, 1, gb, block)).astype(np.int32), bsh_multi)
            y = jax.device_put(rng.integers(
                0, 50304, (steps, 1, gb, block)).astype(np.int32), bsh_multi)
        else:
            x = jax.device_put(
                rng.integers(0, 50304, (1, gb, block)).astype(np.int32), bsh)
            y = jax.device_put(
                rng.integers(0, 50304, (1, gb, block)).astype(np.int32), bsh)
        try:
            key = jax.random.key(0)
            p, o = params, opt_state
            if multi:
                p, o, m = step(p, o, key, x, y)  # warmup / compile
                float(m["loss"][-1])
            else:
                for _ in range(2):  # warmup / compile
                    p, o, m = step(p, o, key, x, y)
                # NB: a scalar host readback, not block_until_ready — on the
                # axon-tunneled platform only a D2H transfer reliably fences
                # the execution queue
                float(m["loss"])
            if multi:
                # PIPELINED rounds (round 4): dispatch round i+1 BEFORE
                # fetching round i's loss, exactly like the trainer's
                # one-window-lag logging — the D2H fence (~100ms tunnel
                # RTT) hides behind the next round's device time instead
                # of being billed to the measurement. ONE implementation,
                # shared with tools/bench_ladder.py.
                from avenir_tpu.utils.benching import time_pipelined_rounds

                st = [p, o]

                def dispatch():
                    st[0], st[1], m = step(st[0], st[1], key, x, y)
                    return m

                rounds = time_pipelined_rounds(
                    dispatch, lambda m: float(m["loss"][-1]))
                p, o = st
            else:
                # median of 3 fenced rounds: single rounds spread ~±4% on
                # the tunneled platform (medians ~±2%, BASELINE.md)
                rounds = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    for i in range(steps):
                        p, o, m = step(p, o, key, x, y)
                    float(m["loss"])  # fences the whole donated-state chain
                    rounds.append(time.perf_counter() - t0)
            from avenir_tpu.utils.benching import median_low

            dt = median_low(rounds)
            value = gb * block * steps / dt / n_chips
            del p, o
            break
        except Exception as e:  # OOM at this batch — try smaller
            msg = str(e)
            if not any(s in msg for s in (
                "RESOURCE_EXHAUSTED", "Out of memory", "Ran out of memory",
            )):
                raise
            params = jax.jit(init_fn, out_shardings=shard_tree)()
            opt_state = jax.jit(tx.init)(params)

    assert value is not None, "all batch sizes OOMed"

    mfu = _gpt_mfu(value, n_layer=cfg.n_layer, n_head=cfg.n_head,
                   n_embd=cfg.n_embd, block=block)
    result = {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(value / A100_BASELINE_TOKENS_PER_SEC_PER_CHIP, 4),
        "extra": {
            "device": str(jax.devices()[0].device_kind),
            "n_chips": n_chips,
            "batch_per_chip": batch,
            "block_size": block,
            "mfu": round(float(mfu), 4),
            "attn": attn_impl,
            "opt": "optax_xla_fused",
            "form": "step",
            "dispatch": "multi" if multi else "single",
            "timing": "pipelined" if multi else "fenced",
            "remat": cfg.remat,
            "scan_layers": cfg.scan_layers,
            "loss_impl": resolve_loss_impl(cfg.loss_impl),
            "compute_dtype": _resolved_compute(cfg.compute_dtype, cfg.compute_dtype),
            "peak_hbm_bytes": _peak_hbm_bytes(),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
